"""`pio router` — the fault-tolerant front door of a query-server fleet.

One process, however sharded or quantized, caps at one host; ROADMAP
item 5 is the scale-OUT half. This daemon fans ``POST /queries.json``
out to N query-server replicas over keep-alive connections, and the
product is robustness, not routing cleverness — a fleet only earns its
second replica if the front door survives a replica dying mid-request:

- **Health-driven membership.** A poller thread reads each backend's
  ``/readyz`` (liveness + readiness + the model ``generation`` id) on a
  ``PIO_ROUTER_HEALTH_MS`` cadence; a failing backend is ejected from
  rotation and re-admitted when the probe recovers, with a journal
  event (category ``router``) on every transition. Each backend also
  carries its own always-on :class:`resilience.CircuitBreaker`, so a
  replica failing *requests* (not just probes) fast-fails out of
  rotation between polls.
- **Per-request failover.** ``POST /queries.json`` is a pure read, so a
  forward that fails in transport or times out on one replica is
  retried ONCE on another (``resilience.RetryPolicy`` bounds the
  schedule). The router's deadline budget (``PIO_ROUTER_DEADLINE_MS``,
  or a smaller incoming ``X-PIO-Deadline-Ms``) is propagated to the
  backend and spent across attempts: a spent budget answers 504 instead
  of retrying. No other route is ever failover-retried — a
  non-idempotent request replayed after a torn response could
  double-apply (KNOWN_ISSUES #15).
- **Load shedding.** Admission is bounded (``PIO_ROUTER_MAX_INFLIGHT``)
  and an empty rotation (every backend ejected, draining or
  breaker-open) answers the existing ``503 + Retry-After`` contract
  immediately — the router never queues unboundedly in front of a dead
  fleet.
- **Coordinated hot-swap barrier.** ``POST /reload`` drains each
  backend's reload one at a time behind the QueryAPI ``generation`` id:
  queries keep routing ONLY to backends still on the old generation
  while replicas flip one by one; when a single old replica remains the
  router cuts over atomically to the already-flipped set, then reloads
  the last one. A fleet therefore never serves two model generations
  to one client (per-client responses are generation-monotonic) and
  zero queries drop during the swap — each replica's own in-process
  hot-swap keeps its in-flight requests answered.

The router is itself a first-class daemon on the shared transport
(data/api/http.py — ``PIO_TRANSPORT=async`` gives it the keep-alive
event loop): ``/metrics``, ``/healthz``, ``/readyz``,
``/debug/events.json`` and the rest of ``telemetry.handle_route``, plus
trace adoption — an incoming ``X-PIO-Trace`` is propagated to the
chosen backend so ``pio trace`` assembles router→replica trees.
"""

from __future__ import annotations

import dataclasses
import http.client
import itertools
import json
import logging
import os
import threading
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Tuple

from predictionio_tpu.common import journal, resilience, telemetry, tracing

logger = logging.getLogger("predictionio_tpu.router")

#: (status, payload) or (status, payload, extra_headers) — same handler
#: contract as every other daemon on the shared transport.
Response = Tuple[int, Any]

#: transport failures that trigger a failover retry (torn keep-alive
#: responses after a replica kill surface as HTTPException)
_TRANSPORT_ERRORS = (ConnectionError, OSError, http.client.HTTPException)


def _env_pos(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        v = float(raw) if raw else default
    except ValueError:
        v = default
    return v if v > 0 else default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        v = int(raw) if raw else default
    except ValueError:
        v = default
    return v if v > 0 else default


@dataclasses.dataclass
class RouterConfig:
    """`pio router` args. Every knob has an env twin so a config-managed
    fleet and an ad-hoc one read the same defaults."""
    backends: Tuple[str, ...] = ()
    ip: str = "localhost"
    port: int = 8100
    #: membership poll cadence (each backend's /readyz) in ms
    health_ms: float = 0.0
    #: per-query deadline budget in ms (an incoming X-PIO-Deadline-Ms
    #: smaller than this wins); spent budget = 504, never a retry
    deadline_ms: float = 0.0
    #: admission ceiling: concurrent in-flight forwards beyond this shed
    #: with 503 + Retry-After instead of queueing
    max_inflight: int = 0
    #: per-tenant admission ceiling (multi-tenant backends): concurrent
    #: in-flight forwards carrying one tenant's access key beyond this
    #: shed with a tenant-labeled 503 — one tenant's flood never fills
    #: the shared inflight pool. 0 (the default) disables the cap:
    #: single-tenant fleets keep the PR 15 behavior byte for byte.
    tenant_max_inflight: int = 0

    def resolved(self) -> "RouterConfig":
        return dataclasses.replace(
            self,
            health_ms=self.health_ms or _env_pos("PIO_ROUTER_HEALTH_MS", 500.0),
            deadline_ms=(self.deadline_ms
                         or _env_pos("PIO_ROUTER_DEADLINE_MS", 2000.0)),
            max_inflight=(self.max_inflight
                          or _env_int("PIO_ROUTER_MAX_INFLIGHT", 256)),
            tenant_max_inflight=(
                self.tenant_max_inflight
                or _env_int("PIO_ROUTER_TENANT_MAX_INFLIGHT", 0)))


def _parse_backend(url: str) -> Tuple[str, int]:
    u = url.strip()
    if "://" in u:
        scheme, u = u.split("://", 1)
        if scheme.lower() != "http":
            raise ValueError(
                f"router backends must be http:// URLs, got {url!r}")
    host, _, port = u.partition(":")
    if not host or not port.rstrip("/").isdigit():
        raise ValueError(
            f"router backend {url!r} must be host:port or http://host:port")
    return host, int(port.rstrip("/"))


class _Backend:
    """One replica: membership state + keep-alive connections + breaker.

    ``healthy`` is the poller's verdict (readiness probe), ``admitted``
    the reload barrier's (a flipped-but-not-cut-over replica is healthy
    yet held out of rotation). A backend serves queries only when both
    hold AND its breaker admits the call.
    """

    #: idle keep-alive sockets retained per backend
    POOL = 4

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.host, self.port = _parse_backend(url)
        self.name = f"{self.host}:{self.port}"
        self.healthy = False
        self.admitted = True
        self.generation: Optional[int] = None
        #: per-tenant generation ids (multi-tenant backends report a
        #: dict on /readyz; None for a legacy single-engine replica)
        self.tenant_generations: Optional[Dict[str, int]] = None
        self.draining = False
        #: always-on breaker (unlike the remote driver's opt-in
        #: registry): a fleet front door without one queues on corpses.
        #: Tuned by the same PIO_BREAKER_* knobs operators already know.
        self.breaker = resilience.CircuitBreaker(
            self.name,
            window_s=_env_pos("PIO_BREAKER_WINDOW_S", 30.0),
            error_threshold=_env_pos("PIO_BREAKER_ERROR_RATE", 0.5),
            min_calls=_env_int("PIO_BREAKER_MIN_CALLS", 10),
            open_s=_env_pos("PIO_BREAKER_OPEN_S", 5.0))
        self._idle: List[http.client.HTTPConnection] = []
        self._idle_lock = threading.Lock()

    # ------------------------------------------------------------- transport
    def _acquire(self, timeout: float) -> http.client.HTTPConnection:
        with self._idle_lock:
            conn = self._idle.pop() if self._idle else None
        if conn is None:
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=timeout)
        elif conn.sock is not None:
            conn.sock.settimeout(timeout)
        return conn

    def _release(self, conn, reusable: bool) -> None:
        if reusable:
            with self._idle_lock:
                if len(self._idle) < self.POOL:
                    self._idle.append(conn)
                    return
        try:
            conn.close()
        except Exception:
            pass

    def request(self, method: str, path: str, body: bytes,
                headers: Dict[str, str], timeout: float
                ) -> Tuple[int, bytes, Dict[str, str]]:
        """One forwarded request over a pooled keep-alive connection.
        Raises the transport error on failure; a failed socket is never
        re-pooled (the failover retry dials fresh elsewhere)."""
        conn = self._acquire(timeout)
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            rheaders = {k.lower(): v for k, v in resp.getheaders()}
            self._release(conn, reusable=not resp.will_close)
            return resp.status, payload, rheaders
        except BaseException:
            try:
                conn.close()
            except Exception:
                pass
            raise

    def probe(self, timeout: float = 2.0
              ) -> Tuple[bool, bool, Optional[int],
                         Optional[Dict[str, int]]]:
        """(healthy, draining, generation, tenant_generations) from one
        /readyz read over a FRESH connection — a pooled keep-alive
        socket can outlive the listener it connected to, and membership
        must answer "can a new request reach this replica", not "does
        an old socket still drain". A 503 body still carries
        ``status``/``generation`` — a draining replica is
        distinguishable from a dead one. Multi-tenant replicas also
        report a per-tenant ``generations`` dict; a legacy replica's
        body has no such key and the 4th element stays None."""
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout)
        try:
            conn.request("GET", "/readyz")
            resp = conn.getresponse()
            status, payload = resp.status, resp.read()
        except _TRANSPORT_ERRORS:
            return False, False, None, None
        finally:
            try:
                conn.close()
            except Exception:
                pass
        gen: Optional[int] = None
        tenant_gens: Optional[Dict[str, int]] = None
        draining = False
        try:
            obj = json.loads(payload)
            if isinstance(obj, dict):
                if obj.get("generation") is not None:
                    gen = int(obj["generation"])
                raw = obj.get("generations")
                if isinstance(raw, dict):
                    tenant_gens = {str(k): int(v)
                                   for k, v in raw.items()}
                draining = obj.get("status") == "draining"
        except (ValueError, TypeError):
            pass
        return status == 200, draining, gen, tenant_gens

    def close(self) -> None:
        with self._idle_lock:
            idle, self._idle = self._idle, []
        for conn in idle:
            try:
                conn.close()
            except Exception:
                pass

    def state(self) -> Dict[str, Any]:
        out = {
            "url": self.url,
            "healthy": self.healthy,
            "inRotation": self.healthy and self.admitted,
            "draining": self.draining,
            "generation": self.generation,
            "breaker": self.breaker.state,
        }
        if self.tenant_generations is not None:
            # only for multi-tenant replicas: a legacy fleet's status
            # payload keeps the exact PR 15 key set (wire parity)
            out["generations"] = dict(self.tenant_generations)
        return out


class RouterAPI:
    """Pure route handler for the fleet front door (hosted by
    data/api/http.make_server like every other daemon)."""

    def __init__(self, config: RouterConfig):
        if not config.backends:
            raise ValueError("router needs at least one backend "
                             "(--backends url,...)")
        self.config = config.resolved()
        self.backends = [_Backend(u) for u in self.config.backends]
        if len({b.name for b in self.backends}) != len(self.backends):
            raise ValueError("router backends must be distinct host:port "
                             f"pairs, got {list(self.config.backends)}")
        self._lock = threading.Lock()
        self._rr = itertools.count()
        #: the failover schedule: exactly one retry, no backoff sleep —
        #: the replacement replica is immediately available or the
        #: request should surface, and the deadline (not a sleep curve)
        #: bounds the whole operation
        self._retry = resilience.RetryPolicy(max_attempts=2)
        self._inflight = threading.Semaphore(self.config.max_inflight)
        self._stop_requested = threading.Event()
        self._draining = threading.Event()
        self._reload_lock = threading.Lock()
        self._reload_state: Dict[str, Any] = {"active": False}
        #: tenant-aware front door: access key -> tenant name, learned
        #: from backend X-PIO-Tenant response headers (the backend's
        #: AccessKeys-DAO resolution — the router never opens a storage
        #: connection of its own); and the per-tenant in-flight counts
        #: the tenant_max_inflight cap charges. Keys that have not
        #: answered yet are charged under the key itself, so the cap
        #: binds from the very first request.
        self._tenant_by_key: Dict[str, str] = {}
        self._tenant_inflight: Dict[str, int] = {}
        self.start_time = time.perf_counter()
        self.request_count = 0
        self.shed_count = 0
        self.failover_count = 0
        # uniform daemon observability surface (idempotent)
        from predictionio_tpu.common import devicewatch, slo
        devicewatch.install()
        slo.install()
        reg = telemetry.registry()
        self._m_requests = reg.counter(
            "pio_router_requests_total",
            "Routed /queries.json requests by outcome (ok / failover_ok "
            "/ shed / deadline / error) and tenant ('-' when the query "
            "carries no access key)", labelnames=("outcome", "tenant"))
        self._m_failovers = reg.counter(
            "pio_router_failovers_total",
            "Forwards retried on another replica after a transport "
            "failure or timeout on the first").child()
        self._m_overhead = reg.histogram(
            "pio_router_overhead_seconds",
            "Router-added latency per request: handler time minus the "
            "backend call itself (selection + header assembly + "
            "serialization)",
            buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                     0.01, 0.05, float("inf"))).child()
        self._m_backend_up = reg.gauge(
            "pio_router_backend_up",
            "1 while this backend is in rotation (healthy + admitted by "
            "the reload barrier), 0 while ejected",
            labelnames=("backend",))
        # first sweep runs synchronously so a router that starts against
        # a live fleet is ready the moment its own /readyz answers
        self._poll_once(timeout=min(2.0, self.config.health_ms / 1e3 * 4))
        self._poller = threading.Thread(
            target=self._poll_loop, name="pio-router-health", daemon=True)
        self._poller.start()

    # ----------------------------------------------------------- membership
    def _poll_once(self, timeout: float = 2.0) -> None:
        for b in self.backends:
            healthy, draining, gen, tenant_gens = b.probe(timeout=timeout)
            with self._lock:
                was = b.healthy
                b.healthy = healthy
                b.draining = draining
                if gen is not None:
                    b.generation = gen
                if tenant_gens is not None:
                    b.tenant_generations = tenant_gens
            if healthy and not was:
                journal.emit(
                    "router", f"backend {b.name} re-admitted "
                    f"(readiness probe recovered, generation {gen})",
                    level=journal.INFO, backend=b.name,
                    generation=gen)
            elif was and not healthy:
                # drop the idle keep-alive pool: sockets to an ejected
                # replica are stale at best
                b.close()
                journal.emit(
                    "router", f"backend {b.name} ejected from rotation "
                    + ("(draining)" if draining
                       else "(readiness probe failed)"),
                    level=(journal.WARN if draining else journal.RED),
                    backend=b.name, draining=draining)
            self._m_backend_up.labels(backend=b.name).set(
                1.0 if (healthy and b.admitted) else 0.0)

    def _poll_loop(self) -> None:
        interval = self.config.health_ms / 1e3
        while not self._stop_requested.is_set():
            if self._stop_requested.wait(interval):
                return
            try:
                self._poll_once(timeout=max(interval * 4, 0.5))
            except Exception:
                logger.exception("health poll sweep failed")

    def note_backend_failure(self, b: _Backend) -> None:
        """A forwarded request failed in transport: eject immediately
        instead of waiting out the poll interval (the poller re-admits
        on the next successful probe)."""
        with self._lock:
            was = b.healthy
            b.healthy = False
        if was:
            journal.emit(
                "router", f"backend {b.name} ejected from rotation "
                "(forwarded request failed in transport)",
                level=journal.RED, backend=b.name)
            self._m_backend_up.labels(backend=b.name).set(0.0)

    def _eligible(self) -> List[_Backend]:
        with self._lock:
            return [b for b in self.backends if b.healthy and b.admitted]

    def _pick(self, exclude: Optional[set] = None) -> Optional[_Backend]:
        """Round-robin over the rotation, skipping excluded backends and
        open breakers."""
        eligible = [b for b in self._eligible()
                    if not exclude or b.name not in exclude]
        if not eligible:
            return None
        start = next(self._rr)
        for k in range(len(eligible)):
            b = eligible[(start + k) % len(eligible)]
            try:
                b.breaker.allow()
            except resilience.CircuitOpenError:
                continue
            return b
        return None

    # ------------------------------------------------------------ dispatch
    def handle(self, method: str, path: str,
               query: Optional[Dict[str, str]] = None,
               body: bytes = b"",
               headers: Optional[Dict[str, str]] = None) -> Response:
        method = method.upper()
        path = (path or "/").rstrip("/") or "/"
        try:
            if path == "/" and method == "GET":
                return 200, self._status()
            if path == "/healthz" and method == "GET":
                return 200, {"status": "ok"}
            if path == "/readyz" and method == "GET":
                return self._readyz()
            t = telemetry.handle_route(
                method, path, query,
                accept=(headers or {}).get("accept")
                or (headers or {}).get("Accept"))
            if t is not None:
                return t
            if path == "/queries.json" and method == "POST":
                return self._queries(body, headers or {}, query or {})
            if path == "/reload" and method == "POST":
                return self._start_reload(query or {})
            if path == "/stop" and method == "POST":
                self._stop_requested.set()
                return 200, {"message": "Shutting down."}
            return 404, {"message": "Not Found"}
        except Exception as e:
            logger.exception("router request failed: %s %s", method, path)
            return 500, {"message": str(e)}

    def _status(self) -> Dict[str, Any]:
        with self._lock:
            backends = [b.state() for b in self.backends]
        gens = {b["generation"] for b in backends
                if b["generation"] is not None}
        out = {
            "status": "alive",
            "router": True,
            "backends": backends,
            "inRotation": sum(1 for b in backends if b["inRotation"]),
            "generations": sorted(gens),
            "generationSkew": len(gens) > 1,
            "requestCount": self.request_count,
            "shedCount": self.shed_count,
            "failoverCount": self.failover_count,
            "reload": dict(self._reload_state),
            "draining": self._draining.is_set(),
        }
        # per-tenant skew over multi-tenant backends only: a legacy
        # fleet's payload keeps the exact PR 15 key set (wire parity).
        # tenantGenerations maps tenant -> sorted distinct generations
        # seen across the fleet; a list longer than 1 is skew for THAT
        # tenant (the doctor WARN names it).
        tenant_gens: Dict[str, set] = {}
        for b in backends:
            for name, g in (b.get("generations") or {}).items():
                tenant_gens.setdefault(name, set()).add(g)
        if tenant_gens:
            out["tenantGenerations"] = {
                n: sorted(v) for n, v in sorted(tenant_gens.items())}
            out["tenantGenerationSkew"] = sorted(
                n for n, v in tenant_gens.items() if len(v) > 1)
        return out

    def _readyz(self) -> Response:
        """Ready while at least one backend is in rotation — the router's
        own upstream (an external LB or DNS) steers elsewhere when the
        whole fleet is dark or this router drains."""
        if self._draining.is_set():
            return 503, {"status": "draining"}
        eligible = self._eligible()
        payload = {
            "status": "ready" if eligible else "unready",
            "backendsInRotation": len(eligible),
            "backendsTotal": len(self.backends),
        }
        return (200 if eligible else 503), payload

    # ----------------------------------------------------------- query path
    def _budget_s(self, headers: Dict[str, str]) -> float:
        """The request's deadline budget in seconds: the router default,
        or a smaller client-propagated X-PIO-Deadline-Ms."""
        budget = self.config.deadline_ms / 1e3
        raw = None
        for k, v in headers.items():
            if k.lower() == "x-pio-deadline-ms":
                raw = v
                break
        if raw is not None:
            try:
                client_ms = float(raw)
                if 0 <= client_ms / 1e3 < budget:
                    budget = client_ms / 1e3
            except ValueError:
                pass
        return budget

    def _tenant_label(self, key: Optional[str]) -> str:
        """The metric/shed label for a query's tenant: the learned name
        when a backend has answered for this key, the key itself before
        that, '-' for a key-less (legacy) query."""
        if not key:
            return "-"
        with self._lock:
            return self._tenant_by_key.get(key, key)

    def _queries(self, body: bytes, headers: Dict[str, str],
                 query: Optional[Dict[str, str]] = None) -> Response:
        t_start = time.perf_counter()
        if self._draining.is_set():
            return 503, {"message": "router is draining"}, \
                {"Retry-After": "1"}
        key = (query or {}).get("accessKey")
        tenant = self._tenant_label(key)
        cap = self.config.tenant_max_inflight
        charged = False
        if key and cap > 0:
            # per-tenant shedding at the front door: one tenant's flood
            # sheds ITS queries before it can fill the shared pool
            with self._lock:
                count = self._tenant_inflight.get(tenant, 0)
                if count >= cap:
                    over = True
                else:
                    self._tenant_inflight[tenant] = count + 1
                    over = False
            if over:
                self._shed("tenant-inflight", tenant=tenant)
                return 503, {"message": (
                    f"tenant '{tenant}' is saturated at the router "
                    "(per-tenant admission control); retry later")}, \
                    {"Retry-After": "1"}
            charged = True
        try:
            if not self._inflight.acquire(blocking=False):
                # admission control: the fleet is saturated end to end;
                # queueing here would only grow latency without bound
                self._shed("inflight", tenant=tenant)
                return 503, {"message": (
                    "router is saturated (admission control); "
                    "retry later")}, \
                    {"Retry-After": "1"}
            try:
                return self._forward(body, headers, t_start, key=key)
            finally:
                self._inflight.release()
        finally:
            if charged:
                with self._lock:
                    n = self._tenant_inflight.get(tenant, 1) - 1
                    if n <= 0:
                        self._tenant_inflight.pop(tenant, None)
                    else:
                        self._tenant_inflight[tenant] = n

    def _shed(self, reason: str, tenant: str = "-") -> None:
        with self._lock:
            self.shed_count += 1
        if telemetry.on():
            self._m_requests.labels(outcome="shed", tenant=tenant).inc()
        logger.warning("router shed a query (%s)", reason)

    def _forward(self, body: bytes, headers: Dict[str, str],
                 t_start: float, key: Optional[str] = None) -> Response:
        deadline = t_start + self._budget_s(headers)
        # tenant-aware routing: the query's access key rides the
        # forwarded URL so the backend's admission control resolves the
        # SAME key the client presented (key-less legacy queries keep
        # the bare path, byte for byte)
        fwd_path = "/queries.json"
        if key:
            fwd_path += "?" + urllib.parse.urlencode({"accessKey": key})
        tenant = self._tenant_label(key)
        fwd_headers = {"Content-Type": "application/json"}
        ctx = tracing.current()
        if ctx is not None:
            # the transport adopted (or originated) this request's trace;
            # propagating it is what lets `pio trace` assemble the
            # router->replica tree
            fwd_headers[tracing.TRACE_HEADER] = ctx.header_value()
        attempt = 0
        backend_s = 0.0
        exclude: set = set()
        failed_over = False
        while True:
            b = self._pick(exclude)
            if b is None:
                self._shed("no backend in rotation", tenant=tenant)
                return 503, {"message": (
                    "no healthy backend in rotation; retry later")}, \
                    {"Retry-After": "1"}
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                if telemetry.on():
                    self._m_requests.labels(outcome="deadline",
                                            tenant=tenant).inc()
                return 504, {"message": "deadline exceeded"}
            # while a failover retry is still possible, reserve half the
            # remaining budget for it: a replica slower than half the
            # budget TIMES OUT here (a breaker-visible failure — this is
            # how injected latency on one replica shifts traffic) and
            # the retry still has room to succeed elsewhere. The last
            # attempt gets everything that is left.
            attempt_timeout = (
                remaining / 2
                if self._retry.may_retry(attempt, deadline,
                                         clock=time.perf_counter)
                and len(self._eligible()) > 1
                else remaining)
            hdrs = {**fwd_headers,
                    "X-PIO-Deadline-Ms": str(int(attempt_timeout * 1e3))}
            t0 = time.perf_counter()
            try:
                if ctx is not None:
                    with tracing.span("route", service=b.name):
                        status, payload, rheaders = b.request(
                            "POST", fwd_path, body, hdrs,
                            timeout=attempt_timeout)
                else:
                    status, payload, rheaders = b.request(
                        "POST", fwd_path, body, hdrs,
                        timeout=attempt_timeout)
            except _TRANSPORT_ERRORS as e:
                backend_s += time.perf_counter() - t0
                b.breaker.record(False)
                self.note_backend_failure(b)
                exclude.add(b.name)
                # /queries.json is a pure read: ONE failover retry on
                # another replica is safe; a second failure surfaces
                if self._retry.may_retry(attempt, deadline,
                                         clock=time.perf_counter):
                    attempt += 1
                    failed_over = True
                    with self._lock:
                        self.failover_count += 1
                    if telemetry.on():
                        self._m_failovers.inc()
                    continue
                if telemetry.on():
                    self._m_requests.labels(outcome="error",
                                            tenant=tenant).inc()
                return 502, {"message": (
                    f"backend {b.name} failed ({type(e).__name__}) and "
                    "the failover budget is spent")}
            backend_s += time.perf_counter() - t0
            b.breaker.record(status < 500)
            if status in (502, 503, 504) and self._retry.may_retry(
                    attempt, deadline, clock=time.perf_counter):
                # a draining/saturated replica said "not me" — that is
                # exactly the failover case; its Retry-After floor only
                # matters if the retry fails too
                attempt += 1
                failed_over = True
                exclude.add(b.name)
                with self._lock:
                    self.failover_count += 1
                if telemetry.on():
                    self._m_failovers.inc()
                continue
            return self._respond(status, payload, rheaders, failed_over,
                                 t_start, backend_s, key=key)

    def _respond(self, status: int, payload: bytes,
                 rheaders: Dict[str, str], failed_over: bool,
                 t_start: float, backend_s: float,
                 key: Optional[str] = None) -> Response:
        # learn key→tenant from the backend's resolution (X-PIO-Tenant
        # rides every successful multi-tenant answer) so per-tenant
        # labels and the inflight cap use real names from here on
        learned = rheaders.get("x-pio-tenant")
        if key and learned:
            with self._lock:
                self._tenant_by_key[key] = learned
        tenant = learned or self._tenant_label(key)
        try:
            obj = json.loads(payload) if payload else {}
        except ValueError:
            if telemetry.on():
                self._m_requests.labels(outcome="error",
                                        tenant=tenant).inc()
            return 502, {"message": "backend returned a non-JSON reply"}
        extra: Dict[str, str] = {}
        if rheaders.get("retry-after"):
            extra["Retry-After"] = rheaders["retry-after"]
        with self._lock:
            self.request_count += 1
        if telemetry.on():
            outcome = ("error" if status >= 500
                       else "failover_ok" if failed_over else "ok")
            self._m_requests.labels(outcome=outcome, tenant=tenant).inc()
            # added latency = our handler time minus the backend call —
            # both clocks end host-side in this pure-Python path
            self._m_overhead.observe(
                max(time.perf_counter() - t_start - backend_s, 0.0))
        if extra:
            return status, obj, extra
        return status, obj

    # --------------------------------------------------- hot-swap barrier
    def _start_reload(self, query: Dict[str, str]) -> Response:
        """Kick (or join, with ?wait=1) the coordinated reload barrier.
        One barrier at a time: a second POST while one runs answers 409
        (two interleaved barriers could split the fleet's generations)."""
        if not self._reload_lock.acquire(blocking=False):
            return 409, {"message": "a reload barrier is already running"}
        wait = (query.get("wait") or "") in ("1", "true", "yes")
        done = threading.Event()

        def run():
            try:
                self._reload_barrier()
            finally:
                self._reload_lock.release()
                done.set()

        threading.Thread(target=run, name="pio-router-reload",
                         daemon=True).start()
        if wait:
            done.wait(300.0)
            return 200, {"message": "Reload barrier finished.",
                         "reload": dict(self._reload_state)}
        return 200, {"message": "Reload barrier started."}

    def _await_flip(self, b: _Backend, old_gen: Optional[int],
                    timeout_s: float = 120.0) -> bool:
        """Poll one backend until its generation moves past ``old_gen``
        AND it is ready again."""
        deadline = time.perf_counter() + timeout_s
        old_tenant_gens = dict(b.tenant_generations or {})
        while time.perf_counter() < deadline:
            healthy, _draining, gen, tenant_gens = b.probe()
            with self._lock:
                if gen is not None:
                    b.generation = gen
                if tenant_gens is not None:
                    b.tenant_generations = tenant_gens
                b.healthy = healthy
            if healthy and gen is not None and (
                    old_gen is None or gen > old_gen):
                # a multi-tenant replica's /reload hot-swaps every
                # tenant; verify each advanced and journal the ones
                # that did not (the per-tenant skew the doctor WARNs on)
                if tenant_gens and old_tenant_gens:
                    stale = sorted(
                        n for n, g in old_tenant_gens.items()
                        if tenant_gens.get(n, g + 1) <= g)
                    if stale:
                        journal.emit(
                            "router",
                            f"backend {b.name} flipped but tenant(s) "
                            f"{stale} kept their old generation",
                            level=journal.WARN, backend=b.name,
                            tenants=stale)
                return True
            time.sleep(min(self.config.health_ms / 1e3, 0.2))
        return False

    def _set_admitted(self, backends: List[_Backend], value: bool) -> None:
        with self._lock:
            for b in backends:
                b.admitted = value
        for b in backends:
            self._m_backend_up.labels(backend=b.name).set(
                1.0 if (b.healthy and value) else 0.0)

    def _reload_barrier(self) -> None:
        """The coordinated hot-swap: reload replicas one at a time while
        queries route only to old-generation replicas, then cut over
        atomically. On a failed replica reload the barrier ABORTS and
        re-admits everything — the fleet then has mixed generations
        until the operator re-runs /reload (journaled RED; doctor WARNs
        on the skew; KNOWN_ISSUES #15 records the contract)."""
        t0 = time.perf_counter()
        old = self._eligible()
        self._reload_state = {"active": True, "flipped": 0,
                              "total": len(old)}
        journal.emit(
            "router", f"reload barrier begin over {len(old)} backend(s)",
            level=journal.INFO, backends=[b.name for b in old])
        if not old:
            self._reload_state = {"active": False, "error":
                                  "no backend in rotation"}
            journal.emit("router", "reload barrier aborted: no backend "
                         "in rotation", level=journal.WARN)
            return

        def reload_one(b: _Backend) -> bool:
            old_gen = b.generation
            try:
                status, _p, _h = b.request("POST", "/reload", b"", {},
                                           timeout=10.0)
            except _TRANSPORT_ERRORS as e:
                journal.emit(
                    "router", f"reload of {b.name} failed in transport: "
                    f"{type(e).__name__}", level=journal.RED,
                    backend=b.name)
                return False
            if status != 200:
                journal.emit(
                    "router", f"reload of {b.name} answered {status}",
                    level=journal.RED, backend=b.name, status=status)
                return False
            return self._await_flip(b, old_gen)

        if len(old) == 1:
            # a single replica's in-process hot-swap is already atomic
            # and zero-downtime; pulling it from rotation would be the
            # only way to DROP queries here
            ok = reload_one(old[0])
            self._reload_state = {"active": False, "flipped": int(ok),
                                  "total": 1, "ok": ok}
            journal.emit(
                "router",
                "reload barrier complete (single backend, in-place "
                "hot-swap)" if ok else
                "reload barrier FAILED on the single backend",
                level=journal.INFO if ok else journal.RED,
                durationS=round(time.perf_counter() - t0, 3))
            return

        flipped: List[_Backend] = []
        for b in old[:-1]:
            # hold this replica out; traffic stays on old-generation
            # replicas (flipped ones wait un-admitted for the cutover)
            self._set_admitted([b], False)
            if not reload_one(b):
                # abort: re-admit everything (mixed generations beat a
                # shrinking fleet — the skew is visible and re-runnable)
                self._set_admitted(flipped + [b], True)
                self._reload_state = {"active": False,
                                      "flipped": len(flipped),
                                      "total": len(old), "ok": False,
                                      "error": f"reload of {b.name} failed"}
                journal.emit(
                    "router", "reload barrier ABORTED: fleet has mixed "
                    "generations until /reload is re-run",
                    level=journal.RED, failed=b.name)
                return
            flipped.append(b)
            self._reload_state["flipped"] = len(flipped)
        last = old[-1]
        # THE cutover: one lock-held flip admits every new-generation
        # replica and retires the lone old one — queries admitted before
        # this line answered from the old generation, after it from the
        # new; no interleaving
        with self._lock:
            for b in flipped:
                b.admitted = True
            last.admitted = False
        for b in flipped + [last]:
            self._m_backend_up.labels(backend=b.name).set(
                1.0 if (b.healthy and b.admitted) else 0.0)
        journal.emit(
            "router", f"reload barrier cutover: {len(flipped)} backend(s) "
            f"now serving the new generation; reloading {last.name}",
            level=journal.INFO, flipped=[b.name for b in flipped])
        ok = reload_one(last)
        self._set_admitted([last], True)
        self._reload_state = {"active": False,
                              "flipped": len(flipped) + int(ok),
                              "total": len(old), "ok": ok}
        journal.emit(
            "router",
            f"reload barrier complete over {len(old)} backend(s)" if ok
            else f"reload barrier FAILED on the last backend {last.name}; "
            "it re-admits when its probe recovers",
            level=journal.INFO if ok else journal.RED,
            durationS=round(time.perf_counter() - t0, 3))

    # ------------------------------------------------------------ lifecycle
    @property
    def stop_requested(self) -> bool:
        return self._stop_requested.is_set()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @draining.setter
    def draining(self, value: bool) -> None:
        if value:
            self.drain()

    def drain(self) -> None:
        """Stop admitting (readyz -> 503, queries -> 503 + Retry-After);
        in-flight forwards finish on the transport's own drain."""
        if self._draining.is_set():
            return
        self._draining.set()
        journal.emit("router", "router drain begin: stopped admitting "
                     "queries", level=journal.INFO)
        self._stop_requested.set()

    def close(self) -> None:
        self._stop_requested.set()
        for b in self.backends:
            b.close()


def serve(api: RouterAPI, host: str = "localhost",
          port: int = 8100) -> None:
    """Run the router until /stop or SIGTERM (graceful drain: readiness
    flips, in-flight forwards complete, then exit) on the shared
    transport."""
    from predictionio_tpu.data.api.http import (
        install_sigterm_handler, make_server,
    )
    server = make_server(api, host, port)
    install_sigterm_handler(api.drain)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    logger.info("Router online at http://%s:%s over %d backend(s)",
                host, port, len(api.backends))
    try:
        while not api.stop_requested:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    server.shutdown()
    server.server_close()
    api.close()
