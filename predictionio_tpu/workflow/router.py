"""`pio router` — the fault-tolerant front door of a query-server fleet.

One process, however sharded or quantized, caps at one host; ROADMAP
item 5 is the scale-OUT half. This daemon fans ``POST /queries.json``
out to N query-server replicas over keep-alive connections, and the
product is robustness, not routing cleverness — a fleet only earns its
second replica if the front door survives a replica dying mid-request:

- **Health-driven membership.** A poller thread reads each backend's
  ``/readyz`` (liveness + readiness + the model ``generation`` id) on a
  ``PIO_ROUTER_HEALTH_MS`` cadence; a failing backend is ejected from
  rotation and re-admitted when the probe recovers, with a journal
  event (category ``router``) on every transition. Each backend also
  carries its own always-on :class:`resilience.CircuitBreaker`, so a
  replica failing *requests* (not just probes) fast-fails out of
  rotation between polls.
- **Per-request failover.** ``POST /queries.json`` is a pure read, so a
  forward that fails in transport or times out on one replica is
  retried ONCE on another (``resilience.RetryPolicy`` bounds the
  schedule). The router's deadline budget (``PIO_ROUTER_DEADLINE_MS``,
  or a smaller incoming ``X-PIO-Deadline-Ms``) is propagated to the
  backend and spent across attempts: a spent budget answers 504 instead
  of retrying. No other route is ever failover-retried — a
  non-idempotent request replayed after a torn response could
  double-apply (KNOWN_ISSUES #15).
- **Load shedding.** Admission is bounded (``PIO_ROUTER_MAX_INFLIGHT``)
  and an empty rotation (every backend ejected, draining or
  breaker-open) answers the existing ``503 + Retry-After`` contract
  immediately — the router never queues unboundedly in front of a dead
  fleet.
- **Coordinated hot-swap barrier.** ``POST /reload`` drains each
  backend's reload one at a time behind the QueryAPI ``generation`` id:
  queries keep routing ONLY to backends still on the old generation
  while replicas flip one by one; when a single old replica remains the
  router cuts over atomically to the already-flipped set, then reloads
  the last one. A fleet therefore never serves two model generations
  to one client (per-client responses are generation-monotonic) and
  zero queries drop during the swap — each replica's own in-process
  hot-swap keeps its in-flight requests answered.

The router is itself a first-class daemon on the shared transport
(data/api/http.py — ``PIO_TRANSPORT=async`` gives it the keep-alive
event loop): ``/metrics``, ``/healthz``, ``/readyz``,
``/debug/events.json`` and the rest of ``telemetry.handle_route``, plus
trace adoption — an incoming ``X-PIO-Trace`` is propagated to the
chosen backend so ``pio trace`` assembles router→replica trees.
"""

from __future__ import annotations

import collections
import concurrent.futures
import dataclasses
import http.client
import itertools
import json
import logging
import os
import threading
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Tuple

from predictionio_tpu.common import journal, resilience, telemetry, tracing

logger = logging.getLogger("predictionio_tpu.router")

#: (status, payload) or (status, payload, extra_headers) — same handler
#: contract as every other daemon on the shared transport.
Response = Tuple[int, Any]

#: transport failures that trigger a failover retry (torn keep-alive
#: responses after a replica kill surface as HTTPException)
_TRANSPORT_ERRORS = (ConnectionError, OSError, http.client.HTTPException)


def _env_pos(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        v = float(raw) if raw else default
    except ValueError:
        v = default
    return v if v > 0 else default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        v = int(raw) if raw else default
    except ValueError:
        v = default
    return v if v > 0 else default


@dataclasses.dataclass
class RouterConfig:
    """`pio router` args. Every knob has an env twin so a config-managed
    fleet and an ad-hoc one read the same defaults."""
    backends: Tuple[str, ...] = ()
    ip: str = "localhost"
    port: int = 8100
    #: membership poll cadence (each backend's /readyz) in ms
    health_ms: float = 0.0
    #: per-query deadline budget in ms (an incoming X-PIO-Deadline-Ms
    #: smaller than this wins); spent budget = 504, never a retry
    deadline_ms: float = 0.0
    #: admission ceiling: concurrent in-flight forwards beyond this shed
    #: with 503 + Retry-After instead of queueing
    max_inflight: int = 0
    #: per-tenant admission ceiling (multi-tenant backends): concurrent
    #: in-flight forwards carrying one tenant's access key beyond this
    #: shed with a tenant-labeled 503 — one tenant's flood never fills
    #: the shared inflight pool. 0 (the default) disables the cap:
    #: single-tenant fleets keep the PR 15 behavior byte for byte.
    tenant_max_inflight: int = 0
    #: front-door response cache: "on" answers repeat (tenant, query
    #: bytes, model generation) hits from a bounded LRU without touching
    #: a replica. The generation in the key makes hot-swap invalidation
    #: free — a /reload bumps the generation and every old entry is
    #: unreachable; under multi-tenancy the key uses the PER-TENANT
    #: generation, so one tenant's reload invalidates only its own
    #: entries. "off" (the default) keeps every response byte-identical
    #: to the uncached router. PIO_ROUTER_CACHE overrides.
    cache: str = ""
    #: response-cache byte budget in MB (LRU past it); PIO_ROUTER_CACHE_MB
    cache_mb: int = 0
    #: response-cache entry TTL in ms — bounds fold-in staleness
    #: (KNOWN_ISSUES #17: published rows do not bump the generation);
    #: PIO_ROUTER_CACHE_TTL_MS
    cache_ttl_ms: float = 0.0

    def resolved(self) -> "RouterConfig":
        return dataclasses.replace(
            self,
            health_ms=self.health_ms or _env_pos("PIO_ROUTER_HEALTH_MS", 500.0),
            deadline_ms=(self.deadline_ms
                         or _env_pos("PIO_ROUTER_DEADLINE_MS", 2000.0)),
            max_inflight=(self.max_inflight
                          or _env_int("PIO_ROUTER_MAX_INFLIGHT", 256)),
            tenant_max_inflight=(
                self.tenant_max_inflight
                or _env_int("PIO_ROUTER_TENANT_MAX_INFLIGHT", 0)),
            cache=self.cache or os.environ.get("PIO_ROUTER_CACHE", "off"),
            cache_mb=(self.cache_mb
                      or _env_int("PIO_ROUTER_CACHE_MB", 16)),
            cache_ttl_ms=(self.cache_ttl_ms
                          or _env_pos("PIO_ROUTER_CACHE_TTL_MS", 5000.0)))

    @property
    def cache_on(self) -> bool:
        return str(self.cache).strip().lower() in ("1", "on", "true", "yes")


def _parse_backend(url: str) -> Tuple[str, int]:
    u = url.strip()
    if "://" in u:
        scheme, u = u.split("://", 1)
        if scheme.lower() != "http":
            raise ValueError(
                f"router backends must be http:// URLs, got {url!r}")
    host, _, port = u.partition(":")
    if not host or not port.rstrip("/").isdigit():
        raise ValueError(
            f"router backend {url!r} must be host:port or http://host:port")
    return host, int(port.rstrip("/"))


class _ResponseCache:
    """Bounded-LRU front-door response cache.

    Keys are ``(tenant, generation-token, raw query bytes)`` — the
    generation token is the fleet's agreed model generation for that
    tenant at lookup time, so a hot-swap invalidates by CONSTRUCTION
    (old entries become unreachable) and a TTL bounds what generation
    keying cannot see (fold-in row publishes, KNOWN_ISSUES #17). Only
    200 responses are stored. Thread-safe; sizes are accounted in bytes
    (query bytes + compact-JSON response bytes) against ``max_bytes``,
    evicting least-recently-used past it."""

    def __init__(self, max_bytes: int, ttl_s: float):
        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s)
        self._entries: "collections.OrderedDict[Tuple[str, Any, bytes], Tuple[float, int, int, Any, Dict[str, str]]]" = (
            collections.OrderedDict())
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: Tuple[str, Any, bytes]) -> Optional[Response]:
        now = time.perf_counter()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            expires, size, status, obj, extra = entry
            if now >= expires:
                # expired entries count as evictions, not hits — the
                # TTL is doing its staleness-bounding job
                del self._entries[key]
                self._bytes -= size
                self.evictions += 1
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return (status, obj, dict(extra)) if extra else (status, obj)

    def put(self, key: Tuple[str, Any, bytes], status: int, obj: Any,
            extra: Optional[Dict[str, str]] = None) -> int:
        """Store one response; returns how many entries were evicted."""
        try:
            size = len(key[2]) + len(
                json.dumps(obj, separators=(",", ":")).encode("utf-8"))
        except (TypeError, ValueError):
            return 0                      # unserializable — never cache
        if size > self.max_bytes:
            return 0
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (time.perf_counter() + self.ttl_s, size,
                                  status, obj, dict(extra or {}))
            self._bytes += size
            while self._bytes > self.max_bytes and self._entries:
                _, (_, esize, _, _, _) = self._entries.popitem(last=False)
                self._bytes -= esize
                evicted += 1
            self.evictions += evicted
        return evicted

    def invalidate_tenant(self, tenant: str) -> int:
        """Drop every entry of one tenant (its generation moved — the
        entries are already unreachable; this reclaims their bytes
        immediately instead of waiting out the TTL)."""
        with self._lock:
            stale = [k for k in self._entries if k[0] == tenant]
            for k in stale:
                self._bytes -= self._entries.pop(k)[1]
            self.evictions += len(stale)
            return len(stale)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            looked = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "maxBytes": self.max_bytes,
                "ttlMs": round(self.ttl_s * 1e3, 1),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hitRatio": (self.hits / looked) if looked else 0.0,
            }


class _Backend:
    """One replica: membership state + keep-alive connections + breaker.

    ``healthy`` is the poller's verdict (readiness probe), ``admitted``
    the reload barrier's (a flipped-but-not-cut-over replica is healthy
    yet held out of rotation). A backend serves queries only when both
    hold AND its breaker admits the call.
    """

    #: idle keep-alive sockets retained per backend
    POOL = 4

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.host, self.port = _parse_backend(url)
        self.name = f"{self.host}:{self.port}"
        self.healthy = False
        self.admitted = True
        #: autopilot hold-out: a latency-outlier replica is quarantined
        #: (out of rotation) before its breaker trips, and re-admitted
        #: explicitly — unlike ``healthy`` the poller never flips this
        self.quarantined = False
        self.generation: Optional[int] = None
        #: per-tenant generation ids (multi-tenant backends report a
        #: dict on /readyz; None for a legacy single-engine replica)
        self.tenant_generations: Optional[Dict[str, int]] = None
        #: the item-shard range this replica owns (partition-routed
        #: deploys advertise {"index","count","lo","hi","rows","nItems"}
        #: on /readyz; None for a full-model replica)
        self.partition: Optional[Dict[str, Any]] = None
        self.draining = False
        #: always-on breaker (unlike the remote driver's opt-in
        #: registry): a fleet front door without one queues on corpses.
        #: Tuned by the same PIO_BREAKER_* knobs operators already know.
        self.breaker = resilience.CircuitBreaker(
            self.name,
            window_s=_env_pos("PIO_BREAKER_WINDOW_S", 30.0),
            error_threshold=_env_pos("PIO_BREAKER_ERROR_RATE", 0.5),
            min_calls=_env_int("PIO_BREAKER_MIN_CALLS", 10),
            open_s=_env_pos("PIO_BREAKER_OPEN_S", 5.0))
        self._idle: List[http.client.HTTPConnection] = []
        self._idle_lock = threading.Lock()

    # ------------------------------------------------------------- transport
    def _acquire(self, timeout: float) -> http.client.HTTPConnection:
        with self._idle_lock:
            conn = self._idle.pop() if self._idle else None
        if conn is None:
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=timeout)
        elif conn.sock is not None:
            conn.sock.settimeout(timeout)
        return conn

    def _release(self, conn, reusable: bool) -> None:
        if reusable:
            with self._idle_lock:
                if len(self._idle) < self.POOL:
                    self._idle.append(conn)
                    return
        try:
            conn.close()
        except Exception:
            pass

    def request(self, method: str, path: str, body: bytes,
                headers: Dict[str, str], timeout: float
                ) -> Tuple[int, bytes, Dict[str, str]]:
        """One forwarded request over a pooled keep-alive connection.
        Raises the transport error on failure; a failed socket is never
        re-pooled (the failover retry dials fresh elsewhere)."""
        conn = self._acquire(timeout)
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            rheaders = {k.lower(): v for k, v in resp.getheaders()}
            self._release(conn, reusable=not resp.will_close)
            return resp.status, payload, rheaders
        except BaseException:
            try:
                conn.close()
            except Exception:
                pass
            raise

    def probe(self, timeout: float = 2.0
              ) -> Tuple[bool, bool, Optional[int],
                         Optional[Dict[str, int]],
                         Optional[Dict[str, Any]]]:
        """(healthy, draining, generation, tenant_generations,
        partition) from one /readyz read over a FRESH connection — a
        pooled keep-alive socket can outlive the listener it connected
        to, and membership must answer "can a new request reach this
        replica", not "does an old socket still drain". A 503 body
        still carries ``status``/``generation`` — a draining replica is
        distinguishable from a dead one. Multi-tenant replicas also
        report a per-tenant ``generations`` dict; partition-scoped
        replicas report the owned item-row range; a legacy replica's
        body has neither key and those elements stay None."""
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout)
        try:
            conn.request("GET", "/readyz")
            resp = conn.getresponse()
            status, payload = resp.status, resp.read()
        except _TRANSPORT_ERRORS:
            return False, False, None, None, None
        finally:
            try:
                conn.close()
            except Exception:
                pass
        gen: Optional[int] = None
        tenant_gens: Optional[Dict[str, int]] = None
        partition: Optional[Dict[str, Any]] = None
        draining = False
        try:
            obj = json.loads(payload)
            if isinstance(obj, dict):
                if obj.get("generation") is not None:
                    gen = int(obj["generation"])
                raw = obj.get("generations")
                if isinstance(raw, dict):
                    tenant_gens = {str(k): int(v)
                                   for k, v in raw.items()}
                rawp = obj.get("partition")
                if (isinstance(rawp, dict)
                        and rawp.get("index") is not None
                        and rawp.get("count") is not None):
                    partition = {
                        "index": int(rawp["index"]),
                        "count": int(rawp["count"]),
                        "lo": int(rawp.get("lo", 0)),
                        "hi": int(rawp.get("hi", 0)),
                        "rows": int(rawp.get("rows", 0)),
                        "nItems": int(rawp.get("nItems", 0)),
                    }
                draining = obj.get("status") == "draining"
        except (ValueError, TypeError):
            pass
        return status == 200, draining, gen, tenant_gens, partition

    def close(self) -> None:
        with self._idle_lock:
            idle, self._idle = self._idle, []
        for conn in idle:
            try:
                conn.close()
            except Exception:
                pass

    def state(self) -> Dict[str, Any]:
        out = {
            "url": self.url,
            "healthy": self.healthy,
            "inRotation": (self.healthy and self.admitted
                           and not self.quarantined),
            "draining": self.draining,
            "generation": self.generation,
            "breaker": self.breaker.state,
        }
        if self.quarantined:
            # only while held out (wire parity: an untouched fleet's
            # payload keeps the exact PR 15 key set)
            out["quarantined"] = True
        if self.tenant_generations is not None:
            # only for multi-tenant replicas: a legacy fleet's status
            # payload keeps the exact PR 15 key set (wire parity)
            out["generations"] = dict(self.tenant_generations)
        if self.partition is not None:
            # only for partition-scoped replicas (same parity rule)
            out["partition"] = dict(self.partition)
        return out


class RouterAPI:
    """Pure route handler for the fleet front door (hosted by
    data/api/http.make_server like every other daemon)."""

    def __init__(self, config: RouterConfig):
        if not config.backends:
            raise ValueError("router needs at least one backend "
                             "(--backends url,...)")
        self.config = config.resolved()
        self.backends = [_Backend(u) for u in self.config.backends]
        if len({b.name for b in self.backends}) != len(self.backends):
            raise ValueError("router backends must be distinct host:port "
                             f"pairs, got {list(self.config.backends)}")
        self._lock = threading.Lock()
        self._rr = itertools.count()
        #: the failover schedule: exactly one retry, no backoff sleep —
        #: the replacement replica is immediately available or the
        #: request should surface, and the deadline (not a sleep curve)
        #: bounds the whole operation
        self._retry = resilience.RetryPolicy(max_attempts=2)
        #: admission ceilings as plain counters (not a Semaphore): the
        #: autopilot's degradation ladder adjusts them at runtime, and a
        #: Semaphore's capacity cannot shrink under load
        self._max_inflight = self.config.max_inflight
        self._tenant_cap = self.config.tenant_max_inflight
        self._inflight_count = 0
        self._stop_requested = threading.Event()
        self._draining = threading.Event()
        self._reload_lock = threading.Lock()
        self._reload_state: Dict[str, Any] = {"active": False}
        #: tenant-aware front door: access key -> tenant name, learned
        #: from backend X-PIO-Tenant response headers (the backend's
        #: AccessKeys-DAO resolution — the router never opens a storage
        #: connection of its own); and the per-tenant in-flight counts
        #: the tenant_max_inflight cap charges. Keys that have not
        #: answered yet are charged under the key itself, so the cap
        #: binds from the very first request.
        self._tenant_by_key: Dict[str, str] = {}
        self._tenant_inflight: Dict[str, int] = {}
        #: partition-routed mode: the current partition map — a snapshot
        #: {"count","generation","nItems","owners": {index: [backends]}}
        #: rebuilt after every membership change and swapped ATOMICALLY
        #: (one attribute assignment under the lock), so no query ever
        #: sees backends from two maps. None + _pmap_incomplete=False is
        #: a full-replica fleet (the PR 15/16 path, byte for byte);
        #: None + True means partition replicas exist but coverage is
        #: incomplete or generations are mixed — queries answer 503,
        #: never a partial merge.
        self._pmap: Optional[Dict[str, Any]] = None
        self._pmap_incomplete = False
        #: concurrent scatter legs (lazy: full-replica fleets never pay
        #: for the pool)
        self._scatter_pool: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        self._m_partition_requests = None
        self._m_partition_width = None
        #: embedded autopilot (pio router --autopilot): set via
        #: attach_autopilot; the status payload grows an "autopilot"
        #: block only while one is attached (wire parity)
        self._autopilot: Optional[Any] = None
        #: embedded autotrain (pio router --autotrain): set via
        #: attach_autotrain; the status payload grows an "autotrain"
        #: block the doctor reads
        self._autotrain: Optional[Any] = None
        #: front-door response cache (None unless --cache/PIO_ROUTER_CACHE
        #: turns it on: the off path stays byte-identical to PR 16)
        self._cache: Optional[_ResponseCache] = None
        self._m_cache_hits = self._m_cache_misses = None
        self._m_cache_evictions = self._m_cache_ratio = None
        #: last fleet-agreed generation per tenant ('-' = the scalar
        #: single-engine generation) — the poller's cache-invalidation
        #: sweep journals and reclaims on each bump
        self._cache_gens: Dict[str, Any] = {}
        self.start_time = time.perf_counter()
        self.request_count = 0
        self.shed_count = 0
        self.failover_count = 0
        # uniform daemon observability surface (idempotent)
        from predictionio_tpu.common import devicewatch, history, slo
        devicewatch.install()
        slo.install()
        # metrics flight recorder (one sampler thread per process)
        history.install()
        reg = telemetry.registry()
        self._m_requests = reg.counter(
            "pio_router_requests_total",
            "Routed /queries.json requests by outcome (ok / failover_ok "
            "/ shed / deadline / error) and tenant ('-' when the query "
            "carries no access key)", labelnames=("outcome", "tenant"))
        self._m_failovers = reg.counter(
            "pio_router_failovers_total",
            "Forwards retried on another replica after a transport "
            "failure or timeout on the first").child()
        self._m_overhead = reg.histogram(
            "pio_router_overhead_seconds",
            "Router-added latency per request: handler time minus the "
            "backend call itself (selection + header assembly + "
            "serialization)",
            buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                     0.01, 0.05, float("inf"))).child()
        self._m_backend_seconds = reg.histogram(
            "pio_router_backend_seconds",
            "Backend call time per forwarded attempt, labeled by the "
            "backend that served it — the per-replica latency signal "
            "the autopilot's outlier quarantine reads (the aggregate "
            "pio_router_overhead_seconds cannot name a slow replica)",
            labelnames=("backend",),
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 1.0, float("inf")))
        self._m_backend_up = reg.gauge(
            "pio_router_backend_up",
            "1 while this backend is in rotation (healthy + admitted by "
            "the reload barrier), 0 while ejected",
            labelnames=("backend",))
        if self.config.cache_on:
            self._cache = _ResponseCache(
                max_bytes=self.config.cache_mb * 1024 * 1024,
                ttl_s=self.config.cache_ttl_ms / 1e3)
            self._m_cache_hits = reg.counter(
                "pio_router_cache_hits_total",
                "Front-door response-cache hits: queries answered from "
                "the (tenant, query bytes, model generation) LRU without "
                "touching a replica").child()
            self._m_cache_misses = reg.counter(
                "pio_router_cache_misses_total",
                "Front-door response-cache misses (forwarded to a "
                "replica; 200 answers are stored on the way back)"
            ).child()
            self._m_cache_evictions = reg.counter(
                "pio_router_cache_evictions_total",
                "Response-cache entries dropped: LRU past the byte "
                "budget, TTL expiry, or a generation-bump invalidation "
                "sweep").child()
            self._m_cache_ratio = reg.gauge(
                "pio_router_cache_hit_ratio",
                "hits / (hits + misses) over this router's lifetime — "
                "the zipfian hot-key absorption the cache exists for"
            ).child()
        # first sweep runs synchronously so a router that starts against
        # a live fleet is ready the moment its own /readyz answers
        self._poll_once(timeout=min(2.0, self.config.health_ms / 1e3 * 4))
        self._poller = threading.Thread(
            target=self._poll_loop, name="pio-router-health", daemon=True)
        self._poller.start()

    # ----------------------------------------------------------- membership
    def _poll_once(self, timeout: float = 2.0) -> None:
        for b in self.backends:
            healthy, draining, gen, tenant_gens, partition = b.probe(
                timeout=timeout)
            with self._lock:
                was = b.healthy
                b.healthy = healthy
                b.draining = draining
                if gen is not None:
                    b.generation = gen
                if tenant_gens is not None:
                    b.tenant_generations = tenant_gens
                if healthy:
                    # a partition range is only trusted from a live 200
                    # probe; an ejected replica keeps its last-known
                    # range for the status page but the map rebuild
                    # ignores it anyway (healthy+admitted only)
                    b.partition = partition
            if healthy and not was:
                journal.emit(
                    "router", f"backend {b.name} re-admitted "
                    f"(readiness probe recovered, generation {gen})",
                    level=journal.INFO, backend=b.name,
                    generation=gen)
            elif was and not healthy:
                # drop the idle keep-alive pool: sockets to an ejected
                # replica are stale at best
                b.close()
                journal.emit(
                    "router", f"backend {b.name} ejected from rotation "
                    + ("(draining)" if draining
                       else "(readiness probe failed)"),
                    level=(journal.WARN if draining else journal.RED),
                    backend=b.name, draining=draining)
            self._m_backend_up.labels(backend=b.name).set(
                1.0 if (healthy and b.admitted and not b.quarantined)
                else 0.0)
        self._rebuild_pmap()
        self._cache_sweep()

    def _poll_loop(self) -> None:
        interval = self.config.health_ms / 1e3
        while not self._stop_requested.is_set():
            if self._stop_requested.wait(interval):
                return
            try:
                self._poll_once(timeout=max(interval * 4, 0.5))
            except Exception:
                logger.exception("health poll sweep failed")

    def note_backend_failure(self, b: _Backend) -> None:
        """A forwarded request failed in transport: eject immediately
        instead of waiting out the poll interval (the poller re-admits
        on the next successful probe)."""
        with self._lock:
            was = b.healthy
            b.healthy = False
        if was:
            journal.emit(
                "router", f"backend {b.name} ejected from rotation "
                "(forwarded request failed in transport)",
                level=journal.RED, backend=b.name)
            self._m_backend_up.labels(backend=b.name).set(0.0)
            self._rebuild_pmap()

    # -------------------------------------------------- fleet control plane
    def add_backend(self, url: str) -> _Backend:
        """Admit a new replica into the configured set (the autopilot's
        scale-up / replacement path). The newcomer is probed
        synchronously so an already-ready replica enters rotation on
        this call, not a poll interval later."""
        b = _Backend(url)
        with self._lock:
            if any(x.name == b.name for x in self.backends):
                raise ValueError(
                    f"backend {b.name} is already configured")
            self.backends.append(b)
        healthy, draining, gen, tenant_gens, partition = b.probe()
        with self._lock:
            b.healthy = healthy
            b.draining = draining
            if gen is not None:
                b.generation = gen
            if tenant_gens is not None:
                b.tenant_generations = tenant_gens
            if healthy:
                b.partition = partition
        self._m_backend_up.labels(backend=b.name).set(
            1.0 if healthy else 0.0)
        journal.emit(
            "router", f"backend {b.name} added to the fleet "
            + ("(in rotation)" if healthy else "(awaiting readiness)"),
            level=journal.INFO, backend=b.name, healthy=healthy)
        self._rebuild_pmap()
        return b

    def remove_backend(self, name: str) -> bool:
        """Retire one backend by name. Membership removal is immediate
        — in-flight forwards finish on their already-open sockets — so
        a scale-down that stops the PROCESS a grace period later never
        drops a query. Returns False for an unknown name."""
        with self._lock:
            found = next((b for b in self.backends if b.name == name),
                         None)
            if found is None:
                return False
            if len(self.backends) == 1:
                raise ValueError("cannot remove the last backend")
            found.admitted = False
            self.backends.remove(found)
        found.close()
        self._m_backend_up.labels(backend=found.name).set(0.0)
        journal.emit(
            "router", f"backend {found.name} removed from the fleet",
            level=journal.INFO, backend=found.name)
        self._rebuild_pmap()
        return True

    def set_quarantine(self, name: str, value: bool) -> bool:
        """Hold one backend out of rotation (or release it) without
        touching its health state — the autopilot's latency-outlier
        ejection. Returns False for an unknown name."""
        with self._lock:
            found = next((b for b in self.backends if b.name == name),
                         None)
            if found is None:
                return False
            changed = found.quarantined != value
            found.quarantined = value
        if changed:
            self._m_backend_up.labels(backend=found.name).set(
                1.0 if (found.healthy and found.admitted and not value)
                else 0.0)
            journal.emit(
                "router", f"backend {found.name} "
                + ("quarantined (held out of rotation)" if value
                   else "released from quarantine"),
                level=journal.WARN if value else journal.INFO,
                backend=found.name, quarantined=value)
            self._rebuild_pmap()
        return True

    def set_shed_thresholds(self, max_inflight: Optional[int] = None,
                            tenant_max_inflight: Optional[int] = None
                            ) -> Dict[str, int]:
        """Read (no args) or adjust the shed thresholds at runtime;
        returns the PREVIOUS values so the autopilot's degradation
        ladder can restore them exactly on recovery."""
        with self._lock:
            prev = {"maxInflight": self._max_inflight,
                    "tenantMaxInflight": self._tenant_cap}
            if max_inflight is not None:
                self._max_inflight = max(1, int(max_inflight))
            if tenant_max_inflight is not None:
                self._tenant_cap = max(0, int(tenant_max_inflight))
            cur = {"maxInflight": self._max_inflight,
                   "tenantMaxInflight": self._tenant_cap}
        if cur != prev:
            journal.emit(
                "router",
                f"shed thresholds changed: maxInflight "
                f"{prev['maxInflight']} -> {cur['maxInflight']}, "
                f"tenantMaxInflight {prev['tenantMaxInflight']} -> "
                f"{cur['tenantMaxInflight']}",
                level=journal.INFO, **cur)
        return prev

    def attach_autopilot(self, ap: Any) -> None:
        self._autopilot = ap

    def attach_autotrain(self, autotrain: Any) -> None:
        self._autotrain = autotrain

    # ------------------------------------------------------ partition map
    def _rebuild_pmap(self) -> None:
        """Recompute the partition map from current membership and swap
        it in atomically.

        A candidate map is one (count, generation) group of in-rotation
        partition replicas; it is SERVABLE only when indices 0..count-1
        are all covered AND every member reports the same scalar
        generation — the two halves of the "mixed maps never co-serve
        one query" contract (a re-partition or hot-swap becomes visible
        only once its whole new map is up). Among servable candidates
        the highest generation wins (the re-partition cutover). Queries
        racing this rebuild hold a reference to the OLD snapshot — maps
        are immutable once published."""
        with self._lock:
            part = [b for b in self.backends
                    if b.healthy and b.admitted and not b.quarantined
                    and b.partition]
            old = self._pmap
            if not part:
                had_parts = any(b.partition for b in self.backends)
                self._pmap = None
                # partition replicas configured but none in rotation is
                # a coverage gap, not a silent fall-back to full-model
                # round-robin (there may be no full replica to fall to)
                self._pmap_incomplete = had_parts
            else:
                groups: Dict[Tuple[int, Any], Dict[int, List[_Backend]]] = {}
                for b in part:
                    gkey = (b.partition["count"], b.generation)
                    groups.setdefault(gkey, {}).setdefault(
                        b.partition["index"], []).append(b)
                best = None
                for (count, gen), owners in groups.items():
                    if set(owners) != set(range(count)):
                        continue
                    if best is None or (gen or 0) > (best[1] or 0):
                        best = (count, gen, owners)
                if best is None:
                    self._pmap = None
                    self._pmap_incomplete = True
                else:
                    count, gen, owners = best
                    self._pmap = {
                        "count": count,
                        "generation": gen,
                        "nItems": next(iter(owners.values()))[0]
                        .partition["nItems"],
                        "owners": {i: list(bs) for i, bs in owners.items()},
                    }
                    self._pmap_incomplete = False
            new = self._pmap
            incomplete = self._pmap_incomplete
        if (new is None) != (old is None) or (
                new is not None and old is not None
                and (new["count"] != old["count"]
                     or new["generation"] != old["generation"])):
            if new is not None:
                self._partition_width_gauge().set(float(new["count"]))
                journal.emit(
                    "router",
                    f"partition map live: {new['count']} partition(s) "
                    f"over {sum(len(v) for v in new['owners'].values())} "
                    f"replica(s), generation {new['generation']}",
                    level=journal.INFO, partitions=new["count"],
                    generation=new["generation"])
            else:
                journal.emit(
                    "router",
                    "partition map LOST: coverage incomplete or "
                    "generations mixed — partition queries answer 503 "
                    "until a full map is back in rotation",
                    level=journal.RED if incomplete else journal.INFO)

    def _partition_metrics(self):
        if self._m_partition_requests is None:
            self._m_partition_requests = telemetry.registry().counter(
                "pio_router_partition_requests_total",
                "Partition-scattered /queries.json requests by outcome "
                "(merged / coverage_gap / error / deadline)",
                labelnames=("outcome",))
        return self._m_partition_requests

    def _partition_width_gauge(self):
        if self._m_partition_width is None:
            self._m_partition_width = telemetry.registry().gauge(
                "pio_router_partition_width",
                "Scatter width of the live partition map (how many "
                "owning partitions one query fans out to); 0 = no map"
            ).child()
        return self._m_partition_width

    # -------------------------------------------------------- cache plumbing
    def _generation_token(self, tenant: str) -> Optional[Any]:
        """The fleet-agreed model generation for ``tenant`` — the cache
        key's invalidation component. Multi-tenant backends vote with
        their per-tenant ``generations`` dict entry (the PR 16 fix: a
        tenant's /reload must invalidate only ITS entries), legacy
        backends with the scalar. No vote or a split vote (mid-barrier
        skew) returns None — the cache stands aside rather than serve
        either generation's answer for the other."""
        votes = set()
        with self._lock:
            for b in self.backends:
                if not (b.healthy and b.admitted and not b.quarantined):
                    continue
                if b.tenant_generations is not None:
                    g = b.tenant_generations.get(tenant)
                    if g is not None:
                        votes.add(("t", g))
                elif b.generation is not None:
                    votes.add(("s", b.generation))
        if len(votes) != 1:
            return None
        return next(iter(votes))

    def _cache_sweep(self) -> None:
        """Reclaim cache entries whose tenant's fleet generation moved
        (they are unreachable already — generation is IN the key; this
        frees their bytes now and journals the invalidation)."""
        cache = self._cache
        if cache is None:
            return
        tenants: set = {"-"}
        with self._lock:
            for b in self.backends:
                tenants.update((b.tenant_generations or {}).keys())
        for t in sorted(tenants):
            token = self._generation_token(t)
            if token is None:
                continue
            last = self._cache_gens.get(t)
            self._cache_gens[t] = token
            if last is not None and last != token:
                dropped = cache.invalidate_tenant(t)
                self._cache_metrics_update()
                journal.emit(
                    "router",
                    f"response cache invalidated for tenant '{t}': "
                    f"generation {last[1]} -> {token[1]} "
                    f"({dropped} entries dropped)",
                    level=journal.INFO, tenant=t, dropped=dropped)

    def _cache_metrics_update(self) -> None:
        """Sync the prom counters to the cache's own op counts (one
        place, so TTL expiries inside get() and LRU evictions inside
        put() are never under-reported)."""
        cache = self._cache
        if cache is None or self._m_cache_hits is None:
            return
        stats = cache.stats()
        for metric, k in ((self._m_cache_hits, "hits"),
                          (self._m_cache_misses, "misses"),
                          (self._m_cache_evictions, "evictions")):
            delta = stats[k] - metric.value
            if delta > 0:
                metric.inc(delta)
        self._m_cache_ratio.set(stats["hitRatio"])

    def _eligible(self) -> List[_Backend]:
        with self._lock:
            return [b for b in self.backends
                    if b.healthy and b.admitted and not b.quarantined]

    def _pick(self, exclude: Optional[set] = None) -> Optional[_Backend]:
        """Round-robin over the rotation, skipping excluded backends and
        open breakers."""
        eligible = [b for b in self._eligible()
                    if not exclude or b.name not in exclude]
        if not eligible:
            return None
        start = next(self._rr)
        for k in range(len(eligible)):
            b = eligible[(start + k) % len(eligible)]
            try:
                b.breaker.allow()
            except resilience.CircuitOpenError:
                continue
            return b
        return None

    # ------------------------------------------------------------ dispatch
    def handle(self, method: str, path: str,
               query: Optional[Dict[str, str]] = None,
               body: bytes = b"",
               headers: Optional[Dict[str, str]] = None) -> Response:
        method = method.upper()
        path = (path or "/").rstrip("/") or "/"
        try:
            if path == "/" and method == "GET":
                return 200, self._status()
            if path == "/healthz" and method == "GET":
                return 200, {"status": "ok"}
            if path == "/readyz" and method == "GET":
                return self._readyz()
            t = telemetry.handle_route(
                method, path, query,
                accept=(headers or {}).get("accept")
                or (headers or {}).get("Accept"))
            if t is not None:
                return t
            if path == "/queries.json" and method == "POST":
                return self._queries(body, headers or {}, query or {})
            if path == "/reload" and method == "POST":
                return self._start_reload(query or {})
            if path == "/backends" and method == "POST":
                return self._backends_route(query or {})
            if path == "/quarantine" and method == "POST":
                return self._quarantine_route(query or {})
            if path == "/shed" and method == "POST":
                return self._shed_route(query or {})
            if path == "/stop" and method == "POST":
                self._stop_requested.set()
                return 200, {"message": "Shutting down."}
            return 404, {"message": "Not Found"}
        except Exception as e:
            logger.exception("router request failed: %s %s", method, path)
            return 500, {"message": str(e)}

    def _status(self) -> Dict[str, Any]:
        with self._lock:
            backends = [b.state() for b in self.backends]
        gens = {b["generation"] for b in backends
                if b["generation"] is not None}
        out = {
            "status": "alive",
            "router": True,
            "backends": backends,
            "inRotation": sum(1 for b in backends if b["inRotation"]),
            "generations": sorted(gens),
            "generationSkew": len(gens) > 1,
            "requestCount": self.request_count,
            "shedCount": self.shed_count,
            "failoverCount": self.failover_count,
            "reload": dict(self._reload_state),
            "draining": self._draining.is_set(),
        }
        # per-tenant skew over multi-tenant backends only: a legacy
        # fleet's payload keeps the exact PR 15 key set (wire parity).
        # tenantGenerations maps tenant -> sorted distinct generations
        # seen across the fleet; a list longer than 1 is skew for THAT
        # tenant (the doctor WARN names it).
        tenant_gens: Dict[str, set] = {}
        for b in backends:
            for name, g in (b.get("generations") or {}).items():
                tenant_gens.setdefault(name, set()).add(g)
        if tenant_gens:
            out["tenantGenerations"] = {
                n: sorted(v) for n, v in sorted(tenant_gens.items())}
            out["tenantGenerationSkew"] = sorted(
                n for n, v in tenant_gens.items() if len(v) > 1)
            # the PR 16 fix: under multi-tenancy the scalar generation
            # legitimately differs per replica (it counts that PROCESS'S
            # loads) — fleet skew is a per-tenant question, so the
            # headline bool must follow the per-tenant verdict, not the
            # scalar set
            out["generationSkew"] = bool(out["tenantGenerationSkew"])
        with self._lock:
            pmap, incomplete = self._pmap, self._pmap_incomplete
        if pmap is not None or incomplete or any(
                b.get("partition") for b in backends):
            # partition-routed fleets only (full fleets keep the exact
            # PR 16 key set, wire parity asserted by test): the live
            # map's owned ranges — what `pio doctor` summarizes and
            # flags coverage gaps RED on
            owners: Dict[str, List[Dict[str, Any]]] = {}
            for b in backends:
                p = b.get("partition")
                if p and b["inRotation"]:
                    owners.setdefault(str(p["index"]), []).append({
                        "backend": b["url"], "lo": p["lo"], "hi": p["hi"]})
            out["partitions"] = {
                "complete": pmap is not None,
                "count": (pmap or {}).get("count"),
                "generation": (pmap or {}).get("generation"),
                "nItems": (pmap or {}).get("nItems"),
                "owners": {k: owners[k] for k in sorted(owners, key=int)},
            }
        cache = self._cache
        if cache is not None:
            # cache-enabled routers only (same parity rule): the stats
            # the doctor's hit-ratio WARN reads
            out["cache"] = {"enabled": True, **cache.stats()}
        if self._autopilot is not None:
            # embedded-autopilot routers only (same parity rule): the
            # block `pio doctor`'s autopilot line reads
            out["autopilot"] = self._autopilot.summary()
        if self._autotrain is not None:
            # embedded-autotrain routers only (same parity rule): the
            # block `pio doctor`'s autotrain line reads
            out["autotrain"] = self._autotrain.summary()
        return out

    # ------------------------------------------------------- admin routes
    def _backends_route(self, query: Dict[str, str]) -> Response:
        add, remove = query.get("add"), query.get("remove")
        if bool(add) == bool(remove):
            return 400, {"message": ("POST /backends needs exactly one "
                                     "of ?add=url or ?remove=name")}
        try:
            if add:
                b = self.add_backend(add)
                return 200, {"message": f"backend {b.name} added.",
                             "backend": b.state()}
            if not self.remove_backend(remove or ""):
                return 404, {"message": f"unknown backend {remove}"}
            return 200, {"message": f"backend {remove} removed."}
        except ValueError as e:
            return 400, {"message": str(e)}

    def _quarantine_route(self, query: Dict[str, str]) -> Response:
        name = query.get("backend", "")
        if not name:
            return 400, {"message":
                         "POST /quarantine needs ?backend=name"}
        clear = (query.get("clear") or "") in ("1", "true", "yes")
        if not self.set_quarantine(name, not clear):
            return 404, {"message": f"unknown backend {name}"}
        return 200, {"message": f"backend {name} "
                     + ("released from quarantine."
                        if clear else "quarantined.")}

    def _shed_route(self, query: Dict[str, str]) -> Response:
        try:
            mi = query.get("maxInflight")
            ti = query.get("tenantMaxInflight")
            prev = self.set_shed_thresholds(
                max_inflight=int(mi) if mi is not None else None,
                tenant_max_inflight=int(ti) if ti is not None else None)
        except ValueError:
            return 400, {"message": ("maxInflight/tenantMaxInflight "
                                     "must be integers")}
        with self._lock:
            cur = {"maxInflight": self._max_inflight,
                   "tenantMaxInflight": self._tenant_cap}
        return 200, {"previous": prev, "current": cur}

    def _readyz(self) -> Response:
        """Ready while at least one backend is in rotation — the router's
        own upstream (an external LB or DNS) steers elsewhere when the
        whole fleet is dark or this router drains."""
        if self._draining.is_set():
            return 503, {"status": "draining"}
        eligible = self._eligible()
        payload = {
            "status": "ready" if eligible else "unready",
            "backendsInRotation": len(eligible),
            "backendsTotal": len(self.backends),
        }
        return (200 if eligible else 503), payload

    # ----------------------------------------------------------- query path
    def _budget_s(self, headers: Dict[str, str]) -> float:
        """The request's deadline budget in seconds: the router default,
        or a smaller client-propagated X-PIO-Deadline-Ms."""
        budget = self.config.deadline_ms / 1e3
        raw = None
        for k, v in headers.items():
            if k.lower() == "x-pio-deadline-ms":
                raw = v
                break
        if raw is not None:
            try:
                client_ms = float(raw)
                if 0 <= client_ms / 1e3 < budget:
                    budget = client_ms / 1e3
            except ValueError:
                pass
        return budget

    def _tenant_label(self, key: Optional[str]) -> str:
        """The metric/shed label for a query's tenant: the learned name
        when a backend has answered for this key, the key itself before
        that, '-' for a key-less (legacy) query."""
        if not key:
            return "-"
        with self._lock:
            return self._tenant_by_key.get(key, key)

    def _queries(self, body: bytes, headers: Dict[str, str],
                 query: Optional[Dict[str, str]] = None) -> Response:
        t_start = time.perf_counter()
        if self._draining.is_set():
            return 503, {"message": "router is draining"}, \
                {"Retry-After": "1"}
        key = (query or {}).get("accessKey")
        tenant = self._tenant_label(key)
        cache = self._cache
        token = None
        if cache is not None:
            # front-door lookup BEFORE any admission charge: a hit
            # touches no replica and must not consume inflight permits.
            # token None = the fleet has no agreed generation for this
            # tenant (empty rotation or mid-barrier skew) — stand aside
            # rather than answer across a generation boundary.
            token = self._generation_token(tenant)
            if token is not None:
                hit = cache.get((tenant, token, bytes(body)))
                self._cache_metrics_update()
                if hit is not None:
                    with self._lock:
                        self.request_count += 1
                    if telemetry.on():
                        self._m_requests.labels(outcome="ok",
                                                tenant=tenant).inc()
                        self._m_overhead.observe(
                            max(time.perf_counter() - t_start, 0.0))
                    return hit
        with self._lock:
            cap = self._tenant_cap
        charged = False
        if key and cap > 0:
            # per-tenant shedding at the front door: one tenant's flood
            # sheds ITS queries before it can fill the shared pool
            with self._lock:
                count = self._tenant_inflight.get(tenant, 0)
                if count >= cap:
                    over = True
                else:
                    self._tenant_inflight[tenant] = count + 1
                    over = False
            if over:
                self._shed("tenant-inflight", tenant=tenant)
                return 503, {"message": (
                    f"tenant '{tenant}' is saturated at the router "
                    "(per-tenant admission control); retry later")}, \
                    {"Retry-After": "1"}
            charged = True
        try:
            with self._lock:
                if self._inflight_count >= self._max_inflight:
                    admitted = False
                else:
                    self._inflight_count += 1
                    admitted = True
            if not admitted:
                # admission control: the fleet is saturated end to end;
                # queueing here would only grow latency without bound
                self._shed("inflight", tenant=tenant)
                return 503, {"message": (
                    "router is saturated (admission control); "
                    "retry later")}, \
                    {"Retry-After": "1"}
            try:
                with self._lock:
                    pmap, pincomplete = self._pmap, self._pmap_incomplete
                if pmap is not None or pincomplete:
                    resp = self._scatter(pmap, body, headers, t_start)
                else:
                    resp = self._forward(body, headers, t_start, key=key)
                if cache is not None and resp[0] == 200:
                    # store under the POST-forward tenant label (the
                    # forward may have just learned key→name) and a
                    # freshly-agreed generation token
                    label = self._tenant_label(key)
                    store_token = self._generation_token(label)
                    if store_token is not None:
                        cache.put((label, store_token, bytes(body)),
                                  resp[0], resp[1],
                                  resp[2] if len(resp) > 2 else None)
                        self._cache_metrics_update()
                return resp
            finally:
                with self._lock:
                    self._inflight_count -= 1
        finally:
            if charged:
                with self._lock:
                    n = self._tenant_inflight.get(tenant, 1) - 1
                    if n <= 0:
                        self._tenant_inflight.pop(tenant, None)
                    else:
                        self._tenant_inflight[tenant] = n

    def _shed(self, reason: str, tenant: str = "-") -> None:
        with self._lock:
            self.shed_count += 1
        if telemetry.on():
            self._m_requests.labels(outcome="shed", tenant=tenant).inc()
        logger.warning("router shed a query (%s)", reason)

    def _forward(self, body: bytes, headers: Dict[str, str],
                 t_start: float, key: Optional[str] = None) -> Response:
        deadline = t_start + self._budget_s(headers)
        # tenant-aware routing: the query's access key rides the
        # forwarded URL so the backend's admission control resolves the
        # SAME key the client presented (key-less legacy queries keep
        # the bare path, byte for byte)
        fwd_path = "/queries.json"
        if key:
            fwd_path += "?" + urllib.parse.urlencode({"accessKey": key})
        tenant = self._tenant_label(key)
        fwd_headers = {"Content-Type": "application/json"}
        ctx = tracing.current()
        if ctx is not None:
            # the transport adopted (or originated) this request's trace;
            # propagating it is what lets `pio trace` assemble the
            # router->replica tree
            fwd_headers[tracing.TRACE_HEADER] = ctx.header_value()
        attempt = 0
        backend_s = 0.0
        exclude: set = set()
        failed_over = False
        while True:
            b = self._pick(exclude)
            if b is None:
                self._shed("no backend in rotation", tenant=tenant)
                return 503, {"message": (
                    "no healthy backend in rotation; retry later")}, \
                    {"Retry-After": "1"}
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                if telemetry.on():
                    self._m_requests.labels(outcome="deadline",
                                            tenant=tenant).inc()
                return 504, {"message": "deadline exceeded"}
            # while a failover retry is still possible, reserve half the
            # remaining budget for it: a replica slower than half the
            # budget TIMES OUT here (a breaker-visible failure — this is
            # how injected latency on one replica shifts traffic) and
            # the retry still has room to succeed elsewhere. The last
            # attempt gets everything that is left.
            attempt_timeout = (
                remaining / 2
                if self._retry.may_retry(attempt, deadline,
                                         clock=time.perf_counter)
                and len(self._eligible()) > 1
                else remaining)
            hdrs = {**fwd_headers,
                    "X-PIO-Deadline-Ms": str(int(attempt_timeout * 1e3))}
            t0 = time.perf_counter()
            try:
                if ctx is not None:
                    with tracing.span("route", service=b.name):
                        status, payload, rheaders = b.request(
                            "POST", fwd_path, body, hdrs,
                            timeout=attempt_timeout)
                else:
                    status, payload, rheaders = b.request(
                        "POST", fwd_path, body, hdrs,
                        timeout=attempt_timeout)
            except _TRANSPORT_ERRORS as e:
                backend_s += time.perf_counter() - t0
                b.breaker.record(False)
                self.note_backend_failure(b)
                exclude.add(b.name)
                # /queries.json is a pure read: ONE failover retry on
                # another replica is safe; a second failure surfaces
                if self._retry.may_retry(attempt, deadline,
                                         clock=time.perf_counter):
                    attempt += 1
                    failed_over = True
                    with self._lock:
                        self.failover_count += 1
                    if telemetry.on():
                        self._m_failovers.inc()
                    continue
                if telemetry.on():
                    self._m_requests.labels(outcome="error",
                                            tenant=tenant).inc()
                return 502, {"message": (
                    f"backend {b.name} failed ({type(e).__name__}) and "
                    "the failover budget is spent")}
            dt = time.perf_counter() - t0
            backend_s += dt
            b.breaker.record(status < 500)
            if telemetry.on():
                # the per-replica latency signal the autopilot's outlier
                # quarantine compares across the fleet
                self._m_backend_seconds.labels(
                    backend=b.name).observe(dt)
            if status in (502, 503, 504) and self._retry.may_retry(
                    attempt, deadline, clock=time.perf_counter):
                # a draining/saturated replica said "not me" — that is
                # exactly the failover case; its Retry-After floor only
                # matters if the retry fails too
                attempt += 1
                failed_over = True
                exclude.add(b.name)
                with self._lock:
                    self.failover_count += 1
                if telemetry.on():
                    self._m_failovers.inc()
                continue
            return self._respond(status, payload, rheaders, failed_over,
                                 t_start, backend_s, key=key)

# --------------------------------------------------------- scatter/merge
    def _ensure_scatter_pool(self) -> concurrent.futures.ThreadPoolExecutor:
        with self._lock:
            if self._scatter_pool is None:
                self._scatter_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=32, thread_name_prefix="pio-router-scatter")
            return self._scatter_pool

    def _scatter(self, pmap: Optional[Dict[str, Any]], body: bytes,
                 headers: Dict[str, str], t_start: float) -> Response:
        """Partition-routed dispatch: fan one query out to every owning
        partition concurrently under the shared deadline budget, then
        merge the per-partition top-k with serve_dist.merge_candidates —
        the host twin of the device all-gather merge, so the answer is
        bit-identical (values, indices, tie order) to one full-model
        replica's. An incomplete map NEVER partial-merges: missing
        coverage answers 503 outright."""
        metrics = self._partition_metrics()
        if pmap is None:
            self._shed("partition coverage gap")
            if telemetry.on():
                metrics.labels(outcome="coverage_gap").inc()
            return 503, {"message": (
                "partition coverage is incomplete (no servable map); "
                "retry later")}, {"Retry-After": "1"}
        deadline = t_start + self._budget_s(headers)
        self._partition_width_gauge().set(float(pmap["count"]))
        fwd_headers = {"Content-Type": "application/json"}
        ctx = tracing.current()
        if ctx is not None:
            fwd_headers[tracing.TRACE_HEADER] = ctx.header_value()

        def leg(replicas: List[_Backend]) -> Tuple[str, Any, Any]:
            """One partition's sub-request with intra-partition
            failover: walk that partition's replicas (rr-rotated,
            breaker-gated) until one answers; transport failures eject
            (note_backend_failure → the map rebuilds without them)."""
            start = next(self._rr)
            last_err = "all replicas breaker-open"
            for j in range(len(replicas)):
                b = replicas[(start + j) % len(replicas)]
                try:
                    b.breaker.allow()
                except resilience.CircuitOpenError:
                    continue
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return "deadline", None, None
                hdrs = {**fwd_headers,
                        "X-PIO-Deadline-Ms": str(int(remaining * 1e3))}
                t0 = time.perf_counter()
                try:
                    with tracing.activate(ctx):
                        if ctx is not None:
                            with tracing.span("scatter", service=b.name):
                                status, payload, _rh = b.request(
                                    "POST", "/queries.json", body, hdrs,
                                    timeout=remaining)
                        else:
                            status, payload, _rh = b.request(
                                "POST", "/queries.json", body, hdrs,
                                timeout=remaining)
                except _TRANSPORT_ERRORS as e:
                    b.breaker.record(False)
                    self.note_backend_failure(b)
                    last_err = f"{b.name}: {type(e).__name__}"
                    continue
                b.breaker.record(status < 500)
                if telemetry.on():
                    self._m_backend_seconds.labels(
                        backend=b.name).observe(
                            time.perf_counter() - t0)
                if status in (502, 503, 504):
                    # per-partition failover: a draining/saturated
                    # replica said "not me" — try its partition peers
                    last_err = f"{b.name}: HTTP {status}"
                    continue
                return "ok", status, payload
            return "exhausted", last_err, None

        pool = self._ensure_scatter_pool()
        owners = [pmap["owners"][i] for i in range(pmap["count"])]
        t_fan = time.perf_counter()
        futures = [pool.submit(leg, replicas) for replicas in owners]
        results = []
        try:
            for f in futures:
                results.append(f.result(
                    timeout=max(deadline - time.perf_counter(), 0.001)))
        except concurrent.futures.TimeoutError:
            for f in futures:
                f.cancel()
            if telemetry.on():
                metrics.labels(outcome="deadline").inc()
                self._m_requests.labels(outcome="deadline",
                                        tenant="-").inc()
            return 504, {"message": "deadline exceeded"}
        backend_s = time.perf_counter() - t_fan

        def finish(outcome: str, resp: Response) -> Response:
            with self._lock:
                self.request_count += 1
            if telemetry.on():
                metrics.labels(outcome=outcome).inc()
                self._m_requests.labels(
                    outcome=("ok" if outcome == "merged"
                             else "deadline" if outcome == "deadline"
                             else "error"), tenant="-").inc()
                self._m_overhead.observe(
                    max(time.perf_counter() - t_start - backend_s, 0.0))
            return resp

        for verdict, a, payload in results:
            if verdict == "deadline":
                return finish("deadline",
                              (504, {"message": "deadline exceeded"}))
            if verdict == "exhausted":
                # a whole partition went dark mid-flight — that is a
                # coverage gap, and a gap never partial-merges
                self._shed(f"partition leg failed ({a})")
                return finish("coverage_gap", (
                    503, {"message": (
                        f"a partition became unavailable ({a}); "
                        "retry later")}, {"Retry-After": "1"}))
        parts = []
        for verdict, status, payload in results:
            try:
                obj = json.loads(payload) if payload else {}
            except ValueError:
                return finish("error", (502, {
                    "message": "backend returned a non-JSON reply"}))
            if status != 200:
                # every partition ran the same parse/validation on the
                # same body — propagate the first non-200 verbatim
                # (e.g. a 400 malformed query), exactly what one full
                # replica would have answered
                return finish("error" if status >= 500 else "merged",
                              (status, obj))
            parts.append(obj)
        return finish("merged", self._merge(pmap, body, parts))

    def _merge(self, pmap: Dict[str, Any], body: bytes,
               parts: List[Dict[str, Any]]) -> Response:
        """Reassemble the client-facing answer from per-partition 200s.

        Each sub-response carries its candidates' GLOBAL item indices
        (the replica's partition block); the two-key (value, lowest
        global index) sort over the concatenated candidates is the same
        rule the device all-gather merge applies, and the merged entry
        dicts are the replicas' own parsed entries — Python's exact
        float round-trip makes the re-serialized bytes identical to a
        full replica's."""
        from predictionio_tpu.parallel.serve_dist import merge_candidates
        entries: List[Dict[str, Any]] = []
        values: List[float] = []
        gids: List[int] = []
        degraded = False
        n_items = None
        for obj in parts:
            block = obj.get("partition") if isinstance(obj, dict) else None
            scores = (obj or {}).get("itemScores")
            if (not isinstance(block, dict)
                    or not isinstance(scores, list)
                    or block.get("count") != pmap["count"]
                    or len(block.get("itemIndices") or []) != len(scores)):
                return 502, {"message": (
                    "a partition replica answered without a consistent "
                    "partition block (map raced a re-partition?); "
                    "retry later")}, {"Retry-After": "1"}
            if n_items is None:
                n_items = int(block["nItems"])
            elif n_items != int(block["nItems"]):
                return 502, {"message": (
                    "partition replicas disagree on the catalog size; "
                    "retry later")}, {"Retry-After": "1"}
            degraded = degraded or bool(obj.get("degraded"))
            for entry, gid in zip(scores, block["itemIndices"]):
                entries.append(entry)
                values.append(float(entry.get("score", 0.0)))
                gids.append(int(gid))
        try:
            num = int(json.loads(body).get("num", 0))
        except (ValueError, TypeError, AttributeError):
            num = 0
        k = max(0, min(num, int(n_items or 0)))
        if entries:
            _v, _g, order = merge_candidates(values, gids, k)
            merged = [entries[int(j)] for j in order]
        else:
            merged = []
        out: Dict[str, Any] = {"itemScores": merged}
        if degraded:
            out["degraded"] = True
        return 200, out

    def _respond(self, status: int, payload: bytes,
                 rheaders: Dict[str, str], failed_over: bool,
                 t_start: float, backend_s: float,
                 key: Optional[str] = None) -> Response:
        # learn key→tenant from the backend's resolution (X-PIO-Tenant
        # rides every successful multi-tenant answer) so per-tenant
        # labels and the inflight cap use real names from here on
        learned = rheaders.get("x-pio-tenant")
        if key and learned:
            with self._lock:
                self._tenant_by_key[key] = learned
        tenant = learned or self._tenant_label(key)
        try:
            obj = json.loads(payload) if payload else {}
        except ValueError:
            if telemetry.on():
                self._m_requests.labels(outcome="error",
                                        tenant=tenant).inc()
            return 502, {"message": "backend returned a non-JSON reply"}
        extra: Dict[str, str] = {}
        if rheaders.get("retry-after"):
            extra["Retry-After"] = rheaders["retry-after"]
        with self._lock:
            self.request_count += 1
        if telemetry.on():
            outcome = ("error" if status >= 500
                       else "failover_ok" if failed_over else "ok")
            self._m_requests.labels(outcome=outcome, tenant=tenant).inc()
            # added latency = our handler time minus the backend call —
            # both clocks end host-side in this pure-Python path
            self._m_overhead.observe(
                max(time.perf_counter() - t_start - backend_s, 0.0))
        if extra:
            return status, obj, extra
        return status, obj

    # --------------------------------------------------- hot-swap barrier
    def _start_reload(self, query: Dict[str, str]) -> Response:
        """Kick (or join, with ?wait=1) the coordinated reload barrier.
        One barrier at a time: a second POST while one runs answers 409
        (two interleaved barriers could split the fleet's generations)."""
        if not self._reload_lock.acquire(blocking=False):
            return 409, {"message": "a reload barrier is already running"}
        wait = (query.get("wait") or "") in ("1", "true", "yes")
        done = threading.Event()

        def run():
            try:
                self._reload_barrier()
            finally:
                self._reload_lock.release()
                done.set()

        threading.Thread(target=run, name="pio-router-reload",
                         daemon=True).start()
        if wait:
            done.wait(300.0)
            return 200, {"message": "Reload barrier finished.",
                         "reload": dict(self._reload_state)}
        return 200, {"message": "Reload barrier started."}

    def _await_flip(self, b: _Backend, old_gen: Optional[int],
                    timeout_s: float = 120.0) -> bool:
        """Poll one backend until its generation moves past ``old_gen``
        AND it is ready again."""
        deadline = time.perf_counter() + timeout_s
        old_tenant_gens = dict(b.tenant_generations or {})
        while time.perf_counter() < deadline:
            healthy, _draining, gen, tenant_gens, partition = b.probe()
            with self._lock:
                if gen is not None:
                    b.generation = gen
                if tenant_gens is not None:
                    b.tenant_generations = tenant_gens
                if healthy:
                    b.partition = partition
                b.healthy = healthy
            if healthy and gen is not None and (
                    old_gen is None or gen > old_gen):
                # a multi-tenant replica's /reload hot-swaps every
                # tenant; verify each advanced and journal the ones
                # that did not (the per-tenant skew the doctor WARNs on)
                if tenant_gens and old_tenant_gens:
                    stale = sorted(
                        n for n, g in old_tenant_gens.items()
                        if tenant_gens.get(n, g + 1) <= g)
                    if stale:
                        journal.emit(
                            "router",
                            f"backend {b.name} flipped but tenant(s) "
                            f"{stale} kept their old generation",
                            level=journal.WARN, backend=b.name,
                            tenants=stale)
                return True
            time.sleep(min(self.config.health_ms / 1e3, 0.2))
        return False

    def _set_admitted(self, backends: List[_Backend], value: bool) -> None:
        with self._lock:
            for b in backends:
                b.admitted = value
        for b in backends:
            self._m_backend_up.labels(backend=b.name).set(
                1.0 if (b.healthy and value and not b.quarantined)
                else 0.0)
        # admission changes re-shape the partition map (the barrier's
        # coordinated re-partition rides the same atomic map swap)
        self._rebuild_pmap()

    def _reload_barrier(self) -> None:
        """The coordinated hot-swap: reload replicas one at a time while
        queries route only to old-generation replicas, then cut over
        atomically. On a failed replica reload the barrier ABORTS and
        re-admits everything — the fleet then has mixed generations
        until the operator re-runs /reload (journaled RED; doctor WARNs
        on the skew; KNOWN_ISSUES #15 records the contract)."""
        t0 = time.perf_counter()
        old = self._eligible()
        self._reload_state = {"active": True, "flipped": 0,
                              "total": len(old)}
        journal.emit(
            "router", f"reload barrier begin over {len(old)} backend(s)",
            level=journal.INFO, backends=[b.name for b in old])
        if not old:
            self._reload_state = {"active": False, "error":
                                  "no backend in rotation"}
            journal.emit("router", "reload barrier aborted: no backend "
                         "in rotation", level=journal.WARN)
            return

        def reload_one(b: _Backend) -> bool:
            old_gen = b.generation
            try:
                status, _p, _h = b.request("POST", "/reload", b"", {},
                                           timeout=10.0)
            except _TRANSPORT_ERRORS as e:
                journal.emit(
                    "router", f"reload of {b.name} failed in transport: "
                    f"{type(e).__name__}", level=journal.RED,
                    backend=b.name)
                return False
            if status != 200:
                journal.emit(
                    "router", f"reload of {b.name} answered {status}",
                    level=journal.RED, backend=b.name, status=status)
                return False
            return self._await_flip(b, old_gen)

        if len(old) == 1:
            # a single replica's in-process hot-swap is already atomic
            # and zero-downtime; pulling it from rotation would be the
            # only way to DROP queries here
            ok = reload_one(old[0])
            self._reload_state = {"active": False, "flipped": int(ok),
                                  "total": 1, "ok": ok}
            journal.emit(
                "router",
                "reload barrier complete (single backend, in-place "
                "hot-swap)" if ok else
                "reload barrier FAILED on the single backend",
                level=journal.INFO if ok else journal.RED,
                durationS=round(time.perf_counter() - t0, 3))
            return

        flipped: List[_Backend] = []
        for b in old[:-1]:
            # hold this replica out; traffic stays on old-generation
            # replicas (flipped ones wait un-admitted for the cutover)
            self._set_admitted([b], False)
            if not reload_one(b):
                # abort: re-admit everything (mixed generations beat a
                # shrinking fleet — the skew is visible and re-runnable)
                self._set_admitted(flipped + [b], True)
                self._reload_state = {"active": False,
                                      "flipped": len(flipped),
                                      "total": len(old), "ok": False,
                                      "error": f"reload of {b.name} failed"}
                journal.emit(
                    "router", "reload barrier ABORTED: fleet has mixed "
                    "generations until /reload is re-run",
                    level=journal.RED, failed=b.name)
                return
            flipped.append(b)
            self._reload_state["flipped"] = len(flipped)
        last = old[-1]
        # THE cutover: one lock-held flip admits every new-generation
        # replica and retires the lone old one — queries admitted before
        # this line answered from the old generation, after it from the
        # new; no interleaving
        with self._lock:
            for b in flipped:
                b.admitted = True
            last.admitted = False
        for b in flipped + [last]:
            self._m_backend_up.labels(backend=b.name).set(
                1.0 if (b.healthy and b.admitted and not b.quarantined)
                else 0.0)
        self._rebuild_pmap()
        journal.emit(
            "router", f"reload barrier cutover: {len(flipped)} backend(s) "
            f"now serving the new generation; reloading {last.name}",
            level=journal.INFO, flipped=[b.name for b in flipped])
        ok = reload_one(last)
        self._set_admitted([last], True)
        self._reload_state = {"active": False,
                              "flipped": len(flipped) + int(ok),
                              "total": len(old), "ok": ok}
        journal.emit(
            "router",
            f"reload barrier complete over {len(old)} backend(s)" if ok
            else f"reload barrier FAILED on the last backend {last.name}; "
            "it re-admits when its probe recovers",
            level=journal.INFO if ok else journal.RED,
            durationS=round(time.perf_counter() - t0, 3))

    # ------------------------------------------------------------ lifecycle
    @property
    def stop_requested(self) -> bool:
        return self._stop_requested.is_set()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @draining.setter
    def draining(self, value: bool) -> None:
        if value:
            self.drain()

    def drain(self) -> None:
        """Stop admitting (readyz -> 503, queries -> 503 + Retry-After);
        in-flight forwards finish on the transport's own drain."""
        if self._draining.is_set():
            return
        self._draining.set()
        journal.emit("router", "router drain begin: stopped admitting "
                     "queries", level=journal.INFO)
        self._stop_requested.set()

    def close(self) -> None:
        self._stop_requested.set()
        pool, self._scatter_pool = self._scatter_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        for b in self.backends:
            b.close()


def serve(api: RouterAPI, host: str = "localhost",
          port: int = 8100) -> None:
    """Run the router until /stop or SIGTERM (graceful drain: readiness
    flips, in-flight forwards complete, then exit) on the shared
    transport."""
    from predictionio_tpu.data.api.http import (
        install_sigterm_handler, make_server,
    )
    server = make_server(api, host, port)
    install_sigterm_handler(api.drain)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    logger.info("Router online at http://%s:%s over %d backend(s)",
                host, port, len(api.backends))
    try:
        while not api.stop_requested:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    server.shutdown()
    server.server_close()
    api.close()
