"""Engine-server plugin SPI.

Reference: core/.../workflow/EngineServerPlugin.scala:24-40 and
EngineServerPluginContext.scala:40-91 — "outputblocker" plugins transform
(or veto) each prediction synchronously; "outputsniffer" plugins observe
asynchronously and can answer REST calls under /plugins/.
"""

from __future__ import annotations

from typing import Sequence

from predictionio_tpu.common.plugin_registry import PluginContextBase

OUTPUT_BLOCKER = "outputblocker"
OUTPUT_SNIFFER = "outputsniffer"


class EngineServerPlugin:
    plugin_name = ""
    plugin_description = ""
    plugin_type = OUTPUT_SNIFFER

    def process(self, engine_instance, query_obj, prediction_obj, context):
        """Blockers return the (possibly rewritten) prediction JSON object;
        sniffers' return value is ignored."""
        return prediction_obj

    def handle_rest(self, args: Sequence[str]) -> str:
        return "{}"

    def start(self, context) -> None:
        """Called once when the server starts (EngineServerPlugin.start)."""


class EngineServerPluginContext(PluginContextBase):
    BLOCKER_KIND = OUTPUT_BLOCKER
    SNIFFER_KIND = OUTPUT_SNIFFER

    @property
    def output_blockers(self):
        return self.kind(OUTPUT_BLOCKER)

    @property
    def output_sniffers(self):
        return self.kind(OUTPUT_SNIFFER)
