"""Engine-server plugin SPI.

Reference: core/.../workflow/EngineServerPlugin.scala:24-40 and
EngineServerPluginContext.scala:40-91 — "outputblocker" plugins transform
(or veto) each prediction synchronously; "outputsniffer" plugins observe
asynchronously and can answer REST calls under /plugins/.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

OUTPUT_BLOCKER = "outputblocker"
OUTPUT_SNIFFER = "outputsniffer"


class EngineServerPlugin:
    plugin_name = ""
    plugin_description = ""
    plugin_type = OUTPUT_SNIFFER

    def process(self, engine_instance, query_obj, prediction_obj, context):
        """Blockers return the (possibly rewritten) prediction JSON object;
        sniffers' return value is ignored."""
        return prediction_obj

    def handle_rest(self, args: Sequence[str]) -> str:
        return "{}"

    def start(self, context) -> None:
        """Called once when the server starts (EngineServerPlugin.start)."""


class EngineServerPluginContext:
    def __init__(self, plugins: Sequence[EngineServerPlugin] = ()):
        self.output_blockers: Dict[str, EngineServerPlugin] = {}
        self.output_sniffers: Dict[str, EngineServerPlugin] = {}
        for p in plugins:
            self.register(p)

    def register(self, plugin: EngineServerPlugin) -> None:
        target = (self.output_blockers
                  if plugin.plugin_type == OUTPUT_BLOCKER
                  else self.output_sniffers)
        target[plugin.plugin_name] = plugin

    def describe(self) -> Dict[str, Dict[str, Dict[str, str]]]:
        def block(ps):
            return {
                n: {"name": p.plugin_name,
                    "description": p.plugin_description,
                    "class": type(p).__module__ + "." + type(p).__qualname__}
                for n, p in ps.items()}
        return {"plugins": {
            "outputblockers": block(self.output_blockers),
            "outputsniffers": block(self.output_sniffers),
        }}
