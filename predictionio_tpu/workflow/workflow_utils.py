"""Engine/evaluation loading + engine.json parsing.

Reference: core/.../workflow/WorkflowUtils.scala:53-121 (reflective
getEngine/getEvaluation/getEngineParamsGenerator) and the engine variant
JSON contract (Engine.scala:357-420). JVM reflection becomes Python import
paths: "package.module:attr" where attr is an Engine instance, a zero-arg
factory returning one, or an Evaluation/EngineParamsGenerator subclass.
"""

from __future__ import annotations

import importlib
import importlib.util
import json
import os
import sys
from typing import Any, Dict, Optional, Tuple

from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.controller.evaluation import Evaluation, EngineParamsGenerator


def load_object(path: str, base_dir: Optional[str] = None) -> Any:
    """Resolve "module.sub:attr" (or "module.sub.attr") to a Python object.

    `base_dir` (the engine directory, analogue of the engine assembly jar on
    the spark-submit classpath) is prepended to sys.path so engine templates
    load from their own directory.
    """
    if base_dir and base_dir not in sys.path:
        sys.path.insert(0, os.path.abspath(base_dir))
    if ":" in path:
        module_name, attr = path.split(":", 1)
    else:
        module_name, _, attr = path.rpartition(".")
        if not module_name:
            raise ValueError(
                f"cannot resolve {path!r}: expected 'module:attr' or "
                "'module.attr'")
    module = importlib.import_module(module_name)
    obj = module
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def get_engine(engine_factory: str, base_dir: Optional[str] = None) -> Engine:
    """EngineFactory resolution (WorkflowUtils.getEngine, scala object vs
    class detection :53-87 → instance vs callable detection here)."""
    obj = load_object(engine_factory, base_dir)
    if isinstance(obj, Engine):
        return obj
    if callable(obj):
        engine = obj()
        if isinstance(engine, Engine):
            return engine
    raise TypeError(
        f"{engine_factory!r} is neither an Engine nor a factory returning one")


def get_evaluation(path: str, base_dir: Optional[str] = None) -> Evaluation:
    obj = load_object(path, base_dir)
    if isinstance(obj, Evaluation):
        return obj
    if isinstance(obj, type) and issubclass(obj, Evaluation):
        return obj()
    raise TypeError(f"{path!r} is not an Evaluation")


def get_engine_params_generator(
        path: str, base_dir: Optional[str] = None) -> EngineParamsGenerator:
    obj = load_object(path, base_dir)
    if isinstance(obj, EngineParamsGenerator):
        return obj
    if isinstance(obj, type) and issubclass(obj, EngineParamsGenerator):
        return obj()
    raise TypeError(f"{path!r} is not an EngineParamsGenerator")


def read_engine_variant(engine_dir: str,
                        variant: str = "engine.json") -> Dict[str, Any]:
    """Load + minimally validate an engine variant file."""
    path = variant if os.path.isabs(variant) else os.path.join(engine_dir, variant)
    with open(path) as f:
        variant_json = json.load(f)
    for field in ("id", "engineFactory"):
        if field not in variant_json:
            raise ValueError(f"{path}: missing required field {field!r}")
    return variant_json


def runtime_conf_from_variant(variant_json: Dict[str, Any]) -> Dict[str, str]:
    """Flatten the optional `runtimeConf`/`sparkConf` subtree into dotted
    key/value pairs (WorkflowUtils.extractSparkConf, WorkflowUtils.scala:
    317-351 — kept for config-surface parity; TPU runs use it for XLA/mesh
    settings)."""
    sub = variant_json.get("runtimeConf", variant_json.get("sparkConf", {}))
    out: Dict[str, str] = {}

    def walk(prefix: str, node: Any):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}.{k}" if prefix else k, v)
        else:
            out[prefix] = str(node)

    walk("", sub)
    return out
