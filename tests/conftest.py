"""Test config: force JAX onto a virtual 8-device CPU platform.

Mirrors the reference's strategy of testing distributed semantics on
`local[*]` Spark (SURVEY.md §4): identical semantics, one process. Meshes
built in tests span 8 virtual CPU devices.
"""

import os
import re

# jax is preloaded by the environment's sitecustomize, so plain env vars are
# too late — but the backend is not initialized yet, so config still applies.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
_m = re.search(r"--xla_force_host_platform_device_count=(\d+)", _flags)
if _m is None:
    _flags += " --xla_force_host_platform_device_count=8"
elif int(_m.group(1)) < 8:
    _flags = _flags.replace(
        _m.group(0), "--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = _flags.strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from predictionio_tpu.data.storage import reset_storage, use_memory_storage  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running load/throughput tests excluded from tier-1 "
        "(run with `-m slow`)")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection / crash-recovery suite (tests marked ONLY "
        "chaos are the fast smoke subset and run in tier-1; the heavy "
        "legs carry chaos+slow and run with `-m chaos`)")


@pytest.fixture()
def memory_storage():
    """A fresh all-in-memory Storage singleton per test."""
    storage = use_memory_storage()
    yield storage
    reset_storage()
