"""Test config: force JAX onto a virtual 8-device CPU platform.

Mirrors the reference's strategy of testing distributed semantics on
`local[*]` Spark (SURVEY.md §4): identical semantics, one process. Meshes
built in tests span 8 virtual CPU devices.
"""

import os

# jax is preloaded by the environment's sitecustomize, so plain env vars are
# too late — but the backend is not initialized yet, so config still applies.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from predictionio_tpu.data.storage import reset_storage, use_memory_storage  # noqa: E402


@pytest.fixture()
def memory_storage():
    """A fresh all-in-memory Storage singleton per test."""
    storage = use_memory_storage()
    yield storage
    reset_storage()
