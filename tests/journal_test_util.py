"""Shared fixture code for the flight-recorder tests (test_journal.py,
test_traceview.py): seed, train, and deploy a small recommendation
engine — the test_telemetry.py recipe, factored out so both new suites
reuse one trainer."""

import datetime as dt

from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.workflow import WorkflowContext, run_train
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig


def train_engine(storage, app_name="JournalApp"):
    """Seed ratings + train one small ALS instance; returns the engine."""
    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(0, app_name, None))
    storage.get_events().init(app_id)
    events = []
    for u in range(8):
        for i in range(6):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap(
                    {"rating": 5.0 if (u % 2) == (i % 2) else 1.0}),
                event_time=dt.datetime(2021, 1, 1, 0, (u * 6 + i) % 60,
                                       tzinfo=dt.timezone.utc)))
    storage.get_events().insert_batch(events, app_id)
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName=app_name),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=4, numIterations=3,
                                       lambda_=0.05, seed=3)),))
    run_train(WorkflowContext(storage=storage), engine, ep,
              engine_factory="journal-test",
              params_json={
                  "datasource": {"params": {"appName": app_name}},
                  "algorithms": [{"name": "als", "params": {
                      "rank": 4, "numIterations": 3, "lambda": 0.05,
                      "seed": 3}}]})
    return engine


def trained_query_api(storage, **config):
    """A deployed QueryAPI over a freshly-trained engine."""
    engine = train_engine(storage)
    return QueryAPI(storage=storage, engine=engine,
                    config=ServerConfig(**config))
