"""$set/$unset/$delete folding parity with LEventAggregator.scala:94-135."""

import datetime as dt

from predictionio_tpu.data.aggregate import aggregate_properties, aggregate_properties_single
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event


def mk(event, entity_id, props, minute):
    return Event(
        event=event, entity_type="user", entity_id=entity_id,
        properties=DataMap(props),
        event_time=dt.datetime(2021, 1, 1, 0, minute, tzinfo=dt.timezone.utc),
    )


def test_set_merge_right_biased():
    pm = aggregate_properties_single([
        mk("$set", "u1", {"a": 1, "b": 2}, 0),
        mk("$set", "u1", {"b": 5, "c": 6}, 1),
    ])
    assert pm is not None
    assert pm.to_dict() == {"a": 1, "b": 5, "c": 6}
    assert pm.first_updated.minute == 0
    assert pm.last_updated.minute == 1


def test_events_sorted_by_event_time_not_arrival():
    pm = aggregate_properties_single([
        mk("$set", "u1", {"b": 5}, 1),
        mk("$set", "u1", {"b": 2}, 0),  # earlier, must lose
    ])
    assert pm.to_dict() == {"b": 5}


def test_unset_removes_keys():
    pm = aggregate_properties_single([
        mk("$set", "u1", {"a": 1, "b": 2}, 0),
        mk("$unset", "u1", {"a": 0}, 1),
    ])
    assert pm.to_dict() == {"b": 2}


def test_unset_before_set_stays_absent():
    pm = aggregate_properties_single([mk("$unset", "u1", {"a": 0}, 0)])
    assert pm is None


def test_delete_drops_entity():
    pm = aggregate_properties_single([
        mk("$set", "u1", {"a": 1}, 0),
        mk("$delete", "u1", {}, 1),
    ])
    assert pm is None


def test_set_after_delete_keeps_first_updated():
    pm = aggregate_properties_single([
        mk("$set", "u1", {"a": 1}, 0),
        mk("$delete", "u1", {}, 1),
        mk("$set", "u1", {"z": 9}, 2),
    ])
    assert pm.to_dict() == {"z": 9}
    assert pm.first_updated.minute == 0  # times survive the $delete
    assert pm.last_updated.minute == 2


def test_non_special_events_ignored():
    pm = aggregate_properties_single([
        mk("$set", "u1", {"a": 1}, 0),
        mk("rate", "u1", {"rating": 5}, 1),
    ])
    assert pm.to_dict() == {"a": 1}
    assert pm.last_updated.minute == 0  # rate didn't touch times


def test_aggregate_multi_entity():
    out = aggregate_properties([
        mk("$set", "u1", {"a": 1}, 0),
        mk("$set", "u2", {"a": 2}, 0),
        mk("$delete", "u2", {}, 1),
    ])
    assert set(out.keys()) == {"u1"}
    assert out["u1"].to_dict() == {"a": 1}
