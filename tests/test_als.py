"""ALS kernel correctness (parity target: MLlib ALS as used by the
recommendation template, ALSAlgorithm.scala:50-94)."""

import jax.numpy as jnp
import numpy as np
import pytest

from predictionio_tpu.ops import als


def make_problem(n_u=30, n_i=20, rank=3, density=0.7, seed=0):
    rng = np.random.default_rng(seed)
    U0 = rng.normal(size=(n_u, rank))
    V0 = rng.normal(size=(n_i, rank))
    R = U0 @ V0.T
    mask = rng.random((n_u, n_i)) < density
    ui, ii = np.nonzero(mask)
    return ui.astype(np.int32), ii.astype(np.int32), R[ui, ii].astype(np.float32)


def test_prepare_ratings_layout():
    ui = np.array([2, 0, 1, 0], dtype=np.int32)
    ii = np.array([1, 0, 1, 2], dtype=np.int32)
    r = np.array([1.0, 2.0, 3.0, 4.0], dtype=np.float32)
    data = als.prepare_ratings(ui, ii, r, n_users=3, n_items=3, chunk=8)
    bu = data.by_user
    # sorted by user, padded to 8 with self_idx == n_users
    assert bu.self_idx.shape == (8,)
    np.testing.assert_array_equal(bu.self_idx[:4], [0, 0, 1, 2])
    np.testing.assert_array_equal(bu.self_idx[4:], [3, 3, 3, 3])
    np.testing.assert_array_equal(bu.counts, [2, 1, 1])
    np.testing.assert_array_equal(bu.rating[4:], 0.0)
    bi = data.by_item
    np.testing.assert_array_equal(bi.self_idx[:4], [0, 1, 1, 2])
    np.testing.assert_array_equal(bi.counts, [1, 2, 1])
    assert data.nnz == 4


def test_half_step_solves_normal_equations():
    """One U half-step must equal the per-user ridge solution (numpy)."""
    ui, ii, vals = make_problem()
    n_u, n_i = 30, 20
    rank, lam = 3, 0.1
    data = als.prepare_ratings(ui, ii, vals, n_u, n_i, chunk=64)
    rng = np.random.default_rng(1)
    V = rng.normal(size=(n_i, rank)).astype(np.float32)

    bu = data.by_user
    import jax.numpy as jnp
    U = als._half_step_explicit(
        jnp.asarray(V), jnp.asarray(bu.self_idx), jnp.asarray(bu.other_idx),
        jnp.asarray(bu.rating), jnp.asarray(bu.counts), n_u, lam,
        chunk=64, reg_scaling="count")
    U = np.asarray(U)

    for u in range(n_u):
        sel = ui == u
        Vu = V[ii[sel]]
        A = Vu.T @ Vu + lam * sel.sum() * np.eye(rank)
        b = Vu.T @ vals[sel]
        expected = np.linalg.solve(A + 1e-8 * np.eye(rank), b)
        np.testing.assert_allclose(U[u], expected, rtol=2e-3, atol=2e-3)


def test_train_recovers_low_rank_matrix():
    ui, ii, vals = make_problem(n_u=50, n_i=35, rank=4, seed=2)
    data = als.prepare_ratings(ui, ii, vals, 50, 35, chunk=256)
    U, V = als.train_explicit(data, rank=4, iterations=15, lambda_=1e-6,
                              chunk=256)
    pred = np.sum(np.asarray(U)[ui] * np.asarray(V)[ii], axis=1)
    assert np.sqrt(np.mean((pred - vals) ** 2)) < 1e-3


def test_train_multiple_chunks_matches_single_chunk():
    ui, ii, vals = make_problem(seed=3)
    data1 = als.prepare_ratings(ui, ii, vals, 30, 20, chunk=1 << 12)
    data2 = als.prepare_ratings(ui, ii, vals, 30, 20, chunk=32)
    U1, V1 = als.train_explicit(data1, rank=3, iterations=3, lambda_=0.05)
    U2, V2 = als.train_explicit(data2, rank=3, iterations=3, lambda_=0.05,
                                chunk=32)
    np.testing.assert_allclose(np.asarray(U1), np.asarray(U2), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(V1), np.asarray(V2), rtol=1e-4,
                               atol=1e-5)


def test_implicit_half_step_matches_dense_hkv():
    """Implicit U half-step vs dense Hu-Koren-Volinsky solution."""
    rng = np.random.default_rng(4)
    n_u, n_i, rank, lam, alpha = 12, 9, 3, 0.1, 5.0
    counts_mat = (rng.random((n_u, n_i)) < 0.5) * rng.integers(1, 6, (n_u, n_i))
    ui, ii = np.nonzero(counts_mat)
    vals = counts_mat[ui, ii].astype(np.float32)
    data = als.prepare_ratings(ui.astype(np.int32), ii.astype(np.int32),
                               vals, n_u, n_i, chunk=32)
    V = rng.normal(size=(n_i, rank)).astype(np.float32)

    import jax.numpy as jnp
    bu = data.by_user
    U = als._half_step_implicit(
        jnp.asarray(V), jnp.asarray(bu.self_idx), jnp.asarray(bu.other_idx),
        jnp.asarray(bu.rating), jnp.asarray(bu.counts), n_u, lam, alpha,
        chunk=32, reg_scaling="count")
    U = np.asarray(U)

    YtY = V.T @ V
    for u in range(n_u):
        sel = ui == u
        Vu = V[ii[sel]]
        Cu = alpha * vals[sel]
        A = YtY + Vu.T @ (Cu[:, None] * Vu) + lam * sel.sum() * np.eye(rank)
        b = Vu.T @ (1.0 + Cu)
        expected = np.linalg.solve(A + 1e-8 * np.eye(rank), b)
        np.testing.assert_allclose(U[u], expected, rtol=2e-3, atol=2e-3)


def test_train_implicit_ranks_preferred_items_higher():
    rng = np.random.default_rng(5)
    n_u, n_i = 20, 15
    # users 0-9 view items 0-7 heavily; users 10-19 view items 8-14
    ui, ii, vals = [], [], []
    for u in range(n_u):
        items = range(0, 8) if u < 10 else range(8, 15)
        for i in items:
            if rng.random() < 0.8:
                ui.append(u); ii.append(i); vals.append(rng.integers(1, 5))
    data = als.prepare_ratings(
        np.array(ui, np.int32), np.array(ii, np.int32),
        np.array(vals, np.float32), n_u, n_i, chunk=64)
    U, V = als.train_implicit(data, rank=4, iterations=10, lambda_=0.01,
                              alpha=10.0, chunk=64)
    scores = np.asarray(U) @ np.asarray(V).T
    # group-A user scores group-A items above group-B items on average
    assert scores[0, :8].mean() > scores[0, 8:].mean()
    assert scores[15, 8:].mean() > scores[15, :8].mean()


def test_zero_rating_user_stays_finite():
    # user 2 has no ratings at all
    ui = np.array([0, 1], dtype=np.int32)
    ii = np.array([0, 1], dtype=np.int32)
    vals = np.array([1.0, 2.0], dtype=np.float32)
    data = als.prepare_ratings(ui, ii, vals, n_users=3, n_items=2, chunk=8)
    U, V = als.train_explicit(data, rank=2, iterations=2, lambda_=0.1, chunk=8)
    assert np.isfinite(np.asarray(U)).all()
    np.testing.assert_allclose(np.asarray(U)[2], 0.0, atol=1e-6)


def test_rmse_helper():
    ui, ii, vals = make_problem(seed=6)
    data = als.prepare_ratings(ui, ii, vals, 30, 20, chunk=64)
    U, V = als.train_explicit(data, rank=3, iterations=10, lambda_=1e-5,
                              chunk=64)
    bu = data.by_user
    mask = (bu.self_idx < 30).astype(np.float32)
    import jax.numpy as jnp
    err = als.rmse(U, V, jnp.asarray(np.clip(bu.self_idx, 0, 29)),
                   jnp.asarray(bu.other_idx), jnp.asarray(bu.rating),
                   jnp.asarray(mask), chunk=64)
    assert float(err) < 0.01


@pytest.mark.parametrize("implicit", [False, True])
def test_csrb_kernel_matches_scan_kernel(implicit):
    """The csrb (mini-block wide-gather) and scan (per-entry segment-sum)
    kernels are the same math; full trains must agree to float tolerance."""
    ui, ii, vals = make_problem(n_u=40, n_i=25, rank=4, density=0.4, seed=7)
    if implicit:
        vals = np.abs(vals) + 0.5
    data = als.prepare_ratings(ui, ii, vals, 40, 25, chunk=64)
    train = als.train_implicit if implicit else als.train_explicit
    U1, V1 = train(data, rank=4, iterations=4, lambda_=0.05, seed=11,
                   chunk=64, kernel="scan")
    U2, V2 = train(data, rank=4, iterations=4, lambda_=0.05, seed=11,
                   chunk=64, kernel="csrb")
    np.testing.assert_allclose(np.asarray(U1), np.asarray(U2),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(V1), np.asarray(V2),
                               rtol=2e-4, atol=2e-5)


def test_csrb_layout_roundtrip():
    """Every real entry appears exactly once in the csrb layout, in a
    mini-block owned by its row; all other slots are zero-weight."""
    ui, ii, vals = make_problem(n_u=17, n_i=9, rank=2, density=0.5, seed=3)
    data = als.prepare_ratings(ui, ii, vals, 17, 9, chunk=32)
    bu = data.by_user
    b = 8
    n_mb, _ = als._csrb_plan(data.nnz, 17, b, 32)
    oi, rat, pres, seg = als.csrb_layout(
        np.asarray(bu.other_idx), np.asarray(bu.rating),
        np.asarray(bu.counts), 17, b, n_mb)
    oi, rat, pres, seg = (np.asarray(x) for x in (oi, rat, pres, seg))
    assert pres.sum() == data.nnz
    rows = np.repeat(seg, b)
    got = sorted(zip(rows[pres > 0].tolist(), oi[pres > 0].tolist(),
                     rat[pres > 0].tolist()))
    want = sorted(zip(ui.tolist(), ii.tolist(), vals.tolist()))
    assert got == want
    # padding slots carry zero weight and a nondecreasing segment map
    assert np.all(np.diff(seg) >= 0)
    assert np.all(rat[pres == 0] == 0.0)


def test_ship_coo_narrow_dtypes_lossless():
    """Narrow-dtype device shipping (uint16 ids / int8 half-star codes)
    must be exactly lossless, and must fall back to full width for big
    vocabularies or non-half-step ratings (incl. signed implicit weights)."""
    rng = np.random.default_rng(0)
    n = 1000
    u = rng.integers(0, 70_000, n).astype(np.int32)     # > uint16 range
    i = rng.integers(0, 30_000, n).astype(np.int32)     # fits uint16
    r = (rng.integers(-10, 11, n) / 2.0).astype(np.float32)  # signed halves
    ju, ji, jr = als._ship_coo(u, i, r, 70_000, 30_000)
    np.testing.assert_array_equal(np.asarray(ju), u)
    np.testing.assert_array_equal(np.asarray(ji), i)
    np.testing.assert_array_equal(np.asarray(jr), r)
    # arbitrary floats fall back untouched
    r2 = rng.uniform(0, 5, n).astype(np.float32)
    _ju, _ji, jr2 = als._ship_coo(u, i, r2, 70_000, 30_000)
    np.testing.assert_array_equal(np.asarray(jr2), r2)
    # boundary: id exactly 65535 fits, 65536-vocab still narrow
    ub = np.array([0, 65_535], np.int32)
    jub, _, _ = als._ship_coo(ub, ub, np.ones(2, np.float32), 1 << 16,
                              1 << 16)
    np.testing.assert_array_equal(np.asarray(jub), ub)


def test_solve_factors_clamps_indefinite_rows():
    """Round-4 postmortem regression: kernel rounding pushed per-row Grams
    slightly indefinite and the unpivoted sweep turned a near-zero Schur
    pivot into inf -> model-wide NaN two iterations later. The solve must
    (a) stay exact on clean SPD systems and (b) return BOUNDED finite
    solutions on indefinite ones (sign-preserving pivot magnitude floor)."""
    rng = np.random.default_rng(0)
    r, n = 6, 64
    M = rng.normal(0, 1, (n, r, r)).astype(np.float32)
    A = np.einsum("nij,nkj->nik", M, M)              # SPD batch
    # poison a few rows: rank-1 negative update far beyond the ridge
    for row in (3, 17, 40):
        v = rng.normal(0, 1, r).astype(np.float32)
        A[row] -= 3.0 * np.linalg.norm(A[row]) * np.outer(v, v) \
            / np.dot(v, v)
    b = rng.normal(0, 1, (n, r)).astype(np.float32)
    reg = np.full(n, 0.05, np.float32)
    x = np.asarray(als.solve_factors(
        jnp.asarray(A), jnp.asarray(b), jnp.asarray(reg)))
    assert np.isfinite(x).all()
    clean = np.setdiff1d(np.arange(n), [3, 17, 40])
    ref = np.linalg.solve(
        A[clean] + reg[clean, None, None] * np.eye(r),
        b[clean][..., None])[..., 0]
    np.testing.assert_allclose(x[clean], ref, rtol=2e-3, atol=2e-3)
    # bounded: the floor caps the inverse around 2/reg per sweep step
    assert np.abs(x).max() < np.abs(b).max() * (2 / 0.05) * r


def test_split_hilo_dense_path_precision():
    """Round-4 postmortem regression: single-bf16 quantization of
    X = [v(x)v | v] left ~4e-3 relative Gram error, which exceeded the
    ridge once factors grew to |v|~50 at ML-20M. The split hi/lo pair
    must keep the dense-hot Gram within ~1e-4 relative of the f32
    reference at exactly those magnitudes (single-bf16 fails this by two
    orders)."""
    rng = np.random.default_rng(1)
    n_u, K, r = 256, 32, 8
    V_hot = (rng.normal(0, 1, (K, r)) * 50).astype(np.float32)
    D = np.zeros((n_u, 2 * K), np.float32)
    D[:, :K] = rng.integers(0, 3, (n_u, K))          # counts
    D[:, K:] = D[:, :K] * rng.uniform(0.5, 5.0, (n_u, K))
    X_hot = np.asarray(als._expand_X(jnp.asarray(V_hot), r, jnp.float32))
    AB = np.asarray(als._dense_hot_user(
        jnp.asarray(D, dtype=als._HYBRID_DTYPE), jnp.asarray(X_hot), K, r))
    ref_gram = D[:, :K] @ X_hot[:, :r * r]
    err = np.abs(AB[:, :r * r] - ref_gram).max()
    scale = np.abs(ref_gram).max()
    assert err / scale < 1e-4, f"dense gram rel err {err/scale:.2e}"


@pytest.mark.parametrize("implicit", [False, True])
def test_hybrid_kernel_matches_csrb(implicit, monkeypatch):
    """The hybrid (dense-hot + csrb-tail) kernel uses bf16 for the hot
    matmuls, so parity is at model level: ~1% Frobenius on factors and
    equivalent reconstruction RMSE vs the f32 csrb kernel. The threshold
    is lowered so the bf16 dense path is ACTUALLY exercised (avg user
    count here is ~24; the default 64 would zero out D entirely)."""
    monkeypatch.setenv("PIO_ALS_HOT_K", "64")
    monkeypatch.setenv("PIO_ALS_DENSE_MIN_COUNT", "8")
    rng = np.random.default_rng(3)
    n_u, n_i, nnz = 500, 300, 12000
    item_w = 1.0 / np.arange(1, n_i + 1) ** 0.8
    ii = np.searchsorted(np.cumsum(item_w / item_w.sum()),
                         rng.random(nnz)).astype(np.int32)
    np.clip(ii, 0, n_i - 1, out=ii)
    ui = rng.integers(0, n_u, nnz).astype(np.int32)
    vals = np.clip(np.round(rng.uniform(0.5, 5.0, nnz) * 2) / 2,
                   0.5, 5.0).astype(np.float32)
    data = als.prepare_ratings(ui, ii, vals, n_u, n_i, chunk=1024)
    train = als.train_implicit if implicit else als.train_explicit
    U1, V1 = train(data, rank=6, iterations=4, lambda_=0.05, seed=7,
                   chunk=1024, kernel="csrb")
    U2, V2 = train(data, rank=6, iterations=4, lambda_=0.05, seed=7,
                   chunk=1024, kernel="hybrid")
    U1, V1, U2, V2 = map(np.asarray, (U1, V1, U2, V2))
    assert np.linalg.norm(U1 - U2) / np.linalg.norm(U1) < 0.02
    assert np.linalg.norm(V1 - V2) / np.linalg.norm(V1) < 0.02
    if not implicit:
        p1 = (U1 @ V1.T)[ui, ii]
        p2 = (U2 @ V2.T)[ui, ii]
        r1 = float(np.sqrt(np.mean((p1 - vals) ** 2)))
        r2 = float(np.sqrt(np.mean((p2 - vals) ** 2)))
        assert abs(r1 - r2) < 0.01 * max(r1, 1e-6)


def test_hybrid_small_item_set_falls_back(monkeypatch):
    """n_items < 2K: hybrid silently uses the csrb path (bit-identical)."""
    monkeypatch.setenv("PIO_ALS_HOT_K", "4096")
    ui, ii, vals = make_problem(n_u=40, n_i=25, rank=4, density=0.4, seed=7)
    data = als.prepare_ratings(ui, ii, vals, 40, 25, chunk=64)
    U1, V1 = als.train_explicit(data, rank=4, iterations=3, lambda_=0.05,
                                seed=11, chunk=64, kernel="csrb")
    U2, V2 = als.train_explicit(data, rank=4, iterations=3, lambda_=0.05,
                                seed=11, chunk=64, kernel="hybrid")
    np.testing.assert_array_equal(np.asarray(U1), np.asarray(U2))
    np.testing.assert_array_equal(np.asarray(V1), np.asarray(V2))


def test_hybrid_below_floor_hot_items_stay_on_tail(monkeypatch):
    """Tail-budget regression (review r4): candidate hot items whose count
    is below the dense floor must be BUDGETED into the tail, not silently
    dropped. Flat popularity + dense-eligible users exercises it."""
    monkeypatch.setenv("PIO_ALS_HOT_K", "8")
    rng = np.random.default_rng(1)
    n_u, n_i = 5, 20
    ui = np.repeat(np.arange(n_u, dtype=np.int32), 100)      # 100 each >= 64
    ii = rng.integers(0, n_i, 500).astype(np.int32)          # ~25/item < 64
    vals = rng.uniform(0.5, 5.0, 500).astype(np.float32)
    data = als.prepare_ratings(ui, ii, vals, n_u, n_i, chunk=64)
    U1, V1 = als.train_explicit(data, rank=3, iterations=3, lambda_=0.05,
                                seed=5, chunk=64, kernel="csrb")
    U2, V2 = als.train_explicit(data, rank=3, iterations=3, lambda_=0.05,
                                seed=5, chunk=64, kernel="hybrid")
    np.testing.assert_allclose(np.asarray(U1), np.asarray(U2),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(V1), np.asarray(V2),
                               rtol=1e-4, atol=1e-5)


def test_layout_cache_reused_across_variants(memory_storage):
    """Two trains over the SAME TrainingData (the FastEval grid shape)
    compute the COO layout once; a different TrainingData gets its own."""
    from unittest import mock

    from predictionio_tpu.models.recommendation.als_algorithm import (
        ALSAlgorithm, ALSAlgorithmParams)
    from predictionio_tpu.models.recommendation.data_source import (
        TrainingData)
    from predictionio_tpu.models.recommendation.preparator import (
        PreparedData)
    from predictionio_tpu.data.bimap import BiMap

    rng = np.random.default_rng(0)
    n = 500
    td = TrainingData(
        user_idx=rng.integers(0, 40, n).astype(np.int32),
        item_idx=rng.integers(0, 30, n).astype(np.int32),
        rating=rng.uniform(1, 5, n).astype(np.float32),
        user_vocab=BiMap.string_int(f"u{k}" for k in range(40)),
        item_vocab=BiMap.string_int(f"i{k}" for k in range(30)))
    pd = PreparedData(ratings=td)
    real = als.prepare_ratings
    with mock.patch.object(als, "prepare_ratings",
                           side_effect=real) as spy:
        ALSAlgorithm(ALSAlgorithmParams(rank=4, numIterations=2,
                                        seed=1)).train(None, pd)
        ALSAlgorithm(ALSAlgorithmParams(rank=6, numIterations=2,
                                        seed=2)).train(None, pd)
        assert spy.call_count == 1          # second variant reused layout
    m1 = ALSAlgorithm(ALSAlgorithmParams(rank=4, numIterations=3,
                                         seed=3)).train(None, pd)
    assert m1.user_factors.shape == (40, 4)


def test_batch_predict_clamps_nonpositive_num(memory_storage):
    """Eval-path parity with predict(): num <= 0 yields empty results, and
    an all-nonpositive batch must not reach lax.top_k with negative k."""
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.models.recommendation.als_algorithm import (
        ALSAlgorithm, ALSAlgorithmParams)
    from predictionio_tpu.models.recommendation.data_source import (
        TrainingData)
    from predictionio_tpu.models.recommendation.engine import Query
    from predictionio_tpu.models.recommendation.preparator import (
        PreparedData)

    rng = np.random.default_rng(1)
    n = 300
    td = TrainingData(
        user_idx=rng.integers(0, 20, n).astype(np.int32),
        item_idx=rng.integers(0, 15, n).astype(np.int32),
        rating=rng.uniform(1, 5, n).astype(np.float32),
        user_vocab=BiMap.string_int(f"u{k}" for k in range(20)),
        item_vocab=BiMap.string_int(f"i{k}" for k in range(15)))
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=4, numIterations=2, seed=1))
    model = algo.train(None, PreparedData(ratings=td))
    res = dict(algo.batch_predict(model, [
        (0, Query(user="u1", num=-1)),
        (1, Query(user="u2", num=3)),
        (2, Query(user="u3", num=0))]))
    assert res[0].itemScores == () and res[2].itemScores == ()
    assert len(res[1].itemScores) == 3
    # all-nonpositive batch: no device call, all empty
    res2 = dict(algo.batch_predict(model, [
        (0, Query(user="u1", num=0)), (1, Query(user="u2", num=-5))]))
    assert all(r.itemScores == () for r in res2.values())


@pytest.mark.parametrize("kernel", ["csrb", "scan"])
def test_implicit_cold_rows_do_not_poison_model(kernel):
    """An item (or user) with ZERO interactions must solve to a zero row,
    not NaN: with the bare 1e-8 ridge (invisible in f32 next to YtY) the
    cold row's unpivoted solve produced 0/0, and one NaN row made the
    next iteration's YtY — and the entire model — NaN."""
    u = np.array([0, 0, 1, 1, 2], dtype=np.int32)
    i = np.array([0, 1, 0, 1, 2], dtype=np.int32)
    r = np.ones(5, dtype=np.float32)
    # item 3 and user 3 exist in the vocab but have no interactions
    data = als.prepare_ratings(u, i, r, n_users=4, n_items=4)
    U, V = als.train_implicit(data, rank=4, iterations=10, lambda_=0.01,
                              alpha=1.0, seed=3, kernel=kernel)
    U, V = np.asarray(U), np.asarray(V)
    assert np.isfinite(U).all() and np.isfinite(V).all()
    np.testing.assert_allclose(U[3], 0.0)
    np.testing.assert_allclose(V[3], 0.0)
    # trained rows still reconstruct the signal
    pred = np.sum(U[u] * V[i], axis=1)
    assert (pred > 0).all()


class TestPallasSolver:
    """ops/solve_pallas.py: the VMEM Gauss-Jordan batch solver."""

    @staticmethod
    def systems(n=700, r=10, seed=0):
        rng = np.random.default_rng(seed)
        F = rng.normal(size=(n, r, 3)).astype(np.float32)
        A = np.einsum("nri,nsi->nrs", F, F)     # PSD, rank 3 < r
        b = rng.normal(size=(n, r)).astype(np.float32)
        reg = rng.uniform(0.05, 0.5, n).astype(np.float32)
        return A, b, reg

    def test_matches_xla_gj_interpret(self, monkeypatch):
        """Interpret mode (runs everywhere) must agree with solve_factors
        bit-for-bit at an awkward (non-BN-multiple) batch size."""
        import jax.numpy as jnp
        from predictionio_tpu.ops.solve_pallas import solve_factors_pallas
        monkeypatch.setenv("PIO_ALS_SOLVER", "gj")   # reference path
        A, b, reg = self.systems()
        x_ref = np.asarray(als.solve_factors(
            jnp.asarray(A), jnp.asarray(b), jnp.asarray(reg)))
        x = np.asarray(solve_factors_pallas(
            jnp.asarray(A), jnp.asarray(b), jnp.asarray(reg),
            interpret=True))
        # rank-deficient PSD + small ridge is deliberately marginal, so
        # compare by residual (the solver contract), plus a loose direct
        # comparison
        np.testing.assert_allclose(x, x_ref, rtol=5e-2, atol=5e-3)
        r = A.shape[-1]
        Ar = A + reg[:, None, None] * np.eye(r, dtype=np.float32)[None]
        resid = np.einsum("nrs,ns->nr", Ar, x) - b
        ref_resid = np.einsum("nrs,ns->nr", Ar, x_ref) - b
        assert np.abs(resid).max() < max(2 * np.abs(ref_resid).max(), 1e-3)

    def test_solver_choice_env_and_platform(self, monkeypatch):
        from predictionio_tpu.ops import solve_pallas as sp
        monkeypatch.setenv("PIO_ALS_SOLVER", "gj")
        assert sp.solver_choice() == "gj"
        monkeypatch.setenv("PIO_ALS_SOLVER", "pallas")
        # off-TPU the opt-in downgrades (with a warning) instead of
        # failing to lower; on a real TPU backend it engages
        import jax
        expected = "pallas" if jax.default_backend() == "tpu" else "gj"
        assert sp.solver_choice() == expected
        monkeypatch.delenv("PIO_ALS_SOLVER")
        # default is gj: the pallas solver measured end-to-end neutral
        # (it overlaps other work in the fused loop), so it is opt-in
        assert sp.solver_choice() == "gj"

    def test_env_flip_retraces_cached_trainer(self, monkeypatch):
        """Flipping PIO_ALS_XPAD between same-shape trains must change the
        compiled program (the knobs are trace-time env reads; the tuning
        static arg makes them part of the jit cache key)."""
        monkeypatch.setenv("PIO_ALS_XPAD", "1")
        u = np.array([0, 0, 1, 2], dtype=np.int32)
        i = np.array([0, 1, 1, 0], dtype=np.int32)
        r = np.ones(4, dtype=np.float32)
        data = als.prepare_ratings(u, i, r, 3, 2, chunk=32)
        U1, V1 = als.train_explicit(data, rank=2, iterations=2,
                                    lambda_=0.1, seed=1, chunk=32,
                                    kernel="csrb")
        n_compiled = als._train_csrb_jit._cache_size()
        monkeypatch.setenv("PIO_ALS_XPAD", "0")
        U2, V2 = als.train_explicit(data, rank=2, iterations=2,
                                    lambda_=0.1, seed=1, chunk=32,
                                    kernel="csrb")
        assert als._train_csrb_jit._cache_size() == n_compiled + 1
        np.testing.assert_allclose(np.asarray(U1), np.asarray(U2),
                                   rtol=1e-5, atol=1e-6)
