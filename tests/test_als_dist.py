"""Block-sharded ALS on an 8-device virtual mesh (SURVEY.md §4: the CPU
XLA_FLAGS-device-count analogue of the reference's Spark local[*] testing)."""

import numpy as np
import pytest

from predictionio_tpu.ops import als
from predictionio_tpu.parallel import als_dist
from predictionio_tpu.parallel.mesh import get_mesh, shard_rows


def make_problem(n_u=60, n_i=40, rank=4, seed=0):
    rng = np.random.default_rng(seed)
    U0 = rng.normal(size=(n_u, rank))
    V0 = rng.normal(size=(n_i, rank))
    R = U0 @ V0.T
    mask = rng.random((n_u, n_i)) < 0.6
    ui, ii = np.nonzero(mask)
    return ui.astype(np.int32), ii.astype(np.int32), R[ui, ii].astype(np.float32)


def test_shard_side_partitioning():
    ui, ii, vals = make_problem()
    data = als.prepare_ratings(ui, ii, vals, 60, 40, chunk=16)
    su, si = als_dist.prepare_sharded(data, n_dev=4, chunk=16)
    assert su.n_rows_pad == 60 and su.rows_dev == 15
    assert su.self_idx.shape[0] == 4 * su.nnz_dev
    # every real entry preserved exactly once, with local indices in range
    s = su.self_idx.reshape(4, su.nnz_dev)
    r = su.rating.reshape(4, su.nnz_dev)
    real = s < su.rows_dev
    assert int(real.sum()) == data.nnz
    for d in range(4):
        local = s[d][real[d]]
        assert local.min() >= 0 and local.max() < su.rows_dev
    # ratings sum preserved
    np.testing.assert_allclose(r.sum(), vals.sum(), rtol=1e-5)


def test_sharded_training_converges(n_dev=8):
    ui, ii, vals = make_problem(seed=1)
    data = als.prepare_ratings(ui, ii, vals, 60, 40, chunk=64)
    mesh = get_mesh(n_dev)
    U, V = als_dist.train_explicit_sharded(
        mesh, data, rank=4, iterations=15, lambda_=1e-6, chunk=64)
    U, V = np.asarray(U)[:60], np.asarray(V)[:40]
    pred = np.sum(U[ui] * V[ii], axis=1)
    assert np.sqrt(np.mean((pred - vals) ** 2)) < 1e-3


def test_sharded_implicit_runs():
    ui, ii, vals = make_problem(seed=2)
    data = als.prepare_ratings(ui, ii, np.abs(vals) + 1, 60, 40, chunk=64)
    mesh = get_mesh(8)
    U, V = als_dist.train_explicit_sharded(
        mesh, data, rank=4, iterations=3, lambda_=0.05, chunk=64,
        implicit=True, alpha=10.0)
    assert np.isfinite(np.asarray(U)).all() and np.isfinite(np.asarray(V)).all()


def test_sharded_matches_quality_of_single_device():
    """Same data, same hyperparams: sharded must reach the quality of the
    single-device solve (different init, so compare fit, not values)."""
    ui, ii, vals = make_problem(seed=3)
    data = als.prepare_ratings(ui, ii, vals, 60, 40, chunk=64)
    U1, V1 = als.train_explicit(data, rank=4, iterations=10, lambda_=0.01,
                                chunk=64)
    pred1 = np.sum(np.asarray(U1)[ui] * np.asarray(V1)[ii], axis=1)
    rmse1 = np.sqrt(np.mean((pred1 - vals) ** 2))

    mesh = get_mesh(8)
    U2, V2 = als_dist.train_explicit_sharded(
        mesh, data, rank=4, iterations=10, lambda_=0.01, chunk=64)
    pred2 = np.sum(np.asarray(U2)[:60][ui] * np.asarray(V2)[:40][ii], axis=1)
    rmse2 = np.sqrt(np.mean((pred2 - vals) ** 2))
    assert rmse2 < rmse1 * 1.5 + 1e-3


def test_shard_rows_balancing():
    starts, ends = shard_rows([10, 1, 1, 10, 1, 1, 10, 2], 4)
    assert starts[0] == 0 and ends[-1] == 8
    # contiguous, non-overlapping, covering
    for s in range(1, 4):
        assert starts[s] == ends[s - 1]
