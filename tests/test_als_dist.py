"""Block-sharded ALS on an 8-device virtual mesh (SURVEY.md §4: the CPU
XLA_FLAGS-device-count analogue of the reference's Spark local[*] testing)."""

import numpy as np

from predictionio_tpu.ops import als
from predictionio_tpu.parallel import als_dist
from predictionio_tpu.parallel.mesh import get_mesh
from predictionio_tpu.workflow.checkpoint import FactorCheckpointer


def make_problem(n_u=60, n_i=40, rank=4, seed=0):
    rng = np.random.default_rng(seed)
    U0 = rng.normal(size=(n_u, rank))
    V0 = rng.normal(size=(n_i, rank))
    R = U0 @ V0.T
    mask = rng.random((n_u, n_i)) < 0.6
    ui, ii = np.nonzero(mask)
    return ui.astype(np.int32), ii.astype(np.int32), R[ui, ii].astype(np.float32)


def zipf_problem(n_u=200, n_i=80, nnz=4000, seed=0):
    """Power-law skew like the bench's synthetic ML-20M (bench.py:31-33)."""
    rng = np.random.default_rng(seed)
    user_w = rng.lognormal(0.0, 1.2, n_u)
    item_w = 1.0 / np.arange(1, n_i + 1) ** 0.8
    u = rng.choice(n_u, size=nnz, p=user_w / user_w.sum()).astype(np.int32)
    i = rng.choice(n_i, size=nnz, p=item_w / item_w.sum()).astype(np.int32)
    r = np.clip(rng.normal(3.5, 1.1, nnz), 0.5, 5.0).astype(np.float32)
    return u, i, r


def test_shard_side_partitioning():
    ui, ii, vals = make_problem()
    data = als.prepare_ratings(ui, ii, vals, 60, 40, chunk=16)
    su, si = als_dist.prepare_sharded(data, n_dev=4, chunk=16)
    assert su.n_rows_pad == 4 * su.rows_dev
    assert su.self_idx.shape[0] == 4 * su.nnz_dev
    # every real entry preserved exactly once, with local indices in range
    s = su.self_idx.reshape(4, su.nnz_dev)
    r = su.rating.reshape(4, su.nnz_dev)
    real = s < su.rows_dev
    assert int(real.sum()) == data.nnz
    for d in range(4):
        if real[d].any():
            local = s[d][real[d]]
            assert local.min() >= 0 and local.max() < su.rows_dev
    # ratings sum preserved
    np.testing.assert_allclose(r.sum(), vals.sum(), rtol=1e-5)
    # pos is a bijection onto distinct padded addresses
    assert len(np.unique(su.pos)) == 60
    assert su.pos.min() >= 0 and su.pos.max() < su.n_rows_pad
    # per-device real nnz accounted exactly
    assert int(su.nnz_per_dev.sum()) == data.nnz


def test_shard_side_nnz_balanced_under_skew():
    """Under Zipf skew, per-device padded nnz must stay near total/n_dev —
    the round-1 uniform-row split paid the hottest block everywhere
    (VERDICT round 1, weak #3)."""
    u, i, r = zipf_problem()
    n_dev, chunk = 8, 16
    data = als.prepare_ratings(u, i, r, 200, 80, chunk=chunk)
    su, si = als_dist.prepare_sharded(data, n_dev=n_dev, chunk=chunk)
    for side, raw, n_rows in ((su, u, 200), (si, i, 80)):
        # one row's ratings can't be split across devices, so the floor is
        # max(hottest row, total/n_dev); at ML-20M scale the hottest row is
        # ~3% of ideal and the ideal term dominates
        hottest = int(np.bincount(raw).max())
        ideal = max(len(u) / n_dev, hottest)
        assert side.nnz_dev <= 1.5 * ideal + chunk, (
            f"padded nnz/device {side.nnz_dev} vs ideal {ideal}")
        # row slots stay minimal — no padded-row blowup under skew
        assert side.rows_dev == -(-n_rows // n_dev)


def test_sharded_training_converges(n_dev=8):
    ui, ii, vals = make_problem(seed=1)
    data = als.prepare_ratings(ui, ii, vals, 60, 40, chunk=64)
    mesh = get_mesh(n_dev)
    U, V = als_dist.train_explicit_sharded(
        mesh, data, rank=4, iterations=15, lambda_=1e-6, chunk=64)
    U, V = np.asarray(U), np.asarray(V)
    assert U.shape == (60, 4) and V.shape == (40, 4)
    pred = np.sum(U[ui] * V[ii], axis=1)
    assert np.sqrt(np.mean((pred - vals) ** 2)) < 1e-3


def test_sharded_implicit_runs():
    ui, ii, vals = make_problem(seed=2)
    data = als.prepare_ratings(ui, ii, np.abs(vals) + 1, 60, 40, chunk=64)
    mesh = get_mesh(8)
    U, V = als_dist.train_implicit_sharded(
        mesh, data, rank=4, iterations=3, lambda_=0.05, chunk=64, alpha=10.0)
    assert np.isfinite(np.asarray(U)).all() and np.isfinite(np.asarray(V)).all()


def test_sharded_matches_single_device_for_seed():
    """Host-side seeding: same seed => sharded and single-device start from
    identical factors and agree to accumulation-order tolerance
    (VERDICT round 1, weak #4)."""
    ui, ii, vals = make_problem(seed=3)
    data = als.prepare_ratings(ui, ii, vals, 60, 40, chunk=64)
    U1, V1 = als.train_explicit(data, rank=4, iterations=5, lambda_=0.01,
                                seed=7, chunk=64)
    mesh = get_mesh(8)
    U2, V2 = als_dist.train_explicit_sharded(
        mesh, data, rank=4, iterations=5, lambda_=0.01, seed=7, chunk=64)
    np.testing.assert_allclose(np.asarray(U1), np.asarray(U2),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(V1), np.asarray(V2),
                               rtol=1e-3, atol=1e-4)


def test_sharded_checkpoint_resume(tmp_path):
    """Mesh-path snapshots restore mid-run and produce the same result as an
    uninterrupted train (canonical snapshot format shared with the
    single-device path)."""
    ui, ii, vals = make_problem(seed=4)
    data = als.prepare_ratings(ui, ii, vals, 60, 40, chunk=64)
    mesh = get_mesh(8)

    full_U, full_V = als_dist.train_explicit_sharded(
        mesh, data, rank=4, iterations=6, lambda_=0.01, seed=9, chunk=64)

    ck = FactorCheckpointer(str(tmp_path))
    als_dist.train_explicit_sharded(
        mesh, data, rank=4, iterations=6, lambda_=0.01, seed=9, chunk=64,
        checkpoint_every=2, checkpointer=ck)
    step, arrays = ck.latest()
    assert 0 < step < 6 and arrays["U"].shape == (60, 4)

    # resume from the snapshot: same final factors as uninterrupted
    U, V = als_dist.train_explicit_sharded(
        mesh, data, rank=4, iterations=6, lambda_=0.01, seed=9, chunk=64,
        checkpoint_every=2, checkpointer=ck)
    np.testing.assert_allclose(np.asarray(U), np.asarray(full_U),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(V), np.asarray(full_V),
                               rtol=1e-3, atol=1e-4)


def test_sharded_hybrid_matches_single_device(monkeypatch):
    """The per-device hybrid kernel (dense-hot D blocks + psum'd item
    partials) must agree with the single-device hybrid to bf16
    accumulation tolerance, and with the f32 csrb kernel at model level
    (the test_als.py hybrid bar)."""
    monkeypatch.setenv("PIO_ALS_HOT_K", "16")
    monkeypatch.setenv("PIO_ALS_DENSE_MIN_COUNT", "4")
    ui, ii, vals = zipf_problem(seed=11)
    data = als.prepare_ratings(ui, ii, vals, 200, 80, chunk=256)
    mesh = get_mesh(8)
    U2, V2 = als_dist.train_explicit_sharded(
        mesh, data, rank=4, iterations=5, lambda_=0.05, seed=7, chunk=256,
        kernel="hybrid")
    U1, V1 = als.train_explicit(data, rank=4, iterations=5, lambda_=0.05,
                                seed=7, chunk=256, kernel="hybrid")
    Uc, Vc = als.train_explicit(data, rank=4, iterations=5, lambda_=0.05,
                                seed=7, chunk=256, kernel="csrb")
    U1, V1, U2, V2, Uc, Vc = map(np.asarray, (U1, V1, U2, V2, Uc, Vc))
    # vs single-device hybrid: same split rule, same bf16 dense path
    assert np.linalg.norm(U1 - U2) / np.linalg.norm(U1) < 0.02
    assert np.linalg.norm(V1 - V2) / np.linalg.norm(V1) < 0.02
    # vs f32 csrb: the established hybrid parity bar
    assert np.linalg.norm(Uc - U2) / np.linalg.norm(Uc) < 0.02
    assert np.linalg.norm(Vc - V2) / np.linalg.norm(Vc) < 0.02


def test_sharded_hybrid_implicit_matches(monkeypatch):
    monkeypatch.setenv("PIO_ALS_HOT_K", "16")
    monkeypatch.setenv("PIO_ALS_DENSE_MIN_COUNT", "4")
    ui, ii, vals = zipf_problem(seed=12)
    data = als.prepare_ratings(ui, ii, np.abs(vals), 200, 80, chunk=256)
    mesh = get_mesh(8)
    U2, V2 = als_dist.train_implicit_sharded(
        mesh, data, rank=4, iterations=4, lambda_=0.05, alpha=2.0, seed=5,
        chunk=256, kernel="hybrid")
    U1, V1 = als.train_implicit(data, rank=4, iterations=4, lambda_=0.05,
                                alpha=2.0, seed=5, chunk=256,
                                kernel="hybrid")
    U1, V1, U2, V2 = map(np.asarray, (U1, V1, U2, V2))
    assert np.linalg.norm(U1 - U2) / np.linalg.norm(U1) < 0.02
    assert np.linalg.norm(V1 - V2) / np.linalg.norm(V1) < 0.02


def test_sharded_hybrid_small_items_falls_back(monkeypatch):
    """n_items < 2K: the sharded driver degrades to csrb exactly like the
    single-device one (no hot/cold split worth building)."""
    monkeypatch.setenv("PIO_ALS_HOT_K", "4096")
    ui, ii, vals = make_problem(seed=6)
    data = als.prepare_ratings(ui, ii, vals, 60, 40, chunk=64)
    mesh = get_mesh(8)
    U2, V2 = als_dist.train_explicit_sharded(
        mesh, data, rank=4, iterations=3, lambda_=0.01, seed=7, chunk=64,
        kernel="hybrid")
    Uc, Vc = als_dist.train_explicit_sharded(
        mesh, data, rank=4, iterations=3, lambda_=0.01, seed=7, chunk=64,
        kernel="csrb")
    np.testing.assert_array_equal(np.asarray(U2), np.asarray(Uc))
    np.testing.assert_array_equal(np.asarray(V2), np.asarray(Vc))
