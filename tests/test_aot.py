"""AOT serving/training compilation subsystem (serving/aot.py).

The acceptance surface of ISSUE 6: every enumerated (bucket, template,
k) program is bit-identical to the lazy-jit path; deploy prebuilds the
program set before /readyz flips ready, marks the recompile watchdog's
warmup done, and records time-to-ready; the compile cache exports from
`pio train` as a deploy artifact and imports gracefully (a mismatched
environment degrades to lazy compile, never errors); ``PIO_AOT=0``
deploy is wire-byte-identical to the pre-AOT server; and a tier-1 lint
fails when a ``@jax.jit`` entry point on the serving path is not
registered with the AOT enumerator.
"""

import ast
import datetime as dt
import json
import os

import numpy as np
import pytest

from predictionio_tpu.common import devicewatch, telemetry
from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App, Model
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.ops import als, topk
from predictionio_tpu.serving import aot, protocol
from predictionio_tpu.workflow import WorkflowContext, model_io, run_train
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig

PKG = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "predictionio_tpu")


def _clear_counter_family(name):
    """Zero one counter family's children (the process registry is
    additive by design; doctor-style readers consume absolutes)."""
    reg = telemetry.registry()
    with reg._lock:
        fam = reg._families.get(name)
    if fam is not None:
        with fam._lock:
            fam._children.clear()


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """AOT state never leaks across tests: telemetry override reset,
    watchdog reset. The program memo is left alone on purpose (it is
    additive and shape-keyed, like the jit cache it mirrors)."""
    telemetry.set_enabled(None)
    devicewatch.reset_watchdog()
    yield
    telemetry.set_enabled(None)
    devicewatch.reset_watchdog()
    devicewatch.note_aot(None)


def _train_engine(storage, n_items=7, rank=3):
    """Item count unique to this module so its programs are not already
    jit-cached by other test files."""
    app_id = storage.get_meta_data_apps().insert(App(0, "AotApp"))
    storage.get_events().init(app_id)
    events = []
    for u in range(9):
        for i in range(n_items):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap(
                    {"rating": 5.0 if (u % 2) == (i % 2) else 1.0}),
                event_time=dt.datetime(2021, 1, 3, 0, (u + i) % 60,
                                       tzinfo=dt.timezone.utc)))
    storage.get_events().insert_batch(events, app_id)
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="AotApp"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=rank, numIterations=2,
                                       lambda_=0.05, seed=7)),))
    iid = run_train(WorkflowContext(storage=storage), engine, ep,
                    engine_factory="aot-test",
                    params_json={
                        "datasource": {"params": {"appName": "AotApp"}},
                        "algorithms": [{"name": "als", "params": {
                            "rank": rank, "numIterations": 2,
                            "lambda": 0.05, "seed": 7}}]})
    return engine, iid


# ---------------------------------------------------------------------------
# the registration lint: no unregistered @jax.jit on the serving path
# ---------------------------------------------------------------------------

def _jit_decorated_defs(path):
    """Function names in ``path`` decorated with jax.jit (bare or via
    functools.partial(jax.jit, ...)) — AST-based so aliasing/formatting
    can't hide one."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            target = dec
            if (isinstance(dec, ast.Call) and dec.args
                    and isinstance(dec.func, ast.Name)
                    and dec.func.id == "partial"):
                target = dec.args[0]
            if isinstance(target, ast.Call):
                target = target.func
            if (isinstance(target, ast.Attribute) and target.attr == "jit"
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "jax"):
                out.append(node.name)
    return out


def test_every_serving_path_jit_is_registered():
    """RUNTIME half of the AOT-registration lint: after real imports,
    every jitted def in these modules is the SAME OBJECT a register_jit
    call recorded (catches registration of a stale alias/wrapper). The
    static half — which modules are in scope at all — is now the
    structural `aot-registration` pass of `pio lint`
    (tools/analyze/passes/aot_registration.py): repo-wide, no opt-in
    list; tests/test_lint.py asserts this list is a subset of what the
    pass discovers, so a module added here without the pass knowing it
    is impossible."""
    import importlib

    serving_modules = [
        ("ops/topk.py", "predictionio_tpu.ops.topk"),
        # the sharded serving kernel lives with its layout machinery in
        # parallel/ but is very much on the serving path
        ("parallel/serve_dist.py", "predictionio_tpu.parallel.serve_dist"),
    ]
    serving_dir = os.path.join(PKG, "serving")
    for f in sorted(os.listdir(serving_dir)):
        if f.endswith(".py") and f != "__init__.py":
            serving_modules.append(
                (f"serving/{f}", f"predictionio_tpu.serving.{f[:-3]}"))
    # import every linted module FIRST: registration happens at import
    # time (serve_dist registers its kernel in its own module body)
    modules = {rel: importlib.import_module(modname)
               for rel, modname in serving_modules}
    registered_fns = {id(r.fn) for r in aot._REGISTRY.values()}
    # jit wrappers may nest (e.g. devicewatch.watch_jit); compare on
    # the module attribute object itself
    offenders = []
    for rel, modname in serving_modules:
        mod = modules[rel]
        for name in _jit_decorated_defs(os.path.join(PKG, rel)):
            fn = getattr(mod, name, None)
            if fn is None:
                continue
            if id(fn) not in registered_fns:
                offenders.append(f"{rel}:{name}")
    assert not offenders, (
        "jitted serving-path entry points not registered with the AOT "
        "enumerator (serving/aot.py register_jit) — they would compile "
        "lazily on the first request and reintroduce the warmup cliff:"
        "\n  " + "\n  ".join(offenders))


def test_lint_actually_detects_jit_defs(tmp_path):
    src = ("from functools import partial\nimport jax\n"
           "@partial(jax.jit, static_argnames=('k',))\n"
           "def f(x, k=1):\n    return x\n"
           "@jax.jit\ndef g(x):\n    return x\n"
           "def h(x):\n    return x\n")
    p = tmp_path / "m.py"
    p.write_text(src)
    assert _jit_decorated_defs(str(p)) == ["f", "g"]


# ---------------------------------------------------------------------------
# shape oracle: k clamp + bucket pruning
# ---------------------------------------------------------------------------

def test_serving_ks_default_clamps_to_model(monkeypatch):
    monkeypatch.delenv("PIO_AOT_KS", raising=False)
    assert aot.serving_ks(100) == (10,)
    assert aot.serving_ks(6) == (6,)   # min(num, n_items), like serving
    monkeypatch.setenv("PIO_AOT_KS", "1, 5,10, junk, -2")
    assert aot.serving_ks(100) == (1, 5, 10)
    assert aot.serving_ks(7) == (1, 5, 7)   # 10 clamps onto 7, deduped


def test_prune_buckets():
    buckets = (1, 4, 16, 64)
    # no observations: nothing pruned (a fresh process must stay safe)
    assert aot.prune_buckets(buckets, observed={}) == buckets
    # observed 3-query flushes map to bucket 4; the top bucket is
    # always kept as the overflow cap
    assert aot.prune_buckets(buckets, observed={3: 5}) == (4, 64)
    assert aot.prune_buckets(buckets, observed={1: 9, 20: 1}) == (1, 64)
    # everything observed: everything survives
    assert aot.prune_buckets(
        buckets, observed={1: 1, 3: 1, 9: 1, 40: 1}) == buckets


def test_prune_buckets_env_off(monkeypatch):
    monkeypatch.setenv("PIO_AOT_PRUNE", "0")
    assert aot.prune_buckets((1, 4, 16, 64),
                             observed={1: 5}) == (1, 4, 16, 64)


def test_pruned_serve_buckets_caps_at_batch_size(monkeypatch):
    monkeypatch.delenv("PIO_SERVE_BUCKETS", raising=False)
    # pruning pinned off: the process registry may hold flush-size
    # observations from earlier tests (by design — that histogram is
    # exactly what a live /reload prunes against)
    monkeypatch.setenv("PIO_AOT_PRUNE", "0")
    assert aot.pruned_serve_buckets(8) == (1, 4)
    assert aot.pruned_serve_buckets(64) == (1, 4, 16, 64)
    # observed sizes recorded by the batcher feed the pruning
    monkeypatch.delenv("PIO_AOT_PRUNE")
    assert aot.prune_buckets((1, 4, 16, 64), observed={2: 3}) == (4, 64)


def test_flush_scoped_buckets_resolution():
    """The batcher installs its pruned set on the worker thread for the
    duration of a flush; outside that scope — and on every other
    thread — resolution stays env/default."""
    assert protocol.pad_buckets() == (1, 4, 16, 64)
    with protocol.flush_buckets((4, 64)):
        assert protocol.pad_buckets() == (4, 64)        # scoped set wins
        assert protocol.bucket_for(2) == 4
        assert protocol.pad_buckets((1, 2)) == (1, 2)   # explicit arg wins
        # nesting restores correctly
        with protocol.flush_buckets((1, 8)):
            assert protocol.pad_buckets() == (1, 8)
        assert protocol.pad_buckets() == (4, 64)
        # other threads are unaffected
        import threading
        seen = []
        t = threading.Thread(
            target=lambda: seen.append(protocol.pad_buckets()))
        t.start(); t.join()
        assert seen == [(1, 4, 16, 64)]
    assert protocol.pad_buckets() == (1, 4, 16, 64)
    with protocol.flush_buckets(None):                  # passthrough
        assert protocol.pad_buckets() == (1, 4, 16, 64)


def test_batcher_flush_sees_its_own_buckets():
    """predict_batch inside a flush resolves the BATCHER's bucket set —
    the set whose programs the deploy prebuilt."""
    seen = []

    def flush(items):
        seen.append(protocol.pad_buckets())
        return list(items)

    from predictionio_tpu.serving import MicroBatcher
    b = MicroBatcher(flush, max_batch_size=2, max_delay_ms=1,
                     buckets=(2, 64))
    try:
        b.submit("x")
    finally:
        b.close()
    assert seen == [(2, 64)]
    assert protocol.pad_buckets() == (1, 4, 16, 64)     # nothing leaked


# ---------------------------------------------------------------------------
# AOT / lazy-jit parity: bit-identical programs
# ---------------------------------------------------------------------------

def test_topk_programs_aot_jit_parity():
    """Every enumerated (bucket, k) serving program, compiled via
    jit(...).lower().compile() from declared shapes, produces BIT-
    identical outputs to the lazy jit path."""
    import jax

    rng = np.random.RandomState(11)
    n_users, n_items, rank = 13, 8, 4
    U = jax.device_put(rng.randn(n_users, rank).astype(np.float32))
    V = jax.device_put(rng.randn(n_items, rank).astype(np.float32))
    for spec in aot.specs_topk_for_users(n_users, n_items, rank,
                                         buckets=(1, 4), ks=(1, 3)):
        bucket, k = spec.key[-2], spec.key[-1]
        compiled = spec.build()
        ix = np.asarray(rng.randint(0, n_users, bucket), dtype=np.int32)
        va, ia = jax.device_get(compiled(U, V, jax.device_put(ix)))
        vj, ij = jax.device_get(topk.topk_for_users(U, V, ix, k=k))
        assert np.array_equal(va, vj) and np.array_equal(ia, ij), spec.key
    for spec in aot.specs_topk_for_user(n_users, n_items, rank, ks=(3,)):
        compiled = spec.build()
        va, ia = jax.device_get(
            compiled(U, V, jax.device_put(np.int32(5))))
        vj, ij = jax.device_get(topk.topk_for_user(U, V, np.int32(5), k=3))
        assert np.array_equal(va, vj) and np.array_equal(ia, ij)


def test_training_program_aot_jit_parity():
    """The declared-shape-lowered scan trainer (bucket_units as the
    shape oracle) matches train_explicit(kernel="scan") bit for bit."""
    rng = np.random.RandomState(3)
    nnz, n_u, n_i, rank = 150, 11, 8, 3
    u = rng.randint(0, n_u, nnz).astype(np.int32)
    i = rng.randint(0, n_i, nnz).astype(np.int32)
    r = (rng.randint(1, 11, nnz) * 0.5).astype(np.float32)
    data = als.prepare_ratings(u, i, r, n_users=n_u, n_items=n_i,
                               chunk=64, device=True)
    U_jit, V_jit = als.train_explicit(data, rank=rank, iterations=3,
                                      seed=5, chunk=64, kernel="scan")
    compiled = als.lower_train_explicit(n_u, n_i, rank, nnz,
                                        chunk=64).compile()
    u0, v0 = als._seed_factors(5, n_u, n_i, rank)
    bu, bi = data.by_user, data.by_item
    U_aot, V_aot = compiled(
        bu.self_idx, bu.other_idx, bu.rating, bu.counts,
        bi.self_idx, bi.other_idx, bi.rating, bi.counts,
        u0, v0, 3, 0.01)
    assert np.array_equal(np.asarray(U_jit), np.asarray(U_aot))
    assert np.array_equal(np.asarray(V_jit), np.asarray(V_aot))


def test_training_program_specs_scan_only(monkeypatch):
    monkeypatch.setenv("PIO_ALS_KERNEL", "scan")
    specs = aot.training_program_specs(10, 8, 4, 100, chunk=64)
    assert [s.name for s in specs] == ["als_train_scan"]
    monkeypatch.setenv("PIO_ALS_KERNEL", "hybrid")
    assert aot.training_program_specs(10, 8, 4, 100, chunk=64) == []


def test_prebuild_reports_and_memoizes():
    import jax

    rng = np.random.RandomState(0)
    U = jax.device_put(rng.randn(17, 3).astype(np.float32))
    V = jax.device_put(rng.randn(5, 3).astype(np.float32))
    specs = aot.specs_topk_for_users(17, 5, 3, (1, 4), (2,),
                                     arrays=(U, V))
    rep = aot.prebuild(specs)
    assert rep.summary()["programs"] == 2
    assert rep.summary()["failed"] == 0
    assert rep.summary()["compiled"] + rep.summary()["memoized"] == 2
    # second prebuild of the same keys is memoized (a /reload of same-
    # shape factors costs nothing)
    rep2 = aot.prebuild(specs)
    assert rep2.summary()["memoized"] == 2


def test_prebuild_failure_degrades_to_lazy():
    bad = aot.ProgramSpec(name="broken", key=("broken", 1),
                          lower=lambda: (_ for _ in ()).throw(
                              RuntimeError("boom")))
    rep = aot.prebuild([bad])
    assert rep.summary()["failed"] == 1   # logged + counted, not raised
    # the deliberate failure must not poison later doctor green paths
    # (the registry is process-global and doctor reads absolutes)
    _clear_counter_family("pio_aot_programs_total")


# ---------------------------------------------------------------------------
# compile-cache artifact: export / import / graceful mismatch
# ---------------------------------------------------------------------------

def _fake_cache(d, entries):
    os.makedirs(d, exist_ok=True)
    for name, payload in entries.items():
        with open(os.path.join(d, name), "wb") as f:
            f.write(payload)


def test_cache_artifact_roundtrip(tmp_path):
    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    _fake_cache(src, {"old_entry": b"OLD"})
    before = model_io.cache_snapshot(src)
    _fake_cache(src, {"new_a": b"AAAA", "new_b": b"BB"})
    blob = model_io.export_compile_cache(src, since=before)
    assert blob is not None
    summary = model_io.import_compile_cache(blob, dst)
    assert summary == {"imported": 2, "skipped": 0, "reason": ""}
    with open(os.path.join(dst, "new_a"), "rb") as f:
        assert f.read() == b"AAAA"
    assert not os.path.exists(os.path.join(dst, "old_entry"))
    # existing files are never overwritten
    summary = model_io.import_compile_cache(blob, dst)
    assert summary["imported"] == 0 and summary["skipped"] == 2


def test_cache_artifact_empty_delta_exports_nothing(tmp_path):
    src = str(tmp_path / "src")
    _fake_cache(src, {"only": b"X"})
    before = model_io.cache_snapshot(src)
    assert model_io.export_compile_cache(src, since=before) is None


def test_cache_artifact_mismatch_degrades_gracefully(tmp_path):
    """A jaxlib/platform mismatch — the portability hazard of shipped
    cache entries (KNOWN_ISSUES #9) — imports nothing and reports why,
    instead of erroring or seeding unusable entries."""
    import pickle

    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    _fake_cache(src, {"e1": b"X"})
    blob = model_io.export_compile_cache(src)
    artifact = pickle.loads(blob)
    artifact["meta"]["jaxlib"] = "0.0.0-elsewhere"
    summary = model_io.import_compile_cache(pickle.dumps(artifact), dst)
    assert summary["imported"] == 0 and summary["skipped"] == 1
    assert "mismatch" in summary["reason"]
    assert not os.path.exists(os.path.join(dst, "e1"))
    # corrupt blob: summary, not an exception
    summary = model_io.import_compile_cache(b"\x80garbage", dst)
    assert summary["imported"] == 0 and summary["reason"]


def test_cache_artifact_refuses_path_traversal(tmp_path):
    import pickle

    dst = str(tmp_path / "dst")
    blob = pickle.dumps({
        "format": "pio-jaxcache-v1", "meta": model_io.cache_fingerprint(),
        "entries": {"../escape": b"X", ".hidden": b"Y", "fine": b"Z"}})
    summary = model_io.import_compile_cache(blob, dst)
    assert summary["imported"] == 1 and summary["skipped"] == 2
    assert not os.path.exists(str(tmp_path / "escape"))


def test_export_train_artifact_inserts_models_row(memory_storage,
                                                  tmp_path):
    """The `pio train` side: serving programs AOT-build from declared
    shapes (host numpy model — no device residency needed) and the
    cache delta lands in the Models store under <instance>.jaxcache."""
    from predictionio_tpu.models.recommendation.als_algorithm import (
        ALSAlgorithm, ALSModel,
    )
    from predictionio_tpu.data.bimap import BiMap

    cache = str(tmp_path / "cache")
    _fake_cache(cache, {"seed": b"S"})
    before = model_io.cache_snapshot(cache)
    _fake_cache(cache, {"train_entry": b"T"})
    rng = np.random.RandomState(1)
    model = ALSModel(
        rank=3,
        user_factors=rng.randn(6, 3).astype(np.float32),
        item_factors=rng.randn(4, 3).astype(np.float32),
        user_vocab=BiMap.string_int([f"u{i}" for i in range(6)]),
        item_vocab=BiMap.string_int([f"i{i}" for i in range(4)]))
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=3))
    summary = aot.export_train_artifact(
        memory_storage, "inst-1", [algo], [model], cache, before)
    assert summary["programs"] >= 1 and summary["failed"] == 0
    assert summary["entries"] == 1
    row = memory_storage.get_model_data_models().get(
        model_io.cache_artifact_id("inst-1"))
    assert row is not None
    imported = model_io.import_compile_cache(
        row.models, str(tmp_path / "replica"))
    assert imported["imported"] == 1


# ---------------------------------------------------------------------------
# deploy integration: prebuild before ready, explicit warmup mark,
# artifact import, time-to-ready
# ---------------------------------------------------------------------------

def test_deploy_prebuilds_and_marks_warmup(memory_storage):
    engine, _iid = _train_engine(memory_storage)
    telemetry.set_enabled(True)
    assert not devicewatch.serving_warmup_done()
    api = QueryAPI(storage=memory_storage, engine=engine,
                   config=ServerConfig(batching="on"))
    try:
        # warmup end is the AOT-complete mark, not a flush count
        assert devicewatch.serving_warmup_done()
        assert api.time_to_ready_s is not None
        st, info = api.handle("GET", "/")
        assert st == 200
        assert info["aot"]["enabled"] is True
        assert info["aot"]["programs"] >= 1
        assert info["aot"]["failed"] == 0
        assert info["aot"]["timeToReadyS"] is not None
        st, rz = api.handle("GET", "/readyz")
        assert st == 200 and rz["aotPrograms"] == info["aot"]["programs"]
        # the metrics surface doctor scrapes
        _st, payload, _h = api.handle("GET", "/metrics")
        assert "pio_aot_programs_total" in payload
        assert "pio_time_to_ready_seconds" in payload
        # /debug/device.json carries the same summary
        _st, dev, _h = api.handle("GET", "/debug/device.json")
        assert json.loads(dev)["aot"]["programs"] >= 1
        # a post-ready query compiles NOTHING: the prebuilt program set
        # covers the standard bucketed path for the declared k
        base = devicewatch.post_warmup_recompiles()
        st, body = api.handle("POST", "/queries.json", body=json.dumps(
            {"user": "u1", "num": 10}).encode())
        assert st == 200 and body["itemScores"]
        assert devicewatch.post_warmup_recompiles() == base
    finally:
        api.close()


def test_deploy_imports_cache_artifact(memory_storage, tmp_path,
                                       monkeypatch):
    engine, iid = _train_engine(memory_storage)
    art_src = str(tmp_path / "train_cache")
    _fake_cache(art_src, {"shipped_entry": b"E"})
    memory_storage.get_model_data_models().insert(Model(
        id=model_io.cache_artifact_id(iid),
        models=model_io.export_compile_cache(art_src)))
    replica_cache = str(tmp_path / "replica_cache")
    monkeypatch.setattr(aot, "ensure_persistent_cache",
                        lambda: replica_cache)
    api = QueryAPI(storage=memory_storage, engine=engine)
    try:
        st, info = api.handle("GET", "/")
        assert info["aot"]["cacheImport"]["imported"] == 1
        assert os.path.exists(os.path.join(replica_cache,
                                           "shipped_entry"))
    finally:
        api.close()


def test_deploy_mismatched_artifact_never_errors(memory_storage,
                                                 tmp_path, monkeypatch):
    import pickle

    engine, iid = _train_engine(memory_storage)
    blob = pickle.dumps({
        "format": "pio-jaxcache-v1",
        "meta": {"jax": "?", "jaxlib": "other", "backend": "mars"},
        "entries": {"e": b"X"}})
    memory_storage.get_model_data_models().insert(Model(
        id=model_io.cache_artifact_id(iid), models=blob))
    replica_cache = str(tmp_path / "replica_cache")
    monkeypatch.setattr(aot, "ensure_persistent_cache",
                        lambda: replica_cache)
    api = QueryAPI(storage=memory_storage, engine=engine)
    try:
        st, info = api.handle("GET", "/")
        ci = info["aot"]["cacheImport"]
        assert ci["imported"] == 0 and "mismatch" in ci["reason"]
        # the deploy still serves (lazy compile fallback)
        st, body = api.handle("POST", "/queries.json", body=json.dumps(
            {"user": "u1", "num": 3}).encode())
        assert st == 200 and body["itemScores"]
    finally:
        api.close()


def test_pio_aot_0_wire_byte_identical(memory_storage, monkeypatch):
    """The escape hatch: PIO_AOT=0 restores the pre-AOT deploy exactly
    — legacy `GET /` key set, no warmup mark, byte-identical query
    responses."""
    engine, _iid = _train_engine(memory_storage)
    body = json.dumps({"user": "u2", "num": 4}).encode()

    monkeypatch.setenv("PIO_AOT", "0")
    devicewatch.reset_watchdog()
    api_off = QueryAPI(storage=memory_storage, engine=engine)
    st_off, resp_off = api_off.handle("POST", "/queries.json", body=body)
    _, info_off = api_off.handle("GET", "/")
    assert set(info_off) == {
        "status", "engineInstance", "algorithms", "requestCount",
        "avgServingSec", "lastServingSec", "degradedCount", "draining",
        "serverStartTime", "generation", "batching"}
    assert not devicewatch.serving_warmup_done()
    _, rz_off = api_off.handle("GET", "/readyz")
    assert "aotPrograms" not in rz_off
    api_off.close()

    monkeypatch.delenv("PIO_AOT")
    api_on = QueryAPI(storage=memory_storage, engine=engine)
    st_on, resp_on = api_on.handle("POST", "/queries.json", body=body)
    api_on.close()
    assert (st_off, json.dumps(resp_off)) == (st_on, json.dumps(resp_on))


def test_aot_off_config_mode(memory_storage):
    engine, _iid = _train_engine(memory_storage)
    api = QueryAPI(storage=memory_storage, engine=engine,
                   config=ServerConfig(aot="off"))
    try:
        _, info = api.handle("GET", "/")
        assert "aot" not in info
    finally:
        api.close()
    with pytest.raises(ValueError, match="auto/on/off"):
        QueryAPI(storage=memory_storage, engine=engine,
                 config=ServerConfig(aot="bogus"))


def test_deploy_installs_pruned_buckets(memory_storage, monkeypatch):
    """The deploy's bucket set is capped at the batcher's max batch
    size and handed to the batcher, so flush padding resolves exactly
    the prebuilt programs. (Pruning is pinned off here: the process
    registry may hold flush-size observations from earlier tests.)"""
    monkeypatch.setenv("PIO_AOT_PRUNE", "0")
    engine, _iid = _train_engine(memory_storage)
    api = QueryAPI(storage=memory_storage, engine=engine,
                   config=ServerConfig(batching="on", batch_max_size=8))
    try:
        _, info = api.handle("GET", "/")
        assert info["aot"]["buckets"] == [1, 4]
        assert info["batching"]["buckets"] == [1, 4]
        # outside any flush, process defaults are untouched
        assert protocol.pad_buckets() == (1, 4, 16, 64)
    finally:
        api.close()


# ---------------------------------------------------------------------------
# doctor + benchtrend satellites
# ---------------------------------------------------------------------------

def _scraped(metrics_body="", device=None):
    ok = {"status": 200, "body": json.dumps({"status": "ok"})}
    return {
        "url": "http://t", "healthz": dict(ok), "readyz": dict(ok),
        "metrics": {"status": 200, "body": metrics_body},
        "traces": {"status": 404, "body": ""},
        "device": {"status": 200,
                   "body": json.dumps(device or {"telemetry": True})},
    }


def _aot_check(checks):
    return next(c for c in checks if c[0] == "aot")


def test_doctor_aot_line():
    from predictionio_tpu.tools import doctor

    # no AOT metrics at all: informational, not a failure
    checks = doctor.diagnose(_scraped())
    assert _aot_check(checks)[1] == doctor.NA

    body = ('pio_aot_programs_total{status="primed"} 4\n'
            'pio_aot_programs_total{status="memoized"} 4\n'
            'pio_aot_prebuild_seconds 2.5\n'
            'pio_time_to_ready_seconds{server="query#0"} 3.25\n')
    check = _aot_check(doctor.diagnose(_scraped(body)))
    assert check[1] == doctor.OK
    assert "8 programs" in check[2] and "50% hit" in check[2]
    assert "ready in 3.2" in check[2]

    # failed builds are RED (lazy compiles back on the latency path)
    body_fail = body + 'pio_aot_programs_total{status="failed"} 1\n'
    assert _aot_check(doctor.diagnose(_scraped(body_fail)))[1] == doctor.RED

    # over the 10 s warm-replica target: WARN
    slow = body.replace("3.25", "45.0")
    assert _aot_check(doctor.diagnose(_scraped(slow)))[1] == doctor.WARN


def test_benchtrend_absolute_time_to_ready_gate():
    from predictionio_tpu.tools import benchtrend

    def rnd(ttr, entries_before):
        return {"label": "rX", "path": "x", "metric": "m", "value": 1.0,
                "detail": {"time_to_ready_s": ttr,
                           "compile_cache": {
                               "before": {"entries": entries_before}}}}

    # warm cache + breach: gated even with NO prior round
    failures = benchtrend.gate([rnd(12.5, 3)])
    assert failures and "time_to_ready_s" in failures[0]
    # warm cache, inside the ceiling: green
    assert benchtrend.gate([rnd(4.0, 3)]) == []
    # cold cache legitimately pays compiles: not gated
    assert benchtrend.gate([rnd(120.0, 0)]) == []


def test_time_to_ready_gauge_exported(memory_storage):
    engine, _iid = _train_engine(memory_storage)
    telemetry.set_enabled(True)
    api = QueryAPI(storage=memory_storage, engine=engine)
    try:
        _st, payload, _h = api.handle("GET", "/metrics")
        values = [float(ln.rsplit(" ", 1)[1])
                  for ln in payload.splitlines()
                  if ln.startswith("pio_time_to_ready_seconds{")]
        # per-server labels; earlier instances whose constructor raised
        # (deliberately, in other tests) legitimately sit at 0
        assert values and max(values) > 0
    finally:
        api.close()


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
