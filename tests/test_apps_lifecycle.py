"""tools/apps.py lifecycle coverage (commands/App.scala +
AccessKey.scala parity): app new/show/delete, channelNew/channelDelete
including event-store cleanup, data-delete truncation, and the
delete-with-live-keys ordering (channel stores torn down before keys
and the meta row)."""

import pytest

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.tools import apps
from predictionio_tpu.tools.apps import CommandError


def _ev(name="view", eid="u1"):
    return Event(event=name, entity_type="user", entity_id=eid,
                 properties=DataMap({}))


class TestAppCreateShow:
    def test_create_show_list(self, memory_storage):
        desc = apps.create("Shop", description="store front",
                           storage=memory_storage)
        assert desc.app.name == "Shop" and desc.app.id > 0
        assert len(desc.keys) == 1 and desc.keys[0].appid == desc.app.id
        assert desc.keys[0].key            # generated, non-empty
        # event store initialized: an insert works immediately
        memory_storage.get_events().insert(_ev(), desc.app.id)

        shown, channels = apps.show("Shop", storage=memory_storage)
        assert shown.app.id == desc.app.id and channels == []

        apps.create("Bazaar", storage=memory_storage)
        listed = apps.list_apps(storage=memory_storage)
        assert [d.app.name for d in listed] == ["Bazaar", "Shop"]

    def test_create_duplicate_name_refused(self, memory_storage):
        apps.create("Shop", storage=memory_storage)
        with pytest.raises(CommandError, match="already exists"):
            apps.create("Shop", storage=memory_storage)

    def test_create_explicit_id(self, memory_storage):
        desc = apps.create("Pinned", app_id=42, storage=memory_storage)
        assert desc.app.id == 42
        with pytest.raises(CommandError, match="already exists"):
            apps.create("Other", app_id=42, storage=memory_storage)
        with pytest.raises(CommandError, match="invalid"):
            apps.create("Neg", app_id=-1, storage=memory_storage)

    def test_create_custom_key(self, memory_storage):
        desc = apps.create("Keyed", access_key="my-key",
                           storage=memory_storage)
        assert desc.keys[0].key == "my-key"
        row = memory_storage.get_meta_data_access_keys().get("my-key")
        assert row is not None and row.appid == desc.app.id

    def test_show_missing(self, memory_storage):
        with pytest.raises(CommandError, match="does not exist"):
            apps.show("ghost", storage=memory_storage)


class TestChannels:
    def test_channel_new_show_delete(self, memory_storage):
        desc = apps.create("Shop", storage=memory_storage)
        ch = apps.channel_new("Shop", "mobile", storage=memory_storage)
        assert ch.name == "mobile" and ch.appid == desc.app.id
        # the channel's event store exists: channel-scoped insert works
        memory_storage.get_events().insert(_ev(), desc.app.id, ch.id)
        _, channels = apps.show("Shop", storage=memory_storage)
        assert [c.name for c in channels] == ["mobile"]

        apps.channel_delete("Shop", "mobile", storage=memory_storage)
        _, channels = apps.show("Shop", storage=memory_storage)
        assert channels == []

    def test_channel_validation(self, memory_storage):
        apps.create("Shop", storage=memory_storage)
        apps.channel_new("Shop", "mobile", storage=memory_storage)
        with pytest.raises(CommandError, match="already exists"):
            apps.channel_new("Shop", "mobile", storage=memory_storage)
        with pytest.raises(CommandError, match="invalid"):
            apps.channel_new("Shop", "way_too_long_channel_name",
                             storage=memory_storage)
        with pytest.raises(CommandError, match="invalid"):
            apps.channel_new("Shop", "bad_chars!", storage=memory_storage)
        with pytest.raises(CommandError, match="does not exist"):
            apps.channel_new("ghost", "mobile", storage=memory_storage)
        with pytest.raises(CommandError, match="doesn't exist"):
            apps.channel_delete("Shop", "desktop", storage=memory_storage)

    def test_channel_new_rolls_back_on_store_failure(self, memory_storage,
                                                     monkeypatch):
        apps.create("Shop", storage=memory_storage)
        monkeypatch.setattr(memory_storage.get_events(), "init",
                            lambda app_id, channel_id=None: False)
        with pytest.raises(CommandError, match="initialize Event Store"):
            apps.channel_new("Shop", "mobile", storage=memory_storage)
        # the half-made channel row was rolled back
        _, channels = apps.show("Shop", storage=memory_storage)
        assert channels == []


class TestDelete:
    def test_delete_with_live_keys_and_channels(self, memory_storage):
        """The App.scala:128-193 ordering: channel event stores first,
        then the app store, THEN keys, then the meta row — so a failed
        event-store removal leaves the keys intact (the app is still
        addressable for a retry)."""
        desc = apps.create("Shop", storage=memory_storage)
        apps.accesskey_new("Shop", key="extra-key", storage=memory_storage)
        ch = apps.channel_new("Shop", "mobile", storage=memory_storage)
        memory_storage.get_events().insert(_ev(), desc.app.id)
        memory_storage.get_events().insert(_ev(), desc.app.id, ch.id)

        apps.delete("Shop", storage=memory_storage)
        keys = memory_storage.get_meta_data_access_keys()
        assert memory_storage.get_meta_data_apps().get_by_name("Shop") is None
        assert keys.get("extra-key") is None      # both keys cleaned up
        assert keys.get_by_appid(desc.app.id) == []
        assert memory_storage.get_meta_data_channels().get_by_appid(
            desc.app.id) == []
        with pytest.raises(CommandError, match="does not exist"):
            apps.delete("Shop", storage=memory_storage)

    def test_delete_keeps_keys_when_store_removal_fails(self, memory_storage,
                                                        monkeypatch):
        desc = apps.create("Shop", access_key="live-key",
                           storage=memory_storage)
        monkeypatch.setattr(memory_storage.get_events(), "remove",
                            lambda app_id, channel_id=None: False)
        with pytest.raises(CommandError, match="Error removing Event Store"):
            apps.delete("Shop", storage=memory_storage)
        # ordering contract: nothing after the failed store removal ran
        keys = memory_storage.get_meta_data_access_keys()
        assert keys.get("live-key") is not None
        assert memory_storage.get_meta_data_apps().get(desc.app.id) is not None

    def test_data_delete_truncates(self, memory_storage):
        desc = apps.create("Shop", storage=memory_storage)
        ch = apps.channel_new("Shop", "mobile", storage=memory_storage)
        events = memory_storage.get_events()
        events.insert(_ev(), desc.app.id)
        events.insert(_ev(), desc.app.id, ch.id)

        apps.data_delete("Shop", storage=memory_storage)
        assert list(events.find(app_id=desc.app.id)) == []
        # channel data untouched without --all
        assert len(list(events.find(app_id=desc.app.id,
                                    channel_id=ch.id))) == 1

        events.insert(_ev(), desc.app.id)
        apps.data_delete("Shop", delete_all=True, storage=memory_storage)
        assert list(events.find(app_id=desc.app.id)) == []
        assert list(events.find(app_id=desc.app.id, channel_id=ch.id)) == []

        apps.data_delete("Shop", channel="mobile", storage=memory_storage)
        with pytest.raises(CommandError, match="doesn't exist"):
            apps.data_delete("Shop", channel="desktop",
                             storage=memory_storage)


class TestAccessKeys:
    def test_key_lifecycle(self, memory_storage):
        apps.create("Shop", access_key="k0", storage=memory_storage)
        k = apps.accesskey_new("Shop", key="k1", events=("view", "buy"),
                               storage=memory_storage)
        assert k.key == "k1" and k.events == ("view", "buy")
        keys = apps.accesskey_list("Shop", storage=memory_storage)
        assert {x.key for x in keys} == {"k0", "k1"}
        assert len(apps.accesskey_list(storage=memory_storage)) == 2

        apps.accesskey_delete("k1", storage=memory_storage)
        with pytest.raises(CommandError, match="does not exist"):
            apps.accesskey_delete("k1", storage=memory_storage)
        with pytest.raises(CommandError, match="does not exist"):
            apps.accesskey_new("ghost", storage=memory_storage)
        with pytest.raises(CommandError, match="does not exist"):
            apps.accesskey_list("ghost", storage=memory_storage)
