"""Async transport + group-commit WAL acceptance suite.

Three contracts from the transport rewrite (data/api/http.py,
PIO_TRANSPORT=async) and the WAL group commit (data/storage/eventlog.py,
PIO_WAL_GROUP_MS):

1. **Wire-byte parity**: the threaded and async transports emit
   identical bytes for every endpoint — status line, header set and
   order, payload — with only the Date clock value differing. Asserted
   over a deterministic probe set on all three daemons (query, event,
   storage) plus a synthetic handler covering every payload shape the
   transport serializes (dict/str/bytes/extra-headers/500/non-finite).
2. **HTTP/1.1 pipelining**: pipelined requests on one connection are
   answered in request order, keep-alive survives, and a drain
   (shutdown) under a concurrent burst loses zero acknowledged events.
3. **Group-commit durability**: an insert's return (the 201 ack) implies
   its events are in the WAL; a crash mid-group-write loses only
   unacknowledged events and the next writer repairs the torn tail —
   the PR 3 contracts, unchanged under coalescing.
"""

import json
import os
import re
import socket
import threading
import time

import pytest

from predictionio_tpu.common import resilience, tracing
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.data.api.service import EventAPI
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import AccessKey, App, Storage
from predictionio_tpu.data.storage import eventlog
from predictionio_tpu.data.storage.remote import StorageRPCAPI


@pytest.fixture(autouse=True)
def _no_fault_leak():
    resilience.clear()
    yield
    resilience.clear()


def _el_env(tmp_path):
    return {
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
        "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path / "el"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    }


def _mk(eid, iid, rating=3.0):
    import datetime as dt
    return Event(event="rate", entity_type="user", entity_id=eid,
                 target_entity_type="item", target_entity_id=iid,
                 properties=DataMap({"rating": rating}),
                 event_time=dt.datetime(2021, 1, 1,
                                        tzinfo=dt.timezone.utc))


def _raw_response(port, request: bytes) -> bytes:
    """One request -> the full raw response bytes (headers + body read
    by Content-Length, so keep-alive servers work)."""
    sock = socket.create_connection(("127.0.0.1", port))
    sock.sendall(request)
    f = sock.makefile("rb")
    head = b""
    clen = 0
    while True:
        line = f.readline()
        assert line, f"connection closed before headers: {head!r}"
        head += line
        if line in (b"\r\n", b"\n"):
            break
        if line.lower().startswith(b"content-length:"):
            clen = int(line.split(b":", 1)[1])
    body = f.read(clen) if clen else b""
    sock.close()
    return head + body


def _req(method, target, body=b"", headers=()):
    head = [f"{method} {target} HTTP/1.1", "Host: parity"]
    head.extend(f"{k}: {v}" for k, v in headers)
    if body:
        head.append(f"Content-Length: {len(body)}")
    return ("\r\n".join(head) + "\r\n\r\n").encode() + body


_DATE = re.compile(rb"Date: [^\r\n]+")


def _mask_date(raw: bytes) -> bytes:
    return _DATE.sub(b"Date: X", raw)


def _mask_numbers(raw: bytes) -> bytes:
    return re.sub(rb"[0-9.e+-]+", b"N", _mask_date(raw))


class _ShapesAPI:
    """Deterministic handler covering every payload shape the shared
    dispatch path serializes."""

    def handle(self, method, path, query=None, body=b"", headers=None):
        if path == "/dict":
            return 200, {"m": method, "q": query, "n": len(body)}
        if path == "/text":
            return 200, "<html>hi</html>"
        if path == "/blob":
            return 200, b"\x00\x01PIOC"
        if path == "/retry":
            return 503, {"busy": True}, {"Retry-After": "7"}
        if path == "/ctype":
            return 200, "plain text", {"Content-Type": "text/plain",
                                       "X-Extra": "yes"}
        if path == "/boom":
            raise RuntimeError("handler exploded")
        if path == "/nan":
            return 200, {"score": float("nan")}
        return 404, {"message": "Not Found"}


def _pair(api):
    """The same live api on both transports -> (threaded_port, async_port,
    shutdown)."""
    s1, p1 = serve_background(api, transport="threaded")
    s2, p2 = serve_background(api, transport="async")

    def stop():
        s1.shutdown()
        s2.shutdown()
    return p1, p2, stop


def _assert_parity(p1, p2, probes, mask=None):
    mask = mask or {}
    for name, request in probes:
        r1 = _raw_response(p1, request)
        r2 = _raw_response(p2, request)
        m = mask.get(name, _mask_date)
        assert m(r1) == m(r2), (
            f"wire bytes differ on {name}:\n"
            f"threaded: {m(r1)!r}\nasync:    {m(r2)!r}")


def test_payload_shapes_wire_byte_identical():
    """Every serialization branch of the shared dispatch path emits the
    same bytes on both transports."""
    p1, p2, stop = _pair(_ShapesAPI())
    try:
        _assert_parity(p1, p2, [
            ("dict", _req("GET", "/dict?a=1&b=")),
            ("dict-post", _req("POST", "/dict", b'{"x": 1}')),
            ("text", _req("GET", "/text")),
            ("blob", _req("GET", "/blob")),
            ("retry-after", _req("GET", "/retry")),
            ("handler-ctype", _req("GET", "/ctype")),
            ("handler-raise", _req("GET", "/boom")),
            ("non-finite", _req("GET", "/nan")),
            ("404", _req("GET", "/nope")),
            ("put", _req("PUT", "/dict")),
            ("delete", _req("DELETE", "/dict")),
        ])
    finally:
        stop()


def test_event_daemon_wire_byte_identical(memory_storage):
    """The event server's endpoint surface, including auth failures, the
    batch cap, webhooks presence checks and every /debug/* route."""
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "ParityApp"))
    memory_storage.get_meta_data_access_keys().insert(
        AccessKey(key="pk", appid=app_id, events=[]))
    ev = memory_storage.get_events()
    ev.init(app_id)
    # fixed times -> fully deterministic GET /events.json bytes
    ev.insert_batch([_mk("u1", "i1"), _mk("u2", "i2", rating=4.0)], app_id)
    api = EventAPI(storage=memory_storage)
    p1, p2, stop = _pair(api)
    oversized = json.dumps(
        [{"event": "e", "entityType": "u", "entityId": "x"}] * 51).encode()
    try:
        _assert_parity(p1, p2, [
            ("root", _req("GET", "/")),
            ("healthz", _req("GET", "/healthz")),
            ("readyz", _req("GET", "/readyz")),
            ("auth-missing", _req("GET", "/events.json")),
            ("auth-bad", _req("GET", "/events.json?accessKey=wrong")),
            ("events-list", _req("GET", "/events.json?accessKey=pk")),
            ("events-405", _req("PUT", "/events.json?accessKey=pk")),
            ("batch-cap", _req("POST", "/batch/events.json?accessKey=pk",
                               oversized)),
            ("batch-400", _req("POST", "/batch/events.json?accessKey=pk",
                               b"not json")),
            ("webhook-check", _req("GET", "/webhooks/segmentio.json"
                                          "?accessKey=pk")),
            ("plugins", _req("GET", "/plugins.json")),
            ("404", _req("GET", "/never")),
            ("traces", _req("GET", "/traces.json?limit=4")),
            ("slow-ring", _req("GET", "/debug/slow.json")),
            ("device-json", _req("GET", "/debug/device.json")),
            ("profile-list", _req("GET", "/debug/profile")),
            ("metrics", _req("GET", "/metrics")),
        ], mask={"metrics": _mask_numbers, "device-json": _mask_numbers})
    finally:
        stop()


def test_storage_daemon_wire_byte_identical(memory_storage):
    """The storage RPC daemon: health, key auth, JSON RPC, binary model
    routes and the deadline fast-fail, byte-for-byte on both
    transports."""
    memory_storage.get_meta_data_apps().insert(App(0, "S"))
    api = StorageRPCAPI(memory_storage, key="sekrit")
    p1, p2, stop = _pair(api)
    rpc = json.dumps({"dao": "apps", "method": "get_all"}).encode()
    try:
        _assert_parity(p1, p2, [
            ("healthz", _req("GET", "/healthz")),
            ("readyz", _req("GET", "/readyz")),
            ("root-unauth", _req("GET", "/")),
            ("root", _req("GET", "/", headers=[("X-PIO-Storage-Key",
                                                "sekrit")])),
            ("rpc", _req("POST", "/rpc", rpc,
                         headers=[("X-PIO-Storage-Key", "sekrit")])),
            ("rpc-bad-dao", _req(
                "POST", "/rpc",
                json.dumps({"dao": "zap", "method": "x"}).encode(),
                headers=[("X-PIO-Storage-Key", "sekrit")])),
            ("model-404", _req("GET", "/rpc/model?id=zzz",
                               headers=[("X-PIO-Storage-Key", "sekrit")])),
            ("deadline-spent", _req(
                "POST", "/rpc", rpc,
                headers=[("X-PIO-Storage-Key", "sekrit"),
                         ("X-PIO-Deadline-Ms", "0")])),
            ("unknown-route", _req("GET", "/rpc/never",
                                   headers=[("X-PIO-Storage-Key",
                                             "sekrit")])),
            ("metrics", _req("GET", "/metrics")),
        ], mask={"metrics": _mask_numbers})
    finally:
        stop()


def test_query_daemon_wire_byte_identical(memory_storage):
    """The query server's deterministic surface rides the same shared
    dispatch path; parity holds there too."""
    from test_telemetry import _trained_query_api
    api, _ = _trained_query_api(memory_storage)
    p1, p2, stop = _pair(api)
    try:
        _assert_parity(p1, p2, [
            ("healthz", _req("GET", "/healthz")),
            ("readyz", _req("GET", "/readyz")),
            ("404", _req("GET", "/never")),
            ("query", _req("POST", "/queries.json",
                           json.dumps({"user": "u1", "num": 3}).encode())),
            ("query-400", _req("POST", "/queries.json", b"nope")),
            ("slow-ring", _req("GET", "/debug/slow.json")),
            ("device-json", _req("GET", "/debug/device.json")),
            ("metrics", _req("GET", "/metrics")),
        ], mask={"metrics": _mask_numbers,
                 "device-json": _mask_numbers,
                 # serving latencies ride the payload (requestCount etc.
                 # are not in /queries.json, but scores are floats)
                 "query": _mask_numbers})
    finally:
        stop()
        api.close()


def test_trace_header_adopted_on_both_transports(memory_storage):
    """An incoming X-PIO-Trace is adopted identically: the request's
    spans land in the (shared) trace ring under the caller's trace id,
    and the response bytes are unchanged by the header."""
    api = EventAPI(storage=memory_storage)
    p1, p2, stop = _pair(api)
    try:
        for port, tid in ((p1, "aaaa000000000001"),
                          (p2, "bbbb000000000002")):
            plain = _raw_response(port, _req("GET", "/healthz"))
            traced = _raw_response(port, _req(
                "GET", "/healthz",
                headers=[("X-PIO-Trace", f"{tid}-00000001")]))
            assert _mask_date(plain) == _mask_date(traced)
            snap = tracing.snapshot(trace_id=tid)
            spans = snap["traces"][0]["spans"] if snap["traces"] else []
            assert any(s["name"] == "server:/healthz" for s in spans), \
                f"trace {tid} not adopted: {snap}"
    finally:
        stop()


def test_pipelined_requests_answered_in_order():
    """HTTP/1.1 pipelining: many requests written back-to-back on one
    keep-alive connection come back complete and in request order, even
    though the async transport executes them concurrently."""
    class Echo:
        def handle(self, method, path, query=None, body=b"", headers=None):
            n = int(query.get("n", "0"))
            if n == 0:
                time.sleep(0.05)   # the FIRST response must still win
            return 200, {"n": n}

    server, port = serve_background(Echo(), transport="async")
    try:
        sock = socket.create_connection(("127.0.0.1", port))
        k = 12
        sock.sendall(b"".join(
            _req("GET", f"/e?n={j}") for j in range(k)))
        f = sock.makefile("rb")
        got = []
        for _ in range(k):
            line = f.readline()
            assert b"200" in line
            clen = 0
            while True:
                h = f.readline()
                if h in (b"\r\n", b""):
                    break
                if h.lower().startswith(b"content-length:"):
                    clen = int(h.split(b":", 1)[1])
            got.append(json.loads(f.read(clen))["n"])
        assert got == list(range(k))
        sock.close()
    finally:
        server.shutdown()


def test_connection_close_and_http10_semantics():
    """Connection: close and HTTP/1.0 requests end the connection after
    one response on the async transport (keep-alive otherwise)."""
    server, port = serve_background(_ShapesAPI(), transport="async")
    try:
        sock = socket.create_connection(("127.0.0.1", port))
        sock.sendall(_req("GET", "/dict", headers=[("Connection",
                                                    "close")]))
        data = sock.recv(1 << 16)
        assert b"200 OK" in data
        sock.settimeout(5)
        assert sock.recv(1024) == b""   # server closed
        sock.close()
        sock = socket.create_connection(("127.0.0.1", port))
        sock.sendall(b"GET /dict HTTP/1.0\r\nHost: x\r\n\r\n")
        sock.settimeout(5)
        chunks = b""
        while True:
            got = sock.recv(1 << 16)
            if not got:
                break
            chunks += got
        assert b"200 OK" in chunks
        sock.close()
    finally:
        server.shutdown()


@pytest.mark.chaos
def test_async_drain_under_burst_loses_zero_acked_events(tmp_path,
                                                         monkeypatch):
    """SIGTERM-equivalent drain (server.shutdown) during a concurrent
    ingest burst: every event whose batch was ACKNOWLEDGED (HTTP 200
    with per-item 201s) is present in a freshly-opened store exactly
    once — the async loop finishes admitted requests, and the ack only
    ever follows the WAL group commit."""
    monkeypatch.setenv("PIO_TRANSPORT", "async")
    monkeypatch.setenv("PIO_WAL_GROUP_MS", "2")
    monkeypatch.setenv("PIO_WAL_FSYNC", "off")
    storage = Storage(env=_el_env(tmp_path))
    app_id = storage.get_meta_data_apps().insert(App(0, "DrainApp"))
    storage.get_meta_data_access_keys().insert(
        AccessKey(key="dk", appid=app_id, events=[]))
    storage.get_events().init(app_id)
    api = EventAPI(storage=storage)
    server, port = serve_background(api)
    acked: set = set()
    lock = threading.Lock()

    def pump(tid):
        import http.client
        conn = http.client.HTTPConnection("127.0.0.1", port)
        for b in range(40):
            marker = f"t{tid}b{b}"
            body = json.dumps([{
                "event": "rate", "entityType": "user",
                "entityId": f"{marker}e{k}",
                "targetEntityType": "item", "targetEntityId": "i0",
                "properties": {"rating": 1.0}} for k in range(5)]).encode()
            try:
                conn.request("POST",
                             f"/batch/events.json?accessKey=dk",
                             body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = resp.read()
            except Exception:
                return   # drain severed us: this batch is unacknowledged
            if resp.status == 200 and all(
                    r["status"] == 201 for r in json.loads(payload)):
                with lock:
                    acked.add(marker)
            time.sleep(0.001)

    threads = [threading.Thread(target=pump, args=(t,)) for t in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.15)
    server.shutdown()          # drain mid-burst
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "a client hung through drain"
    assert acked, "burst produced no acknowledged batches"

    # crash-restart view: a FRESH store over the same directory (no
    # flush/close of the writer) must hold every acked batch, exactly once
    fresh = Storage(env=_el_env(tmp_path))
    seen: dict = {}
    for e in fresh.get_events().find(app_id):
        seen[e.entity_id] = seen.get(e.entity_id, 0) + 1
    assert all(c == 1 for c in seen.values()), "duplicated events"
    for marker in acked:
        for k in range(5):
            assert f"{marker}e{k}" in seen, \
                f"acked event {marker}e{k} lost by drain"


@pytest.mark.chaos
def test_remote_driver_against_async_storage_server(tmp_path,
                                                    monkeypatch):
    """The PR 3 exactly-once dedup contract holds against the async
    transport: a lost response on a deduped insert_batch retries into
    the server's reply cache, not a second copy."""
    monkeypatch.setenv("PIO_TRANSPORT", "async")
    from predictionio_tpu.data.storage.remote import serve_storage
    backing = Storage(env=_el_env(tmp_path))
    app_id = backing.get_meta_data_apps().insert(App(0, "chaos"))
    backing.get_events().init(app_id)
    server = serve_storage(backing, host="127.0.0.1", port=0)
    try:
        remote_env = {
            "PIO_STORAGE_SOURCES_R_TYPE": "remote",
            "PIO_STORAGE_SOURCES_R_URL":
                f"http://127.0.0.1:{server.server_address[1]}",
            "PIO_STORAGE_SOURCES_R_RETRIES": "3",
            "PIO_STORAGE_SOURCES_R_BACKOFF_MS": "1",
            "PIO_STORAGE_SOURCES_R_WRITE_DEDUP": "1",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "R",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "R",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "R",
        }
        remote = Storage(env=remote_env)
        inj = resilience.install("drop_rx:1:1@client POST /rpc")
        ids = remote.get_events().insert_batch(
            [_mk("u1", "i1"), _mk("u2", "i2")], app_id)
        assert inj.fired.get("drop_rx") == 1
        stored = list(backing.get_events().find(app_id))
        assert len(stored) == 2
        assert sorted(ids) == sorted(e.event_id for e in stored)
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# group-commit WAL durability
# ---------------------------------------------------------------------------

def test_ack_implies_wal_durability_without_flush(tmp_path, monkeypatch):
    """insert_batch returning IS the durability point under group
    commit: the WAL file already holds the events — no flush(), no
    close() — so a fresh store sees them."""
    monkeypatch.setenv("PIO_WAL_GROUP_MS", "2")
    monkeypatch.setenv("PIO_WAL_FSYNC", "off")
    storage = Storage(env=_el_env(tmp_path))
    app_id = storage.get_meta_data_apps().insert(App(0, "A"))
    ev = storage.get_events()
    ev.init(app_id)
    ev.insert_batch([_mk("ack1", "i1"), _mk("ack2", "i2")], app_id)
    sh = ev._shard(app_id, None)
    blob = open(sh.wal_path_for(sh.next_seq), "rb").read()
    assert b"ack1" in blob and b"ack2" in blob
    fresh = Storage(env=_el_env(tmp_path))
    assert {e.entity_id for e in fresh.get_events().find(app_id)} == \
        {"ack1", "ack2"}


def test_concurrent_inserts_group_commit_exactly_once(tmp_path,
                                                      monkeypatch):
    """Concurrent inserts coalesce into shared group commits; every
    acked id resolves, a fresh reader sees each event exactly once, and
    the commit counters show fewer flushes than appends."""
    monkeypatch.setenv("PIO_WAL_GROUP_MS", "5")
    monkeypatch.setenv("PIO_WAL_FSYNC", "group")
    storage = Storage(env=_el_env(tmp_path))
    app_id = storage.get_meta_data_apps().insert(App(0, "A"))
    ev = storage.get_events()
    ev.init(app_id)
    before = dict(eventlog.WAL_GROUP_STATS)
    all_ids: list = []
    lock = threading.Lock()

    def work(tid):
        ids = ev.insert_batch(
            [_mk(f"t{tid}e{k}", "i0") for k in range(25)], app_id)
        with lock:
            all_ids.extend(ids)

    threads = [threading.Thread(target=work, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(all_ids) == 200 and len(set(all_ids)) == 200
    delta_commits = eventlog.WAL_GROUP_STATS["commits"] - before["commits"]
    delta_events = eventlog.WAL_GROUP_STATS["events"] - before["events"]
    assert delta_events == 200
    assert 1 <= delta_commits <= 8
    fresh = Storage(env=_el_env(tmp_path))
    got = [e.entity_id for e in fresh.get_events().find(app_id)]
    assert len(got) == 200 and len(set(got)) == 200


def test_crash_mid_group_commit_loses_only_unacked(tmp_path, monkeypatch):
    """Kill between group flushes: the group's write is cut mid-blob and
    the process 'dies'. Previously-acked events survive; the torn batch
    was never acknowledged (insert raised), so losing or partially
    replaying it breaks nothing — and the restarted writer repairs the
    torn tail before its first append, so nothing ever duplicates."""
    monkeypatch.setenv("PIO_WAL_GROUP_MS", "2")
    monkeypatch.setenv("PIO_WAL_FSYNC", "off")
    storage = Storage(env=_el_env(tmp_path))
    app_id = storage.get_meta_data_apps().insert(App(0, "A"))
    ev = storage.get_events()
    ev.init(app_id)
    ev.insert_batch([_mk("acked1", "i1"), _mk("acked2", "i2")], app_id)

    orig = eventlog._Shard.append_wal_lines

    def power_cut(self, lines, fsync=False):
        blob = "".join(lines)
        path = self.wal_path_for(self.next_seq)
        if os.path.exists(path):
            self._repair_torn_tail(path, self.wal_offset, "WAL")
        with open(path, "a", encoding="utf-8") as f:
            f.write(blob[: max(1, len(blob) // 2)])   # torn mid-record
            f.flush()
        raise OSError("simulated power cut during group commit")

    monkeypatch.setattr(eventlog._Shard, "append_wal_lines", power_cut)
    with pytest.raises(OSError):
        ev.insert_batch([_mk("unacked1", "i1"), _mk("unacked2", "i2"),
                         _mk("unacked3", "i3")], app_id)
    monkeypatch.setattr(eventlog._Shard, "append_wal_lines", orig)

    # 'restart': a fresh writer over the same directory
    fresh = Storage(env=_el_env(tmp_path))
    ev2 = fresh.get_events()
    got = [e.entity_id for e in ev2.find(app_id)]
    assert len(got) == len(set(got)), "duplicated events after crash"
    assert {"acked1", "acked2"} <= set(got), "acked events lost"
    unacked_seen = [g for g in got if g.startswith("unacked")]
    assert len(unacked_seen) < 3, "torn tail replayed in full?"
    # the repaired writer appends cleanly and round-trips
    ev2.insert_batch([_mk("after", "i9")], app_id)
    final = Storage(env=_el_env(tmp_path))
    got2 = [e.entity_id for e in final.get_events().find(app_id)]
    assert len(got2) == len(set(got2))
    assert {"acked1", "acked2", "after"} <= set(got2)


def test_fsync_modes_and_legacy_path(tmp_path, monkeypatch):
    """PIO_WAL_FSYNC=always|off and PIO_WAL_GROUP_MS=0 (the legacy
    per-append path) all keep the ack-implies-durable contract."""
    for j, (group_ms, fsync) in enumerate(
            [("0", "off"), ("0", "always"), ("2", "always"), ("2", "off")]):
        monkeypatch.setenv("PIO_WAL_GROUP_MS", group_ms)
        monkeypatch.setenv("PIO_WAL_FSYNC", fsync)
        sub = tmp_path / f"m{j}"
        sub.mkdir()
        storage = Storage(env=_el_env(sub))
        app_id = storage.get_meta_data_apps().insert(App(0, "A"))
        ev = storage.get_events()
        ev.init(app_id)
        ids = ev.insert_batch([_mk("e1", "i1"), _mk("e2", "i2")], app_id)
        assert len(ids) == 2
        fresh = Storage(env=_el_env(sub))
        assert {e.entity_id for e in fresh.get_events().find(app_id)} == \
            {"e1", "e2"}


def test_group_superseded_by_compaction_still_acks(tmp_path, monkeypatch):
    """An explicit flush() racing an open group: the chunk supersedes
    the group's WAL lines and its waiters ack without a WAL write."""
    monkeypatch.setenv("PIO_WAL_GROUP_MS", "50")
    storage = Storage(env=_el_env(tmp_path))
    app_id = storage.get_meta_data_apps().insert(App(0, "A"))
    ev = storage.get_events()
    ev.init(app_id)
    # hold the leader in its coalescing window so flush() wins the race
    monkeypatch.setattr(eventlog, "_wal_group_ms", lambda: 50.0)
    done = threading.Event()
    ids: list = []

    def insert():
        # a second in-flight appender makes the leader take the window
        ids.extend(ev.insert_batch([_mk("race1", "i1")], app_id))
        done.set()

    with ev._inflight_lock:
        ev._ingest_inflight += 1   # simulate a concurrent appender
    try:
        t = threading.Thread(target=insert)
        t.start()
        time.sleep(0.01)           # let it enlist + start the window
        ev.flush(app_id)           # compaction supersedes the group
        assert done.wait(10), "waiter did not ack after supersession"
        t.join(timeout=5)
    finally:
        with ev._inflight_lock:
            ev._ingest_inflight -= 1
    assert len(ids) == 1
    fresh = Storage(env=_el_env(tmp_path))
    assert {e.entity_id for e in fresh.get_events().find(app_id)} == \
        {"race1"}
