"""`pio autopilot` — SLO-driven self-healing (workflow/autopilot.py).

The contracts under test:

- chaos convergence e2e: with the autopilot live against an in-process
  fleet, a replica kill under a concurrent burst recovers to full
  rotation (spawn + corpse removal) with ZERO non-503 failures and
  every action journaled with its triggering evidence;
- `--dry-run` provably acts on nothing: fleet state byte-identical
  before/after while would-have decisions are journaled and counted;
- the degradation ladder is reversible and hysteretic: burn >= 14.4x
  on BOTH windows widens shedding one rung, recovery restores the
  EXACT prior thresholds, and no action class fires twice within one
  cooldown under a flapping signal;
- quarantine ejects a fleet-outlier p99 backend before its breaker
  trips and re-admits on probe recovery;
- the loop NEVER acts under generation skew or a running reload
  barrier (hold-off, journaled once per transition);
- the router's new control plane: POST /backends, /quarantine, /shed
  (read + adjust + exact restore), and the per-backend
  pio_router_backend_seconds histogram the outlier detector reads.
"""

import http.client
import json
import re
import threading
import time

import pytest

from predictionio_tpu.common import journal, telemetry
from predictionio_tpu.tools import doctor
from predictionio_tpu.workflow.autopilot import (
    Autopilot, AutopilotConfig, LocalRouterControl, ReplicaPool,
    RouterControl, Signals,
)
from predictionio_tpu.workflow.router import RouterAPI, RouterConfig

from tests.test_router import (_post_query, _replica, _router,
                               _train_seeded)


@pytest.fixture(autouse=True)
def _clean():
    journal.clear()
    telemetry.set_enabled(None)
    yield
    telemetry.set_enabled(None)


def _cfg(**kw):
    kw.setdefault("poll_ms", 100.0)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("util_low", 0.2)
    kw.setdefault("util_high", 0.85)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("outlier_x", 3.0)
    kw.setdefault("profile_ms", 500)
    return AutopilotConfig(**kw)


class FakeControl(RouterControl):
    """In-memory router stand-in; records every mutation so dry-run
    tests can assert NOTHING was touched."""

    def __init__(self):
        self.max_inflight = 64
        self.tenant_cap = 8
        self.quarantine_state = {}
        self.calls = []

    def status(self):
        return {"router": True, "backends": []}

    def metrics_text(self):
        return ""

    def add_backend(self, url):
        self.calls.append(("add", url))

    def remove_backend(self, name):
        self.calls.append(("remove", name))

    def set_quarantine(self, name, value):
        self.calls.append(("quarantine", name, value))
        self.quarantine_state[name] = value

    def shed_thresholds(self):
        return {"maxInflight": self.max_inflight,
                "tenantMaxInflight": self.tenant_cap}

    def set_shed(self, max_inflight=None, tenant_max_inflight=None):
        prev = self.shed_thresholds()
        self.calls.append(("set_shed", max_inflight, tenant_max_inflight))
        if max_inflight is not None:
            self.max_inflight = max_inflight
        if tenant_max_inflight is not None:
            self.tenant_cap = tenant_max_inflight
        return prev

    def backend_post(self, backend_url, path, timeout=5.0):
        self.calls.append(("post", backend_url, path))
        return 202


class FakePool(ReplicaPool):
    def __init__(self):
        self.spawned = []
        self.stopped = []
        self._n = 0

    def spawn(self):
        self._n += 1
        url = f"http://127.0.0.1:{9900 + self._n}"
        self.spawned.append(url)
        return url

    def stop(self, url):
        self.stopped.append(url)
        return True


def _f(event):
    """journal.emit(**fields) lands under the record's "fields" key."""
    return event.get("fields") or {}


def _sig(now, burn=0.0, **kw):
    kw.setdefault("in_rotation", ["a:1", "b:2"])
    kw.setdefault("healthy", list(kw["in_rotation"]))
    kw.setdefault("urls", {n: f"http://{n}" for n in kw["in_rotation"]})
    return Signals(now=now, burn_fast=burn, burn_slow=burn, **kw)


# ---------------------------------------------------------------------------
# degradation ladder: reversible + hysteretic
# ---------------------------------------------------------------------------

def test_ladder_widen_requires_both_windows():
    ctl = FakeControl()
    ap = Autopilot(ctl, config=_cfg())
    # fast alight alone (a short spike the slow window absorbs) is not
    # the page condition — nothing moves
    acted = ap.tick(Signals(now=0.0, in_rotation=["a:1"],
                            burn_fast=20.0, burn_slow=2.0))
    assert acted == []
    assert ctl.calls == []


def test_ladder_flap_is_hysteretic_and_restores_exact_thresholds():
    ctl = FakeControl()
    ap = Autopilot(ctl, config=_cfg(cooldown_s=10.0))
    # page -> one rung down: thresholds halved
    acted = ap.tick(_sig(0.0, burn=20.0))
    assert [a["action"] for a in acted] == ["shed_widen", "profile_capture"]
    assert ctl.max_inflight == 32 and ctl.tenant_cap == 4
    # flapping INSIDE the cooldown: recovery then re-page — the shed
    # class must not oscillate
    assert ap.tick(_sig(2.0, burn=0.1)) == []
    assert ap.tick(_sig(4.0, burn=20.0)) == []
    assert ctl.max_inflight == 32 and ctl.tenant_cap == 4
    # cooldown passed + burn subsided -> the rung pops, restoring the
    # EXACT prior thresholds
    acted = ap.tick(_sig(11.0, burn=0.1))
    assert [a["action"] for a in acted] == ["shed_narrow"]
    assert ctl.max_inflight == 64 and ctl.tenant_cap == 8
    assert ap.summary()["ladderDepth"] == 0
    # exactly one widen and one narrow across the whole flap
    widens = [c for c in ctl.calls if c[0] == "set_shed"]
    assert len(widens) == 2


def test_ladder_multi_rung_unwinds_in_order():
    ctl = FakeControl()
    ap = Autopilot(ctl, config=_cfg(cooldown_s=10.0))
    ap.tick(_sig(0.0, burn=20.0))      # 64 -> 32
    ap.tick(_sig(11.0, burn=20.0))     # 32 -> 16
    assert ctl.max_inflight == 16 and ctl.tenant_cap == 2
    assert ap.summary()["ladderDepth"] == 2
    ap.tick(_sig(22.0, burn=0.1))      # -> 32
    assert ctl.max_inflight == 32 and ctl.tenant_cap == 4
    ap.tick(_sig(33.0, burn=0.1))      # -> 64, exactly where it began
    assert ctl.max_inflight == 64 and ctl.tenant_cap == 8
    assert ap.summary()["ladderDepth"] == 0


def test_profile_capture_once_per_burn_episode():
    ctl = FakeControl()
    ap = Autopilot(ctl, config=_cfg(cooldown_s=1.0, profile_ms=500))
    ap.tick(_sig(0.0, burn=20.0))
    posts = [c for c in ctl.calls if c[0] == "post"]
    assert len(posts) == 1
    assert posts[0][2] == "/debug/profile?ms=500"
    # sustained burn: still ONE capture for the episode
    ap.tick(_sig(5.0, burn=20.0))
    assert len([c for c in ctl.calls if c[0] == "post"]) == 1
    # episode ends, a NEW one captures again
    ap.tick(_sig(10.0, burn=0.1))
    ap.tick(_sig(20.0, burn=20.0))
    assert len([c for c in ctl.calls if c[0] == "post"]) == 2


# ---------------------------------------------------------------------------
# elastic replica control (fake pool)
# ---------------------------------------------------------------------------

def test_scale_band_spawns_and_drains():
    ctl = FakeControl()
    pool = FakePool()
    ap = Autopilot(ctl, config=_cfg(cooldown_s=1.0, min_replicas=1,
                                    max_replicas=4), pool=pool)
    # hot: busy fraction over the ceiling
    acted = ap.tick(_sig(0.0, utilization=0.95))
    assert [a["action"] for a in acted] == ["scale_up"]
    assert len(pool.spawned) == 1
    assert ("add", pool.spawned[0]) in ctl.calls
    # cold: busy fraction under the floor -> drain the last replica,
    # membership first, process stop only after the grace period
    acted = ap.tick(_sig(2.0, utilization=0.02))
    assert [a["action"] for a in acted] == ["scale_down"]
    assert ("remove", "b:2") in ctl.calls
    assert pool.stopped == []                  # still draining
    ap.tick(_sig(10.0, utilization=0.5))       # grace passed
    assert pool.stopped == ["http://b:2"]


def test_dead_replica_refills_to_min():
    ctl = FakeControl()
    pool = FakePool()
    ap = Autopilot(ctl, config=_cfg(cooldown_s=1.0, min_replicas=2),
                   pool=pool)
    acted = ap.tick(_sig(0.0, in_rotation=["a:1"], healthy=["a:1"],
                         unhealthy=["dead:9"]))
    assert [a["action"] for a in acted] == ["scale_up"]
    assert len(pool.spawned) == 1
    # the corpse is retired once its replacement is admitted
    assert ("remove", "dead:9") in ctl.calls


def test_no_pool_means_no_replica_control():
    ctl = FakeControl()
    ap = Autopilot(ctl, config=_cfg(min_replicas=3))
    assert ap.tick(_sig(0.0, in_rotation=["a:1"], healthy=["a:1"],
                        utilization=0.99)) == []
    assert ctl.calls == []


# ---------------------------------------------------------------------------
# quarantine: outlier ejection + probe-recovery re-admission
# ---------------------------------------------------------------------------

def test_quarantine_outlier_and_readmit():
    ctl = FakeControl()
    ap = Autopilot(ctl, config=_cfg(cooldown_s=5.0, outlier_x=3.0))
    rot = ["a:1", "b:2", "c:3"]
    p99 = {"a:1": (0.001, 100.0), "b:2": (0.0012, 100.0),
           "c:3": (0.02, 100.0)}
    acted = ap.tick(_sig(0.0, in_rotation=list(rot), backend_p99=p99))
    assert [a["action"] for a in acted] == ["quarantine"]
    assert ctl.quarantine_state == {"c:3": True}
    ev = journal.snapshot(category="autopilot")["events"]
    quar = next(_f(e) for e in ev
                if _f(e).get("action") == "quarantine")
    assert quar["backend"] == "c:3" and "p99Ms" in quar
    # probe recovered + cooldown passed -> re-admit
    acted = ap.tick(_sig(6.0, in_rotation=["a:1", "b:2"],
                         healthy=rot, quarantined=["c:3"]))
    assert [a["action"] for a in acted] == ["readmit"]
    assert ctl.quarantine_state == {"c:3": False}


def test_quarantine_needs_peers_and_floor():
    p99 = {"a:1": (0.001, 100.0), "b:2": (0.0012, 100.0),
           "c:3": (0.02, 100.0)}
    # only two in-rotation candidates: no fleet median to vote an
    # outlier against
    ctl = FakeControl()
    ap = Autopilot(ctl, config=_cfg(min_replicas=1, outlier_x=3.0))
    assert ap.tick(_sig(0.0, backend_p99=dict(p99))) == []
    assert ctl.calls == []
    # three candidates, but holding one out would drop the rotation
    # below min_replicas
    ctl = FakeControl()
    ap = Autopilot(ctl, config=_cfg(min_replicas=3, outlier_x=3.0))
    assert ap.tick(_sig(0.0, in_rotation=["a:1", "b:2", "c:3"],
                        backend_p99=dict(p99))) == []
    # too few samples in the window: microbenchmark noise is not
    # evidence
    ctl = FakeControl()
    ap = Autopilot(ctl, config=_cfg(min_replicas=1, outlier_x=3.0))
    assert ap.tick(_sig(0.0, in_rotation=["a:1", "b:2", "c:3"],
                        backend_p99={k: (p, 3.0)
                                     for k, (p, _c) in p99.items()})) \
        == []
    assert ctl.calls == []


# ---------------------------------------------------------------------------
# hold-off: never act under skew or a running barrier
# ---------------------------------------------------------------------------

def test_holdoff_under_skew_and_reload():
    ctl = FakeControl()
    pool = FakePool()
    ap = Autopilot(ctl, config=_cfg(cooldown_s=1.0, min_replicas=3),
                   pool=pool)
    # every trigger is alight, but the fleet disagrees on generations
    hot = dict(in_rotation=["a:1"], healthy=["a:1"], burn_fast=20.0,
               burn_slow=20.0)
    assert ap.tick(Signals(now=0.0, generation_skew=True, **hot)) == []
    assert ap.tick(Signals(now=2.0, reload_active=True, **hot)) == []
    assert ctl.calls == [] and pool.spawned == []
    ev = journal.snapshot(category="autopilot")["events"]
    assert sum("holding off" in e["message"] for e in ev) == 1
    # skew clears -> control resumes (and the resume is journaled)
    acted = ap.tick(Signals(now=4.0, **hot))
    assert any(a["action"] == "scale_up" for a in acted)
    ev = journal.snapshot(category="autopilot")["events"]
    assert any("resuming control" in e["message"] for e in ev)


# ---------------------------------------------------------------------------
# dry-run: provably acts on nothing
# ---------------------------------------------------------------------------

def test_dry_run_journals_but_never_acts():
    ctl = FakeControl()
    pool = FakePool()
    ap = Autopilot(ctl, config=_cfg(dry_run=True, cooldown_s=1.0,
                                    min_replicas=3), pool=pool)
    before = (ctl.max_inflight, ctl.tenant_cap,
              dict(ctl.quarantine_state))
    acted = ap.tick(_sig(0.0, in_rotation=["a:1"], healthy=["a:1"],
                         burn=20.0, backend_p99={
                             "a:1": (0.02, 100.0),
                             "b:2": (0.001, 100.0),
                             "c:3": (0.001, 100.0)}))
    assert acted and all(a["outcome"] == "dry_run" for a in acted)
    # NOTHING was touched: no control mutations, no spawns, and the
    # ladder stack stayed empty (a dry rung would corrupt a later
    # live restore)
    assert ctl.calls == [] and pool.spawned == []
    assert (ctl.max_inflight, ctl.tenant_cap,
            dict(ctl.quarantine_state)) == before
    assert ap.summary()["ladderDepth"] == 0
    assert ap.summary()["pendingDryRun"] == len(acted)
    ev = journal.snapshot(category="autopilot")["events"]
    would = [e for e in ev if _f(e).get("dryRun")]
    assert would and all(e["message"].startswith("DRY-RUN would")
                         for e in would)
    # the cooldown still charges: a dry-run pacing differently from
    # the live loop it rehearses would be a lie
    assert ap.tick(_sig(0.5, burn=20.0, in_rotation=["a:1"],
                        healthy=["a:1"])) == []


# ---------------------------------------------------------------------------
# router control plane (real RouterAPI)
# ---------------------------------------------------------------------------

def _lone_router():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return RouterAPI(RouterConfig(
        backends=(f"http://127.0.0.1:{port}",), health_ms=60000.0,
        max_inflight=64, tenant_max_inflight=8))


def test_router_shed_route_reads_and_restores():
    router = _lone_router()
    try:
        st, body = router.handle("POST", "/shed")[:2]
        assert st == 200
        assert body["current"] == {"maxInflight": 64,
                                   "tenantMaxInflight": 8}
        st, body = router.handle(
            "POST", "/shed",
            query={"maxInflight": "32", "tenantMaxInflight": "4"})[:2]
        assert body["previous"] == {"maxInflight": 64,
                                    "tenantMaxInflight": 8}
        assert body["current"] == {"maxInflight": 32,
                                   "tenantMaxInflight": 4}
        # restore from the returned previous: bit-identical round trip
        prev = body["previous"]
        router.set_shed_thresholds(
            max_inflight=prev["maxInflight"],
            tenant_max_inflight=prev["tenantMaxInflight"])
        assert router.handle("POST", "/shed")[1]["current"] == prev
        # floors: maxInflight clamps to >= 1, tenant cap to >= 0
        router.set_shed_thresholds(max_inflight=0,
                                   tenant_max_inflight=-5)
        cur = router.handle("POST", "/shed")[1]["current"]
        assert cur == {"maxInflight": 1, "tenantMaxInflight": 0}
    finally:
        router.close()


def test_router_backend_and_quarantine_routes_validate():
    router = _lone_router()
    name = router.backends[0].name
    try:
        assert router.handle("POST", "/backends")[0] == 400
        assert router.handle("POST", "/backends",
                             query={"add": "no-port"})[0] == 400
        assert router.handle("POST", "/backends",
                             query={"remove": "nope:1"})[0] == 404
        # the last backend is not removable (a router with zero
        # configured backends could never recover by itself)
        assert router.handle("POST", "/backends",
                             query={"remove": name})[0] == 400
        assert router.handle("POST", "/quarantine")[0] == 400
        assert router.handle("POST", "/quarantine",
                             query={"backend": "nope:1"})[0] == 404
        st, body = router.handle("POST", "/quarantine",
                                 query={"backend": name})[:2]
        assert st == 200
        state = router.handle("GET", "/")[1]["backends"][0]
        assert state["quarantined"] is True and not state["inRotation"]
        router.handle("POST", "/quarantine",
                      query={"backend": name, "clear": "1"})
        state = router.handle("GET", "/")[1]["backends"][0]
        assert "quarantined" not in state       # wire parity when clear
    finally:
        router.close()


def test_router_status_has_no_autopilot_block_until_attached():
    router = _lone_router()
    try:
        assert "autopilot" not in router.handle("GET", "/")[1]
        ap = Autopilot(LocalRouterControl(router), config=_cfg())
        router.attach_autopilot(ap)
        block = router.handle("GET", "/")[1]["autopilot"]
        assert block["mode"] == "live" and block["actionsTotal"] == 0
    finally:
        router.close()


# ---------------------------------------------------------------------------
# per-backend latency histogram (the quarantine blind-spot fix)
# ---------------------------------------------------------------------------

def test_per_backend_latency_histogram(memory_storage):
    engine = _train_seeded(memory_storage, app_name="ApHist")
    api1, server1, port1 = _replica(memory_storage, engine)
    api2, server2, port2 = _replica(memory_storage, engine)
    router, rserver, rport = _router([port1, port2])
    telemetry.set_enabled(True)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", rport,
                                          timeout=10)
        for _ in range(6):
            status, _body, _h = _post_query(conn)
            assert status == 200
        conn.close()
        samples = doctor.parse_metrics(
            telemetry.registry().exposition())
        backends = set()
        for labels, _v in samples.get(
                "pio_router_backend_seconds_bucket", []):
            m = re.search(r'backend="([^"]+)"', labels)
            if m:
                backends.add(m.group(1))
        # round-robin over two replicas: BOTH carry their own series —
        # the aggregate pio_router_overhead_seconds cannot name a slow
        # replica, this can
        assert {f"127.0.0.1:{port1}", f"127.0.0.1:{port2}"} <= backends
    finally:
        rserver.shutdown()
        router.close()
        for s, a in ((server1, api1), (server2, api2)):
            s.shutdown()
            a.close()


# ---------------------------------------------------------------------------
# e2e: chaos convergence + dry-run inertness against a real fleet
# ---------------------------------------------------------------------------

class InProcessPool(ReplicaPool):
    """Spawns real query-server replicas inside the test process (the
    ReplicaPool hook contract an external orchestrator implements)."""

    def __init__(self, storage, engine):
        self.storage = storage
        self.engine = engine
        self.live = {}
        self.spawn_calls = 0

    def spawn(self):
        self.spawn_calls += 1
        api, server, port = _replica(self.storage, self.engine)
        url = f"http://127.0.0.1:{port}"
        self.live[url] = (api, server)
        return url

    def stop(self, url):
        pair = self.live.pop(url, None)
        if pair is None:
            return False
        pair[1].shutdown()
        pair[0].close()
        return True

    def close(self):
        for url in list(self.live):
            self.stop(url)


@pytest.mark.chaos
def test_autopilot_chaos_convergence_e2e(memory_storage):
    """A replica SIGKILL (in-process: server shutdown severs the
    keep-alive sockets) under a concurrent burst. The live autopilot
    must converge the fleet back to full rotation with zero human
    input and zero non-503 client failures."""
    engine = _train_seeded(memory_storage, app_name="ApChaos")
    api1, server1, port1 = _replica(memory_storage, engine)
    api2, server2, port2 = _replica(memory_storage, engine)
    router, rserver, rport = _router([port1, port2])
    pool = InProcessPool(memory_storage, engine)
    ap = Autopilot(LocalRouterControl(router),
                   config=_cfg(poll_ms=100.0, cooldown_s=1.0,
                               min_replicas=2, max_replicas=3),
                   pool=pool)
    t = threading.Thread(target=ap.run, daemon=True)
    bad, stop = [], threading.Event()

    def burst():
        conn = http.client.HTTPConnection("127.0.0.1", rport,
                                          timeout=10)
        while not stop.is_set():
            try:
                status, _b, _h = _post_query(conn)
            except Exception:
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", rport,
                                                  timeout=10)
                continue
            if status not in (200, 503):
                bad.append(status)
        conn.close()

    workers = [threading.Thread(target=burst, daemon=True)
               for _ in range(4)]
    try:
        t.start()
        for w in workers:
            w.start()
        time.sleep(0.5)
        # the kill, mid-burst
        server1.shutdown()
        api1.close()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = router.handle("GET", "/")[1]
            if (st["inRotation"] == 2
                    and all(b["inRotation"] for b in st["backends"])):
                break
            time.sleep(0.1)
        stop.set()
        for w in workers:
            w.join(timeout=10)
        st = router.handle("GET", "/")[1]
        # converged: the corpse was replaced and retired, full rotation
        assert st["inRotation"] == 2, st
        assert len(st["backends"]) == 2, st
        assert f"http://127.0.0.1:{port1}" not in {
            b["url"] for b in st["backends"]}, st
        assert pool.spawn_calls >= 1
        # zero non-503 failures through the whole episode
        assert bad == [], bad
        # every action journaled with its triggering evidence
        ev = journal.snapshot(category="autopilot")["events"]
        ups = [_f(e) for e in ev
               if _f(e).get("action") == "scale_up"]
        assert ups, [e["message"] for e in ev]
        assert ups[0]["outcome"] == "ok"
        assert ups[0]["minReplicas"] == 2
        assert "inRotation" in ups[0]
    finally:
        stop.set()
        ap.close()
        t.join(timeout=10)
        rserver.shutdown()
        router.close()
        server2.shutdown()
        api2.close()
        pool.close()


def test_autopilot_dry_run_leaves_fleet_byte_identical(memory_storage):
    """--dry-run against a real under-replicated fleet: the loop wants
    to scale up, journals the would-have, and the fleet state is
    byte-identical before and after."""
    engine = _train_seeded(memory_storage, app_name="ApDry")
    api1, server1, port1 = _replica(memory_storage, engine)
    router, rserver, rport = _router([port1])
    pool = InProcessPool(memory_storage, engine)
    ap = Autopilot(LocalRouterControl(router),
                   config=_cfg(dry_run=True, poll_ms=50.0,
                               cooldown_s=0.2, min_replicas=2),
                   pool=pool)
    try:
        before = json.dumps(router.handle("GET", "/")[1],
                            sort_keys=True)
        for i in range(5):
            ap.tick(ap.gather())
            time.sleep(0.25)
        after = json.dumps(router.handle("GET", "/")[1], sort_keys=True)
        assert after == before
        assert pool.spawn_calls == 0
        summary = ap.summary()
        assert summary["mode"] == "dry-run"
        assert summary["pendingDryRun"] >= 1
        assert summary["lastAction"]["outcome"] == "dry_run"
        ev = journal.snapshot(category="autopilot")["events"]
        assert any(_f(e).get("action") == "scale_up"
                   and _f(e).get("dryRun") for e in ev)
    finally:
        ap.close()
        rserver.shutdown()
        router.close()
        server1.shutdown()
        api1.close()


# ---------------------------------------------------------------------------
# doctor surface
# ---------------------------------------------------------------------------

def _scraped_router(root):
    ok = {"status": 200, "body": json.dumps({"status": "ok"})}
    return {
        "url": "http://t", "healthz": dict(ok), "readyz": dict(ok),
        "root": {"status": 200, "body": json.dumps(root)},
        "metrics": {"status": 200, "body": ""},
        "traces": {"status": 404, "body": ""},
        "device": {"status": 200, "body": json.dumps(
            {"telemetry": True})},
    }


def _base_root(**kw):
    root = {"router": True, "backends": [
        {"url": "http://h:1", "inRotation": True, "healthy": True,
         "generation": 1, "breaker": "closed"}],
        "generations": [1], "generationSkew": False}
    root.update(kw)
    return root


def test_doctor_autopilot_line_ok_and_dry_run_warn():
    root = _base_root(autopilot={
        "mode": "live", "ladderDepth": 1, "holdoff": False,
        "cooldownS": 30.0, "cooling": ["shed"], "actionsTotal": 3,
        "pendingDryRun": 0,
        "lastAction": {"action": "shed_widen", "outcome": "ok",
                       "trigger": "burn 16.0x/15.1x over the page "
                                  "threshold", "ageS": 12.0}})
    checks = doctor.diagnose(_scraped_router(root))
    check = next(c for c in checks if c[0] == "autopilot")
    assert check[1] == doctor.OK
    assert "shed_widen" in check[2] and "ladder depth 1" in check[2]
    assert "cooling: shed" in check[2]
    # dry-run with pending would-have actions: the loop believes the
    # fleet needs intervention nobody is applying
    root["autopilot"].update(mode="dry-run", pendingDryRun=4)
    checks = doctor.diagnose(_scraped_router(root))
    check = next(c for c in checks if c[0] == "autopilot")
    assert check[1] == doctor.WARN
    assert "4 would-have action(s)" in check[2]


def test_doctor_warns_on_cache_ttl_over_foldin_gate():
    foldin_root = {"status": 200,
                   "body": json.dumps({"status": "alive",
                                       "foldin": {"enabled": True}})}
    plain_root = {"status": 200,
                  "body": json.dumps({"status": "alive"})}
    root = _base_root(cache={"enabled": True, "ttlMs": 5000.0,
                             "hits": 0, "misses": 0, "entries": 0,
                             "hitRatio": 0.0})
    scraped = _scraped_router(root)
    scraped["backendRoots"] = [foldin_root]
    check = next(c for c in doctor.diagnose(scraped)
                 if c[0] == "router-cache")
    assert check[1] == doctor.WARN
    assert "fold-in" in check[2] and "KNOWN_ISSUES #17" in check[2]
    # TTL at the gate: fine
    root["cache"]["ttlMs"] = 2000.0
    scraped = _scraped_router(root)
    scraped["backendRoots"] = [foldin_root]
    check = next(c for c in doctor.diagnose(scraped)
                 if c[0] == "router-cache")
    assert check[1] == doctor.OK
    # no fold-in behind the cache: no row at all (parity)
    root["cache"]["ttlMs"] = 60000.0
    scraped = _scraped_router(root)
    scraped["backendRoots"] = [plain_root]
    assert not [c for c in doctor.diagnose(scraped)
                if c[0] == "router-cache"]
