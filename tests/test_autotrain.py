"""`pio autotrain` — continuous training (workflow/autotrain.py).

The contracts under test:

- e2e embedded: a live event burst crosses the volume trigger, a
  streamed retrain runs in-process, the candidate clears both
  validation gates and publishes through the in-place swap — zero
  dropped queries, a monotonic generation bump, the decision journaled
  with its triggering evidence, and the fold-in worker rebased onto
  the new batch base;
- the reject path: a seeded-WORSE candidate is refused by the
  validation gates, its ledger row flips to REJECTED (so no resolve
  ever deploys it), the evidence is journaled, and the prior
  generation keeps serving;
- `--dry-run` provably trains nothing: the trainer is never started
  and storage is untouched while would-have decisions are journaled,
  counted, and surfaced by the doctor as a WARN;
- trigger mechanics: evaluation priority (drift before lag before
  volume before staleness), per-class cooldowns charged at decision
  time, the one-retrain-in-flight guard, and hold-off under
  generation skew / a running reload barrier (journaled once per
  transition);
- crash-resume: a dead retrain is restarted exactly once (iteration-
  snapshot auto-resume), a second death fails the cycle;
- the standalone plumbing: CLI parse surfaces, the doctor autotrain
  line, and validate_candidate's skip-vs-measure honesty.
"""

import dataclasses
import json
import threading
import time

import numpy as np
import pytest

from predictionio_tpu.common import journal, telemetry
from predictionio_tpu.data.storage import (
    EngineInstance, Model, Storage,
)
from predictionio_tpu.tools import doctor
from predictionio_tpu.workflow import model_io
from predictionio_tpu.workflow.autotrain import (
    Autotrain, AutotrainConfig, LocalDeployControl, ServerControl,
    Signals, Trainer, mark_rejected, validate_candidate,
)
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig

from tests.test_foldin import APP, _mk_event, _train


@pytest.fixture(autouse=True)
def _clean():
    journal.clear()
    telemetry.set_enabled(None)
    yield
    telemetry.set_enabled(None)


@pytest.fixture(scope="module")
def trained():
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    })
    engine = _train(storage)
    return storage, engine


def _cfg(**kw):
    kw.setdefault("poll_ms", 50.0)
    kw.setdefault("cooldown_s", 30.0)
    kw.setdefault("max_staleness_s", 3600.0)
    kw.setdefault("volume_events", 10)
    kw.setdefault("lag_events", 10)
    kw.setdefault("tolerance", 0.02)
    kw.setdefault("parity_min", 0.2)
    kw.setdefault("probe", 64)
    kw.setdefault("publish_timeout_s", 10.0)
    return AutotrainConfig(**kw)


class FakeControl(ServerControl):
    """In-memory serving stand-in: a mutable status dict plus a
    publish that bumps the generation (the real swap's observable)."""

    def __init__(self, **status):
        self._status = {"generation": 1, "generationSkew": False,
                        "reload": {"active": False}, **status}
        self.publishes = 0

    def status(self):
        return dict(self._status)

    def publish(self):
        self.publishes += 1
        self._status["generation"] += 1


class FakeTrainer(Trainer):
    def __init__(self):
        self.started = 0
        self.results = []        # popped per attempt, FIFO
        self._live = None

    def start(self):
        if self.running:
            raise RuntimeError("a retrain is already in flight")
        self.started += 1
        self._live = self.results.pop(0) if self.results else None

    @property
    def running(self):
        return False

    def poll(self):
        return self._live


def _fake_storage_autotrain(control=None, trainer=None, **cfg_kw):
    """State-machine-only loop: no storage reads happen until a cycle
    reaches validation, so a None storage keeps the test honest about
    what each phase touches."""
    return Autotrain(control or FakeControl(), storage=None,
                     trainer=trainer or FakeTrainer(),
                     config=_cfg(**cfg_kw))


def _sig(**kw):
    kw.setdefault("now", 1000.0)
    return Signals(**kw)


# ---------------------------------------------------------------------------
# trigger mechanics (state machine driven directly, fake clock)
# ---------------------------------------------------------------------------

def test_staleness_trigger_fires_and_cooldown_holds():
    trainer = FakeTrainer()
    at = _fake_storage_autotrain(trainer=trainer)
    acted = at.tick(_sig(staleness_s=4000.0))
    assert [a["trigger"] for a in acted] == ["staleness"]
    assert acted[0]["outcome"] == "ok"
    assert trainer.started == 1 and at._phase == "retraining"
    # cooldown charged at decision time: an idle loop seeing the same
    # signal within the window decides nothing
    at._phase = "idle"
    assert at.tick(_sig(now=1010.0, staleness_s=4000.0)) == []
    # past the cooldown it fires again
    assert [a["trigger"] for a in
            at.tick(_sig(now=1031.0, staleness_s=4000.0))] \
        == ["staleness"]


def test_trigger_priority_drift_wins_and_evidence_journaled():
    at = _fake_storage_autotrain()
    acted = at.tick(_sig(drift=0.5, item_drift=0.4, cursor_lag=999,
                         volume=999, staleness_s=99999.0))
    assert [a["trigger"] for a in acted] == ["drift"]
    evs = [e for e in journal.snapshot(category="autotrain")["events"]
           if e["fields"].get("trigger") == "drift"]
    assert len(evs) == 1
    # the decision carries its triggering evidence: the worst recall,
    # the floor, and which sides drifted
    assert evs[0]["fields"]["driftRecall"] == 0.4
    assert evs[0]["fields"]["sides"] == ["user", "item"]
    assert "drift recall 0.400" in evs[0]["message"]


def test_item_drift_alone_triggers():
    at = _fake_storage_autotrain()
    acted = at.tick(_sig(item_drift=0.3))
    assert [a["trigger"] for a in acted] == ["drift"]
    ev = journal.snapshot(category="autotrain")["events"][-1]
    assert ev["fields"]["sides"] == ["item"]


def test_lag_and_volume_triggers():
    at = _fake_storage_autotrain()
    acted = at.tick(_sig(cursor_lag=25, volume=25))
    assert [a["trigger"] for a in acted] == ["lag"]
    at2 = _fake_storage_autotrain()
    acted = at2.tick(_sig(volume=25))
    assert [a["trigger"] for a in acted] == ["volume"]
    assert at2.tick(_sig(now=1001.0, volume=5)) == []   # under threshold


def test_one_retrain_in_flight_guard():
    trainer = FakeTrainer()
    at = _fake_storage_autotrain(trainer=trainer)
    at.tick(_sig(staleness_s=4000.0))
    assert at._phase == "retraining"
    # every trigger saturated, but a cycle is in flight: nothing fires
    acted = at.tick(_sig(now=2000.0, drift=0.1, cursor_lag=999,
                         volume=999, staleness_s=99999.0))
    assert acted == [] and trainer.started == 1


def test_holdoff_blocks_triggers_and_journals_transitions():
    at = _fake_storage_autotrain()
    assert at.tick(_sig(generation_skew=True, staleness_s=9999.0)) == []
    assert at.tick(_sig(now=1001.0, generation_skew=True,
                        staleness_s=9999.0)) == []
    msgs = [e["message"] for e in
            journal.snapshot(category="autotrain")["events"]]
    assert sum("holding off" in m for m in msgs) == 1   # once per edge
    at.tick(_sig(now=1002.0))
    msgs = [e["message"] for e in
            journal.snapshot(category="autotrain")["events"]]
    assert sum("hold-off cleared" in m for m in msgs) == 1


def test_crash_resume_once_then_fail_cycle():
    trainer = FakeTrainer()
    trainer.results = [{"ok": False, "error": "boom 1"},
                       {"ok": False, "error": "boom 2"}]
    at = _fake_storage_autotrain(trainer=trainer)
    at.tick(_sig(staleness_s=9999.0))
    assert trainer.started == 1 and at._phase == "retraining"
    at.tick(_sig(now=1001.0))           # crash -> one restart
    assert trainer.started == 2 and at._phase == "retraining"
    msgs = [e["message"] for e in
            journal.snapshot(category="autotrain")["events"]]
    assert any("restarting once" in m for m in msgs)
    at.tick(_sig(now=1002.0))           # second crash -> cycle fails
    assert at._phase == "idle"
    reds = [e for e in journal.snapshot(level="red")["events"]
            if e["category"] == "autotrain"]
    assert any("failed twice" in e["message"] for e in reds)


# ---------------------------------------------------------------------------
# dry-run provably trains nothing
# ---------------------------------------------------------------------------

def test_dry_run_decides_without_training():
    trainer = FakeTrainer()
    at = _fake_storage_autotrain(trainer=trainer, dry_run=True)
    acted = at.tick(_sig(volume=999))
    assert [a["outcome"] for a in acted] == ["dry_run"]
    assert trainer.started == 0 and at._phase == "idle"
    ev = journal.snapshot(category="autotrain")["events"][-1]
    assert ev["message"].startswith("DRY-RUN would: ")
    assert ev["fields"]["volume"] == 999
    s = at.summary()
    assert s["mode"] == "dry-run" and s["pendingDryRun"] == 1
    # dry-run paces exactly like the live loop: cooldown was charged
    assert at.tick(_sig(now=1001.0, volume=999)) == []


# ---------------------------------------------------------------------------
# candidate validation (real models)
# ---------------------------------------------------------------------------

def _live_instance(storage):
    return storage.get_meta_data_engine_instances().get_latest_completed(
        "default", "NOT_USED", "default")


def _seed_candidate(storage, live_id, corrupt=False):
    """Clone the live generation's ledger row + blob as a fresh
    COMPLETED candidate; with ``corrupt``, flip the user factors so
    every ranking inverts (a provably worse model)."""
    instances = storage.get_meta_data_engine_instances()
    row = instances.get(live_id)
    models = model_io.deserialize_models(
        storage.get_model_data_models().get(live_id).models)
    if corrupt:
        m = models[0]
        m.user_factors = -np.asarray(m.user_factors, np.float32)
    cand_id = instances.insert(EngineInstance(
        **{**row.__dict__, "id": "", "status": "COMPLETED"}))
    storage.get_model_data_models().insert(Model(
        id=cand_id, models=model_io.serialize_models(models)))
    return cand_id


def test_validate_clone_passes_both_gates(trained):
    storage, engine = trained
    live = _live_instance(storage).id
    cand = _seed_candidate(storage, live)
    ep = QueryAPI(storage=storage, engine=engine,
                  config=ServerConfig()).engine_params
    v = validate_candidate(storage, ep, live, cand)
    assert v["ok"], v
    assert v["score"]["ok"] and v["score"]["probeTriples"] > 0
    assert v["parity"]["ok"] and v["parity"]["recall"] == 1.0


def test_validate_rejects_seeded_worse_candidate(trained):
    storage, engine = trained
    live = _live_instance(storage).id
    cand = _seed_candidate(storage, live, corrupt=True)
    ep = QueryAPI(storage=storage, engine=engine,
                  config=ServerConfig()).engine_params
    v = validate_candidate(storage, ep, live, cand)
    assert not v["ok"]
    assert v["reasons"]          # evidence, not a bare verdict
    mark_rejected(storage, cand)
    row = storage.get_meta_data_engine_instances().get(cand)
    assert row.status == "REJECTED"
    # no resolve ever deploys it: latest-completed skips REJECTED rows
    assert _live_instance(storage).id != cand


def test_validate_skips_are_explicit():
    """A gate that cannot run must say so — never silently pass as
    measured. No live id => both gates skip; no candidate blob =>
    reject outright."""
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    })
    v = validate_candidate(storage, None, None, "ghost")
    assert not v["ok"] and "no model blob" in v["reasons"][0]
    storage.get_model_data_models().insert(
        Model(id="c1", models=model_io.serialize_models([object()])))
    v = validate_candidate(storage, None, None, "c1")
    assert v["ok"]
    assert "skipped" in v["score"] and "skipped" in v["parity"]


# ---------------------------------------------------------------------------
# reject path through the state machine (prior generation keeps serving)
# ---------------------------------------------------------------------------

def test_reject_cycle_keeps_prior_generation_serving(trained):
    storage, engine = trained
    live = _live_instance(storage).id
    cand = _seed_candidate(storage, live, corrupt=True)
    ep = QueryAPI(storage=storage, engine=engine,
                  config=ServerConfig()).engine_params
    control = FakeControl()
    trainer = FakeTrainer()
    trainer.results = [{"ok": True, "instanceId": cand}]
    at = Autotrain(control, storage=storage, engine_params=ep,
                   trainer=trainer, config=_cfg())
    at._live_id = live
    at.tick(_sig(staleness_s=9999.0, live_instance_id=live))
    assert at._phase == "retraining"
    at.tick(_sig(now=1001.0))    # poll -> candidate -> validate: REJECT
    assert at._phase == "idle"
    assert control.publishes == 0                       # never published
    assert control.status()["generation"] == 1          # prior serves
    assert storage.get_meta_data_engine_instances().get(cand).status \
        == "REJECTED"
    reds = [e for e in journal.snapshot(level="red")["events"]
            if e["category"] == "autotrain"]
    assert any("REJECTED" in e["message"]
               and "prior generation keeps serving" in e["message"]
               for e in reds)
    s = at.summary()
    assert s["candidatesRejected"] == 1
    assert s["lastCandidate"]["candidateId"] == cand
    assert not s["lastCandidate"]["ok"]


def test_accept_cycle_publishes_and_bumps_generation(trained):
    storage, engine = trained
    live = _live_instance(storage).id
    cand = _seed_candidate(storage, live)
    ep = QueryAPI(storage=storage, engine=engine,
                  config=ServerConfig()).engine_params
    control = FakeControl()
    trainer = FakeTrainer()
    trainer.results = [{"ok": True, "instanceId": cand}]
    at = Autotrain(control, storage=storage, engine_params=ep,
                   trainer=trainer, config=_cfg())
    at._live_id = live
    at.tick(_sig(volume=999, live_instance_id=live))
    at.tick(_sig(now=1001.0))    # poll -> validate: ACCEPT -> publish
    assert at._phase == "idle" and control.publishes == 1
    assert control.status()["generation"] == 2
    s = at.summary()
    assert s["lastCycle"]["candidateId"] == cand
    assert s["lastCycle"]["generation"] == 2
    assert at._live_id == cand
    msgs = [e["message"] for e in
            journal.snapshot(category="autotrain")["events"]]
    assert any("published: generation 2 live" in m for m in msgs)


def test_publish_waits_out_holdoff(trained):
    storage, engine = trained
    live = _live_instance(storage).id
    cand = _seed_candidate(storage, live)
    ep = QueryAPI(storage=storage, engine=engine,
                  config=ServerConfig()).engine_params
    control = FakeControl()
    trainer = FakeTrainer()
    trainer.results = [{"ok": True, "instanceId": cand}]
    at = Autotrain(control, storage=storage, engine_params=ep,
                   trainer=trainer, config=_cfg())
    at._live_id = live
    at.tick(_sig(staleness_s=9999.0))
    at.tick(_sig(now=1001.0, reload_active=True))   # validated, but a
    assert at._phase == "publishing"                # barrier is running
    assert control.publishes == 0
    at.tick(_sig(now=1002.0))                       # barrier done
    assert at._phase == "idle" and control.publishes == 1


# ---------------------------------------------------------------------------
# e2e embedded: burst -> volume trigger -> real retrain -> validated ->
# published in-place -> fold-in rebased; zero drops, generation bump
# ---------------------------------------------------------------------------

def test_e2e_burst_trigger_retrain_publish_zero_drops(trained,
                                                      monkeypatch):
    monkeypatch.setenv("PIO_FOLDIN_CURSOR_DIR", "/tmp/at_e2e_cur")
    monkeypatch.setenv("PIO_FOLDIN_USER_BUCKETS", "1,4")
    monkeypatch.setenv("PIO_FOLDIN_MAX_EVENTS", "16")
    storage, engine = trained
    from predictionio_tpu.workflow.autotrain import ThreadTrainer
    from predictionio_tpu.workflow.core_workflow import run_train

    api = QueryAPI(storage=storage, engine=engine,
                   config=ServerConfig(batching="on", foldin="on",
                                       foldin_tick_ms=20.0,
                                       foldin_headroom=16))
    try:
        gen_before = api.generation
        live_before = api.engine_instance.id

        def _retrain() -> str:
            return run_train(
                api.ctx, api.engine, api.engine_params,
                engine_factory="foldin-test",
                params_json={
                    "datasource": {"params": {"appName": APP}},
                    "algorithms": [{"name": "als", "params": {
                        "rank": 4, "numIterations": 4,
                        "lambda": 0.05, "seed": 3}}]})

        at = Autotrain(LocalDeployControl(api), storage=storage,
                       engine_params=api.engine_params,
                       trainer=ThreadTrainer(_retrain),
                       config=_cfg(volume_events=5))
        api.attach_autotrain(at)

        burst_errors = []
        stop = threading.Event()

        def burst(cx):
            try:
                while not stop.is_set():
                    status, body = api.handle(
                        "POST", "/queries.json",
                        body=json.dumps({"user": f"u{cx}",
                                         "num": 10}).encode())
                    if status != 200 or not body.get("itemScores"):
                        burst_errors.append((status, body))
                        return
            except Exception as e:      # a dropped query IS a failure
                burst_errors.append(e)

        clients = [threading.Thread(target=burst, args=(cx,))
                   for cx in range(3)]
        for t in clients:
            t.start()
        try:
            # the live burst that crosses the volume trigger
            app_id = storage.get_meta_data_apps().get_by_name(APP).id
            storage.get_events().insert_batch(
                [_mk_event(f"u{u}", f"i{i}", 3.0, month=11)
                 for u in range(4) for i in range(3)], app_id)
            deadline = time.monotonic() + 120.0
            decided = False
            while time.monotonic() < deadline:
                at.tick(at.gather())
                decided = decided or at._phase != "idle"
                if decided and at._phase == "idle":
                    break
                time.sleep(0.05)
        finally:
            stop.set()
            for t in clients:
                t.join(timeout=10)
            at.close()

        assert not burst_errors, burst_errors[:3]   # zero drops
        assert api.generation == gen_before + 1     # monotonic bump
        assert api.engine_instance.id != live_before
        assert api.engine_instance.id == at._live_id
        # the decision journaled with its volume evidence, the cycle
        # journaled with its generation
        evs = journal.snapshot(category="autotrain")["events"]
        dec = [e for e in evs
               if e["fields"].get("trigger") == "volume"
               and e["fields"].get("outcome") == "ok"]
        assert dec and dec[0]["fields"]["volume"] >= 5
        assert any("published: generation" in e["message"]
                   for e in evs)
        # fold-in rebased onto the new batch base: cursor/drift reset
        fold = [e for e in journal.snapshot(category="foldin")["events"]
                if "rebased" in e["message"]]
        assert fold, "fold-in was not rebased after the publish"
        assert api._foldin_instance_id == api.engine_instance.id
        s = at.summary()
        assert s["lastCycle"]["cycleS"] > 0
        assert s["lastCandidate"]["ok"]
    finally:
        api.close()


# ---------------------------------------------------------------------------
# doctor + CLI surfaces
# ---------------------------------------------------------------------------

def _scraped(root):
    ok = {"status": 200, "body": json.dumps({"status": "ok"})}
    return {
        "url": "http://t", "healthz": dict(ok), "readyz": dict(ok),
        "root": {"status": 200, "body": json.dumps(root)},
        "metrics": {"status": 200, "body": ""},
        "traces": {"status": 404, "body": ""},
        "device": {"status": 200, "body": json.dumps(
            {"telemetry": True})},
    }


def test_doctor_autotrain_line_ok_and_dry_run_warn():
    root = {"autotrain": {
        "mode": "live", "phase": "idle", "holdoff": False,
        "retrainInFlight": False, "cooldownS": 600.0, "cooling": [],
        "decisionsTotal": 2, "pendingDryRun": 0,
        "candidatesRejected": 1,
        "lastDecision": {"trigger": "volume", "outcome": "ok",
                         "message": "start streamed retrain",
                         "ageS": 33.0, "at": "t"},
        "lastCandidate": {"candidateId": "abc", "ok": True},
        "lastCycle": {"candidateId": "abc", "generation": 3,
                      "cycleS": 41.0},
        "thresholds": {"maxStalenessS": 86400.0, "volumeEvents": 5000,
                       "lagEvents": 5000, "driftFloor": 0.99},
        "signals": {"stalenessS": 120.0, "volume": 123,
                    "cursorLag": 7, "drift": 1.0, "itemDrift": None},
    }}
    checks = doctor.diagnose(_scraped(root))
    check = next(c for c in checks if c[0] == "autotrain")
    assert check[1] == doctor.OK
    assert "last decision volume (ok) 33.0s ago" in check[2]
    assert "cursor lag 7/5000" in check[2]
    assert "volume 123/5000" in check[2]
    assert "last candidate ACCEPTED" in check[2]
    # dry-run with pending would-haves: the loop believes the model
    # needs a retrain nobody is running
    root["autotrain"].update(mode="dry-run", pendingDryRun=3)
    checks = doctor.diagnose(_scraped(root))
    check = next(c for c in checks if c[0] == "autotrain")
    assert check[1] == doctor.WARN
    assert "3 would-have decision(s)" in check[2]


def test_doctor_foldin_line_surfaces_item_drift():
    root = {"foldin": {"enabled": True, "cursorLag": 0,
                       "lastTickMs": 1.0,
                       "drift": {"recall": 1.0, "ok": True},
                       "itemDrift": {"recall": 0.5, "ok": False}}}
    scraped = _scraped({})
    scraped["device"] = {"status": 200, "body": json.dumps(
        {"telemetry": True, "foldin": root["foldin"]})}
    checks = doctor.diagnose(scraped)
    check = next(c for c in checks if c[0] == "foldin")
    assert check[1] == doctor.WARN              # WARN, never RED
    assert "item drift probe recall 0.5000 FAILED" in check[2]


def test_cli_parses_autotrain_surfaces():
    from predictionio_tpu.tools.cli import build_parser

    p = build_parser()
    args = p.parse_args(["autotrain", "--server", "http://h:8000",
                         "--dry-run", "--train-cmd", "true"])
    assert args.server == "http://h:8000" and args.dry_run
    args = p.parse_args(["deploy", "--autotrain",
                         "--autotrain-dry-run",
                         "--foldin-item-headroom", "32"])
    assert args.autotrain and args.autotrain_dry_run
    assert args.foldin_item_headroom == 32
    args = p.parse_args(["router", "--backends", "http://h:1",
                         "--autotrain", "--engine-dir", "/e"])
    assert args.autotrain and args.engine_dir == "/e"


def test_declarations_cover_autotrain():
    """One seeded defect -> exactly one finding: the autotrain families
    are inside the declarations triangle, and an undeclared sibling
    metric still fails the pass."""
    from predictionio_tpu.common import declarations
    from predictionio_tpu.tools.analyze.passes import (
        declarations as decl_pass,
    )
    from tests.test_lint import _mod

    for name in ("PIO_AUTOTRAIN_POLL_MS", "PIO_AUTOTRAIN_TOLERANCE",
                 "PIO_AUTOTRAIN_PUBLISH_TIMEOUT_S",
                 "PIO_FOLDIN_ITEM_HEADROOM"):
        assert name in declarations.ENV_VARS
    for name in ("pio_autotrain_decisions_total",
                 "pio_autotrain_candidates_total",
                 "pio_autotrain_state",
                 "pio_autotrain_last_decision_age_seconds",
                 "pio_foldin_item_drift_recall",
                 "pio_foldin_items_total"):
        assert name in declarations.METRICS
    assert "autotrain" in declarations.JOURNAL_CATEGORIES
    src = ("from predictionio_tpu.common import telemetry\n"
           "c = telemetry.registry().counter(\n"
           "    'pio_autotrain_ghost_total', 'x')\n")
    found = [f for f in decl_pass.run([_mod(src)], readme_text="")
             if f.rule == "metric-undeclared"]
    assert len(found) == 1
    assert "pio_autotrain_ghost_total" in found[0].message


def test_run_loop_stops_and_survives_gather_failure():
    class DeadControl(ServerControl):
        def status(self):
            raise RuntimeError("server restarting")

        def publish(self):
            pass

    at = Autotrain(DeadControl(), storage=None, trainer=FakeTrainer(),
                   config=_cfg(poll_ms=10.0))
    t = threading.Thread(target=at.run, daemon=True)
    t.start()
    time.sleep(0.15)
    at.stop()
    t.join(timeout=5)
    assert not t.is_alive()
    warns = [e for e in journal.snapshot(level="warn")["events"]
             if e["category"] == "autotrain"]
    # one WARN per failure streak, not one per tick
    assert len([e for e in warns
                if "signal gather failed" in e["message"]]) == 1
