"""Bench-trajectory tracker tests (tools/benchtrend.py).

Acceptance: `python -m predictionio_tpu.tools.benchtrend BENCH_r*.json`
prints a trend table over the historical rounds and exits nonzero on an
injected regression fixture; the comparability rules (metric-name
match, warm-cache-only warmup comparisons) keep the gate honest.
"""

import glob
import json
import os

import pytest

from predictionio_tpu.tools import benchtrend

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_round(tmp_path, n, value, detail=None, metric="m_steady_s",
                 wrapper=True):
    body = {"metric": metric, "value": value, "unit": "s",
            "detail": detail or {}}
    payload = {"n": n, "cmd": "python bench.py", "rc": 0,
               "tail": "...", "parsed": body} if wrapper else body
    path = tmp_path / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps(payload))
    return str(path)


def test_loads_both_wrapper_and_bare_formats(tmp_path):
    p1 = _write_round(tmp_path, 1, 2.0, wrapper=True)
    p2 = _write_round(tmp_path, 2, 1.5, wrapper=False)
    rounds, skipped = benchtrend.load_rounds([p1, p2])
    assert not skipped
    assert [r["label"] for r in rounds] == ["r01", "r02"]
    assert [r["value"] for r in rounds] == [2.0, 1.5]


def test_unparseable_files_skipped_not_fatal(tmp_path):
    good = _write_round(tmp_path, 1, 2.0)
    bad = tmp_path / "BENCH_r02.json"
    bad.write_text("{not json")
    rounds, skipped = benchtrend.load_rounds([good, str(bad)])
    assert len(rounds) == 1 and skipped == [str(bad)]


def test_improving_series_passes_gate(tmp_path):
    paths = [_write_round(tmp_path, n, v, {"serve_http_p99_ms": p})
             for n, (v, p) in enumerate(
                 [(10.0, 2.0), (8.0, 1.8), (7.5, 1.9)], start=1)]
    rounds, _ = benchtrend.load_rounds(paths)
    assert benchtrend.gate(rounds) == []
    assert benchtrend.main(paths) == 0
    assert benchtrend.main(["--gate", *paths]) == 0


def test_injected_regression_fixture_exits_nonzero(tmp_path, capsys):
    paths = [_write_round(tmp_path, n, v)
             for n, v in enumerate([10.0, 8.0, 7.5], start=1)]
    # injected regression: 3x the best prior run's headline
    paths.append(_write_round(tmp_path, 4, 22.5))
    assert benchtrend.main(["--gate", *paths]) == 1
    err = capsys.readouterr().err
    assert "BENCHTREND GATE FAILED" in err and "value" in err
    # report-only mode still prints the table and exits 0
    assert benchtrend.main(paths) == 0
    out = capsys.readouterr().out
    assert "m_steady_s" in out and "r04" in out


def test_gate_honored_via_strict_env(tmp_path, monkeypatch):
    paths = [_write_round(tmp_path, 1, 10.0),
             _write_round(tmp_path, 2, 30.0)]
    monkeypatch.setenv("BENCH_STRICT_EXTRAS", "1")
    assert benchtrend.main(paths) == 1


def test_headline_only_compares_same_metric_name(tmp_path):
    # r01 measured a DIFFERENT headline (wallclock); a later steady-state
    # round must not be compared against it
    p1 = _write_round(tmp_path, 1, 1.0, metric="m_wallclock_s")
    p2 = _write_round(tmp_path, 2, 9.0, metric="m_steady_s")
    rounds, _ = benchtrend.load_rounds([p1, p2])
    assert benchtrend.gate(rounds) == []


def test_warmup_compile_only_compared_warm_cache(tmp_path):
    warm = {"compile_cache": {"before": {"entries": 100, "bytes": 1}}}
    cold = {"compile_cache": {"before": {"entries": 0, "bytes": 0}}}
    # cold round pays the full remote compile: NOT a regression
    paths = [
        _write_round(tmp_path, 1, 1.0, {"warmup_compile_s": 30.0, **warm}),
        _write_round(tmp_path, 2, 1.0, {"warmup_compile_s": 400.0, **cold}),
    ]
    rounds, _ = benchtrend.load_rounds(paths)
    assert benchtrend.gate(rounds) == []
    # two WARM rounds with a blowup between them: that IS a regression
    paths.append(_write_round(
        tmp_path, 3, 1.0, {"warmup_compile_s": 400.0, **warm}))
    rounds, _ = benchtrend.load_rounds(paths)
    failures = benchtrend.gate(rounds)
    assert any("warmup_compile_s" in f for f in failures)


def test_threshold_is_configurable(tmp_path):
    paths = [_write_round(tmp_path, 1, 10.0),
             _write_round(tmp_path, 2, 11.5)]   # +15%
    rounds, _ = benchtrend.load_rounds(paths)
    assert benchtrend.gate(rounds, threshold=0.25) == []
    assert len(benchtrend.gate(rounds, threshold=0.10)) == 1


def test_up_metrics_gate_on_decreases(tmp_path):
    paths = [
        _write_round(tmp_path, 1, 1.0, {"serve_batched_qps_gain": 3.0}),
        _write_round(tmp_path, 2, 1.0, {"serve_batched_qps_gain": 1.2}),
    ]
    rounds, _ = benchtrend.load_rounds(paths)
    failures = benchtrend.gate(rounds)
    assert any("serve_batched_qps_gain" in f for f in failures)


def test_gate_current_for_bench_wiring(tmp_path):
    history = [_write_round(tmp_path, n, v)
               for n, v in enumerate([10.0, 8.0], start=1)]
    current = {"metric": "m_steady_s", "value": 8.2,
               "detail": {"serve_http_p99_ms": 1.0}}
    failures, brief = benchtrend.gate_current(current, history)
    assert failures == []
    assert brief["value"]["best_prior"] == 8.0
    assert brief["value"]["current"] == 8.2
    current["value"] = 30.0
    failures, _brief = benchtrend.gate_current(current, history)
    assert failures and "value" in failures[0]


# ---------------------------------------------------------------------------
# ABSOLUTE_GATES: the warm-cache-only availability ceilings
# ---------------------------------------------------------------------------

_WARM = {"compile_cache": {"before": {"entries": 100, "bytes": 1}}}
_COLD = {"compile_cache": {"before": {"entries": 0, "bytes": 0}}}


def test_absolute_gate_fires_on_first_ever_warm_round(tmp_path):
    """The ceiling needs NO prior round: the very first warm-cache round
    is already accountable for the < 10 s warm-replica promise."""
    p = _write_round(tmp_path, 1, 1.0,
                     {"time_to_ready_s": 42.0, **_WARM})
    rounds, _ = benchtrend.load_rounds([p])
    failures = benchtrend.gate(rounds)
    assert len(failures) == 1
    assert "time_to_ready_s" in failures[0] and "ceiling" in failures[0]
    # and a first warm round UNDER the ceiling gates nothing
    ok = _write_round(tmp_path, 1, 1.0,
                      {"time_to_ready_s": 3.0, **_WARM})
    rounds, _ = benchtrend.load_rounds([ok])
    assert benchtrend.gate(rounds) == []


def test_absolute_gate_skips_cold_and_unknown_cache_rounds(tmp_path):
    # cold cache: full compiles are legitimate, not an availability breach
    cold = _write_round(tmp_path, 1, 1.0,
                        {"time_to_ready_s": 400.0, **_COLD})
    rounds, _ = benchtrend.load_rounds([cold])
    assert benchtrend.gate(rounds) == []
    # no compile_cache detail at all (pre-r05 era): unknown, never gated
    unknown = _write_round(tmp_path, 1, 1.0, {"time_to_ready_s": 400.0})
    rounds, _ = benchtrend.load_rounds([unknown])
    assert benchtrend.gate(rounds) == []


def test_absolute_gate_mixed_history_judges_newest_round_only(tmp_path):
    """Mixed warm/cold history: only the NEWEST round's own cache state
    decides whether its ceiling applies — a breaching warm round fails
    even after a cold round, and a cold newest round passes even after
    warm priors."""
    paths = [
        _write_round(tmp_path, 1, 1.0, {"time_to_ready_s": 3.0, **_WARM}),
        _write_round(tmp_path, 2, 1.0,
                     {"time_to_ready_s": 400.0, **_COLD}),
        _write_round(tmp_path, 3, 1.0, {"time_to_ready_s": 12.0, **_WARM}),
    ]
    rounds, _ = benchtrend.load_rounds(paths)
    failures = benchtrend.gate(rounds)
    assert any("time_to_ready_s" in f and "ceiling" in f
               for f in failures)
    # newest cold round after warm priors: the ceiling stands down
    paths.append(_write_round(tmp_path, 4, 1.0,
                              {"time_to_ready_s": 400.0, **_COLD}))
    rounds, _ = benchtrend.load_rounds(paths)
    assert not any("ceiling" in f for f in benchtrend.gate(rounds))


def test_absolute_gate_breach_exits_nonzero_via_cli(tmp_path, capsys):
    paths = [
        _write_round(tmp_path, 1, 1.0, {"time_to_ready_s": 3.0, **_WARM}),
        _write_round(tmp_path, 2, 1.0, {"time_to_ready_s": 30.0, **_WARM}),
    ]
    assert benchtrend.main(["--gate", *paths]) == 1
    err = capsys.readouterr().err
    assert "BENCHTREND GATE FAILED" in err and "time_to_ready_s" in err
    # report-only mode still prints the table and exits 0
    assert benchtrend.main(paths) == 0


def test_lint_findings_gate_is_unconditional(tmp_path):
    """`lint_findings_total` gates at 0 on the NEWEST round regardless
    of cache state or history depth — static-analysis debt can't ride a
    cold-cache round in, and suppressed (baselined) findings don't
    trip it."""
    # a single COLD round with findings still fails
    p = _write_round(tmp_path, 1, 1.0,
                     {"lint_findings_total": 3, **_COLD})
    rounds, _ = benchtrend.load_rounds([p])
    failures = benchtrend.gate(rounds)
    assert len(failures) == 1 and "lint_findings_total" in failures[0]
    # clean lint with accepted baseline debt passes
    ok = _write_round(tmp_path, 1, 1.0,
                      {"lint_findings_total": 0,
                       "lint_suppressed_total": 5, **_COLD})
    rounds, _ = benchtrend.load_rounds([ok])
    assert benchtrend.gate(rounds) == []
    # rounds predating the lint leg (no key at all) are not judged
    legacy = _write_round(tmp_path, 1, 1.0, {})
    rounds, _ = benchtrend.load_rounds([legacy])
    assert benchtrend.gate(rounds) == []


@pytest.mark.parametrize("gate_flag", [False, True])
def test_real_repo_history_renders_and_passes(gate_flag, capsys):
    """The actual 5-round BENCH_r*.json series in the repo: the table
    renders every round and the default-threshold gate passes (the
    recorded history has no >25% regression on a gated metric)."""
    paths = sorted(glob.glob(os.path.join(HERE, "BENCH_r*.json")))
    if len(paths) < 2:
        pytest.skip("no bench history in this checkout")
    argv = (["--gate"] if gate_flag else []) + paths
    assert benchtrend.main(argv) == 0
    out = capsys.readouterr().out
    for label in ("r01", "r05", "steady_per_iter_ms", "warmup_compile_s"):
        assert label in out
