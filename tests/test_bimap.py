"""BiMap parity with BiMap.scala:28-167 + the vectorized encode path."""

import numpy as np
import pytest

from predictionio_tpu.data.bimap import BiMap


def test_string_int_contiguous_and_stable():
    m = BiMap.string_int(["b", "a", "b", "c", "a"])
    assert len(m) == 3
    assert sorted([m("a"), m("b"), m("c")]) == [0, 1, 2]
    assert m("b") == 0  # first-appearance order


def test_inverse():
    m = BiMap.string_int(["x", "y"])
    inv = m.inverse()
    assert inv(m("x")) == "x"
    assert inv(m("y")) == "y"


def test_non_injective_rejected():
    with pytest.raises(ValueError):
        BiMap({"a": 1, "b": 1})


def test_encode_decode_array():
    m = BiMap.string_int(["u%d" % i for i in range(100)])
    keys = ["u5", "u99", "u0"]
    arr = m.encode_array(keys)
    assert arr.dtype == np.int32
    assert m.decode_array(arr) == keys


def test_string_double():
    m = BiMap.string_double(["a", "b"])
    assert isinstance(m("a"), float)


def test_take_and_contains():
    m = BiMap.string_int(["a", "b", "c"])
    assert "a" in m and "z" not in m
    assert len(m.take(2)) == 2
