"""Chaos suite: end-to-end fault injection across the distributed edges.

Every test here exercises a REAL failure path — lost responses, dead
endpoints, retry storms, drain-during-burst — through the actual HTTP
transport and storage stack, driven by common/resilience.FaultInjector.

Markers: the whole module is `chaos`. Tests carrying ONLY that marker
are the fast smoke subset and run in tier-1 (`-m "not slow"`); the
heavier soak legs also carry `slow` and run via `-m chaos`.
"""

import datetime as dt
import json
import threading
import time

import numpy as np
import pytest

from predictionio_tpu.common import resilience
from predictionio_tpu.common.resilience import CircuitBreaker, CircuitOpenError
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.data.storage.remote import StorageRPCAPI, serve_storage

pytestmark = pytest.mark.chaos

UTC = dt.timezone.utc


@pytest.fixture(autouse=True)
def _clean_injection():
    """No fault spec or breaker state leaks between tests."""
    resilience.clear()
    CircuitBreaker.reset_registry()
    yield
    resilience.clear()
    CircuitBreaker.reset_registry()


def _mk(eid="u1", iid="i1", rating=3.0, sec=0):
    return Event(event="rate", entity_type="user", entity_id=eid,
                 target_entity_type="item", target_entity_id=iid,
                 properties=DataMap({"rating": rating}),
                 event_time=dt.datetime(2021, 1, 1, tzinfo=UTC)
                 + dt.timedelta(seconds=sec))


def _backing(tmp_path, kind="eventlog"):
    if kind == "memory":
        env = {
            "PIO_STORAGE_SOURCES_B_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "B",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "B",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "B",
        }
    else:
        env = {
            "PIO_STORAGE_SOURCES_M_TYPE": "memory",
            "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
            "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path / "el"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        }
    return Storage(env=env)


def _remote(port, **props):
    env = {
        "PIO_STORAGE_SOURCES_R_TYPE": "remote",
        "PIO_STORAGE_SOURCES_R_URL": f"http://127.0.0.1:{port}",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "R",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "R",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "R",
    }
    for k, v in props.items():
        env[f"PIO_STORAGE_SOURCES_R_{k}"] = str(v)
    return Storage(env=env)


# ---------------------------------------------------------------------------
# storage server death + retry recovery
# ---------------------------------------------------------------------------

def test_server_killed_between_reads_recovers_by_reconnect(tmp_path):
    """Kill the storage server, restart it on the same port: the client's
    dead keep-alive connection turns into a ConnectionError that the
    idempotent read path retries on a fresh connection — identical rows,
    no duplicates, no missing."""
    backing = _backing(tmp_path)
    app_id = backing.get_meta_data_apps().insert(App(0, "chaos"))
    ev_b = backing.get_events()
    ev_b.init(app_id)
    ev_b.insert_batch([_mk(f"u{k}", f"i{k % 3}", sec=k) for k in range(20)],
                      app_id)

    server = serve_storage(backing, host="127.0.0.1", port=0)
    port = server.server_address[1]
    remote = _remote(port)
    ev = remote.get_events()
    before = ev.read_columns(app_id, event_names=["rate"])
    assert len(before["rating"]) == 20

    server.shutdown()
    server.server_close()           # the "kill"
    server2 = serve_storage(backing, host="127.0.0.1", port=port)
    try:
        after = ev.read_columns(app_id, event_names=["rate"])
        np.testing.assert_array_equal(before["entity_code"],
                                      after["entity_code"])
        np.testing.assert_array_equal(before["rating"], after["rating"])
        assert len(list(ev.find(app_id))) == 20
    finally:
        server2.shutdown()
        server2.server_close()


def test_response_loss_mid_read_columns_retried(tmp_path):
    """The acceptance scenario: the server dies mid-read_columns (request
    processed, response lost). With retries configured, the idempotent
    binary route replays and returns the full, identical rows."""
    backing = _backing(tmp_path)
    app_id = backing.get_meta_data_apps().insert(App(0, "chaos"))
    ev_b = backing.get_events()
    ev_b.init(app_id)
    ev_b.insert_batch([_mk(f"u{k}", f"i{k % 3}", sec=k) for k in range(10)],
                      app_id)
    server = serve_storage(backing, host="127.0.0.1", port=0)
    try:
        remote = _remote(server.server_address[1], RETRIES=2,
                         BACKOFF_MS=1)
        inj = resilience.install("drop_rx:1:1@read_columns")
        cols = remote.get_events().read_columns(app_id,
                                                event_names=["rate"])
        assert inj.fired.get("drop_rx") == 1   # the fault really fired
        assert len(cols["rating"]) == 10
        direct = ev_b.read_columns(app_id, event_names=["rate"])
        np.testing.assert_array_equal(cols["entity_code"],
                                      direct["entity_code"])
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# the unsafe-retry bug and its dedup fix
# ---------------------------------------------------------------------------

def test_write_response_loss_surfaces_error_without_dedup(tmp_path):
    """Satellite #1, the latent bug made explicit: a ConnectionError
    AFTER the server committed an insert must NOT be silently retried —
    a blind resend would double-store every event. Without dedup the
    client surfaces the error; the server holds exactly one copy."""
    backing = _backing(tmp_path, "memory")
    app_id = backing.get_meta_data_apps().insert(App(0, "chaos"))
    backing.get_events().init(app_id)
    server = serve_storage(backing, host="127.0.0.1", port=0)
    try:
        remote = _remote(server.server_address[1], RETRIES=3,
                         BACKOFF_MS=1)
        resilience.install("drop_rx:1:1@client POST /rpc")
        with pytest.raises((ConnectionError, OSError)):
            remote.get_events().insert(_mk(), app_id)
        # the request DID reach the server (it processes the already-sent
        # bytes on its own thread); poll for the commit, then confirm the
        # client never resent it
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if list(backing.get_events().find(app_id)):
                break
            time.sleep(0.01)
        assert len(list(backing.get_events().find(app_id))) == 1
        time.sleep(0.05)   # any (buggy) resend would land by now
        assert len(list(backing.get_events().find(app_id))) == 1
    finally:
        server.shutdown()
        server.server_close()


def test_write_dedup_makes_insert_retry_exactly_once(tmp_path):
    """With WRITE_DEDUP on, the retried insert carries the same one-shot
    token; the server replays the stored reply instead of re-inserting:
    the client gets the ORIGINAL event ids and the store holds exactly
    one copy — exactly-once across a lost response."""
    backing = _backing(tmp_path, "memory")
    app_id = backing.get_meta_data_apps().insert(App(0, "chaos"))
    backing.get_events().init(app_id)
    server = serve_storage(backing, host="127.0.0.1", port=0)
    try:
        remote = _remote(server.server_address[1], RETRIES=3,
                         BACKOFF_MS=1, WRITE_DEDUP=1)
        inj = resilience.install("drop_rx:1:1@client POST /rpc")
        ids = remote.get_events().insert_batch(
            [_mk("u1", "i1"), _mk("u2", "i2", sec=1)], app_id)
        assert inj.fired.get("drop_rx") == 1
        stored = list(backing.get_events().find(app_id))
        assert len(stored) == 2                      # no duplicates
        assert sorted(ids) == sorted(e.event_id for e in stored)
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# circuit breaker end-to-end
# ---------------------------------------------------------------------------

def test_breaker_opens_fast_fails_and_recovers_endtoend(
        tmp_path, monkeypatch):
    """Sustained faults open the shared per-endpoint breaker: calls fast-
    fail without touching the wire; after open_s a half-open probe goes
    through and, once the endpoint heals, closes the breaker."""
    monkeypatch.setenv("PIO_BREAKER_ENABLED", "1")
    monkeypatch.setenv("PIO_BREAKER_MIN_CALLS", "4")
    monkeypatch.setenv("PIO_BREAKER_ERROR_RATE", "0.5")
    monkeypatch.setenv("PIO_BREAKER_OPEN_S", "0.3")
    CircuitBreaker.reset_registry()

    backing = _backing(tmp_path, "memory")
    app_id = backing.get_meta_data_apps().insert(App(0, "chaos"))
    backing.get_events().init(app_id)

    calls = {"n": 0}
    real_handle = StorageRPCAPI.handle

    class Counting:
        def __init__(self, inner):
            self.inner = inner

        def handle(self, *a, **kw):
            calls["n"] += 1
            return real_handle(self.inner, *a, **kw)

    from predictionio_tpu.data.api.http import serve_background
    api = Counting(StorageRPCAPI(backing))
    server, port = serve_background(api, host="127.0.0.1")
    try:
        remote = _remote(port)
        ev = remote.get_events()
        resilience.install("error:1:503@client")
        for _ in range(4):    # sustained faults fill the window
            with pytest.raises(RuntimeError, match="503"):
                ev.get("nope", app_id)
        wire_before = calls["n"]
        with pytest.raises(CircuitOpenError):   # OPEN: fast-fail
            ev.get("nope", app_id)
        assert calls["n"] == wire_before        # nothing touched the wire
        # endpoint heals; after open_s the half-open probe closes it
        resilience.clear()
        time.sleep(0.35)
        assert ev.get("nope", app_id) is None   # probe succeeds
        assert ev.get("nope", app_id) is None   # breaker closed again
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# health probes, deadline, defaults wire parity
# ---------------------------------------------------------------------------

def test_storage_server_health_probes_and_drain(tmp_path):
    api = StorageRPCAPI(_backing(tmp_path, "memory"), key="sekrit")
    # health endpoints answer WITHOUT the storage key (LB probes)
    assert api.handle("GET", "/healthz")[0] == 200
    status, payload = api.handle("GET", "/readyz")
    assert status == 200 and payload["status"] == "ready"
    api.draining = True
    status, payload = api.handle("GET", "/readyz")
    assert status == 503 and payload["status"] == "draining"
    # a spent deadline fast-fails before the DAO dispatch
    status, _ = api.handle(
        "POST", "/rpc",
        body=json.dumps({"dao": "apps", "method": "get_all",
                         "args": {}}).encode(),
        headers={"X-PIO-Storage-Key": "sekrit",
                 "X-PIO-Deadline-Ms": "0"})
    assert status == 504


def test_event_server_health_probes(memory_storage):
    from predictionio_tpu.data.api import EventAPI
    api = EventAPI(storage=memory_storage)
    assert api.handle("GET", "/healthz")[0] == 200
    assert api.handle("GET", "/readyz")[0] == 200
    api.draining = True
    status, payload = api.handle("GET", "/readyz")
    assert status == 503 and payload["status"] == "draining"


def test_defaults_wire_byte_identical(tmp_path):
    """Acceptance: with PIO_FAULT_SPEC unset and every resilience knob at
    its default, the remote wire traffic is byte-identical to the
    pre-PR driver — no deadline header, no dedup field, same legacy
    retry shape (one reconnect retry for idempotent calls only)."""
    backing = _backing(tmp_path, "memory")
    app_id = backing.get_meta_data_apps().insert(App(0, "chaos"))
    backing.get_events().init(app_id)

    seen = []
    real_handle = StorageRPCAPI.handle

    class Recording:
        def __init__(self, inner):
            self.inner = inner

        def handle(self, method, path, query=None, body=b"",
                   headers=None):
            seen.append((method, path, dict(headers or {}), bytes(body)))
            return real_handle(self.inner, method, path, query, body,
                               headers)

    from predictionio_tpu.data.api.http import serve_background
    server, port = serve_background(Recording(StorageRPCAPI(backing)),
                                    host="127.0.0.1")
    try:
        remote = _remote(port)
        ev = remote.get_events()
        ev.insert(_mk(), app_id)
        assert len(list(ev.find(app_id))) == 1
        for _method, _path, headers, body in seen:
            assert not any(h.lower() == "x-pio-deadline-ms"
                           for h in headers)
            if body[:1] == b"{":
                envelope = json.loads(body)
                if "dao" in envelope:
                    assert set(envelope) == {"dao", "method", "args"}
        # legacy retry shape: a pre-send drop is retried for a read...
        n_before = len(seen)
        resilience.install("drop:1:1@client")
        assert len(list(ev.find(app_id))) == 1
        resilience.clear()
        # ...but an insert facing a pre-send drop fails WITHOUT a resend
        resilience.install("drop:1:1@client")
        with pytest.raises((ConnectionError, OSError)):
            ev.insert(_mk("u2"), app_id)
        resilience.clear()
        inserts = [s for s in seen[n_before:]
                   if b"insert_batch" in s[3]]
        assert inserts == []   # the dropped insert never hit the wire
    finally:
        server.shutdown()
        server.server_close()


def test_connection_pool_reuses_and_bounds_sockets(tmp_path):
    """The remote driver's keep-alive pool: sequential calls share ONE
    dialed connection (even across threads), burst concurrency dials
    more but retains at most the configured bound, and a transport
    failure discards its socket instead of re-pooling it."""
    backing = _backing(tmp_path, "memory")
    app_id = backing.get_meta_data_apps().insert(App(0, "chaos"))
    backing.get_events().init(app_id)
    server = serve_storage(backing, host="127.0.0.1", port=0)
    try:
        remote = _remote(server.server_address[1], POOL=2, RETRIES=2,
                         BACKOFF_MS=1)
        ev = remote.get_events()
        client = ev.c
        ev.insert(_mk("u1"), app_id)
        for _ in range(5):
            assert len(list(ev.find(app_id))) == 1
        # every sequential call reused the first dialed socket — and the
        # reuse crosses threads (the old driver parked one per thread)
        t = threading.Thread(
            target=lambda: list(ev.find(app_id)))
        t.start()
        t.join()
        assert client._pool.dials == 1

        # burst: more dials allowed, idle retention bounded by POOL
        def call():
            list(ev.find(app_id))
        threads = [threading.Thread(target=call) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert len(client._pool._idle) <= 2

        # a failed socket is never re-pooled: the injected drop forces a
        # close + fresh dial on the retry
        dials_before = client._pool.dials
        resilience.install("drop:1:1@client")
        assert len(list(ev.find(app_id))) == 1
        assert client._pool.dials >= dials_before
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# query server: drain under a concurrent burst + degraded responses
# ---------------------------------------------------------------------------

def _train_tiny(memory_storage):
    from predictionio_tpu.controller import EngineParams
    from predictionio_tpu.data import store
    from predictionio_tpu.models.recommendation import (
        ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
    )
    from predictionio_tpu.workflow import WorkflowContext, run_train
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "ChaosApp", None))
    memory_storage.get_events().init(app_id)
    events = []
    for u in range(8):
        for i in range(6):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap(
                    {"rating": 5.0 if (u % 2) == (i % 2) else 1.0}),
                event_time=dt.datetime(2021, 1, 1, 0, (u * 6 + i) % 60,
                                       tzinfo=UTC)))
    store.write(events, app_id, storage=memory_storage)
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="ChaosApp"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=4, numIterations=3,
                                       lambda_=0.05, seed=3)),))
    run_train(
        WorkflowContext(storage=memory_storage), engine, ep,
        engine_factory=("predictionio_tpu.models.recommendation"
                        ":RecommendationEngine"),
        params_json={
            "datasource": {"params": {"appName": "ChaosApp"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 4, "numIterations": 3, "lambda": 0.05,
                "seed": 3}}]})


def test_drain_during_burst_drops_zero_inflight(memory_storage):
    """Acceptance: SIGTERM (-> drain()) during a concurrent query burst.
    Every admitted request completes with its real answer; late arrivals
    get a clean 503 + Retry-After; zero requests hang or error out."""
    from predictionio_tpu.workflow.create_server import (
        QueryAPI, ServerConfig,
    )
    _train_tiny(memory_storage)
    api = QueryAPI(storage=memory_storage, config=ServerConfig(
        batching="on", batch_max_size=4, batch_max_delay_ms=20.0))
    body = json.dumps({"user": "u1", "num": 3}).encode()
    results = [None] * 24
    started = threading.Barrier(25, timeout=10)

    def client(k):
        started.wait()
        time.sleep(0.002 * k)   # stagger across the drain point
        results[k] = api.handle("POST", "/queries.json", body=body)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(24)]
    for t in threads:
        t.start()
    started.wait()
    time.sleep(0.01)
    api.drain()
    for t in threads:
        t.join(15)
        assert not t.is_alive(), "a request hung through drain"

    statuses = [r[0] for r in results]
    assert set(statuses) <= {200, 503}, statuses
    assert statuses.count(200) >= 1     # the early ones were served
    for status, *rest in results:
        if status == 200:
            assert rest[0]["itemScores"], "admitted request lost its answer"
    # post-drain surface: not ready, queries 503, stop requested
    assert api.handle("GET", "/readyz")[0] == 503
    assert api.handle("POST", "/queries.json", body=body)[0] == 503
    assert api.stop_requested
    # idempotent: a second drain is a no-op
    api.drain()
    api.close()


def test_no_lock_order_inversions_under_concurrent_serving(memory_storage):
    """Runtime half of the lock-order lint (tools/analyze/runtime.py):
    the static pass sees syntactic nesting; a lock held while CALLING
    into another module (batcher condition -> telemetry family locks on
    the flush path) is invisible to it. Here the REAL locks of the
    serving stack are wrapped with order-recording proxies, a concurrent
    query burst drives them, and the observed acquisition graph must be
    inversion-free — the same two-phase shape a deadlock needs, caught
    even when this run never interleaved into the deadlock."""
    from predictionio_tpu.common import telemetry
    from predictionio_tpu.tools.analyze.runtime import LockOrderMonitor
    from predictionio_tpu.workflow.create_server import (
        QueryAPI, ServerConfig,
    )
    _train_tiny(memory_storage)
    telemetry.set_enabled(True)
    api = QueryAPI(storage=memory_storage, config=ServerConfig(
        batching="on", batch_max_size=4, batch_max_delay_ms=5.0))
    monitor = LockOrderMonitor()
    reg = telemetry.REGISTRY
    # wrap in place: the proxies forward acquire/release/wait/notify.
    # The interesting holds are batcher._cond -> metric-CHILD locks
    # (admission/flush update counters under the condition); lock
    # identity is per family, matching the static pass's class-level
    # nodes (all children of one family are one node).
    batcher = api._batcher
    batcher._cond = monitor.wrap(batcher._cond, "batcher._cond")
    reg._lock = monitor.wrap(reg._lock, "registry._lock")
    for fam in list(reg._families.values()):
        fam._lock = monitor.wrap(fam._lock, f"family[{fam.name}]._lock")
        for child in list(fam._children.values()):
            child._lock = monitor.wrap(
                child._lock, f"family[{fam.name}].child._lock")
    try:
        body = json.dumps({"user": "u1", "num": 3}).encode()
        results = [None] * 16

        def client(k):
            results[k] = api.handle("POST", "/queries.json", body=body)

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(15)
            assert not t.is_alive()
        assert all(r[0] in (200, 503) for r in results)
        assert any(r[0] == 200 for r in results)
    finally:
        api.close()
        telemetry.set_enabled(None)
    assert monitor.inversions() == [], monitor.edges()
    # the burst actually exercised cross-module holds (the monitor
    # measured something, not an idle graph)
    assert monitor.edges(), "no lock nesting observed — wrap points stale?"


def test_sigterm_handler_invokes_drain():
    """The actual signal wiring: SIGTERM delivered to the process runs
    the registered drain callback (on its own thread)."""
    import os
    import signal

    from predictionio_tpu.data.api.http import install_sigterm_handler
    prior = signal.getsignal(signal.SIGTERM)
    drained = threading.Event()
    try:
        assert install_sigterm_handler(drained.set) is True
        os.kill(os.getpid(), signal.SIGTERM)
        assert drained.wait(5), "SIGTERM did not reach the drain callback"
    finally:
        signal.signal(signal.SIGTERM, prior)


def test_query_api_healthz_readyz(memory_storage):
    from predictionio_tpu.workflow.create_server import QueryAPI
    _train_tiny(memory_storage)
    api = QueryAPI(storage=memory_storage)
    assert api.handle("GET", "/healthz")[0] == 200
    status, payload = api.handle("GET", "/readyz")
    assert status == 200
    assert payload["modelLoaded"] is True and payload["storage"] == "ok"
    api.close()


def test_degraded_side_channel_flags_response(memory_storage):
    """A failed storage side-channel lookup mid-request serves from
    on-device factors with `degraded: true` instead of a 500 — on both
    the batched and the inline path."""
    from predictionio_tpu.workflow.create_server import (
        QueryAPI, ServerConfig,
    )
    _train_tiny(memory_storage)
    body = json.dumps({"user": "u1", "num": 3}).encode()

    for batching in ("on", "off"):
        api = QueryAPI(storage=memory_storage,
                       config=ServerConfig(batching=batching))
        algo = api.algorithms[0]
        if batching == "on":
            real = type(algo).predict_batch

            def flaky_batch(model, queries, _real=real, _a=algo):
                resilience.note_degraded("chaos: lookup failed")
                return _real(_a, model, queries)

            algo.predict_batch = flaky_batch
        else:
            real_p = type(algo).predict

            def flaky(model, query, _real=real_p, _a=algo):
                resilience.note_degraded("chaos: lookup failed")
                return _real(_a, model, query)

            algo.predict = flaky
        status, payload = api.handle("POST", "/queries.json", body=body)
        assert status == 200, payload
        assert payload.get("degraded") is True
        assert payload["itemScores"]
        assert api.degraded_count >= 1
        api.close()


# ---------------------------------------------------------------------------
# crash recovery: pio train auto-resume
# ---------------------------------------------------------------------------

def _train_ckpt(memory_storage, iters=3):
    """Tiny recommendation train WITH iteration checkpointing; returns
    (ctx, instance_id)."""
    from predictionio_tpu.controller import EngineParams
    from predictionio_tpu.data import store
    from predictionio_tpu.models.recommendation import (
        ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
    )
    from predictionio_tpu.workflow import WorkflowContext, run_train
    apps = memory_storage.get_meta_data_apps()
    if not apps.get_by_name("ChaosApp"):
        apps.insert(App(0, "ChaosApp", None))
    app_id = apps.get_by_name("ChaosApp").id
    memory_storage.get_events().init(app_id)
    events = [Event(
        event="rate", entity_type="user", entity_id=f"u{u}",
        target_entity_type="item", target_entity_id=f"i{i}",
        properties=DataMap({"rating": 5.0 if (u % 2) == (i % 2) else 1.0}),
        event_time=dt.datetime(2021, 1, 1, 0, (u * 6 + i) % 60, tzinfo=UTC))
        for u in range(8) for i in range(6)]
    store.write(events, app_id, storage=memory_storage)
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="ChaosApp"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=4, numIterations=iters,
                                       lambda_=0.05, seed=3,
                                       checkpointInterval=1)),))
    ctx = WorkflowContext(storage=memory_storage)
    iid = run_train(
        ctx, engine, ep,
        engine_factory=("predictionio_tpu.models.recommendation"
                        ":RecommendationEngine"))
    return ctx, iid


def test_train_auto_resumes_from_crashed_run(memory_storage, tmp_path,
                                             monkeypatch):
    """A prior run of the same engine/variant that crashed (ERROR row,
    surviving FactorCheckpointer dir) seeds the next `pio train`
    automatically; on success the snapshots are cleared."""
    from predictionio_tpu.data.storage import EngineInstance
    from predictionio_tpu.workflow.checkpoint import (
        FactorCheckpointer, latest_step_in, run_checkpoint_dir,
    )
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
    now = dt.datetime.now(UTC)
    crashed_id = memory_storage.get_meta_data_engine_instances().insert(
        EngineInstance(
            id="", status="ERROR", start_time=now, end_time=now,
            engine_id="default", engine_version="NOT_USED",
            engine_variant="default", engine_factory="f"))
    rng = np.random.default_rng(0)
    FactorCheckpointer(run_checkpoint_dir(crashed_id)).save(1, {
        "U": rng.normal(size=(8, 4)), "V": rng.normal(size=(6, 4))})

    ctx, iid = _train_ckpt(memory_storage)
    # the run adopted the crashed run's checkpoint directory...
    assert ctx.checkpoint_dir == run_checkpoint_dir(crashed_id)
    row = memory_storage.get_meta_data_engine_instances().get(iid)
    assert row.status == "COMPLETED"
    # ...and cleared the scratch snapshots on success
    assert latest_step_in(run_checkpoint_dir(crashed_id)) is None


def test_train_auto_resume_opt_out(memory_storage, tmp_path, monkeypatch):
    from predictionio_tpu.data.storage import EngineInstance
    from predictionio_tpu.workflow.checkpoint import (
        FactorCheckpointer, run_checkpoint_dir,
    )
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
    monkeypatch.setenv("PIO_AUTO_RESUME", "0")
    now = dt.datetime.now(UTC)
    crashed_id = memory_storage.get_meta_data_engine_instances().insert(
        EngineInstance(
            id="", status="ERROR", start_time=now, end_time=now,
            engine_id="default", engine_version="NOT_USED",
            engine_variant="default", engine_factory="f"))
    rng = np.random.default_rng(0)
    FactorCheckpointer(run_checkpoint_dir(crashed_id)).save(1, {
        "U": rng.normal(size=(8, 4)), "V": rng.normal(size=(6, 4))})
    ctx, iid = _train_ckpt(memory_storage)
    assert ctx.checkpoint_dir == run_checkpoint_dir(iid)   # its own dir


# ---------------------------------------------------------------------------
# soak: mixed faults under retries (heavy — excluded from tier-1)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_mixed_faults_zero_surfaced_errors(tmp_path):
    """200 reads under 5% drops + 5% 503s + 20% added latency: with
    retries configured every single call succeeds, and the data is
    identical to a clean read."""
    backing = _backing(tmp_path)
    app_id = backing.get_meta_data_apps().insert(App(0, "chaos"))
    ev_b = backing.get_events()
    ev_b.init(app_id)
    ev_b.insert_batch([_mk(f"u{k}", f"i{k % 5}", sec=k) for k in range(50)],
                      app_id)
    server = serve_storage(backing, host="127.0.0.1", port=0)
    try:
        remote = _remote(server.server_address[1], RETRIES=4,
                         BACKOFF_MS=2, BACKOFF_MAX_MS=20)
        ev = remote.get_events()
        clean = ev.read_columns(app_id, event_names=["rate"])
        inj = resilience.install(
            "drop:0.05@client,error:0.05:503@client,latency:0.2:2@client",
            seed=7)
        errors = 0
        for k in range(200):
            try:
                if k % 10 == 0:
                    cols = ev.read_columns(app_id, event_names=["rate"])
                    np.testing.assert_array_equal(cols["rating"],
                                                  clean["rating"])
                else:
                    ev.get(f"missing-{k}", app_id)
            except Exception:
                errors += 1
        assert errors == 0
        assert inj.fired   # the storm actually happened
    finally:
        server.shutdown()
        server.server_close()
