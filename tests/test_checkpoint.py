"""Iteration-level checkpoint/resume tests (framework improvement over the
reference; SURVEY.md §5 checkpoint/resume)."""

import numpy as np
import pytest

from predictionio_tpu.ops import als
from predictionio_tpu.workflow.checkpoint import FactorCheckpointer


@pytest.fixture()
def data():
    rng = np.random.default_rng(7)
    n_users, n_items, nnz = 40, 30, 400
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    r = rng.uniform(1, 5, nnz).astype(np.float32)
    return als.prepare_ratings(u, i, r, n_users=n_users, n_items=n_items,
                               chunk=128)


def test_checkpointer_save_latest_keep(tmp_path):
    ckpt = FactorCheckpointer(str(tmp_path), keep=2)
    assert ckpt.latest() is None
    for step in (2, 4, 6):
        ckpt.save(step, {"U": np.full((3,), step, dtype=np.float32)})
    assert ckpt.steps() == [4, 6]  # keep=2 pruned step 2
    step, arrays = ckpt.latest()
    assert step == 6 and arrays["U"][0] == 6.0
    ckpt.clear()
    assert ckpt.latest() is None


def test_segmented_equals_straight_run(data, tmp_path):
    """Checkpointed training must be bit-identical to a straight run: the
    segments chain factor state, not RNG state."""
    U1, V1 = als.train_explicit(data, rank=4, iterations=6, seed=3,
                                chunk=128)
    ckpt = FactorCheckpointer(str(tmp_path))
    U2, V2 = als.train_explicit(data, rank=4, iterations=6, seed=3,
                                chunk=128, checkpoint_every=2,
                                checkpointer=ckpt)
    np.testing.assert_array_equal(np.asarray(U1), np.asarray(U2))
    np.testing.assert_array_equal(np.asarray(V1), np.asarray(V2))
    assert ckpt.steps() == [2, 4]  # intermediate snapshots only


def test_resume_from_interruption(data, tmp_path):
    """Simulate a crash after iteration 4 of 6: the rerun must resume from
    the snapshot and produce the same factors as an uninterrupted run."""
    ckpt = FactorCheckpointer(str(tmp_path))

    class Boom(RuntimeError):
        pass

    class FailingCheckpointer(FactorCheckpointer):
        def save(self, step, arrays):
            super().save(step, arrays)
            if step == 4:
                raise Boom()

    failing = FailingCheckpointer(str(tmp_path))
    with pytest.raises(Boom):
        als.train_explicit(data, rank=4, iterations=6, seed=3, chunk=128,
                           checkpoint_every=2, checkpointer=failing)
    assert ckpt.latest()[0] == 4
    U2, V2 = als.train_explicit(data, rank=4, iterations=6, seed=3,
                                chunk=128, checkpoint_every=2,
                                checkpointer=ckpt)
    U1, V1 = als.train_explicit(data, rank=4, iterations=6, seed=3,
                                chunk=128)
    np.testing.assert_array_equal(np.asarray(U1), np.asarray(U2))
    np.testing.assert_array_equal(np.asarray(V1), np.asarray(V2))


def test_workflow_resume_from_crashed_run(data, tmp_path, memory_storage,
                                          monkeypatch):
    """run_train(resume_from=<crashed id>) consults the crashed run's
    snapshots (the reviewer scenario: without resume_from each rerun got a
    fresh empty checkpoint dir and silently restarted from iteration 0)."""
    import json

    from predictionio_tpu.data import store as dstore
    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage import App
    from predictionio_tpu.models.recommendation import (
        ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
    )
    from predictionio_tpu.controller import EngineParams
    from predictionio_tpu.workflow import WorkflowContext, run_train
    from predictionio_tpu.workflow.checkpoint import (
        FactorCheckpointer, run_checkpoint_dir,
    )

    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
    app_id = memory_storage.get_meta_data_apps().insert(App(0, "RApp"))
    memory_storage.get_events().init(app_id)
    evs = []
    for u in range(6):
        for i in range(5):
            evs.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(1 + (u + i) % 5)})))
    dstore.write(evs, app_id, storage=memory_storage)

    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="RApp"),
        algorithm_params_list=(("als", ALSAlgorithmParams(
            rank=3, numIterations=6, seed=3, checkpointInterval=2)),))
    ctx = WorkflowContext(storage=memory_storage)

    # fake a crashed run: snapshots exist under its instance id
    crashed_id = "crashed-run"
    probe = np.full((6, 3), 7.0, dtype=np.float32)
    FactorCheckpointer(run_checkpoint_dir(crashed_id)).save(
        4, {"U": probe, "V": np.full((5, 3), 7.0, dtype=np.float32)})

    iid = run_train(ctx, engine, ep, engine_factory="x",
                    resume_from=crashed_id)
    # the resumed dir is cleared on success
    assert FactorCheckpointer(run_checkpoint_dir(crashed_id)).latest() is None
    # the run trained only iterations 5..6 from the probe factors: the
    # result must differ from a full 6-iteration run from the cold seed
    from predictionio_tpu.workflow import model_io
    blob = memory_storage.get_model_data_models().get(iid)
    resumed = model_io.deserialize_models(blob.models)[0]
    cold_iid = run_train(WorkflowContext(storage=memory_storage), engine, ep,
                         engine_factory="x")
    cold = model_io.deserialize_models(
        memory_storage.get_model_data_models().get(cold_iid).models)[0]
    assert not np.allclose(resumed.user_factors, cold.user_factors)


def test_implicit_checkpoint_roundtrip(data, tmp_path):
    ckpt = FactorCheckpointer(str(tmp_path))
    U1, V1 = als.train_implicit(data, rank=4, iterations=4, seed=5,
                                chunk=128)
    U2, V2 = als.train_implicit(data, rank=4, iterations=4, seed=5,
                                chunk=128, checkpoint_every=3,
                                checkpointer=ckpt)
    np.testing.assert_array_equal(np.asarray(U1), np.asarray(U2))
    np.testing.assert_array_equal(np.asarray(V1), np.asarray(V2))
