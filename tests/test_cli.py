"""CLI + dashboard + admin tests, ending with the quickstart lifecycle
(ref: tests/pio_tests/scenarios/{quickstart_test,basic_app_usecases}.py and
tools/.../console/Console.scala)."""

import json
import threading
import time
import urllib.request

import pytest

from predictionio_tpu.data.api import EventAPI
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.tools import apps as app_cmds
from predictionio_tpu.tools.admin import AdminAPI
from predictionio_tpu.tools.cli import main
from predictionio_tpu.tools.dashboard import DashboardAPI
from predictionio_tpu.tools.transfer import events_to_file, file_to_events


def test_version_and_template(capsys, memory_storage):
    assert main(["version"]) == 0
    assert main(["template", "list"]) == 0
    out = capsys.readouterr().out
    assert "recommendation" in out


def test_app_lifecycle(capsys, memory_storage):
    assert main(["app", "new", "CliApp", "--access-key", "ck"]) == 0
    out = capsys.readouterr().out
    assert "Access Key: ck" in out
    # duplicate fails
    assert main(["app", "new", "CliApp"]) == 1
    assert "already exists" in capsys.readouterr().err
    assert main(["app", "list"]) == 0
    assert "CliApp" in capsys.readouterr().out
    assert main(["app", "channel-new", "CliApp", "mobile"]) == 0
    assert main(["app", "show", "CliApp"]) == 0
    out = capsys.readouterr().out
    assert "mobile" in out
    assert main(["app", "channel-delete", "CliApp", "mobile", "-f"]) == 0
    assert main(["accesskey", "new", "CliApp", "--event", "view"]) == 0
    keys = app_cmds.accesskey_list("CliApp", storage=memory_storage)
    assert {k.events for k in keys} >= {(), ("view",)}
    extra = [k for k in keys if k.events == ("view",)][0]
    assert main(["accesskey", "delete", extra.key]) == 0
    assert main(["app", "data-delete", "CliApp", "-f"]) == 0
    assert main(["app", "delete", "CliApp", "-f"]) == 0
    assert app_cmds.list_apps(storage=memory_storage) == []


def test_import_export_roundtrip(tmp_path, memory_storage):
    d = app_cmds.create("IoApp", storage=memory_storage)
    src = tmp_path / "events.jsonl"
    lines = [
        {"event": "rate", "entityType": "user", "entityId": f"u{i}",
         "targetEntityType": "item", "targetEntityId": "i1",
         "properties": {"rating": float(i)},
         "eventTime": f"2021-01-01T00:{i:02d}:00.000Z"}
        for i in range(5)]
    src.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
    n = file_to_events(str(src), d.app.id, storage=memory_storage)
    assert n == 5
    dst = tmp_path / "out.jsonl"
    n = events_to_file(str(dst), d.app.id, storage=memory_storage)
    assert n == 5
    back = [json.loads(l) for l in dst.read_text().splitlines()]
    assert {e["entityId"] for e in back} == {f"u{i}" for i in range(5)}
    # malformed line errors with location
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"event": "x"}\n')
    with pytest.raises(app_cmds.CommandError, match="bad.jsonl:1"):
        file_to_events(str(bad), d.app.id, storage=memory_storage)


def test_admin_api(memory_storage):
    api = AdminAPI(storage=memory_storage)
    assert api.handle("GET", "/")[0] == 200
    status, body = api.handle("POST", "/cmd/app",
                              body=json.dumps({"name": "AdminApp"}).encode())
    assert status == 201 and body["name"] == "AdminApp"
    assert len(body["accessKeys"]) == 1
    status, listing = api.handle("GET", "/cmd/app")
    assert status == 200 and listing[0]["name"] == "AdminApp"
    # duplicate -> 400
    status, _ = api.handle("POST", "/cmd/app",
                           body=json.dumps({"name": "AdminApp"}).encode())
    assert status == 400
    assert api.handle("DELETE", "/cmd/app/AdminApp/data")[0] == 200
    assert api.handle("DELETE", "/cmd/app/AdminApp")[0] == 200
    assert api.handle("GET", "/cmd/app")[1] == []


def test_dashboard_lists_completed_evaluations(memory_storage):
    from predictionio_tpu.data.storage import EvaluationInstance
    import datetime as dt
    now = dt.datetime.now(dt.timezone.utc)
    instances = memory_storage.get_meta_data_evaluation_instances()
    iid = instances.insert(EvaluationInstance(
        id="", status="EVALCOMPLETED", start_time=now, end_time=now,
        evaluation_class="my.Evaluation",
        evaluator_results_html="<p>score 0.5</p>",
        evaluator_results_json='{"bestIdx": 0}'))
    instances.insert(EvaluationInstance(
        id="", status="INIT", start_time=now, end_time=now,
        evaluation_class="pending.Eval"))
    api = DashboardAPI(storage=memory_storage)
    status, page = api.handle("GET", "/")
    assert status == 200 and "my.Evaluation" in page
    assert "pending.Eval" not in page
    status, body = api.handle("GET", f"/engine_instances/{iid}.json")
    assert status == 200 and body == {"bestIdx": 0}
    status, page = api.handle("GET", f"/engine_instances/{iid}.html")
    assert status == 200 and "score 0.5" in page
    assert api.handle("GET", "/engine_instances/zzz.json")[0] == 404


def test_router_cli_surface(capsys):
    """`pio router` parses its fleet flags and refuses an empty backend
    list with the reference-style one-liner (exit 1, no traceback)."""
    from predictionio_tpu.tools.cli import build_parser

    args = build_parser().parse_args(
        ["router", "--backends", "http://a:8000,http://b:8000",
         "--port", "8123", "--health-ms", "250", "--deadline-ms", "900",
         "--max-inflight", "64"])
    assert args.command == "router"
    assert args.backends == "http://a:8000,http://b:8000"
    assert (args.port, args.health_ms, args.deadline_ms,
            args.max_inflight) == (8123, 250.0, 900.0, 64)
    # doctor grows the fleet sweep flag
    args = build_parser().parse_args(
        ["doctor", "--targets", "http://r:8100,http://q:8000"])
    assert args.targets == "http://r:8100,http://q:8000"
    assert main(["router", "--backends", " , "]) == 1
    assert "--backends" in capsys.readouterr().err


def test_quickstart_lifecycle(tmp_path, capsys, memory_storage, monkeypatch):
    """pio app new -> events via REST -> pio train -> deploy -> query
    (quickstart_test.py:50-140)."""
    assert main(["app", "new", "MyApp1", "--access-key", "qs"]) == 0
    capsys.readouterr()

    # ingest ratings through a live event server
    es, es_port = serve_background(EventAPI(storage=memory_storage))
    try:
        batch = []
        for u in range(8):
            for i in range(6):
                batch.append({
                    "event": "rate", "entityType": "user",
                    "entityId": f"u{u}",
                    "targetEntityType": "item", "targetEntityId": f"i{i}",
                    "properties": {
                        "rating": 5.0 if (u % 2) == (i % 2) else 1.0}})
        for off in range(0, len(batch), 50):
            req = urllib.request.Request(
                f"http://localhost:{es_port}/batch/events.json?accessKey=qs",
                data=json.dumps(batch[off:off + 50]).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req) as r:
                assert all(x["status"] == 201 for x in json.loads(r.read()))
    finally:
        es.shutdown()

    # engine directory with engine.json (template parity: engine.json:14-17)
    engine_dir = tmp_path / "rec-engine"
    engine_dir.mkdir()
    (engine_dir / "engine.json").write_text(json.dumps({
        "id": "default",
        "description": "Default settings",
        "engineFactory":
            "predictionio_tpu.models.recommendation:RecommendationEngine",
        "datasource": {"params": {"appName": "MyApp1"}},
        "algorithms": [{"name": "als", "params": {
            "rank": 4, "numIterations": 5, "lambda": 0.05, "seed": 3}}],
    }))
    assert main(["build", "--engine-dir", str(engine_dir)]) == 0
    assert main(["train", "--engine-dir", str(engine_dir)]) == 0
    out = capsys.readouterr().out
    assert "Training completed" in out

    # deploy on an ephemeral port in a thread; query; undeploy stops it
    from predictionio_tpu.workflow.create_server import QueryAPI, serve
    api = QueryAPI()
    port_holder = {}

    def run():
        from predictionio_tpu.data.api.http import serve_background as sb
        server, port = sb(api)
        port_holder["port"] = port
        while not api.stop_requested:
            time.sleep(0.05)
        server.shutdown()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    for _ in range(100):
        if "port" in port_holder:
            break
        time.sleep(0.05)
    port = port_holder["port"]
    req = urllib.request.Request(
        f"http://localhost:{port}/queries.json",
        data=json.dumps({"user": "u1", "num": 4}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req) as r:
        body = json.loads(r.read())
    assert len(body["itemScores"]) == 4  # quickstart_test.py:95-100
    assert main(["undeploy", "--port", str(port)]) == 0
    t.join(timeout=5)
    assert not t.is_alive()
