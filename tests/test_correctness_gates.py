"""Round-5 correctness gates: a numerically-poisoned model must fail
visibly at every layer it previously slipped through (VERDICT r04 Weak #2).

1. run_train refuses to mark the EngineInstance COMPLETED when any
   persisted model array is non-finite (CoreWorkflow.scala:84-88 —
   the ledger exists so deploy never serves a bad instance).
2. The serving layer returns 500 (with strict JSON) instead of emitting
   bare NaN tokens to clients (quickstart_test.py:95-100 contract:
   real itemScores).
3. The generic HTTP transport never emits non-JSON NaN/Infinity tokens.
"""

import dataclasses
import datetime as dt
import json
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data import store
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.workflow import WorkflowContext, model_io, run_train
from predictionio_tpu.workflow.create_server import QueryAPI


@pytest.fixture()
def rated_app(memory_storage):
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "MyApp1", None))
    memory_storage.get_events().init(app_id)
    events = []
    minute = 0
    for u in range(8):
        for i in range(6):
            minute += 1
            r = 5.0 if (u % 2) == (i % 2) else 1.0
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": r}),
                event_time=dt.datetime(2021, 1, 1, 0, minute % 60,
                                       tzinfo=dt.timezone.utc)))
    store.write(events, app_id, storage=memory_storage)
    return app_id


def _params(n_iters=3, seed=3):
    return EngineParams(
        data_source_params=DataSourceParams(appName="MyApp1"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=4, numIterations=n_iters,
                                       lambda_=0.05, seed=seed)),))


def _train(storage, poison=False, monkeypatch=None):
    from predictionio_tpu.ops import als

    if poison:
        real = als.train_explicit

        def poisoned(*a, **kw):
            U, V = real(*a, **kw)
            U = np.asarray(U).copy()
            U[0, 0] = np.nan
            return U, V

        monkeypatch.setattr(als, "train_explicit", poisoned)
    return run_train(
        WorkflowContext(storage=storage), RecommendationEngine(), _params(),
        engine_factory=("predictionio_tpu.models.recommendation"
                        ":RecommendationEngine"),
        params_json={
            "datasource": {"params": {"appName": "MyApp1"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 4, "numIterations": 3, "lambda": 0.05,
                "seed": 3}}]})


def test_non_finite_report_walks_model_trees():
    @dataclasses.dataclass
    class M:
        w: np.ndarray
        meta: dict

    clean = M(np.ones((3, 2), np.float32), {"b": [np.zeros(4)]})
    assert model_io.non_finite_report([clean]) == []
    bad = M(np.array([[1.0, np.nan]]), {"b": [np.array([np.inf])]})
    rep = model_io.non_finite_report([bad])
    assert len(rep) == 2 and "1 NaN" in rep[0] and "1 Inf" in rep[1]
    # int arrays can't be non-finite and must not be touched
    assert model_io.non_finite_report(np.array([1, 2, 3])) == []


def test_run_train_refuses_poisoned_model(memory_storage, rated_app,
                                          monkeypatch):
    with pytest.raises(model_io.NonFiniteModelError, match="non-finite"):
        _train(memory_storage, poison=True, monkeypatch=monkeypatch)
    # ledger shows ERROR, not COMPLETED — deploy will refuse the instance
    rows = memory_storage.get_meta_data_engine_instances().get_all()
    assert [r.status for r in rows] == ["ERROR"]
    with pytest.raises(ValueError, match="No valid engine instance"):
        QueryAPI(storage=memory_storage)


def test_finite_check_opt_out(memory_storage, rated_app, monkeypatch):
    monkeypatch.setenv("PIO_FINITE_CHECK", "0")
    iid = _train(memory_storage, poison=True, monkeypatch=monkeypatch)
    rows = memory_storage.get_meta_data_engine_instances().get_all()
    assert [r.status for r in rows] == ["COMPLETED"] and iid


def test_serving_refuses_non_finite_scores(memory_storage, rated_app,
                                           monkeypatch):
    # train clean, then poison the deployed factors in memory: the serving
    # gate must catch a bad model even when the persist gate was bypassed
    _train(memory_storage)
    api = QueryAPI(storage=memory_storage)
    model = api.models[0]
    uf = np.asarray(model.user_factors).copy()
    uf[:, :] = np.nan
    api.models[0] = dataclasses.replace(model, user_factors=uf)
    status, body = api.handle(
        "POST", "/queries.json",
        body=json.dumps({"user": "u1", "num": 4}).encode())
    assert status == 500 and "non-finite" in body["message"]


def test_ingest_rejects_non_finite_properties(memory_storage):
    """python json.loads accepts bare NaN/Infinity tokens; accepting such
    an event would make every later read of it a permanent 500 under the
    strict-JSON transport. The event API must 400 it at the door."""
    from predictionio_tpu.data.api import EventAPI
    from predictionio_tpu.data.storage import AccessKey, App

    app_id = memory_storage.get_meta_data_apps().insert(App(0, "NApp"))
    memory_storage.get_events().init(app_id)
    memory_storage.get_meta_data_access_keys().insert(
        AccessKey("nk", app_id, ()))
    api = EventAPI(storage=memory_storage)
    body = (b'{"event": "rate", "entityType": "user", "entityId": "u1",'
            b' "properties": {"rating": NaN}}')
    status, resp = api.handle("POST", "/events.json", {"accessKey": "nk"},
                              body)
    assert status == 400 and "NaN" in resp["message"]
    status, resp = api.handle(
        "POST", "/batch/events.json", {"accessKey": "nk"},
        b'[{"event": "rate", "entityType": "user", "entityId": "u1",'
        b' "properties": {"w": [1.0, Infinity]}}]')
    assert status == 200 and resp[0]["status"] == 400
    # finite events still ingest
    status, resp = api.handle(
        "POST", "/events.json", {"accessKey": "nk"},
        b'{"event": "rate", "entityType": "user", "entityId": "u1",'
        b' "properties": {"rating": 4.5}}')
    assert status == 201


def test_http_transport_strict_json(memory_storage, rated_app):
    _train(memory_storage)
    api = QueryAPI(storage=memory_storage)
    model = api.models[0]
    uf = np.asarray(model.user_factors).copy()
    uf[:, :] = np.nan
    api.models[0] = dataclasses.replace(model, user_factors=uf)
    server, port = serve_background(api)
    try:
        req = urllib.request.Request(
            f"http://localhost:{port}/queries.json",
            data=json.dumps({"user": "u1", "num": 3}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 500
        # the 500 body must itself be valid, parseable JSON
        payload = json.loads(ei.value.read())
        assert "message" in payload
    finally:
        server.shutdown()
