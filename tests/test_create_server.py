"""Engine (deploy) server tests — CreateServer parity: instance resolution,
model load + device placement, /queries.json hot path, /reload hot-swap,
/stop, feedback loop to a live event server (CreateServer.scala:105-697)."""

import dataclasses
import json
import time
import urllib.request

import pytest

from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.api import EventAPI
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import AccessKey, App
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.workflow import WorkflowContext, run_train
from predictionio_tpu.workflow.create_server import (
    QueryAPI, ServerConfig, engine_params_from_instance,
    resolve_engine_instance, undeploy,
)
from predictionio_tpu.workflow.server_plugins import (
    OUTPUT_BLOCKER, EngineServerPlugin, EngineServerPluginContext,
)


@pytest.fixture()
def trained(memory_storage):
    """App with events + one COMPLETED EngineInstance."""
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "MyApp1", None))
    memory_storage.get_events().init(app_id)
    import datetime as dt
    from predictionio_tpu.data import store
    events = []
    minute = 0
    for u in range(8):
        for i in range(6):
            minute += 1
            r = 5.0 if (u % 2) == (i % 2) else 1.0
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": r}),
                event_time=dt.datetime(2021, 1, 1, 0, minute % 60,
                                       tzinfo=dt.timezone.utc)))
    store.write(events, app_id, storage=memory_storage)

    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="MyApp1"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=4, numIterations=5,
                                       lambda_=0.05, seed=3)),))
    ctx = WorkflowContext(storage=memory_storage)
    instance_id = run_train(
        ctx, engine, ep,
        engine_factory=("predictionio_tpu.models.recommendation"
                        ":RecommendationEngine"),
        params_json={
            "datasource": {"params": {"appName": "MyApp1"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 4, "numIterations": 5, "lambda": 0.05, "seed": 3}}],
        })
    return memory_storage, app_id, instance_id


def test_resolve_and_params_roundtrip(trained):
    storage, _app_id, instance_id = trained
    instance = resolve_engine_instance(storage, ServerConfig())
    assert instance.id == instance_id and instance.status == "COMPLETED"
    ep = engine_params_from_instance(RecommendationEngine(), instance)
    assert ep.data_source_params.appName == "MyApp1"
    name, ap = ep.algorithm_params_list[0]
    assert name == "als" and ap.rank == 4 and ap.lambda_ == 0.05

    with pytest.raises(ValueError, match="not found"):
        resolve_engine_instance(
            storage, ServerConfig(engine_instance_id="missing"))


def test_resolve_refuses_incomplete(memory_storage):
    with pytest.raises(ValueError, match="No valid engine instance"):
        resolve_engine_instance(memory_storage, ServerConfig())


def test_query_roundtrip_and_status(trained):
    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage)
    status, body = api.handle(
        "POST", "/queries.json", body=json.dumps(
            {"user": "u1", "num": 4}).encode())
    assert status == 200
    assert len(body["itemScores"]) == 4
    scores = [s["score"] for s in body["itemScores"]]
    assert scores == sorted(scores, reverse=True)
    # odd user should prefer odd items (the training signal)
    assert body["itemScores"][0]["item"] in {"i1", "i3", "i5"}

    # unknown user -> empty itemScores, not an error
    status, body = api.handle(
        "POST", "/queries.json", body=json.dumps(
            {"user": "nobody", "num": 4}).encode())
    assert status == 200 and body == {"itemScores": []}

    # malformed query -> 400
    status, _ = api.handle("POST", "/queries.json", body=b"{")
    assert status == 400
    status, _ = api.handle(
        "POST", "/queries.json", body=json.dumps({"user": "u1"}).encode())
    assert status == 400

    status, info = api.handle("GET", "/")
    assert status == 200 and info["requestCount"] == 2
    assert info["engineInstance"]["id"] == _iid
    assert info["avgServingSec"] > 0


def test_reload_hot_swap(trained):
    storage, app_id, first_id = trained
    api = QueryAPI(storage=storage)
    assert api.engine_instance.id == first_id

    # train a second instance, then hot-swap
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="MyApp1"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=3, numIterations=4,
                                       lambda_=0.05, seed=5)),))
    second_id = run_train(
        WorkflowContext(storage=storage), engine, ep,
        engine_factory=("predictionio_tpu.models.recommendation"
                        ":RecommendationEngine"),
        params_json={"datasource": {"params": {"appName": "MyApp1"}},
                     "algorithms": [{"name": "als", "params": {
                         "rank": 3, "numIterations": 4, "lambda": 0.05,
                         "seed": 5}}]})
    status, _ = api.handle("POST", "/reload")
    assert status == 200
    for _ in range(100):
        if api.engine_instance.id == second_id:
            break
        time.sleep(0.05)
    assert api.engine_instance.id == second_id
    status, body = api.handle(
        "POST", "/queries.json",
        body=json.dumps({"user": "u1", "num": 2}).encode())
    assert status == 200 and len(body["itemScores"]) == 2


def test_stop_flag_and_undeploy(trained):
    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage)
    assert not api.stop_requested
    status, body = api.handle("POST", "/stop")
    assert status == 200 and not undeploy("localhost", 1)  # nothing listening
    assert api.stop_requested


def test_output_blocker_plugin(trained):
    storage, _app_id, _iid = trained

    class Cap(EngineServerPlugin):
        plugin_name = "cap"
        plugin_description = "keeps only the top result"
        plugin_type = OUTPUT_BLOCKER

        def process(self, engine_instance, query_obj, prediction_obj, context):
            return {"itemScores": prediction_obj["itemScores"][:1]}

    api = QueryAPI(storage=storage,
                   plugin_context=EngineServerPluginContext([Cap()]))
    status, body = api.handle(
        "POST", "/queries.json",
        body=json.dumps({"user": "u1", "num": 4}).encode())
    assert status == 200 and len(body["itemScores"]) == 1
    status, desc = api.handle("GET", "/plugins.json")
    assert "cap" in desc["plugins"]["outputblockers"]


def test_feedback_loop_to_event_server(trained):
    storage, app_id, instance_id = trained
    storage.get_meta_data_access_keys().insert(AccessKey("fk", app_id, ()))
    event_api = EventAPI(storage=storage)
    server, port = serve_background(event_api)
    try:
        api = QueryAPI(
            storage=storage,
            config=ServerConfig(feedback=True, event_server_port=port,
                                access_key="fk"))
        status, _body = api.handle(
            "POST", "/queries.json",
            body=json.dumps({"user": "u1", "num": 2}).encode())
        assert status == 200
        # wait for the async feedback POST to land
        got = None
        for _ in range(100):
            sts, got = event_api.handle(
                "GET", "/events.json",
                {"accessKey": "fk", "entityType": "pio_pr"})
            if sts == 200:
                break
            time.sleep(0.05)
        assert sts == 200 and len(got) == 1
        fb = got[0]
        assert fb["event"] == "predict"
        props = fb["properties"]
        assert props["engineInstanceId"] == instance_id
        assert props["query"] == {"user": "u1", "num": 2}
        assert len(props["prediction"]["itemScores"]) == 2
    finally:
        server.shutdown()


def test_http_transport_smoke(trained):
    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage)
    server, port = serve_background(api)
    try:
        req = urllib.request.Request(
            f"http://localhost:{port}/queries.json",
            data=json.dumps({"user": "u2", "num": 3}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
            assert len(json.loads(r.read())["itemScores"]) == 3
    finally:
        server.shutdown()
        api.close()


# ---------------------------------------------------------------------------
# micro-batched serving (serving/batcher.py wired behind ServerConfig)
# ---------------------------------------------------------------------------

QUERY_SET = [{"user": f"u{k % 8}", "num": 4} for k in range(10)] + [
    {"user": "nobody", "num": 4},      # unknown user -> empty
    {"user": "u3", "num": 2},          # smaller k in a mixed batch
]


def _post(api, q):
    return api.handle("POST", "/queries.json", body=json.dumps(q).encode())


def test_batching_off_is_the_legacy_inline_path(trained):
    """`batching: off` must not construct a batcher and must answer
    byte-for-byte what the inline supplement -> predict -> serve chain
    produces (replicated here literally)."""
    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage, config=ServerConfig(batching="off"))
    assert api._batcher is None
    status, info = api.handle("GET", "/")
    assert status == 200 and info["batching"] == {"enabled": False}
    from predictionio_tpu.workflow import json_extractor
    for q in QUERY_SET:
        status, body = _post(api, q)
        assert status == 200
        query = json_extractor.extract_query(
            api.algorithms[0].query_class, json.dumps(q).encode())
        supplemented = api.serving.supplement(query)
        predictions = [a.predict(m, supplemented)
                       for a, m in zip(api.algorithms, api.models)]
        expected = json_extractor.to_json_obj(
            api.serving.serve(query, predictions))
        assert json.dumps(body) == json.dumps(expected)


def test_batched_responses_match_sequential(trained, monkeypatch):
    """Acceptance parity: under `batching: on` (queries sent alone AND as
    a coalesced concurrent burst, exercising different padding buckets)
    responses are identical to the sequential single-query path."""
    import threading

    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")  # pin the device path
    storage, _app_id, _iid = trained
    api_off = QueryAPI(storage=storage, config=ServerConfig(batching="off"))
    api_on = QueryAPI(storage=storage)     # auto -> ALS is batch-capable
    try:
        assert api_on._batcher is not None
        expected = [_post(api_off, q) for q in QUERY_SET]

        # one at a time through the batcher: batch=1 degenerate case
        for q, (st_exp, body_exp) in zip(QUERY_SET, expected):
            st, body = _post(api_on, q)
            assert (st, json.dumps(body)) == (st_exp, json.dumps(body_exp))

        # concurrent burst: queries coalesce into multi-query batches
        results = [None] * len(QUERY_SET)

        def hit(k):
            results[k] = _post(api_on, QUERY_SET[k])

        threads = [threading.Thread(target=hit, args=(k,))
                   for k in range(len(QUERY_SET))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        for (st, body), (st_exp, body_exp) in zip(results, expected):
            assert (st, json.dumps(body)) == (st_exp, json.dumps(body_exp))

        status, info = api_on.handle("GET", "/")
        b = info["batching"]
        assert b["enabled"] and b["queries"] == 2 * len(QUERY_SET)
        assert b["rejected"] == 0
        assert sum(b["batchSizeHist"].values()) == b["batches"]
        assert b["avgFlushMs"] >= 0 and b["avgQueueWaitMs"] >= 0
    finally:
        api_on.close()
        api_off.close()


def test_bucket_padding_never_changes_results(trained, monkeypatch):
    """predict_batch through different padding-bucket configurations must
    return identical results (padding rows are dropped before results are
    built), and items/ordering must match sequential predict()."""
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")  # pin the device path
    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage)
    try:
        algo, model = api.algorithms[0], api.models[0]
        from predictionio_tpu.models.recommendation.engine import Query
        queries = [Query(user=f"u{k}", num=3) for k in range(3)]  # B=3
        queries.append(Query(user="nobody", num=3))

        def run(buckets):
            monkeypatch.setenv("PIO_SERVE_BUCKETS", buckets)
            return algo.predict_batch(model, queries)

        by_bucket = {b: run(b) for b in ("4", "16", "64", "1,4,16,64")}
        baseline = by_bucket["4"]
        for b, res in by_bucket.items():
            assert res == baseline, f"bucket config {b} changed results"
        monkeypatch.delenv("PIO_SERVE_BUCKETS")
        seq = [algo.predict(model, q) for q in queries]
        assert baseline == seq  # device path: bitwise at this scale
        assert baseline[3].itemScores == ()
    finally:
        api.close()


def _gated_batcher(api):
    """Wrap the deployed batcher's flush so batches block on a gate —
    deterministic queue buildup for the admission-control tests. The
    `entered` event proves the worker is busy inside a flush (i.e. the
    next submits can only queue, not be picked up)."""
    import threading

    entered = threading.Event()
    gate = threading.Event()
    batcher = api._batcher
    real = batcher._flush_fn

    def gated(items):
        entered.set()
        gate.wait(30)
        return real(items)

    batcher._flush_fn = gated
    return gate, entered


def test_admission_control_503_retry_after(trained):
    import threading

    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage, config=ServerConfig(
        batching="on", batch_max_size=1, batch_max_delay_ms=1.0,
        batch_max_queue=2))
    gate, entered = _gated_batcher(api)
    try:
        threads = [threading.Thread(
            target=_post, args=(api, {"user": "u1", "num": 2}))]
        threads[0].start()
        assert entered.wait(10)    # worker provably busy in a flush
        for _ in range(2):         # fill the queue to max_queue
            t = threading.Thread(
                target=_post, args=(api, {"user": "u1", "num": 2}))
            t.start()
            threads.append(t)
        deadline = time.time() + 10
        while time.time() < deadline:
            with api._batcher._cond:
                if len(api._batcher._q) >= 2:
                    break
            time.sleep(0.01)
        response = _post(api, {"user": "u1", "num": 2})
        assert len(response) == 3
        status, body, headers = response
        assert status == 503 and "saturated" in body["message"]
        assert int(headers["Retry-After"]) >= 1
        gate.set()
        for t in threads:
            t.join(30)
        status, info = api.handle("GET", "/")
        assert info["batching"]["rejected"] == 1
    finally:
        gate.set()
        api.close()


def test_admission_control_503_over_http(trained):
    """The transport forwards the 3-tuple's Retry-After header."""
    import threading

    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage, config=ServerConfig(
        batching="on", batch_max_size=1, batch_max_delay_ms=1.0,
        batch_max_queue=1))
    gate, entered = _gated_batcher(api)
    server, port = serve_background(api)
    try:
        def post_http():
            req = urllib.request.Request(
                f"http://localhost:{port}/queries.json",
                data=json.dumps({"user": "u1", "num": 2}).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req) as r:
                return r.status

        threads = [threading.Thread(target=post_http)]
        threads[0].start()
        assert entered.wait(10)    # worker provably busy in a flush
        threads.append(threading.Thread(target=post_http))
        threads[1].start()         # fills the 1-slot queue
        deadline = time.time() + 10
        while time.time() < deadline:
            with api._batcher._cond:
                if len(api._batcher._q) >= 1:
                    break
            time.sleep(0.01)
        with pytest.raises(urllib.error.HTTPError) as ei:
            post_http()
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        gate.set()
        for t in threads:
            t.join(30)
    finally:
        gate.set()
        server.shutdown()
        api.close()


def test_concurrent_burst_smoke(trained):
    """Tier-1 smoke: a 4-query concurrent burst through the batcher over
    real HTTP on CPU — every response correct, stats consistent."""
    import threading

    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage)
    assert api._batcher is not None      # auto: ALS is batch-capable
    server, port = serve_background(api)
    try:
        out = [None] * 4

        def post_http(k):
            req = urllib.request.Request(
                f"http://localhost:{port}/queries.json",
                data=json.dumps({"user": f"u{k}", "num": 3}).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req) as r:
                out[k] = (r.status, json.loads(r.read()))

        threads = [threading.Thread(target=post_http, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        for status, body in out:
            assert status == 200 and len(body["itemScores"]) == 3
        _, info = api.handle("GET", "/")
        assert info["requestCount"] == 4
        b = info["batching"]
        assert b["queries"] == 4 and b["rejected"] == 0
        assert sum(b["batchSizeHist"].values()) == b["batches"] <= 4
    finally:
        server.shutdown()
        api.close()


def test_reload_swaps_batcher(trained):
    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage)
    first = api._batcher
    assert first is not None
    api._reload()           # synchronous variant of POST /reload
    assert api._batcher is not None and api._batcher is not first
    assert first._closed    # retired batcher was drained and closed
    status, body = _post(api, {"user": "u1", "num": 2})
    assert status == 200 and len(body["itemScores"]) == 2
    api.close()


@pytest.mark.slow
def test_concurrent_load_throughput(trained):
    """Sustained concurrent load through the batcher: 16 keep-alive
    clients x 25 queries, no rejects, everything coalesces correctly."""
    import http.client
    import threading

    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage)
    server, port = serve_background(api)
    n_clients, per_client = 16, 25
    errors = []
    try:
        def client(cx):
            try:
                conn = http.client.HTTPConnection("localhost", port)
                for q in range(per_client):
                    conn.request(
                        "POST", "/queries.json",
                        body=json.dumps(
                            {"user": f"u{(cx + q) % 8}", "num": 4}),
                        headers={"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    body = json.loads(resp.read())
                    assert resp.status == 200, body
                    assert len(body["itemScores"]) == 4
                conn.close()
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=client, args=(cx,))
                   for cx in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors, errors[:3]
        _, info = api.handle("GET", "/")
        b = info["batching"]
        assert b["queries"] == n_clients * per_client
        assert b["rejected"] == 0
        # concurrency must actually coalesce: fewer batches than queries
        assert b["batches"] < b["queries"]
    finally:
        server.shutdown()
        api.close()
