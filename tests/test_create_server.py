"""Engine (deploy) server tests — CreateServer parity: instance resolution,
model load + device placement, /queries.json hot path, /reload hot-swap,
/stop, feedback loop to a live event server (CreateServer.scala:105-697)."""

import dataclasses
import json
import time
import urllib.request

import pytest

from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.api import EventAPI
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import AccessKey, App
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.workflow import WorkflowContext, run_train
from predictionio_tpu.workflow.create_server import (
    QueryAPI, ServerConfig, engine_params_from_instance,
    resolve_engine_instance, undeploy,
)
from predictionio_tpu.workflow.server_plugins import (
    OUTPUT_BLOCKER, EngineServerPlugin, EngineServerPluginContext,
)


@pytest.fixture()
def trained(memory_storage):
    """App with events + one COMPLETED EngineInstance."""
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "MyApp1", None))
    memory_storage.get_events().init(app_id)
    import datetime as dt
    from predictionio_tpu.data import store
    events = []
    minute = 0
    for u in range(8):
        for i in range(6):
            minute += 1
            r = 5.0 if (u % 2) == (i % 2) else 1.0
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": r}),
                event_time=dt.datetime(2021, 1, 1, 0, minute % 60,
                                       tzinfo=dt.timezone.utc)))
    store.write(events, app_id, storage=memory_storage)

    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="MyApp1"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=4, numIterations=5,
                                       lambda_=0.05, seed=3)),))
    ctx = WorkflowContext(storage=memory_storage)
    instance_id = run_train(
        ctx, engine, ep,
        engine_factory=("predictionio_tpu.models.recommendation"
                        ":RecommendationEngine"),
        params_json={
            "datasource": {"params": {"appName": "MyApp1"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 4, "numIterations": 5, "lambda": 0.05, "seed": 3}}],
        })
    return memory_storage, app_id, instance_id


def test_resolve_and_params_roundtrip(trained):
    storage, _app_id, instance_id = trained
    instance = resolve_engine_instance(storage, ServerConfig())
    assert instance.id == instance_id and instance.status == "COMPLETED"
    ep = engine_params_from_instance(RecommendationEngine(), instance)
    assert ep.data_source_params.appName == "MyApp1"
    name, ap = ep.algorithm_params_list[0]
    assert name == "als" and ap.rank == 4 and ap.lambda_ == 0.05

    with pytest.raises(ValueError, match="not found"):
        resolve_engine_instance(
            storage, ServerConfig(engine_instance_id="missing"))


def test_resolve_refuses_incomplete(memory_storage):
    with pytest.raises(ValueError, match="No valid engine instance"):
        resolve_engine_instance(memory_storage, ServerConfig())


def test_query_roundtrip_and_status(trained):
    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage)
    status, body = api.handle(
        "POST", "/queries.json", body=json.dumps(
            {"user": "u1", "num": 4}).encode())
    assert status == 200
    assert len(body["itemScores"]) == 4
    scores = [s["score"] for s in body["itemScores"]]
    assert scores == sorted(scores, reverse=True)
    # odd user should prefer odd items (the training signal)
    assert body["itemScores"][0]["item"] in {"i1", "i3", "i5"}

    # unknown user -> empty itemScores, not an error
    status, body = api.handle(
        "POST", "/queries.json", body=json.dumps(
            {"user": "nobody", "num": 4}).encode())
    assert status == 200 and body == {"itemScores": []}

    # malformed query -> 400
    status, _ = api.handle("POST", "/queries.json", body=b"{")
    assert status == 400
    status, _ = api.handle(
        "POST", "/queries.json", body=json.dumps({"user": "u1"}).encode())
    assert status == 400

    status, info = api.handle("GET", "/")
    assert status == 200 and info["requestCount"] == 2
    assert info["engineInstance"]["id"] == _iid
    assert info["avgServingSec"] > 0


def test_reload_hot_swap(trained):
    storage, app_id, first_id = trained
    api = QueryAPI(storage=storage)
    assert api.engine_instance.id == first_id

    # train a second instance, then hot-swap
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="MyApp1"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=3, numIterations=4,
                                       lambda_=0.05, seed=5)),))
    second_id = run_train(
        WorkflowContext(storage=storage), engine, ep,
        engine_factory=("predictionio_tpu.models.recommendation"
                        ":RecommendationEngine"),
        params_json={"datasource": {"params": {"appName": "MyApp1"}},
                     "algorithms": [{"name": "als", "params": {
                         "rank": 3, "numIterations": 4, "lambda": 0.05,
                         "seed": 5}}]})
    status, _ = api.handle("POST", "/reload")
    assert status == 200
    for _ in range(100):
        if api.engine_instance.id == second_id:
            break
        time.sleep(0.05)
    assert api.engine_instance.id == second_id
    status, body = api.handle(
        "POST", "/queries.json",
        body=json.dumps({"user": "u1", "num": 2}).encode())
    assert status == 200 and len(body["itemScores"]) == 2


def test_stop_flag_and_undeploy(trained):
    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage)
    assert not api.stop_requested
    status, body = api.handle("POST", "/stop")
    assert status == 200 and not undeploy("localhost", 1)  # nothing listening
    assert api.stop_requested


def test_output_blocker_plugin(trained):
    storage, _app_id, _iid = trained

    class Cap(EngineServerPlugin):
        plugin_name = "cap"
        plugin_description = "keeps only the top result"
        plugin_type = OUTPUT_BLOCKER

        def process(self, engine_instance, query_obj, prediction_obj, context):
            return {"itemScores": prediction_obj["itemScores"][:1]}

    api = QueryAPI(storage=storage,
                   plugin_context=EngineServerPluginContext([Cap()]))
    status, body = api.handle(
        "POST", "/queries.json",
        body=json.dumps({"user": "u1", "num": 4}).encode())
    assert status == 200 and len(body["itemScores"]) == 1
    status, desc = api.handle("GET", "/plugins.json")
    assert "cap" in desc["plugins"]["outputblockers"]


def test_feedback_loop_to_event_server(trained):
    storage, app_id, instance_id = trained
    storage.get_meta_data_access_keys().insert(AccessKey("fk", app_id, ()))
    event_api = EventAPI(storage=storage)
    server, port = serve_background(event_api)
    try:
        api = QueryAPI(
            storage=storage,
            config=ServerConfig(feedback=True, event_server_port=port,
                                access_key="fk"))
        status, _body = api.handle(
            "POST", "/queries.json",
            body=json.dumps({"user": "u1", "num": 2}).encode())
        assert status == 200
        # wait for the async feedback POST to land
        got = None
        for _ in range(100):
            sts, got = event_api.handle(
                "GET", "/events.json",
                {"accessKey": "fk", "entityType": "pio_pr"})
            if sts == 200:
                break
            time.sleep(0.05)
        assert sts == 200 and len(got) == 1
        fb = got[0]
        assert fb["event"] == "predict"
        props = fb["properties"]
        assert props["engineInstanceId"] == instance_id
        assert props["query"] == {"user": "u1", "num": 2}
        assert len(props["prediction"]["itemScores"]) == 2
    finally:
        server.shutdown()


def test_http_transport_smoke(trained):
    storage, _app_id, _iid = trained
    api = QueryAPI(storage=storage)
    server, port = serve_background(api)
    try:
        req = urllib.request.Request(
            f"http://localhost:{port}/queries.json",
            data=json.dumps({"user": "u2", "num": 3}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
            assert len(json.loads(r.read())["itemScores"]) == 3
    finally:
        server.shutdown()
