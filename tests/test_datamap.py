"""DataMap/PropertyMap semantics (parity: data/.../DataMapSpec in reference)."""

import datetime as dt

import pytest

from predictionio_tpu.data.datamap import DataMap, DataMapError, PropertyMap


def test_get_required_and_optional():
    d = DataMap({"a": 1, "b": "x", "c": None})
    assert d.get("a") == 1
    assert d.get_str("b") == "x"
    assert d.get_opt("missing") is None
    assert d.get_opt("missing", 7) == 7
    # JSON null behaves like absent for get_opt, error for get
    assert d.get_opt("c") is None
    with pytest.raises(DataMapError):
        d.get("c")
    with pytest.raises(DataMapError):
        d.get("missing")


def test_typed_getters():
    d = DataMap({"f": 1.5, "i": 3, "l": [1, 2], "s": ["a", "b"]})
    assert d.get_float("f") == 1.5
    assert d.get_int("i") == 3
    assert d.get_list("l") == [1, 2]
    assert d.get_string_list("s") == ["a", "b"]
    with pytest.raises(DataMapError):
        d.get_list("f")


def test_union_is_right_biased():
    a = DataMap({"x": 1, "y": 2})
    b = DataMap({"y": 9, "z": 3})
    assert a.union(b).to_dict() == {"x": 1, "y": 9, "z": 3}
    # originals untouched (immutability)
    assert a.to_dict() == {"x": 1, "y": 2}


def test_remove_keys():
    a = DataMap({"x": 1, "y": 2, "z": 3})
    assert a.remove(["y", "nope"]).to_dict() == {"x": 1, "z": 3}


def test_extract_into_dataclass():
    from dataclasses import dataclass

    @dataclass
    class P:
        attr0: float
        attr1: float

    p = DataMap({"attr0": 1.0, "attr1": 2.0}).extract(P)
    assert p == P(1.0, 2.0)


def test_property_map_not_equal_datamap():
    t = dt.datetime(2020, 1, 1, tzinfo=dt.timezone.utc)
    pm = PropertyMap({"a": 1}, first_updated=t, last_updated=t)
    dm = DataMap({"a": 1})
    assert pm != dm
    assert pm == PropertyMap({"a": 1}, first_updated=t, last_updated=t)
    assert pm.get("a") == 1
