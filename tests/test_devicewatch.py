"""Device-observability tests (common/devicewatch.py + pio doctor).

The acceptance surface of ISSUE 5: with PIO_TELEMETRY=1 the query
server's /metrics exports pio_xla_compiles_total and compile-cache/HBM
gauges; a deliberately shape-varying query burst (bypassing the padding
buckets' protection) increments the post-warmup recompile counter while
the standard bucketed burst keeps it at 0; /debug/device.json renders
on all daemons; `pio doctor` exits 0 on a healthy server and nonzero on
one with an open circuit breaker or post-warmup recompiles; and wire
parity holds — with telemetry off the new surfaces are empty.
"""

import datetime as dt
import io
import json

import pytest

from predictionio_tpu.common import devicewatch, telemetry, tracing
from predictionio_tpu.common.resilience import CircuitBreaker
from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.api import EventAPI
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App
from predictionio_tpu.data.storage.remote import StorageRPCAPI
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.tools import doctor
from predictionio_tpu.workflow import WorkflowContext, run_train
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig


def _clear_counter_family(name):
    """Zero one counter family's children (the process registry is
    additive by design; `pio doctor` reads absolutes, so its green-path
    tests start the alarm counters from a clean slate). Safe for the
    watchdog families: devicewatch looks children up per record instead
    of caching them."""
    reg = telemetry.registry()
    with reg._lock:
        fam = reg._families.get(name)
    if fam is not None:
        with fam._lock:
            fam._children.clear()


@pytest.fixture(autouse=True)
def _clean():
    """Watchdog state, telemetry overrides, and the SLO engine never
    leak across tests (registry families persist by design — assert on
    deltas). A fresh SLO engine per test keeps the doctor's burn-rate
    line deterministic: its first scrape forms the baseline, so no
    earlier test's slow serves read as an in-window burn here."""
    from predictionio_tpu.common import slo
    telemetry.set_enabled(None)
    tracing.set_enabled(None)
    devicewatch.reset_watchdog()
    CircuitBreaker.reset_registry()
    slo.reset()
    yield
    telemetry.set_enabled(None)
    tracing.set_enabled(None)
    devicewatch.reset_watchdog()
    CircuitBreaker.reset_registry()
    slo.reset()


def _train_engine(storage, n_items=9, rank=5):
    """Train a small recommendation engine with an item count unique to
    this module so its top-k programs are not already in the process jit
    cache (other test files train 6-item rank-4 models)."""
    app_id = storage.get_meta_data_apps().insert(App(0, "DevWatchApp"))
    storage.get_events().init(app_id)
    events = []
    for u in range(10):
        for i in range(n_items):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap(
                    {"rating": 5.0 if (u % 3) == (i % 3) else 1.0}),
                event_time=dt.datetime(2021, 1, 2, 0, (u + i) % 60,
                                       tzinfo=dt.timezone.utc)))
    storage.get_events().insert_batch(events, app_id)
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="DevWatchApp"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=rank, numIterations=2,
                                       lambda_=0.05, seed=5)),))
    run_train(WorkflowContext(storage=storage), engine, ep,
              engine_factory="devicewatch-test",
              params_json={
                  "datasource": {"params": {"appName": "DevWatchApp"}},
                  "algorithms": [{"name": "als", "params": {
                      "rank": rank, "numIterations": 2, "lambda": 0.05,
                      "seed": 5}}]})
    return engine


def _query(api, user, num):
    st, body = api.handle("POST", "/queries.json", body=json.dumps(
        {"user": user, "num": num}).encode())
    assert st == 200, body
    return body


# ---------------------------------------------------------------------------
# the watchdog core
# ---------------------------------------------------------------------------

def test_install_is_idempotent_and_hooks_monitoring():
    assert devicewatch.install() is True    # jax.monitoring exists here
    assert devicewatch.install() is True    # re-entrant


def test_compile_events_attributed_to_regions():
    import jax
    import jax.numpy as jnp

    devicewatch.install()
    telemetry.set_enabled(True)
    before = devicewatch.compiles_total()
    with devicewatch.attribution("dw_test_fn", phase="train"):
        jax.jit(lambda x: x + 41)(jnp.ones((17,)))
    assert devicewatch.compiles_total() > before
    fam = telemetry.registry().counter(
        "pio_xla_compiles_total", labelnames=("fn", "phase"))
    assert fam.labels(fn="dw_test_fn", phase="train").value >= 1
    # compile durations observed (JAX's own host-side event)
    hist = telemetry.registry().histogram("pio_xla_compile_seconds")
    assert hist.labels().count >= 1


def test_compile_events_not_recorded_with_telemetry_off():
    import jax
    import jax.numpy as jnp

    devicewatch.install()
    telemetry.set_enabled(False)
    before = devicewatch.compiles_total()
    with devicewatch.attribution("dw_off_fn"):
        jax.jit(lambda x: x - 3)(jnp.ones((19,)))
    assert devicewatch.compiles_total() == before


def test_post_warmup_detector_via_jit_shapes():
    import jax
    import jax.numpy as jnp

    devicewatch.install()
    telemetry.set_enabled(True)
    f = jax.jit(lambda x: x * 2.5)
    # warmup: compiles are expected and not alarmed
    with devicewatch.serving_region("dw_serve", signature="a"):
        f(jnp.ones((23,)))
    base = devicewatch.post_warmup_recompiles()
    devicewatch.mark_serving_warmup_done()
    # steady state, same shape: no compile, no alarm
    with devicewatch.serving_region("dw_serve", signature="a"):
        f(jnp.ones((23,)))
    assert devicewatch.post_warmup_recompiles() == base
    # new shape post-warmup: the alarm fires and logs the signature
    with devicewatch.serving_region("dw_serve", signature="b"):
        f(jnp.ones((29,)))
    assert devicewatch.post_warmup_recompiles() > base
    snap = devicewatch.debug_snapshot()
    recent = snap["watchdog"]["recentPostWarmup"]
    assert recent and recent[-1]["fn"] == "dw_serve"
    assert recent[-1]["signature"] == "b"


def test_warmup_auto_arms_after_flush_count(monkeypatch):
    monkeypatch.setenv("PIO_SERVE_WARMUP_FLUSHES", "3")
    devicewatch.reset_watchdog()
    assert not devicewatch.serving_warmup_done()
    for _ in range(3):
        devicewatch.note_serving_flush()
    assert devicewatch.serving_warmup_done()


# ---------------------------------------------------------------------------
# acceptance: the query server end to end
# ---------------------------------------------------------------------------

def test_query_server_recompile_watchdog_end_to_end(memory_storage,
                                                    monkeypatch):
    """The acceptance pair: a standard bucketed burst post-warmup keeps
    the recompile counter at 0; a shape-varying burst (num=k is a static
    arg of the batched top-k, so varying it bypasses the padding-bucket
    protection exactly like a bucket regression would) increments it."""
    # force device-resident serving so the batched path is jitted
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")
    telemetry.set_enabled(True)
    devicewatch.install()
    engine = _train_engine(memory_storage)
    api = QueryAPI(storage=memory_storage, engine=engine,
                   config=ServerConfig(batching="on"))
    try:
        assert api._batcher is not None
        # warmup: the standard burst at a fixed num compiles its program
        for q in range(6):
            _query(api, f"u{q}", 4)
        devicewatch.mark_serving_warmup_done()
        base = devicewatch.post_warmup_recompiles()
        # standard bucketed burst: same shapes, zero recompiles
        for q in range(8):
            _query(api, f"u{q % 10}", 4)
        assert devicewatch.post_warmup_recompiles() == base
        # shape-varying burst: every new num is a new static k
        for num in (5, 6, 7):
            _query(api, "u1", num)
        assert devicewatch.post_warmup_recompiles() > base
        # /metrics on this daemon exports the counter + device gauges
        _st, payload, _h = api.handle("GET", "/metrics")
        assert "pio_xla_compiles_total" in payload
        assert "pio_xla_post_warmup_recompiles_total" in payload
        assert "pio_compile_cache_entries" in payload
        assert "pio_live_arrays" in payload
        # the flight recorder names the culprit
        snap = devicewatch.debug_snapshot()
        assert any(e["fn"] == "serve_flush"
                   for e in snap["watchdog"]["recentPostWarmup"])
    finally:
        api.close()


def test_hbm_gauges_gracefully_absent_on_cpu(memory_storage):
    """CPU devices answer memory_stats() with None: the scrape must not
    carry HBM series and /debug/device.json records the None outcome
    (KNOWN_ISSUES #8)."""
    telemetry.set_enabled(True)
    devicewatch.install()
    text = telemetry.registry().exposition()
    assert "pio_hbm_bytes_in_use" not in text
    snap = devicewatch.debug_snapshot()
    assert snap["devices"], "jax is imported in tests; devices must list"
    assert all(d["memoryStats"] is None for d in snap["devices"])


def test_debug_device_route_on_all_three_daemons(memory_storage):
    telemetry.set_enabled(True)
    apis = [EventAPI(storage=memory_storage),
            StorageRPCAPI(memory_storage, key="sekrit")]
    for api in apis:
        status, payload, headers = api.handle("GET", "/debug/device.json")
        assert status == 200
        assert headers["Content-Type"].startswith("application/json")
        snap = json.loads(payload)
        assert snap["telemetry"] is True
        assert {"watchdog", "devices", "liveArrays",
                "compileCache"} <= set(snap)


def test_debug_device_route_empty_with_telemetry_off(memory_storage):
    """Wire parity: until the operator opts in, the new endpoint says
    only that the subsystem is dormant."""
    telemetry.set_enabled(False)
    api = EventAPI(storage=memory_storage)
    status, payload, _h = api.handle("GET", "/debug/device.json")
    assert status == 200
    assert json.loads(payload) == {"telemetry": False}
    # and the scrape carries no devicewatch gauges
    text = telemetry.registry().exposition()
    for name in ("pio_live_arrays", "pio_compile_cache_entries",
                 "pio_hbm_bytes_in_use"):
        assert name not in text


# ---------------------------------------------------------------------------
# /traces.json query filters (satellite)
# ---------------------------------------------------------------------------

def test_traces_limit_and_trace_id_filters(memory_storage):
    tracing.clear()
    contexts = []
    for k in range(5):
        ctx = tracing.new_context()
        contexts.append(ctx)
        with tracing.activate(ctx):
            with tracing.span(f"op{k}", service="t"):
                pass
    api = EventAPI(storage=memory_storage)
    # default: all five traces
    st, snap = api.handle("GET", "/traces.json")
    assert st == 200 and len(snap["traces"]) == 5
    # ?limit=2 -> the two NEWEST traces; spanCount still reports the ring
    st, snap = api.handle("GET", "/traces.json", {"limit": "2"})
    assert st == 200 and len(snap["traces"]) == 2
    assert snap["spanCount"] == 5
    got = {t["traceId"] for t in snap["traces"]}
    assert got == {contexts[-1].trace_id, contexts[-2].trace_id}
    # ?trace_id= -> exactly that trace
    st, snap = api.handle(
        "GET", "/traces.json", {"trace_id": contexts[1].trace_id})
    assert st == 200
    assert [t["traceId"] for t in snap["traces"]] == [contexts[1].trace_id]
    assert snap["traces"][0]["spans"][0]["name"] == "op1"
    # bounds-checking: malformed limit is a 400, huge limit is clamped
    st, err = api.handle("GET", "/traces.json", {"limit": "bogus"})
    assert st == 400
    st, snap = api.handle("GET", "/traces.json", {"limit": "999999999"})
    assert st == 200 and len(snap["traces"]) == 5
    tracing.clear()


# ---------------------------------------------------------------------------
# pio doctor (tier-1 smoke + red conditions)
# ---------------------------------------------------------------------------

def _doctor(url):
    buf = io.StringIO()
    code = doctor.run_doctor(url, timeout=10.0, out=buf)
    return code, buf.getvalue()


_SECTIONS = ("health", "readiness", "queue", "serving", "breakers",
             "degraded", "recompiles", "hbm", "traces", "VERDICT")


def test_doctor_green_against_live_query_server(memory_storage,
                                                monkeypatch):
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")
    # declare the k this test serves: the AOT prebuild (serving/aot.py)
    # marks warmup done at deploy, so a query at an UNDECLARED k would
    # correctly trip the recompile alarm — the green path is a deploy
    # whose declared programs cover its traffic
    monkeypatch.setenv("PIO_AOT_KS", "4")
    telemetry.set_enabled(True)
    _clear_counter_family("pio_xla_post_warmup_recompiles_total")
    _clear_counter_family("pio_batcher_rejected_total")
    _clear_counter_family("pio_degraded_batches_total")
    _clear_counter_family("pio_aot_programs_total")
    engine = _train_engine(memory_storage)
    api = QueryAPI(storage=memory_storage, engine=engine,
                   config=ServerConfig(batching="on"))
    server, port = serve_background(api)
    try:
        for q in range(4):
            _query(api, f"u{q}", 4)
        code, text = _doctor(f"http://localhost:{port}")
        assert code == 0, text
        for section in _SECTIONS:
            assert section in text, f"missing section {section}:\n{text}"
        assert "VERDICT: OK" in text
    finally:
        server.shutdown()
        api.close()


def test_doctor_red_on_post_warmup_recompiles(memory_storage,
                                              monkeypatch):
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")
    telemetry.set_enabled(True)
    engine = _train_engine(memory_storage)
    api = QueryAPI(storage=memory_storage, engine=engine,
                   config=ServerConfig(batching="on"))
    server, port = serve_background(api)
    try:
        _query(api, "u0", 4)
        devicewatch.mark_serving_warmup_done()
        # shape-varying: fires the alarm. ks distinct from every other
        # test in this module — the process jit cache would otherwise
        # serve the program without a compile event.
        for num in (8, 3):
            _query(api, "u0", num)
        assert devicewatch.post_warmup_recompiles() >= 1
        code, text = _doctor(f"http://localhost:{port}")
        assert code == 1, text
        assert "VERDICT: RED" in text
        assert "recompile" in text
    finally:
        server.shutdown()
        api.close()


def test_doctor_red_on_open_circuit_breaker(memory_storage, monkeypatch):
    monkeypatch.setenv("PIO_BREAKER_ENABLED", "1")
    monkeypatch.setenv("PIO_BREAKER_MIN_CALLS", "2")
    telemetry.set_enabled(True)
    _clear_counter_family("pio_xla_post_warmup_recompiles_total")
    br = CircuitBreaker.for_endpoint("dead-storage:7072")
    for _ in range(4):
        br.record(False)
    assert br.state == CircuitBreaker.OPEN
    api = EventAPI(storage=memory_storage)
    server, port = serve_background(api)
    try:
        code, text = _doctor(f"http://localhost:{port}")
        assert code == 1, text
        assert "VERDICT: RED" in text
        assert "dead-storage:7072" in text
    finally:
        server.shutdown()


def test_doctor_unreachable_exits_2():
    code, text = _doctor("http://127.0.0.1:1")    # nothing listens there
    assert code == 2
    assert "unreachable" in text


def test_doctor_cli_wiring(memory_storage):
    from predictionio_tpu.tools.cli import main as cli_main
    # the registry is process-global and additive: earlier tests that
    # deliberately served undeclared ks past the AOT warmup mark (or
    # exercised failing AOT builds) left alarm counts this green path
    # must not inherit
    _clear_counter_family("pio_xla_post_warmup_recompiles_total")
    _clear_counter_family("pio_aot_programs_total")
    api = EventAPI(storage=memory_storage)
    server, port = serve_background(api)
    try:
        assert cli_main(["doctor", f"http://localhost:{port}"]) == 0
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# histogram-quantile helper (doctor's p99 math)
# ---------------------------------------------------------------------------

def test_histogram_quantile_from_exposition():
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("q_seconds", "q", buckets=(0.001, 0.01, 0.1)
                      ).labels()
    for _ in range(99):
        h.observe(0.005)
    h.observe(5.0)    # one outlier past every finite bucket
    samples = doctor.parse_metrics(reg.exposition())
    assert doctor.histogram_quantile(samples, "q_seconds", 0.5) == 0.01
    assert doctor.histogram_quantile(
        samples, "q_seconds", 0.999) == float("inf")


def test_parse_metrics_tolerates_junk():
    samples = doctor.parse_metrics(
        "# HELP x y\nx_total 3\nx_total{a=\"b\"} 4\nnot a line\n")
    assert doctor.metric_sum(samples, "x_total") == 7


# ---------------------------------------------------------------------------
# watchdog state isolation helper
# ---------------------------------------------------------------------------

def test_serving_region_restores_thread_state():
    with devicewatch.attribution("outer", phase="train"):
        with devicewatch.serving_region("inner", signature="s"):
            pass
        assert getattr(devicewatch._tls, "fn") == "outer"
        assert getattr(devicewatch._tls, "phase") == "train"
        assert getattr(devicewatch._tls, "serving") is False
