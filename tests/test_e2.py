"""e2 library tests (ref: e2/src/test/scala/.../e2/ — NaiveBayesTest,
MarkovChainTest, BinaryVectorizerTest, CrossValidationTest fixtures)."""

import math

import numpy as np
import pytest

from predictionio_tpu.e2 import (
    BinaryVectorizer, CategoricalNaiveBayes, LabeledPoint, MarkovChain,
    split_data,
)


@pytest.fixture()
def nb_points():
    # the reference's NaiveBayesFixture: labels by weather-ish categoricals
    return [
        LabeledPoint("yes", ("sunny", "hot")),
        LabeledPoint("yes", ("sunny", "mild")),
        LabeledPoint("yes", ("overcast", "mild")),
        LabeledPoint("no", ("rain", "mild")),
        LabeledPoint("no", ("rain", "hot")),
    ]


def test_categorical_nb_priors_and_likelihoods(nb_points):
    model = CategoricalNaiveBayes.train(nb_points)
    assert model.priors["yes"] == pytest.approx(math.log(3 / 5))
    assert model.priors["no"] == pytest.approx(math.log(2 / 5))
    # P(sunny | yes) = 2/3, no smoothing
    assert model.likelihoods["yes"][0]["sunny"] == pytest.approx(
        math.log(2 / 3))
    assert "sunny" not in model.likelihoods["no"][0]
    assert model.feature_count == 2


def test_categorical_nb_predict_and_log_score(nb_points):
    model = CategoricalNaiveBayes.train(nb_points)
    assert model.predict(("sunny", "hot")) == "yes"
    assert model.predict(("rain", "mild")) == "no"
    # log_score: None for unknown label; -inf default for unseen value
    assert model.log_score(LabeledPoint("maybe", ("sunny", "hot"))) is None
    s = model.log_score(LabeledPoint("no", ("sunny", "hot")))
    assert s == float("-inf")
    # custom default likelihood (CategoricalNaiveBayes.scala:96-101)
    s = model.log_score(LabeledPoint("no", ("sunny", "hot")),
                        default_likelihood=lambda ls: min(ls) - 1.0)
    assert s is not None and s > float("-inf")


def test_markov_chain_topn_and_predict():
    # transitions: 0->1 x3, 0->2 x1, 1->0 x2; topN=1 keeps the best per row
    model = MarkovChain.train(
        rows=[0, 0, 1], cols=[1, 2, 0], counts=[3.0, 1.0, 2.0],
        n_states=3, top_n=1)
    t = np.asarray(model.transition)
    assert t[0, 1] == pytest.approx(0.75)   # 3 / (3+1), full-row total
    assert t[0, 2] == 0.0                   # truncated by top-1
    assert t[1, 0] == pytest.approx(1.0)
    nxt = model.predict([1.0, 0.0, 0.0])
    assert nxt[1] == pytest.approx(0.75) and nxt[0] == 0.0


def test_binary_vectorizer():
    vec = BinaryVectorizer.from_maps(
        [{"color": "red", "size": "L", "junk": "x"},
         {"color": "blue", "size": "L"}],
        properties=["color", "size"])
    assert vec.num_features == 3  # (blue), (red), (L)
    v = vec.to_binary([("color", "red"), ("size", "L")])
    assert v.sum() == 2.0 and v.dtype == np.float32
    # unknown pair ignored
    assert vec.to_binary([("color", "green")]).sum() == 0.0
    batch = vec.to_binary_batch([[("color", "red")], [("size", "L")]])
    assert batch.shape == (2, 3)
    v2 = BinaryVectorizer.from_pairs([("a", "1"), ("b", "2")])
    assert v2.to_binary([("b", "2")]).tolist() == [0.0, 1.0]


def test_split_data_folds():
    data = list(range(10))
    folds = split_data(
        eval_k=3, dataset=data, evaluator_info="EI",
        training_data_creator=list,
        query_creator=lambda d: ("q", d),
        actual_creator=lambda d: ("a", d))
    assert len(folds) == 3
    for f, (train, ei, qa) in enumerate(folds):
        assert ei == "EI"
        test_points = [d for _q, (_tag, d) in
                       [(q, q) for q, _a in qa]]
        assert all(d % 3 == f for d in test_points)
        assert sorted(train + test_points) == data
    # every point appears in exactly one test fold
    all_test = [d for _td, _ei, qa in folds for (_t, d), _a in qa]
    assert sorted(all_test) == data
