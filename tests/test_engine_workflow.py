"""DASE engine + workflow tests (parity with the reference's
EngineWorkflowTest/EngineTest fixtures plus the recommendation template)."""

import json

import numpy as np
import pytest

from predictionio_tpu.controller import (
    Algorithm, DataSource, EngineParams, Engine, FirstServing, Params,
    Preparator, Serving,
)
from predictionio_tpu.data import store
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, Query, RecommendationEngine,
)
from predictionio_tpu.workflow import (
    WorkflowContext, WorkflowParams, run_train,
)
from predictionio_tpu.workflow import model_io
from predictionio_tpu.workflow.workflow_utils import (
    get_engine, read_engine_variant,
)


@pytest.fixture()
def rated_app(memory_storage):
    """An app with deterministic rate/buy events: users u0..u9, items i0..i7."""
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "MyApp1", None))
    memory_storage.get_events().init(app_id)
    import datetime as dt
    events = []
    minute = 0
    for u in range(10):
        for i in range(8):
            if (u + i) % 3 == 0:
                continue  # hold some pairs out
            minute += 1
            # users like items with matching parity (structured signal)
            r = 5.0 if (u % 2) == (i % 2) else 1.0
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": r}),
                event_time=dt.datetime(2021, 1, 1, 0, minute % 60,
                                       tzinfo=dt.timezone.utc)))
    # a couple of buy events (implicit 4.0)
    events.append(Event(
        event="buy", entity_type="user", entity_id="u0",
        target_entity_type="item", target_entity_id="i0",
        event_time=dt.datetime(2021, 1, 1, 1, tzinfo=dt.timezone.utc)))
    store.write(events, app_id, storage=memory_storage)
    return app_id


def engine_params(app_name="MyApp1", rank=4, iters=8, eval_params=None):
    return EngineParams(
        data_source_params=DataSourceParams(appName=app_name,
                                            evalParams=eval_params),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=rank, numIterations=iters,
                                       lambda_=0.05, seed=3)),))


def test_engine_json_extraction():
    engine = RecommendationEngine()
    variant = json.loads("""
    {"id": "default", "engineFactory": "x",
     "datasource": {"params": {"appName": "MyApp1"}},
     "algorithms": [{"name": "als",
        "params": {"rank": 10, "numIterations": 10, "lambda": 0.01, "seed": 3}}]}
    """)
    ep = engine.engine_params_from_json(variant)
    assert ep.data_source_params.appName == "MyApp1"
    name, ap = ep.algorithm_params_list[0]
    assert name == "als" and ap.rank == 10 and ap.lambda_ == 0.01 and ap.seed == 3


def test_engine_json_unknown_param_rejected():
    engine = RecommendationEngine()
    variant = {"id": "x", "engineFactory": "x",
               "datasource": {"params": {"appName": "a", "bogus": 1}},
               "algorithms": [{"name": "als", "params": {}}]}
    with pytest.raises(ValueError, match="bogus"):
        engine.engine_params_from_json(variant)


def test_engine_json_unknown_algorithm_rejected():
    engine = RecommendationEngine()
    variant = {"id": "x", "engineFactory": "x",
               "datasource": {"params": {"appName": "a"}},
               "algorithms": [{"name": "nope", "params": {}}]}
    with pytest.raises(KeyError, match="nope"):
        engine.engine_params_from_json(variant)


def test_train_and_predict(memory_storage, rated_app):
    engine = RecommendationEngine()
    ctx = WorkflowContext(storage=memory_storage)
    models = engine.train(ctx, engine_params())
    assert len(models) == 1
    model = models[0]
    algo = engine.algorithm_class_map["als"](
        ALSAlgorithmParams(rank=4, numIterations=8, seed=3))
    result = algo.predict(model, Query(user="u0", num=4))
    assert len(result.itemScores) == 4
    items = [s.item for s in result.itemScores]
    assert len(set(items)) == 4
    # structured signal: u0 (even) should rank an even item first
    assert int(result.itemScores[0].item[1:]) % 2 == 0
    # unknown user -> empty result, no crash (ALSAlgorithm.scala:104-108)
    empty = algo.predict(model, Query(user="ghost", num=4))
    assert empty.itemScores == ()


def test_run_train_ledger_and_model_roundtrip(memory_storage, rated_app):
    engine = RecommendationEngine()
    ctx = WorkflowContext(storage=memory_storage)
    instance_id = run_train(
        ctx, engine, engine_params(), engine_variant="default",
        engine_factory="predictionio_tpu.models.recommendation.engine:RecommendationEngine")
    row = memory_storage.get_meta_data_engine_instances().get(instance_id)
    assert row.status == "COMPLETED"
    blob = memory_storage.get_model_data_models().get(instance_id)
    assert blob is not None
    models = model_io.deserialize_models(blob.models)
    model = models[0]
    assert isinstance(model.user_factors, np.ndarray)  # host arrays persisted
    # deploy-side: arrays go back to device and serve
    model = model_io.device_put_tree(model)
    algo = engine.algorithm_class_map["als"](ALSAlgorithmParams())
    result = algo.predict(model, Query(user="u1", num=3))
    assert len(result.itemScores) == 3


def test_run_train_failure_marks_error(memory_storage):
    # no app in storage -> DataSource raises -> instance must be ERROR
    engine = RecommendationEngine()
    ctx = WorkflowContext(storage=memory_storage)
    with pytest.raises(Exception):
        run_train(ctx, engine, engine_params(app_name="missing"))
    rows = memory_storage.get_meta_data_engine_instances().get_all()
    assert len(rows) == 1 and rows[0].status == "ERROR"


def test_stop_after_read_flag(memory_storage, rated_app):
    from predictionio_tpu.controller.engine import StopAfterReadInterruption
    engine = RecommendationEngine()
    ctx = WorkflowContext(
        workflow_params=WorkflowParams(stop_after_read=True),
        storage=memory_storage)
    with pytest.raises(StopAfterReadInterruption):
        engine.train(ctx, engine_params())


def test_sanity_check_empty_ratings(memory_storage):
    apps = memory_storage.get_meta_data_apps()
    apps.insert(App(0, "EmptyApp", None))
    engine = RecommendationEngine()
    ctx = WorkflowContext(storage=memory_storage)
    with pytest.raises(ValueError, match="empty"):
        engine.train(ctx, engine_params(app_name="EmptyApp"))


def test_engine_factory_loading():
    engine = get_engine(
        "predictionio_tpu.models.recommendation.engine:RecommendationEngine")
    assert isinstance(engine, Engine)
    variant = read_engine_variant(
        "predictionio_tpu/models/recommendation", "engine.json")
    ep = engine.engine_params_from_json(variant)
    assert ep.algorithm_params_list[0][1].rank == 10
