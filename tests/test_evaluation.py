"""Evaluation workflow: k-fold metrics, grid search, FastEval memoization
(parity: MetricEvaluatorTest, FastEvalEngineTest, EvaluationWorkflowTest)."""

import datetime as dt

import pytest

from predictionio_tpu.controller import (
    AverageMetric, EngineParams, Evaluation, MetricEvaluator, OptionAverageMetric,
    StdevMetric, SumMetric, ZeroMetric,
)
from predictionio_tpu.data import store
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.models.recommendation.engine import (
    ActualResult, ItemScore, PredictedResult, Query, Rating,
)
from predictionio_tpu.models.recommendation.evaluation import (
    PositiveCount, PrecisionAtK, RecommendationEvaluation,
)
from predictionio_tpu.workflow import WorkflowContext, run_evaluation
from predictionio_tpu.workflow.fast_eval import FastEvalEngineWorkflow


# -- metric unit behavior ----------------------------------------------------

class _Avg(AverageMetric):
    def calculate_qpa(self, q, p, a):
        return p


class _OptAvg(OptionAverageMetric):
    def calculate_qpa(self, q, p, a):
        return p if p >= 0 else None


class _Sum(SumMetric):
    def calculate_qpa(self, q, p, a):
        return p


class _Std(StdevMetric):
    def calculate_qpa(self, q, p, a):
        return p


def _ds(values):
    return [(None, [(None, v, None) for v in values])]


def test_metric_family():
    assert _Avg().calculate(_ds([1.0, 2.0, 3.0])) == 2.0
    assert _OptAvg().calculate(_ds([1.0, -5.0, 3.0])) == 2.0  # None dropped
    assert _Sum().calculate(_ds([1.0, 2.0])) == 3.0
    assert _Std().calculate(_ds([2.0, 2.0])) == 0.0
    assert ZeroMetric().calculate(_ds([9.0])) == 0.0
    # multiple eval-info groups are pooled globally (Metric.scala:108-122)
    two_folds = _ds([1.0]) + _ds([3.0])
    assert _Avg().calculate(two_folds) == 2.0


def test_precision_at_k_semantics():
    m = PrecisionAtK(k=2, ratingThreshold=4.0)
    q = Query(user="u", num=2)
    p = PredictedResult((ItemScore("a", 9.0), ItemScore("b", 8.0),
                         ItemScore("c", 7.0)))
    a = ActualResult((Rating("u", "a", 5.0), Rating("u", "c", 5.0),
                      Rating("u", "b", 1.0)))
    # top-2 = [a, b]; positives = {a, c}; tp=1; min(k, positives)=2
    assert m.calculate_qpa(q, p, a) == 0.5
    # no positives -> None -> excluded from the average
    none_case = m.calculate_qpa(q, p, ActualResult((Rating("u", "a", 1.0),)))
    assert none_case is None
    with pytest.raises(ValueError):
        PrecisionAtK(k=0)


def test_positive_count():
    m = PositiveCount(ratingThreshold=2.0)
    a = ActualResult((Rating("u", "a", 5.0), Rating("u", "b", 1.0)))
    assert m.calculate_qpa(None, None, a) == 1


# -- full evaluation over the template --------------------------------------

@pytest.fixture()
def rated_app(memory_storage):
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "MyApp1", None))
    memory_storage.get_events().init(app_id)
    events = []
    minute = 0
    for u in range(12):
        for i in range(10):
            if (u * 7 + i * 3) % 4 == 0:
                continue
            minute += 1
            r = 5.0 if (u % 2) == (i % 2) else 1.0
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": r}),
                event_time=dt.datetime(2021, 1, 1, minute // 60, minute % 60,
                                       tzinfo=dt.timezone.utc)))
    store.write(events, app_id, storage=memory_storage)
    return app_id


def grid(ranks=(2, 4), iters=(2, 5)):
    base_ds = DataSourceParams(
        appName="MyApp1", evalParams={"kFold": 3, "queryNum": 5})
    return [
        EngineParams(
            data_source_params=base_ds,
            algorithm_params_list=(
                ("als", ALSAlgorithmParams(rank=r, numIterations=it,
                                           lambda_=0.05, seed=3)),))
        for r in ranks for it in iters]


def test_run_evaluation_grid(memory_storage, rated_app, tmp_path):
    evaluation = RecommendationEvaluation()
    ctx = WorkflowContext(storage=memory_storage)
    out = tmp_path / "best.json"
    result = run_evaluation(
        ctx, evaluation, grid(), evaluation_class="RecommendationEvaluation",
        output_path=str(out))
    assert len(result.engine_params_scores) == 4
    assert 0.0 <= result.best_score.score <= 1.0
    # PositiveCount (first other metric) must see the positive actuals
    assert result.best_score.other_scores[0] > 0.0
    assert out.exists()
    # ledger row written with results
    rows = memory_storage.get_meta_data_evaluation_instances().get_completed()
    assert len(rows) == 1
    assert "Precision@K" in rows[0].evaluator_results_json
    # more iterations should not hurt on the training signal:
    # ensure scores are finite and ordered info is present
    assert all(s.score == s.score for s in result.engine_params_scores)


def test_fast_eval_memoization(memory_storage, rated_app):
    """Grid of 4 sharing one data source: read_eval and prepare run ONCE
    (FastEvalEngineTest parity — assert pipeline build counts)."""
    engine = RecommendationEngine()
    ctx = WorkflowContext(storage=memory_storage)
    wf = FastEvalEngineWorkflow(engine, ctx)
    for ep in grid():
        wf.eval(ep)
    assert wf.counts["read_eval"] == 1
    assert wf.counts["prepare"] == 1
    assert wf.counts["train"] == 4
    assert wf.counts["serve"] == 4
    # re-evaluating an already-seen variant is fully cached
    wf.eval(grid()[0])
    assert wf.counts["train"] == 4 and wf.counts["serve"] == 4


def test_fake_run_executes_under_workflow(memory_storage, tmp_path):
    """FakeWorkflow parity (FakeWorkflow.scala:28-109): a FakeRun's func
    executes with the real WorkflowContext via run_evaluation, and its
    noSave result leaves only the ledger row."""
    from predictionio_tpu.workflow.fake import FakeRun

    seen = {}

    class Hello(FakeRun):
        def func(self, ctx):
            seen["storage"] = ctx.storage

    fr = Hello()
    ctx = WorkflowContext(storage=memory_storage)
    result = run_evaluation(ctx, fr, fr.engine_params_list,
                            evaluation_class="Hello")
    assert seen["storage"] is memory_storage
    assert str(result) == "FakeEvalResult()"
    rows = memory_storage.get_meta_data_evaluation_instances().get_completed()
    assert len(rows) == 1 and rows[0].evaluator_results == ""
