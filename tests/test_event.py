"""Event validation parity with EventValidation (Event.scala:112-141)."""

import datetime as dt

import pytest

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event, EventValidation, format_event_time, parse_event_time


def ev(**kw):
    base = dict(event="rate", entity_type="user", entity_id="u1")
    base.update(kw)
    return Event(**base)


def test_valid_plain_event():
    EventValidation.validate(ev())


def test_empty_fields_rejected():
    for kw in ({"event": ""}, {"entity_type": ""}, {"entity_id": ""}):
        with pytest.raises(ValueError):
            EventValidation.validate(ev(**kw))


def test_target_entity_pairing():
    with pytest.raises(ValueError):
        EventValidation.validate(ev(target_entity_type="item"))
    with pytest.raises(ValueError):
        EventValidation.validate(ev(target_entity_id="i1"))
    EventValidation.validate(ev(target_entity_type="item", target_entity_id="i1"))


def test_unset_requires_properties():
    with pytest.raises(ValueError):
        EventValidation.validate(ev(event="$unset"))
    EventValidation.validate(ev(event="$unset", properties=DataMap({"a": 1})))


def test_reserved_event_names():
    with pytest.raises(ValueError):
        EventValidation.validate(ev(event="$not_special"))
    with pytest.raises(ValueError):
        EventValidation.validate(ev(event="pio_custom"))
    EventValidation.validate(ev(event="$set"))
    EventValidation.validate(ev(event="$delete"))


def test_special_event_cannot_have_target():
    with pytest.raises(ValueError):
        EventValidation.validate(
            ev(event="$set", target_entity_type="item", target_entity_id="i1"))


def test_reserved_entity_type():
    with pytest.raises(ValueError):
        EventValidation.validate(ev(entity_type="pio_user"))
    EventValidation.validate(ev(entity_type="pio_pr"))  # built-in


def test_reserved_property_prefix():
    with pytest.raises(ValueError):
        EventValidation.validate(ev(properties=DataMap({"pio_x": 1})))


def test_json_round_trip():
    e = ev(
        target_entity_type="item", target_entity_id="i1",
        properties=DataMap({"rating": 4.5}),
        event_time=dt.datetime(2021, 6, 1, 12, 0, 0, tzinfo=dt.timezone.utc),
        tags=["a"], pr_id="pr1",
    ).with_event_id("abc")
    e2 = Event.from_json(e.to_json())
    assert e2.event == "rate" and e2.entity_id == "u1"
    assert e2.target_entity_id == "i1"
    assert e2.properties.get_float("rating") == 4.5
    assert e2.event_time == e.event_time
    assert e2.pr_id == "pr1" and list(e2.tags) == ["a"] and e2.event_id == "abc"
    assert isinstance(hash(e2), int)  # Events are hashable (dedup via set)


def test_from_dict_malformed():
    with pytest.raises(ValueError):
        Event.from_dict({"entityType": "user", "entityId": "u1"})  # no event
    with pytest.raises(ValueError):
        Event.from_dict({"event": 3, "entityType": "user", "entityId": "u1"})
    with pytest.raises(ValueError):
        Event.from_dict(
            {"event": "e", "entityType": "user", "entityId": "u1",
             "properties": [1, 2]})


def test_time_parse_formats():
    t = parse_event_time("2021-06-01T12:00:00.123Z")
    assert t.tzinfo is not None and t.microsecond == 123000
    t2 = parse_event_time("2021-06-01T12:00:00+02:00")
    assert t2.utcoffset() == dt.timedelta(hours=2)
    naive = parse_event_time("2021-06-01T12:00:00")
    assert naive.tzinfo == dt.timezone.utc
    assert format_event_time(t) == "2021-06-01T12:00:00.123Z"
