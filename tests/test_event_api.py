"""Event Server route tests (ref: data/src/test/scala/.../api/EventServiceSpec.scala
and webhooks/*Spec.scala — spray-testkit route tests against an in-memory
LEvents; here the pure EventAPI handler is exercised directly, plus one
socket smoke test)."""

import base64
import json
import urllib.request

import pytest

from predictionio_tpu.data.api import EventAPI, EventServerConfig
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.data.api.plugins import (
    INPUT_BLOCKER, EventServerPlugin, EventServerPluginContext,
)
from predictionio_tpu.data.storage import AccessKey, App, Channel


@pytest.fixture()
def api(memory_storage):
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "testapp", None))
    memory_storage.get_events().init(app_id)
    memory_storage.get_meta_data_access_keys().insert(
        AccessKey("secret", app_id, ()))
    api = EventAPI(storage=memory_storage)
    api.app_id = app_id
    return api


def ev(name="rate", entity="u0", **kw):
    d = {"event": name, "entityType": "user", "entityId": entity}
    d.update(kw)
    return json.dumps(d).encode()


def test_alive_and_unknown_route(api):
    assert api.handle("GET", "/") == (200, {"status": "alive"})
    status, _ = api.handle("GET", "/nope.json")
    assert status == 404


def test_auth_missing_invalid_and_basic_header(api):
    status, body = api.handle("POST", "/events.json", {}, ev())
    assert status == 401 and "Missing" in body["message"]
    status, _ = api.handle("POST", "/events.json", {"accessKey": "wrong"}, ev())
    assert status == 401
    # Basic auth: key as username (EventServer.scala:115-127)
    hdr = {"Authorization":
           "Basic " + base64.b64encode(b"secret:").decode()}
    status, body = api.handle("POST", "/events.json", {}, ev(), hdr)
    assert status == 201 and "eventId" in body


def test_post_get_delete_event(api):
    q = {"accessKey": "secret"}
    status, body = api.handle("POST", "/events.json", q, ev())
    assert status == 201
    eid = body["eventId"]
    status, got = api.handle("GET", f"/events/{eid}.json", q)
    assert status == 200 and got["event"] == "rate" and got["eventId"] == eid
    status, body = api.handle("DELETE", f"/events/{eid}.json", q)
    assert (status, body) == (200, {"message": "Found"})
    status, _ = api.handle("GET", f"/events/{eid}.json", q)
    assert status == 404
    status, _ = api.handle("DELETE", f"/events/{eid}.json", q)
    assert status == 404


def test_malformed_event_400(api):
    q = {"accessKey": "secret"}
    status, _ = api.handle("POST", "/events.json", q, b"{not json")
    assert status == 400
    status, body = api.handle("POST", "/events.json", q,
                              json.dumps({"event": "rate"}).encode())
    assert status == 400 and "entityType" in body["message"]


def test_allowed_events_enforcement(memory_storage):
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "app2", None))
    memory_storage.get_events().init(app_id)
    memory_storage.get_meta_data_access_keys().insert(
        AccessKey("limited", app_id, ("view",)))
    api = EventAPI(storage=memory_storage)
    q = {"accessKey": "limited"}
    status, body = api.handle("POST", "/events.json", q, ev("rate"))
    assert status == 403 and "not allowed" in body["message"]
    status, _ = api.handle("POST", "/events.json", q, ev("view"))
    assert status == 201


def test_get_events_filters_and_limit(api):
    q = {"accessKey": "secret"}
    for n in range(25):
        api.handle("POST", "/events.json", q, ev(
            "rate", f"u{n}", eventTime=f"2021-01-01T00:{n:02d}:00.000Z"))
    # default limit 20 (EventServer.scala:353)
    status, body = api.handle("GET", "/events.json", q)
    assert status == 200 and len(body) == 20
    status, body = api.handle("GET", "/events.json", dict(q, limit="-1"))
    assert len(body) == 25
    status, body = api.handle(
        "GET", "/events.json", dict(q, entityId="u3", entityType="user"))
    assert len(body) == 1 and body[0]["entityId"] == "u3"
    # time-window filter
    status, body = api.handle("GET", "/events.json", dict(
        q, startTime="2021-01-01T00:10:00.000Z",
        untilTime="2021-01-01T00:12:00.000Z"))
    assert [e["entityId"] for e in body] == ["u10", "u11"]
    # empty result -> 404 (EventServer.scala:356-360)
    status, body = api.handle(
        "GET", "/events.json", dict(q, entityId="zzz", entityType="user"))
    assert status == 404
    # reversed requires entityType+entityId
    status, body = api.handle("GET", "/events.json", dict(q, reversed="true"))
    assert status == 400
    status, body = api.handle("GET", "/events.json", dict(
        q, reversed="true", entityType="user", entityId="u3"))
    assert status == 200


def test_batch_events(api):
    q = {"accessKey": "secret"}
    items = [
        {"event": "rate", "entityType": "user", "entityId": "a"},
        {"event": "rate"},  # malformed
        {"event": "buy", "entityType": "user", "entityId": "b"},
    ]
    status, results = api.handle("POST", "/batch/events.json", q,
                                 json.dumps(items).encode())
    assert status == 200
    assert [r["status"] for r in results] == [201, 400, 201]
    # cap at 50 (EventServer.scala:70)
    too_many = [{"event": "e", "entityType": "user", "entityId": "x"}] * 51
    status, body = api.handle("POST", "/batch/events.json", q,
                              json.dumps(too_many).encode())
    assert status == 400 and "50" in body["message"]


def test_batch_cap_configurable(api, monkeypatch):
    """PIO_BATCH_EVENTS_MAX raises (or lowers) the per-request item cap;
    unset/invalid keeps the reference default of 50."""
    q = {"accessKey": "secret"}
    items = [{"event": "e", "entityType": "user", "entityId": f"x{k}"}
             for k in range(51)]
    body = json.dumps(items).encode()
    monkeypatch.setenv("PIO_BATCH_EVENTS_MAX", "100")
    status, results = api.handle("POST", "/batch/events.json", q, body)
    assert status == 200 and len(results) == 51
    assert all(r["status"] == 201 for r in results)
    monkeypatch.setenv("PIO_BATCH_EVENTS_MAX", "2")
    status, payload = api.handle("POST", "/batch/events.json", q,
                                 json.dumps(items[:3]).encode())
    assert status == 400 and "2" in payload["message"]
    monkeypatch.setenv("PIO_BATCH_EVENTS_MAX", "junk")
    status, payload = api.handle("POST", "/batch/events.json", q, body)
    assert status == 400 and "50" in payload["message"]


def test_batch_bulk_and_per_item_paths_agree(api, monkeypatch):
    """PIO_BATCH_BULK_INSERT=0 (the per-item legacy path) produces the
    same per-item statuses, in order, as the default bulk path."""
    q = {"accessKey": "secret"}
    items = [
        {"event": "rate", "entityType": "user", "entityId": "a"},
        {"event": "rate"},                       # malformed -> 400
        {"event": "buy", "entityType": "user", "entityId": "b"},
    ]
    body = json.dumps(items).encode()
    status, bulk = api.handle("POST", "/batch/events.json", q, body)
    monkeypatch.setenv("PIO_BATCH_BULK_INSERT", "0")
    status2, per_item = api.handle("POST", "/batch/events.json", q, body)
    assert status == status2 == 200
    assert [r["status"] for r in bulk] == [r["status"] for r in per_item] \
        == [201, 400, 201]


def test_channel_auth_and_separation(api, memory_storage):
    cid = memory_storage.get_meta_data_channels().insert(
        Channel(0, "mobile", api.app_id))
    memory_storage.get_events().init(api.app_id, cid)
    status, body = api.handle(
        "POST", "/events.json",
        {"accessKey": "secret", "channel": "nope"}, ev())
    assert status == 401 and "Invalid channel" in body["message"]
    q = {"accessKey": "secret", "channel": "mobile"}
    status, _ = api.handle("POST", "/events.json", q, ev("tap", "u9"))
    assert status == 201
    # default channel does not see it
    status, _ = api.handle("GET", "/events.json", {"accessKey": "secret"})
    assert status == 404
    status, body = api.handle("GET", "/events.json", q)
    assert len(body) == 1 and body[0]["event"] == "tap"


def test_stats_route(memory_storage):
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "app3", None))
    memory_storage.get_events().init(app_id)
    memory_storage.get_meta_data_access_keys().insert(
        AccessKey("k3", app_id, ()))
    off = EventAPI(storage=memory_storage)
    status, body = off.handle("GET", "/stats.json", {"accessKey": "k3"})
    assert status == 404 and "--stats" in body["message"]

    on = EventAPI(storage=memory_storage,
                  config=EventServerConfig(stats=True))
    on.handle("POST", "/events.json", {"accessKey": "k3"}, ev())
    status, snap = on.handle("GET", "/stats.json", {"accessKey": "k3"})
    assert status == 200
    basic = snap["longLive"]["basic"]
    assert basic == [{"key": {"entityType": "user", "targetEntityType": None,
                              "event": "rate"}, "value": 1}]
    assert snap["longLive"]["statusCode"] == [{"key": 201, "value": 1}]


def test_webhooks_segmentio(api):
    q = {"accessKey": "secret"}
    payload = {
        "version": "2", "type": "track", "user_id": "alice",
        "event": "Signed Up", "properties": {"plan": "Pro"},
        "timestamp": "2021-03-04T05:06:07.000Z",
    }
    status, body = api.handle("POST", "/webhooks/segmentio.json", q,
                              json.dumps(payload).encode())
    assert status == 201
    status, got = api.handle("GET", f"/events/{body['eventId']}.json", q)
    assert got["event"] == "track"
    assert got["entityId"] == "alice"
    assert got["properties"]["event"] == "Signed Up"
    assert got["eventTime"] == "2021-03-04T05:06:07.000Z"
    # presence checks + unsupported connector
    assert api.handle("GET", "/webhooks/segmentio.json", q)[0] == 200
    assert api.handle("GET", "/webhooks/nope.json", q)[0] == 404
    assert api.handle("POST", "/webhooks/nope.json", q, b"{}")[0] == 404
    # bad payload
    status, _ = api.handle("POST", "/webhooks/segmentio.json", q,
                           json.dumps({"version": "2"}).encode())
    assert status == 400


def test_webhooks_mailchimp_form(api):
    q = {"accessKey": "secret"}
    form = {
        "type": "subscribe", "fired_at": "2009-03-26 21:35:57",
        "data[id]": "8a25ff1d98", "data[list_id]": "a6b5da1054",
        "data[email]": "api@mailchimp.com", "data[email_type]": "html",
        "data[merges][EMAIL]": "api@mailchimp.com",
        "data[merges][FNAME]": "MailChimp", "data[merges][LNAME]": "API",
        "data[ip_opt]": "10.20.10.30", "data[ip_signup]": "10.20.10.30",
    }
    body = urllib.parse.urlencode(form).encode()
    status, out = api.handle("POST", "/webhooks/mailchimp.form", q, body)
    assert status == 201
    _, got = api.handle("GET", f"/events/{out['eventId']}.json", q)
    assert got["event"] == "subscribe"
    assert got["targetEntityId"] == "a6b5da1054"
    assert got["eventTime"] == "2009-03-26T21:35:57.000Z"
    assert api.handle("GET", "/webhooks/mailchimp.form", q)[0] == 200


def test_plugins_describe_and_blocker(memory_storage):
    class Blocker(EventServerPlugin):
        plugin_name = "strict"
        plugin_description = "rejects buy events"
        plugin_type = INPUT_BLOCKER

        def process(self, info, context):
            if info.event.event == "buy":
                raise ValueError("buy blocked")

        def handle_rest(self, app_id, channel_id, args):
            return json.dumps({"args": list(args)})

    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "app4", None))
    memory_storage.get_events().init(app_id)
    memory_storage.get_meta_data_access_keys().insert(
        AccessKey("k4", app_id, ()))
    api = EventAPI(storage=memory_storage,
                   plugin_context=EventServerPluginContext([Blocker()]))
    status, desc = api.handle("GET", "/plugins.json")
    assert "strict" in desc["plugins"]["inputblockers"]
    q = {"accessKey": "k4"}
    status, _ = api.handle("POST", "/events.json", q, ev("buy"))
    assert status == 500  # blocker raises -> exceptionHandler path
    status, _ = api.handle("POST", "/events.json", q, ev("view"))
    assert status == 201
    status, body = api.handle("GET", "/plugins/inputblocker/strict/a/b", q)
    assert (status, body) == (200, {"args": ["a", "b"]})


def test_http_transport_smoke(api):
    server, port = serve_background(api)
    try:
        base = f"http://localhost:{port}"
        with urllib.request.urlopen(f"{base}/") as r:
            assert json.loads(r.read()) == {"status": "alive"}
        req = urllib.request.Request(
            f"{base}/events.json?accessKey=secret", data=ev(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req) as r:
            assert r.status == 201
            assert "eventId" in json.loads(r.read())
        # error statuses surface over the wire too
        try:
            urllib.request.urlopen(f"{base}/events.json")
            assert False, "expected 401"
        except urllib.error.HTTPError as e:
            assert e.code == 401
    finally:
        server.shutdown()
