"""Columnar event log: TPU-ingestion path correctness.

The fast path (eventlog.read_columns → store._columnar_from_codes) must
agree with the generic per-event path (find → Python encode) event for
event — same ratings, same vocab contents, same COO up to vocab relabeling.
"""

import datetime as dt
import os

import numpy as np
import pytest

from predictionio_tpu.data import store
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App, Storage

UTC = dt.timezone.utc


def make_storage(tmp_path, backend):
    if backend == "memory":
        env = {
            "PIO_STORAGE_SOURCES_T_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "T",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "T",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "T",
        }
    else:
        env = {
            "PIO_STORAGE_SOURCES_M_TYPE": "memory",
            "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
            "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path / "el"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        }
    s = Storage(env=env)
    app_id = s.get_meta_data_apps().insert(App(0, "app"))
    s.get_events().init(app_id)
    return s, app_id


def seed_events(rng, n=300, n_u=20, n_i=12):
    evs = []
    for j in range(n):
        u, i = rng.integers(0, n_u), rng.integers(0, n_i)
        name = "rate" if j % 3 else "buy"
        props = {"rating": float(rng.uniform(1, 5))} if name == "rate" else {}
        evs.append(Event(
            event=name, entity_type="user", entity_id=f"u{u}",
            target_entity_type="item", target_entity_id=f"i{i}",
            properties=DataMap(props),
            event_time=dt.datetime(2021, 1, 1, tzinfo=UTC)
            + dt.timedelta(seconds=j)))
    # plus some $set events with no target
    for u in range(3):
        evs.append(Event(
            event="$set", entity_type="user", entity_id=f"u{u}",
            properties=DataMap({"plan": "basic"}),
            event_time=dt.datetime(2021, 1, 2, tzinfo=UTC)))
    return evs


def triples(col):
    """Vocab-independent view: set of (user, item, rating, event)."""
    inv_e = col.entity_ids.inverse()
    inv_t = col.target_ids.inverse()
    out = set()
    for j in range(col.n):
        r = col.rating[j]
        out.add((
            inv_e(int(col.entity_idx[j])),
            inv_t(int(col.target_idx[j])) if col.target_idx[j] >= 0 else None,
            None if np.isnan(r) else round(float(r), 5),
            col.event_names[col.event_name_idx[j]],
        ))
    return out


def test_fast_path_matches_object_path(tmp_path):
    rng = np.random.default_rng(0)
    evs = seed_events(rng)
    s_mem, _ = make_storage(tmp_path, "memory")
    s_el, _ = make_storage(tmp_path, "eventlog")
    s_mem.get_events().insert_batch(evs, 1)
    s_el.get_events().insert_batch(evs, 1)

    kw = dict(event_names=["rate", "buy"], entity_type="user",
              target_entity_type="item")
    slow = store.find_columnar("app", storage=s_mem, **kw)
    fast = store.find_columnar("app", storage=s_el, **kw)
    assert fast.n == slow.n
    assert triples(fast) == triples(slow)
    assert set(fast.entity_ids.to_dict()) == set(slow.entity_ids.to_dict())
    assert set(fast.target_ids.to_dict()) == set(slow.target_ids.to_dict())


def test_fast_path_fixed_vocab_drops_unseen(tmp_path):
    rng = np.random.default_rng(1)
    evs = seed_events(rng)
    s_el, _ = make_storage(tmp_path, "eventlog")
    s_el.get_events().insert_batch(evs, 1)
    full = store.find_columnar("app", storage=s_el,
                               event_names=["rate"], entity_type="user")
    partial_vocab = full.entity_ids.take(5)
    col = store.find_columnar(
        "app", storage=s_el, event_names=["rate"], entity_type="user",
        entity_vocab=partial_vocab, target_vocab=full.target_ids)
    kept = set(partial_vocab.to_dict().values())
    assert col.n > 0
    assert set(col.entity_idx.tolist()) <= kept


def test_append_encoded_roundtrip(tmp_path):
    s_el, app_id = make_storage(tmp_path, "eventlog")
    ev = s_el.get_events()
    pool = ["rate", "user", "item", "u0", "u1", "i0"]
    ev.append_encoded(
        app_id, None, pool,
        event=np.zeros(4, np.int32),
        entity_type=np.full(4, 1, np.int32),
        entity_id=np.asarray([3, 3, 4, 4], np.int32),
        time_ms=np.arange(4, dtype=np.int64) * 1000 + 1_600_000_000_000,
        target_type=np.full(4, 2, np.int32),
        target_id=np.full(4, 5, np.int32),
        numeric={"rating": np.asarray([1, 2, 3, 4], np.float32)},
    )
    col = store.find_columnar("app", storage=s_el, event_names=["rate"])
    assert col.n == 4
    assert sorted(col.rating.tolist()) == [1, 2, 3, 4]
    # and the generic object path sees the same events
    events = list(ev.find(app_id))
    assert len(events) == 4
    assert {e.entity_id for e in events} == {"u0", "u1"}
    assert events[0].properties.get("rating") == 1


def el_env(tmp_path):
    return {
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
        "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path / "el"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    }


def test_unflushed_inserts_durable_without_close(tmp_path):
    """WAL semantics: an acknowledged insert survives a writer that never
    flushes or closes (process crash), and is visible to a second
    'process' (fresh Events instance over the same directory)."""
    s1, app_id = make_storage(tmp_path, "eventlog")
    ev1 = s1.get_events()
    eid = ev1.insert(Event(event="rate", entity_type="user", entity_id="u1",
                           target_entity_type="item", target_entity_id="i1",
                           properties=DataMap({"rating": 4.5})), app_id)
    # no flush/close — simulate a concurrent reader process
    s2 = Storage(env=el_env(tmp_path))
    got = list(s2.get_events().find(app_id))
    assert [e.entity_id for e in got] == ["u1"]
    assert s2.get_events().get(eid, app_id) is not None
    col = s2.get_events().read_columns(app_id, event_names=["rate"])
    assert col["rating"].tolist() == [4.5]


def test_concurrent_reader_sees_new_strings_and_chunks(tmp_path):
    """Round-1 review finding: a reader opened before later writes must not
    crash on dictionary codes it has never seen."""
    s_w, app_id = make_storage(tmp_path, "eventlog")
    writer = s_w.get_events()
    writer.insert(Event(event="rate", entity_type="user", entity_id="early"),
                  app_id)
    s_r = Storage(env=el_env(tmp_path))
    reader = s_r.get_events()
    assert len(list(reader.find(app_id))) == 1  # reader opens its shard now
    # writer introduces NEW strings and compacts a chunk
    writer.insert(Event(event="brand-new-event", entity_type="thing",
                        entity_id="later"), app_id)
    writer.flush(app_id)
    got = {e.event for e in reader.find(app_id)}
    assert got == {"rate", "brand-new-event"}
    assert len(list(reader.find(app_id, event_names=["brand-new-event"]))) == 1


def test_numeric_property_fidelity(tmp_path):
    """float64 columns + was-int flags: big ints exact, float-typed values
    stay floats (round-1 review finding: float32 silently corrupted
    16777217 and 4.0 came back as int)."""
    s, app_id = make_storage(tmp_path, "eventlog")
    ev = s.get_events()
    eid = ev.insert(Event(
        event="$set", entity_type="user", entity_id="u1",
        properties=DataMap({"count": 16777217, "score": 4.0})), app_id)
    ev.flush(app_id)
    got = ev.get(eid, app_id).properties.to_dict()
    assert got["count"] == 16777217 and isinstance(got["count"], int)
    assert got["score"] == 4.0 and isinstance(got["score"], float)


def test_string_rating_coerced_like_object_path(tmp_path):
    """Client quirk: {"rating": "4.5"} must train identically on eventlog
    and on the object-path backends."""
    s, app_id = make_storage(tmp_path, "eventlog")
    ev = s.get_events()
    ev.insert(Event(event="rate", entity_type="user", entity_id="u1",
                    target_entity_type="item", target_entity_id="i1",
                    properties=DataMap({"rating": "4.5"})), app_id)
    ev.flush(app_id)
    col = store.find_columnar("app", storage=s, event_names=["rate"])
    assert col.rating.tolist() == [4.5]


def test_eventlog_persists_across_instances(tmp_path):
    s1, app_id = make_storage(tmp_path, "eventlog")
    rng = np.random.default_rng(2)
    s1.get_events().insert_batch(seed_events(rng, n=50), app_id)
    s1.get_events().close()

    env = {
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
        "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path / "el"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    }
    s2 = Storage(env=env)
    assert len(list(s2.get_events().find(app_id))) == 53


def test_flush_crash_window_idempotent(tmp_path):
    """ADVICE r2 (medium): a crash between chunk publication and WAL
    removal must not duplicate rows — for a restarted writer, a fresh
    reader, or a reader that was already tailing the WAL."""
    s1, app_id = make_storage(tmp_path, "eventlog")
    ev1 = s1.get_events()
    rng = np.random.default_rng(3)
    evs = seed_events(rng, n=10)
    ev1.insert_batch(evs, app_id)

    # a reader process opens mid-window and tails the WAL
    s_r = Storage(env=el_env(tmp_path))
    reader = s_r.get_events()
    assert len(list(reader.find(app_id))) == 13

    # snapshot the WAL, flush (chunk published + WAL removed), then put the
    # WAL back: exactly the on-disk state after a crash between the two
    sh = ev1._shard(app_id, None)
    wal = sh.wal_path_for(sh.next_seq)
    blob = open(wal, "rb").read()
    ev1.flush(app_id)
    with open(wal, "wb") as f:
        f.write(blob)

    # fresh reader: chunk supersedes its WAL — rows appear exactly once
    s2 = Storage(env=el_env(tmp_path))
    assert len(list(s2.get_events().find(app_id))) == 13
    # the pre-existing reader refreshes through the same window
    assert len(list(reader.find(app_id))) == 13
    col = s2.get_events().read_columns(app_id, event_names=["rate", "buy"])
    assert len(col["rating"]) == 10

    # restarted writer: replays nothing for the superseded WAL, and its
    # next flush does not re-compact those rows into a second chunk
    s3 = Storage(env=el_env(tmp_path))
    ev3 = s3.get_events()
    ev3.insert(Event(event="rate", entity_type="user", entity_id="u99",
                     target_entity_type="item", target_entity_id="i0",
                     properties=DataMap({"rating": 1.0})), app_id)
    ev3.flush(app_id)
    s4 = Storage(env=el_env(tmp_path))
    assert len(list(s4.get_events().find(app_id))) == 14


def test_wal_midfile_corruption_warns(tmp_path, caplog):
    """ADVICE r2 (low): corruption of a complete WAL line is not a torn
    tail — it must be logged, and surrounding events must survive."""
    import logging

    s1, app_id = make_storage(tmp_path, "eventlog")
    ev1 = s1.get_events()
    ev1.insert_batch(seed_events(np.random.default_rng(4), n=5)[:5], app_id)
    sh = ev1._shard(app_id, None)
    wal = sh.wal_path_for(sh.next_seq)
    lines = open(wal, "rb").read().split(b"\n")
    lines[2] = b'{"busted'
    with open(wal, "wb") as f:
        f.write(b"\n".join(lines))
    with caplog.at_level(logging.WARNING):
        s2 = Storage(env=el_env(tmp_path))
        got = list(s2.get_events().find(app_id))
    assert len(got) == 4
    assert any("corrupt WAL record" in r.message for r in caplog.records)


def test_wal_incomplete_tail_retried_not_misparsed(tmp_path):
    """A record observed mid-write (no trailing newline) is not consumed;
    once the writer completes it, the same reader picks it up whole."""
    s1, app_id = make_storage(tmp_path, "eventlog")
    ev1 = s1.get_events()
    ev1.insert(Event(event="rate", entity_type="user", entity_id="u1",
                     target_entity_type="item", target_entity_id="i1",
                     properties=DataMap({"rating": 2.0})), app_id)
    sh = ev1._shard(app_id, None)
    wal = sh.wal_path_for(sh.next_seq)
    full = Event(event="rate", entity_type="user", entity_id="u2",
                 target_entity_type="item", target_entity_id="i2",
                 properties=DataMap({"rating": 3.0}))
    import json as _json
    line = _json.dumps(full.to_dict(with_event_id=False)) + "\n"
    with open(wal, "a", encoding="utf-8") as f:
        f.write(line[:10])  # partial write observed by the reader
    s_r = Storage(env=el_env(tmp_path))
    reader = s_r.get_events()
    assert {e.entity_id for e in reader.find(app_id)} == {"u1"}
    with open(wal, "a", encoding="utf-8") as f:
        f.write(line[10:])
    assert {e.entity_id for e in reader.find(app_id)} == {"u1", "u2"}


def test_point_read_touches_only_matching_rows(tmp_path, monkeypatch):
    """VERDICT r2 #3: find(entity_id=..) must materialize O(matching)
    events via the chunk postings index, not scan every row."""
    from predictionio_tpu.data.storage import eventlog as el_mod

    s, app_id = make_storage(tmp_path, "eventlog")
    ev = s.get_events()
    rng = np.random.default_rng(5)
    base = dt.datetime(2022, 1, 1, tzinfo=UTC)
    for c in range(3):  # three chunks with disjoint time ranges
        evs = [Event(
            event="view", entity_type="user", entity_id=f"u{int(j % 40)}",
            target_entity_type="item", target_entity_id=f"i{int(j % 17)}",
            event_time=base + dt.timedelta(days=c, seconds=j))
            for j in range(200)]
        ev.insert_batch(evs, app_id)
        ev.flush(app_id)
    # every chunk has a sidecar index
    sh = ev._shard(app_id, None)
    assert all(sh.chunk_index(seq) is not None for seq in sh.chunk_seqs())

    calls = {"n": 0}
    orig = el_mod.EventlogEvents._materialize
    orig_batch = el_mod.EventlogEvents._materialize_batch

    def counting(self, *a, **kw):
        calls["n"] += 1
        return orig(self, *a, **kw)

    def counting_batch(self, sh, seq, data, rows, offsets):
        out = orig_batch(self, sh, seq, data, rows, offsets)
        calls["n"] += len(out)
        return out

    monkeypatch.setattr(el_mod.EventlogEvents, "_materialize", counting)
    monkeypatch.setattr(el_mod.EventlogEvents, "_materialize_batch",
                        counting_batch)
    got = list(ev.find(app_id, entity_id="u7", entity_type="user"))
    assert len(got) == 15  # 5 rows per chunk x 3 chunks
    assert calls["n"] == 15  # exactly the matching rows, not 600

    # target-entity postings too
    calls["n"] = 0
    got = list(ev.find(app_id, target_entity_id="i3"))
    assert len(got) == 36 and calls["n"] == 36

    # limit + reversed early-exit: only the newest chunk is opened
    # (chunk columns are LRU-cached mmaps now, so drop the cache to count
    # opens; both the mmap path and the np.load fallback count as one)
    loads = {"n": 0}
    orig_load = el_mod.np.load
    orig_mmap = el_mod._mmap_npz_columns

    def counting_load(path, *a, **kw):
        if str(path).endswith(".npz") and "idx" not in str(path):
            loads["n"] += 1
        return orig_load(path, *a, **kw)

    def counting_mmap(path):
        loads["n"] += 1
        return orig_mmap(path)

    monkeypatch.setattr(el_mod.np, "load", counting_load)
    monkeypatch.setattr(el_mod, "_mmap_npz_columns", counting_mmap)
    sh.col_cache.clear()
    sh.col_cache_bytes = 0
    got = list(ev.find(app_id, entity_id="u7", entity_type="user",
                       limit=3, reversed_=True))
    assert [e.event_time for e in got] == sorted(
        (e.event_time for e in got), reverse=True)
    assert len(got) == 3
    assert loads["n"] == 1  # later chunks pruned by the k-th-best bound

    # repeating the query serves entirely from the column cache: zero I/O
    loads["n"] = 0
    got2 = list(ev.find(app_id, entity_id="u7", entity_type="user",
                        limit=3, reversed_=True))
    assert len(got2) == 3 and loads["n"] == 0

    # time-range pruning skips chunks whose bounds cannot intersect
    sh.col_cache.clear()
    sh.col_cache_bytes = 0
    loads["n"] = 0
    got = list(ev.find(app_id, start_time=base + dt.timedelta(days=2)))
    assert len(got) == 200 and loads["n"] == 1


def test_find_target_ids_fast_path_matches_generic(tmp_path):
    """The serving fast path (no Event materialization) must agree with
    find() on every filter combination, including tombstones and the
    unflushed WAL tail."""
    s, app_id = make_storage(tmp_path, "eventlog")
    ev = s.get_events()
    evs = [Event(event="view" if j % 3 else "buy", entity_type="user",
                 entity_id=f"u{j % 7}", target_entity_type="item",
                 target_entity_id=f"i{j % 11}",
                 event_time=dt.datetime(2022, 1, 1, tzinfo=UTC)
                 + dt.timedelta(seconds=j))
           for j in range(400)]
    ev.insert_batch(evs[:350], app_id)
    ev.flush(app_id)
    ev.insert_batch(evs[350:], app_id)          # unflushed tail
    # tombstone one matching event
    victim = next(e for e in ev.find(app_id, entity_id="u3",
                                     event_names=["view"]))
    ev.delete(victim.event_id, app_id)

    for kwargs in (
        dict(entity_type="user", entity_id="u3", event_names=["view"],
             target_entity_type="item"),
        dict(entity_type="user", entity_id="u5"),
        dict(event_names=["buy"]),
        dict(entity_type="user", entity_id="nope"),
    ):
        want = sorted(e.target_entity_id for e in ev.find(app_id, **kwargs)
                      if e.target_entity_id is not None)
        got = sorted(ev.find_target_ids(app_id, **kwargs))
        assert got == want, kwargs

    # store facade: fast path on eventlog, fallback parity on memory
    from predictionio_tpu.data import store as store_mod
    fast = sorted(store_mod.find_target_ids(
        "app", entity_type="user", entity_id="u3", event_names=["view"],
        target_entity_type="item", storage=s))
    generic = sorted(e.target_entity_id for e in store_mod.find_by_entity(
        "app", "user", "u3", event_names=["view"],
        target_entity_type="item", storage=s))
    assert fast == generic


def test_absent_entity_point_read_skips_all_chunks(tmp_path):
    """A find/find_target_ids on an id the dictionary never coded must not
    probe ANY chunk index (the per-query absent-constraint lookup at 20M
    events measured 14 ms p50 when it walked every chunk's postings)."""
    from unittest import mock

    storage, app_id = make_storage(tmp_path, "eventlog")
    ev = storage.get_events()
    t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
    for c in range(5):         # one flush per batch -> 5 chunks
        for k in range(20):
            n = c * 20 + k
            ev.insert(Event(event="view", entity_type="user",
                            entity_id=f"u{n % 5}",
                            target_entity_type="item",
                            target_entity_id=f"i{n % 7}",
                            event_time=t0 + dt.timedelta(seconds=n)),
                      app_id)
        ev.flush(app_id)
    sh = ev._shard(app_id, None)
    assert len(list(sh.chunk_seqs())) >= 3

    with mock.patch.object(type(sh), "chunk_index",
                           side_effect=AssertionError("chunk probed")) \
            as spy:
        assert list(ev.find(app_id=app_id, entity_type="constraint",
                            entity_id="weightedItems")) == []
        assert ev.find_target_ids(
            app_id=app_id, entity_type="constraint",
            entity_id="weightedItems") == []
        # absent TARGET id too
        assert list(ev.find(app_id=app_id,
                            target_entity_id="ghost-item")) == []
    # present ids still resolve (and DO probe chunks)
    got = ev.find_target_ids(app_id=app_id, entity_type="user",
                             entity_id="u1", event_names=["view"],
                             target_entity_type="item")
    assert got                        # u1 has views
    # an id that exists ONLY in the unflushed buffer is still found
    ev.insert(Event(event="$set", entity_type="constraint",
                    entity_id="brandNewConstraint",
                    properties=DataMap({"x": 1}),
                    event_time=t0 + dt.timedelta(hours=1)), app_id)
    found = list(ev.find(app_id=app_id, entity_type="constraint",
                         entity_id="brandNewConstraint"))
    assert len(found) == 1
    ev.close()


# ---------------------------------------------------------------------------
# crash recovery: torn tails + injected crashes in the flush windows
# ---------------------------------------------------------------------------

def _mk(eid, iid, rating=2.0):
    return Event(event="rate", entity_type="user", entity_id=eid,
                 target_entity_type="item", target_entity_id=iid,
                 properties=DataMap({"rating": rating}))


@pytest.mark.chaos
def test_torn_wal_tail_dropped_and_repaired_roundtrip(tmp_path, caplog):
    """A torn (partially written) WAL tail — crash mid-append — loses
    exactly the one unacknowledged record: the reopened log serves every
    acknowledged event, and the writer's next append lands on a clean
    line boundary instead of concatenating with the partial bytes."""
    import logging
    import os

    s1, app_id = make_storage(tmp_path, "eventlog")
    ev1 = s1.get_events()
    ev1.insert_batch([_mk("u1", "i1"), _mk("u2", "i2")], app_id)
    sh = ev1._shard(app_id, None)
    wal = sh.wal_path_for(sh.next_seq)
    # tear the file mid-way through the LAST record (no trailing newline)
    size = os.path.getsize(wal)
    with open(wal, "r+b") as f:
        f.truncate(size - 10)

    s2 = Storage(env=el_env(tmp_path))
    ev2 = s2.get_events()
    got = {e.entity_id for e in ev2.find(app_id)}
    assert got == {"u1"}   # only the torn, unacknowledged record is gone

    # the writer's next insert repairs the tail before appending
    with caplog.at_level(logging.WARNING):
        ev2.insert(_mk("u3", "i3"), app_id)
    assert any("torn WAL tail" in r.message for r in caplog.records)
    # both survivors + the new event, round-tripped through a fresh open
    s3 = Storage(env=el_env(tmp_path))
    assert {e.entity_id for e in s3.get_events().find(app_id)} == \
        {"u1", "u3"}
    # and the new event parses cleanly (no concatenation corruption)
    cols = s3.get_events().read_columns(app_id, event_names=["rate"])
    assert len(cols["rating"]) == 2


@pytest.mark.chaos
def test_torn_wal_tail_with_newline_warns_as_tail(tmp_path, caplog):
    """A buffered multi-line append can tear such that the broken final
    record still ends in a newline: that record is the unacknowledged
    tail and must be logged as such, not as lost acknowledged data."""
    import logging

    s1, app_id = make_storage(tmp_path, "eventlog")
    ev1 = s1.get_events()
    ev1.insert_batch([_mk("u1", "i1")], app_id)
    sh = ev1._shard(app_id, None)
    wal = sh.wal_path_for(sh.next_seq)
    with open(wal, "ab") as f:
        f.write(b'{"event": "rate", "entityTy\n')
    with caplog.at_level(logging.WARNING):
        s2 = Storage(env=el_env(tmp_path))
        got = {e.entity_id for e in s2.get_events().find(app_id)}
    assert got == {"u1"}
    assert any("torn WAL tail record" in r.message for r in caplog.records)
    assert not any("acknowledged event may be lost" in r.message
                   for r in caplog.records)


@pytest.mark.chaos
def test_torn_dict_tail_no_longer_raises_and_repairs(tmp_path, caplog):
    """The crash that used to poison a shard: a torn last line in
    dict.jsonl raised JSONDecodeError on EVERY refresh, making all reads
    fail. Now the torn entry (never referenced by any acknowledged
    event) is dropped, reads proceed, and the writer truncates it before
    its next dictionary append so codes stay consistent."""
    import logging

    s1, app_id = make_storage(tmp_path, "eventlog")
    ev1 = s1.get_events()
    ev1.insert_batch([_mk("u1", "i1"), _mk("u2", "i2")], app_id)
    sh = ev1._shard(app_id, None)
    with open(sh.dict_path, "ab") as f:
        f.write(b'"torn-str')   # crash mid dictionary append

    s2 = Storage(env=el_env(tmp_path))
    ev2 = s2.get_events()
    assert {e.entity_id for e in ev2.find(app_id)} == {"u1", "u2"}

    # writer repair: new strings append cleanly and resolve to the right
    # values through a full reopen (positional codes intact)
    with caplog.at_level(logging.WARNING):
        ev2.insert(_mk("u9", "i9", 4.0), app_id)
    assert any("torn dictionary tail" in r.message or
               "torn dictionary" in r.message for r in caplog.records)
    s3 = Storage(env=el_env(tmp_path))
    got = {e.entity_id: e for e in s3.get_events().find(app_id)}
    assert set(got) == {"u1", "u2", "u9"}
    assert got["u9"].target_entity_id == "i9"


@pytest.mark.chaos
def test_injected_crash_during_chunk_publish_recovers(tmp_path):
    """Crash point 1: the os.replace that publishes chunk_<seq>.npz
    fails (power loss mid-publish). Every acknowledged row is still in
    the WAL; a restarted writer replays them exactly once and can flush
    successfully."""
    import os as _os

    s1, app_id = make_storage(tmp_path, "eventlog")
    ev1 = s1.get_events()
    evs = seed_events(np.random.default_rng(7), n=10)
    ev1.insert_batch(evs, app_id)
    n_acked = len(list(ev1.find(app_id)))

    real_replace = _os.replace

    def crashing_replace(src, dst, *a, **kw):
        if str(dst).endswith("chunk_0.npz"):
            raise OSError("injected crash during chunk publish")
        return real_replace(src, dst, *a, **kw)

    _os.replace = crashing_replace
    try:
        with pytest.raises(OSError, match="injected crash"):
            ev1.flush(app_id)
    finally:
        _os.replace = real_replace

    # restart: nothing lost, nothing duplicated; once the restarted
    # process writes (becoming the shard's writer — replay alone keeps
    # dirty False so pure readers never compact), flush succeeds and
    # compacts the replayed rows exactly once
    s2 = Storage(env=el_env(tmp_path))
    ev2 = s2.get_events()
    assert len(list(ev2.find(app_id))) == n_acked
    ev2.insert(_mk("u88", "i0"), app_id)
    ev2.flush(app_id)
    s3 = Storage(env=el_env(tmp_path))
    assert len(list(s3.get_events().find(app_id))) == n_acked + 1
    sh3 = s3.get_events()._shard(app_id, None)
    assert sh3.chunk_seqs() == [0]


@pytest.mark.chaos
def test_injected_crash_between_publish_and_wal_removal(tmp_path):
    """Crash point 2: the chunk published but the process died before
    drop_stale_wals. The chunk supersedes its WAL everywhere, so a
    restarted writer neither loses nor duplicates rows — and its own
    next flush GCs the stale WAL."""
    from predictionio_tpu.data.storage import eventlog as el_mod

    s1, app_id = make_storage(tmp_path, "eventlog")
    ev1 = s1.get_events()
    evs = seed_events(np.random.default_rng(8), n=10)
    ev1.insert_batch(evs, app_id)
    n_acked = len(list(ev1.find(app_id)))

    real_drop = el_mod._Shard.drop_stale_wals

    def crashing_drop(self):
        raise OSError("injected crash before WAL removal")

    el_mod._Shard.drop_stale_wals = crashing_drop
    try:
        with pytest.raises(OSError, match="injected crash"):
            ev1.flush(app_id)
    finally:
        el_mod._Shard.drop_stale_wals = real_drop

    # on-disk now: chunk_0.npz AND wal_0.jsonl (the crash window)
    sh = ev1._shard(app_id, None)
    assert os.path.exists(sh.chunk_path(0))
    assert os.path.exists(sh.wal_path_for(0))

    s2 = Storage(env=el_env(tmp_path))
    ev2 = s2.get_events()
    assert len(list(ev2.find(app_id))) == n_acked   # exactly once
    ev2.insert(_mk("u77", "i0"), app_id)
    ev2.flush(app_id)
    assert not os.path.exists(sh.wal_path_for(0))   # stale WAL GC'd
    s3 = Storage(env=el_env(tmp_path))
    assert len(list(s3.get_events().find(app_id))) == n_acked + 1
