"""Experimental example engines (ref: examples/experimental/)."""

import numpy as np
import pytest

from predictionio_tpu.controller import EngineParams
from predictionio_tpu.workflow import WorkflowContext, run_evaluation


class TestHelloWorld:
    """scala-local-helloworld parity: day -> mean temperature."""

    def test_train_and_predict(self, tmp_path):
        from predictionio_tpu.examples import helloworld as hw
        csv = tmp_path / "data.csv"
        csv.write_text("Mon,75.5\nTue,80.5\nWed,69.5\nMon,76.5\n")
        engine = hw.engine()
        ep = EngineParams(
            data_source_params=hw.HelloWorldDataSourceParams(str(csv)),
            algorithm_params_list=(("", None),))
        ctx = WorkflowContext()
        models = engine.train(ctx, ep)
        algo = hw.HelloWorldAlgorithm()
        assert algo.predict(models[0], hw.HelloQuery("Mon")).temperature == \
            pytest.approx(76.0)
        assert algo.predict(models[0], hw.HelloQuery("Tue")).temperature == \
            pytest.approx(80.5)


class TestRegression:
    """scala-parallel-regression parity: SGD linear fit + k-fold MSE."""

    @staticmethod
    def write_data(path, n=200, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.normal(0, 1, (n, 3))
        y = x @ np.array([2.0, -1.0, 0.5]) + 0.25 + rng.normal(0, 0.01, n)
        np.savetxt(path, np.column_stack([y, x]), fmt="%.6f")

    def test_sgd_recovers_weights(self, tmp_path):
        from predictionio_tpu.examples import regression as rg
        f = tmp_path / "lr_data.txt"
        self.write_data(f)
        engine = rg.engine()
        ep = EngineParams(
            data_source_params=rg.RegressionDataSourceParams(str(f)),
            algorithm_params_list=(
                ("SGD", rg.SGDAlgorithmParams(numIterations=400,
                                              stepSize=0.5)),))
        models = engine.train(WorkflowContext(), ep)
        w = models[0]
        np.testing.assert_allclose(w, [2.0, -1.0, 0.5, 0.25], atol=0.05)
        algo = rg.SGDRegressionAlgorithm(rg.SGDAlgorithmParams())
        pred = algo.predict(w, np.array([1.0, 1.0, 1.0]))
        assert pred == pytest.approx(2.0 - 1.0 + 0.5 + 0.25, abs=0.1)

    def test_kfold_eval_grid(self, tmp_path, memory_storage):
        """Three stepSize variants through the full eval pipeline
        (Run.scala's Workflow.run with MeanSquareError)."""
        from predictionio_tpu.controller import Evaluation
        from predictionio_tpu.examples import regression as rg
        f = tmp_path / "lr_data.txt"
        self.write_data(f, n=120)

        class RegEval(Evaluation):
            engine = rg.engine()
            metric = rg.MeanSquareError()

        grid = [EngineParams(
            data_source_params=rg.RegressionDataSourceParams(str(f), k=3),
            algorithm_params_list=(
                ("SGD", rg.SGDAlgorithmParams(numIterations=300,
                                              stepSize=s)),))
            for s in (0.05, 0.2, 0.5)]
        ctx = WorkflowContext(storage=memory_storage)
        result = run_evaluation(ctx, RegEval(), grid,
                                evaluation_class="RegEval")
        assert len(result.engine_params_scores) == 3
        # MSE: lower is better; best must be the minimum, near zero
        scores = [s.score for s in result.engine_params_scores]
        assert result.best_score.score == min(scores)
        assert result.best_score.score < 0.05


class TestRefactorTest:
    """scala-refactor-test parity: vanilla engine through train + eval."""

    def test_train(self):
        from predictionio_tpu.examples import refactor_test as rt
        engine = rt.engine()
        ep = EngineParams(algorithm_params_list=(
            ("algo", rt.VanillaAlgorithmParams(mult=2)),))
        models = engine.train(WorkflowContext(), ep)
        assert models[0] == sum(range(100)) * 2

    def test_eval_three_sets(self, memory_storage):
        from predictionio_tpu.controller import Evaluation
        from predictionio_tpu.examples import refactor_test as rt

        class VanillaEval(Evaluation):
            engine = rt.engine()
            metric = rt.VanillaMetric()

        ep = EngineParams(algorithm_params_list=(
            ("algo", rt.VanillaAlgorithmParams(mult=1)),))
        ctx = WorkflowContext(storage=memory_storage)
        result = run_evaluation(ctx, VanillaEval(), [ep])
        # mean over 3 sets x 20 queries of (4950 + q) = 4950 + 9.5
        assert result.best_score.score == pytest.approx(4959.5)


class TestFriendRecommendation:
    """friend-recommendation parity: keyword dot, random baseline, SimRank."""

    @pytest.fixture()
    def files(self, tmp_path):
        # item: "id cat kw;kw"  user: "id kw:w;kw:w"  action: "src dst a b c"
        (tmp_path / "item.txt").write_text(
            "10 1 1;2\n20 2 2;3\n")
        (tmp_path / "user.txt").write_text(
            "100 1:0.5;2:1.0\n200 3:2.0\n300 2:1.0\n")
        (tmp_path / "action.txt").write_text(
            "100 200 1 0 0\n200 300 0 1 0\n100 300 1 1 0\n")
        return tmp_path

    def params(self, d):
        from predictionio_tpu.examples import friend_recommendation as fr
        return fr.FriendRecommendationDataSourceParams(
            itemFilePath=str(d / "item.txt"),
            userKeywordFilePath=str(d / "user.txt"),
            userActionFilePath=str(d / "action.txt"))

    def test_keyword_similarity(self, files):
        from predictionio_tpu.examples import friend_recommendation as fr
        engine = fr.keyword_engine()
        ep = EngineParams(data_source_params=self.params(files),
                          algorithm_params_list=(("", None),))
        models = engine.train(WorkflowContext(), ep)
        algo = fr.KeywordSimilarityAlgorithm()
        # user 100 {1:0.5, 2:1.0} . item 10 {1,2} = 1.5 -> accepted
        p = algo.predict(models[0], fr.FriendRecommendationQuery(100, 10))
        assert p.confidence == pytest.approx(1.5) and p.acceptance
        # user 200 {3:2.0} . item 10 {1,2} = 0 -> rejected
        p = algo.predict(models[0], fr.FriendRecommendationQuery(200, 10))
        assert p.confidence == 0.0 and not p.acceptance
        # unseen user -> confidence 0 (reference: empty map)
        p = algo.predict(models[0], fr.FriendRecommendationQuery(999, 10))
        assert p.confidence == 0.0

    def test_simrank_against_dense_reference(self, files):
        """Matrix SimRank must equal the textbook per-pair recurrence."""
        from predictionio_tpu.examples import friend_recommendation as fr
        engine = fr.simrank_engine()
        ep = EngineParams(
            data_source_params=self.params(files),
            algorithm_params_list=(
                ("", fr.SimRankAlgorithmParams(numIterations=4, decay=0.8)),))
        models = engine.train(WorkflowContext(), ep)
        model = models[0]
        # dense numpy reference: s(a,b) = C/(|I(a)||I(b)|) sum s(in_a, in_b)
        a = np.zeros((3, 3))
        edges = [(0, 1), (1, 2), (0, 2)]     # internal ids by file order
        for s, d in edges:
            a[s, d] = 1.0
        s_ref = np.eye(3)
        for _ in range(4):
            new = np.eye(3)
            for x in range(3):
                for y in range(3):
                    if x == y:
                        continue
                    in_x, in_y = np.where(a[:, x])[0], np.where(a[:, y])[0]
                    if len(in_x) == 0 or len(in_y) == 0:
                        continue
                    tot = sum(s_ref[i, j] for i in in_x for j in in_y)
                    new[x, y] = 0.8 * tot / (len(in_x) * len(in_y))
            s_ref = new
        np.testing.assert_allclose(model.scores, s_ref, atol=1e-5)
        # users 200,300 (internal 1,2) share in-neighbor 100 -> similar
        p = fr.SimRankAlgorithm().predict(
            model, fr.FriendRecommendationQuery(200, 300))
        assert p.confidence > 0 and p.acceptance

    def test_random_is_deterministic(self, files):
        from predictionio_tpu.examples import friend_recommendation as fr
        engine = fr.random_engine()
        ep = EngineParams(data_source_params=self.params(files),
                          algorithm_params_list=(("", None),))
        models = engine.train(WorkflowContext(), ep)
        algo = fr.RandomAlgorithm()
        q = fr.FriendRecommendationQuery(100, 10)
        assert algo.predict(models[0], q).confidence == \
            algo.predict(models[0], q).confidence


class TestDIMSUM:
    """similarproduct-dimsum parity: exact cosine gram + filtered serving."""

    @pytest.fixture()
    def app(self, memory_storage):
        import datetime as dt
        from predictionio_tpu.data import store
        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage import App
        app_id = memory_storage.get_meta_data_apps().insert(
            App(0, "dimsumapp", None))
        memory_storage.get_events().init(app_id)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        evs = []
        for u in ("u1", "u2", "u3"):
            evs.append(Event(event="$set", entity_type="user", entity_id=u,
                             event_time=t0))
        for i, cats in (("i1", ("a",)), ("i2", ("a", "b")), ("i3", ("b",))):
            evs.append(Event(event="$set", entity_type="item", entity_id=i,
                             properties=DataMap({"categories": list(cats)}),
                             event_time=t0))
        views = [("u1", "i1"), ("u1", "i2"), ("u2", "i1"), ("u2", "i2"),
                 ("u3", "i3"), ("u1", "i1")]      # dup view deduped
        for n, (u, i) in enumerate(views):
            evs.append(Event(
                event="view", entity_type="user", entity_id=u,
                target_entity_type="item", target_entity_id=i,
                event_time=t0 + dt.timedelta(minutes=n)))
        store.write(evs, app_id)
        return app_id

    def train(self, memory_storage, threshold=0.0):
        from predictionio_tpu.examples import dimsum as dm
        from predictionio_tpu.models.similarproduct.data_source import (
            DataSourceParams)
        engine = dm.engine()
        ep = EngineParams(
            data_source_params=DataSourceParams(appName="dimsumapp"),
            algorithm_params_list=(
                ("dimsum", dm.DIMSUMAlgorithmParams(threshold=threshold)),))
        ctx = WorkflowContext(storage=memory_storage)
        return dm, engine.train(ctx, ep)[0]

    def test_cosine_matches_numpy(self, memory_storage, app):
        dm, model = self.train(memory_storage)
        # i1,i2 both viewed by exactly {u1,u2} -> cosine 1; i3 disjoint -> 0
        v1 = model.item_vocab("i1")
        v2 = model.item_vocab("i2")
        v3 = model.item_vocab("i3")
        assert model.similarities[v1, v2] == pytest.approx(1.0, abs=1e-6)
        assert model.similarities[v1, v3] == 0.0
        assert model.similarities[v1, v1] == 0.0      # diag zeroed

    def test_serving_filters(self, memory_storage, app):
        from predictionio_tpu.models.similarproduct.engine import Query
        dm, model = self.train(memory_storage)
        algo = dm.DIMSUMAlgorithm()
        r = algo.predict(model, Query(items=("i1",), num=5))
        assert [s.item for s in r.itemScores] == ["i2"]   # i3 has sim 0
        # category filter: i2 is in b; restricting to b keeps it, to "z" kills
        r = algo.predict(model, Query(items=("i1",), num=5,
                                      categories=("b",)))
        assert [s.item for s in r.itemScores] == ["i2"]
        r = algo.predict(model, Query(items=("i1",), num=5,
                                      categories=("z",)))
        assert r.itemScores == ()
        # blackList
        r = algo.predict(model, Query(items=("i1",), num=5,
                                      blackList=("i2",)))
        assert r.itemScores == ()
        # unseen query item -> empty
        r = algo.predict(model, Query(items=("nope",), num=5))
        assert r.itemScores == ()

    def test_threshold_zeroes_small_sims(self, memory_storage, app):
        dm, model = self.train(memory_storage, threshold=1.1)
        assert not model.similarities.any()


class TestRecommendationVariants:
    """cat / entitymap / custom-datasource parity."""

    @pytest.fixture()
    def cat_app(self, memory_storage):
        import datetime as dt
        from predictionio_tpu.data import store
        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage import App
        app_id = memory_storage.get_meta_data_apps().insert(
            App(0, "catapp", None))
        memory_storage.get_events().init(app_id)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        evs = []
        for u in ("u1", "u2", "u3"):
            evs.append(Event(event="$set", entity_type="user", entity_id=u,
                             event_time=t0))
        for i, cats in (("i1", ["a"]), ("i2", ["b"]), ("i3", ["a", "b"])):
            evs.append(Event(event="$set", entity_type="item", entity_id=i,
                             properties=DataMap({"categories": cats}),
                             event_time=t0))
        # u1, u2 view i1+i3 heavily; u3 views i2
        views = [("u1", "i1"), ("u1", "i1"), ("u1", "i3"), ("u2", "i1"),
                 ("u2", "i3"), ("u2", "i3"), ("u3", "i2")]
        for n, (u, i) in enumerate(views):
            evs.append(Event(
                event="view", entity_type="user", entity_id=u,
                target_entity_type="item", target_entity_id=i,
                event_time=t0 + dt.timedelta(minutes=n)))
        from predictionio_tpu.data import store as st
        st.write(evs, app_id)
        return app_id

    def test_category_als(self, memory_storage, cat_app):
        from predictionio_tpu.examples import recommendation_variants as rv
        from predictionio_tpu.models.similarproduct.data_source import (
            DataSourceParams)
        engine = rv.cat_engine()
        ep = EngineParams(
            data_source_params=DataSourceParams(appName="catapp"),
            algorithm_params_list=(
                ("als", rv.CategoryALSParams(rank=4, numIterations=8,
                                             seed=7)),))
        ctx = WorkflowContext(storage=memory_storage)
        model = engine.train(ctx, ep)[0]
        algo = rv.CategoryALSAlgorithm()
        # u1's top pick should be a viewed-cluster item
        r = algo.predict(model, rv.CatQuery(user="u1", num=2))
        assert len(r.itemScores) == 2
        # category filter "a" excludes i2
        r = algo.predict(model, rv.CatQuery(user="u1", num=3,
                                            categories=("a",)))
        assert all(s.item in ("i1", "i3") for s in r.itemScores)
        # blackList
        r = algo.predict(model, rv.CatQuery(user="u1", num=3,
                                            blackList=("i1", "i3")))
        assert all(s.item == "i2" for s in r.itemScores)
        # whiteList
        r = algo.predict(model, rv.CatQuery(user="u1", num=3,
                                            whiteList=("i1",)))
        assert [s.item for s in r.itemScores] == ["i1"]
        # unseen user -> empty
        assert algo.predict(model,
                            rv.CatQuery(user="zz", num=3)).itemScores == ()

    @pytest.fixture()
    def em_app(self, memory_storage):
        import datetime as dt
        from predictionio_tpu.data import store
        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage import App
        app_id = memory_storage.get_meta_data_apps().insert(
            App(0, "emapp", None))
        memory_storage.get_events().init(app_id)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        evs = []
        for n, u in enumerate(("u1", "u2")):
            evs.append(Event(
                event="$set", entity_type="user", entity_id=u,
                properties=DataMap({"attr0": 1.5 + n, "attr1": n,
                                    "attr2": 10 + n}),
                event_time=t0))
        for n, i in enumerate(("i1", "i2")):
            evs.append(Event(
                event="$set", entity_type="item", entity_id=i,
                properties=DataMap({"attrA": f"s{n}", "attrB": n,
                                    "attrC": bool(n)}),
                event_time=t0))
        pairs = [("u1", "i1", "rate", 5.0), ("u1", "i2", "buy", None),
                 ("u2", "i2", "rate", 3.0)]
        for n, (u, i, e, r) in enumerate(pairs):
            props = DataMap({"rating": r}) if r is not None else DataMap()
            evs.append(Event(
                event=e, entity_type="user", entity_id=u,
                target_entity_type="item", target_entity_id=i,
                properties=props,
                event_time=t0 + dt.timedelta(minutes=n)))
        store.write(evs, app_id)
        return app_id

    def test_entitymap_datasource(self, memory_storage, em_app):
        from predictionio_tpu.examples import recommendation_variants as rv
        ds = rv.EntityMapDataSource(rv.EntityMapDataSourceParams("emapp"))
        ctx = WorkflowContext(storage=memory_storage)
        td = ds.read_training(ctx)
        assert td.n == 3
        # buy -> 4.0 (reference DataSource.scala mapping)
        buys = td.rating[np.isclose(td.rating, 4.0)]
        assert buys.size == 1
        # typed entity maps ride along
        assert td.users.data("u1") == rv.User(attr0=1.5, attr1=0, attr2=10)
        assert td.items.data("i2") == rv.EMItem(attrA="s1", attrB=1,
                                                attrC=True)

    def test_entitymap_full_train(self, memory_storage, em_app):
        from predictionio_tpu.examples import recommendation_variants as rv
        from predictionio_tpu.models.recommendation import ALSAlgorithmParams
        engine = rv.entitymap_engine()
        ep = EngineParams(
            data_source_params=rv.EntityMapDataSourceParams("emapp"),
            algorithm_params_list=(
                ("als", ALSAlgorithmParams(rank=2, numIterations=3,
                                           lambda_=0.1, seed=1)),))
        ctx = WorkflowContext(storage=memory_storage)
        models = engine.train(ctx, ep)
        assert models[0].user_factors.shape[1] == 2

    def test_file_datasource(self, tmp_path, memory_storage):
        from predictionio_tpu.examples import recommendation_variants as rv
        from predictionio_tpu.models.recommendation import ALSAlgorithmParams
        f = tmp_path / "ratings.txt"
        f.write_text("u1::i1::5.0\nu1::i2::1.0\nu2::i1::4.0\nu2::i2::2.0\n")
        engine = rv.file_engine()
        ep = EngineParams(
            data_source_params=rv.FileDataSourceParams(str(f)),
            algorithm_params_list=(
                ("als", ALSAlgorithmParams(rank=2, numIterations=5,
                                           lambda_=0.1, seed=3)),))
        models = engine.train(WorkflowContext(storage=memory_storage), ep)
        m = models[0]
        # reconstruction must rank i1 above i2 for u1
        u = m.user_vocab("u1")
        s1 = m.item_factors[m.item_vocab("i1")] @ m.user_factors[u]
        s2 = m.item_factors[m.item_vocab("i2")] @ m.user_factors[u]
        assert float(s1) > float(s2)


class TestMaintenanceApps:
    """cleanup-app / trim-app parity."""

    @staticmethod
    def seed(memory_storage, name, n=6):
        import datetime as dt
        from predictionio_tpu.data import store
        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage import App
        app_id = memory_storage.get_meta_data_apps().insert(App(0, name, None))
        memory_storage.get_events().init(app_id)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        store.write([Event(
            event="e", entity_type="user", entity_id=f"u{i}",
            properties=DataMap({"i": i}),
            event_time=t0 + dt.timedelta(days=i)) for i in range(n)], app_id)
        return app_id, t0

    def test_cleanup_deletes_before_cutoff(self, memory_storage):
        import datetime as dt
        from predictionio_tpu.examples import apps
        app_id, t0 = self.seed(memory_storage, "cleanapp")
        engine = apps.cleanup_engine()
        ep = EngineParams(
            data_source_params=apps.CleanupDataSourceParams(
                appId=app_id, cutoffTime=t0 + dt.timedelta(days=3)),
            algorithm_params_list=(("", None),))
        ctx = WorkflowContext(storage=memory_storage)
        report = engine.train(ctx, ep)[0]
        assert (report.count_before, report.affected, report.count_after) == \
            (6, 3, 3)
        remaining = list(memory_storage.get_events().find(app_id=app_id))
        assert sorted(e.entity_id for e in remaining) == ["u3", "u4", "u5"]

    def test_trim_copies_window_and_refuses_nonempty(self, memory_storage):
        import datetime as dt
        from predictionio_tpu.data.storage import App
        from predictionio_tpu.examples import apps
        src, t0 = self.seed(memory_storage, "srcapp")
        dst = memory_storage.get_meta_data_apps().insert(App(0, "dstapp", None))
        memory_storage.get_events().init(dst)
        engine = apps.trim_engine()
        ep = EngineParams(
            data_source_params=apps.TrimDataSourceParams(
                srcAppId=src, dstAppId=dst,
                startTime=t0 + dt.timedelta(days=1),
                untilTime=t0 + dt.timedelta(days=4)),
            algorithm_params_list=(("", None),))
        ctx = WorkflowContext(storage=memory_storage)
        report = engine.train(ctx, ep)[0]
        assert report.affected == 3
        copied = list(memory_storage.get_events().find(app_id=dst))
        assert sorted(e.entity_id for e in copied) == ["u1", "u2", "u3"]
        # second run: dst non-empty -> refuse (reference throws)
        with pytest.raises(RuntimeError, match="not empty"):
            engine.train(ctx, ep)


class TestMovieLens:
    """movielens-filtering + movielens-evaluation parity."""

    def test_temp_filter_serving(self, tmp_path):
        from predictionio_tpu.examples import movielens as ml
        from predictionio_tpu.models.recommendation.engine import (
            ItemScore, PredictedResult, Query)
        f = tmp_path / "disabled.txt"
        f.write_text("i2\n")
        serving = ml.TempFilterServing(ml.TempFilterParams(str(f)))
        pred = PredictedResult(itemScores=(
            ItemScore("i1", 3.0), ItemScore("i2", 2.5), ItemScore("i3", 1.0)))
        out = serving.serve(Query(user="u", num=3), [pred])
        assert [s.item for s in out.itemScores] == ["i1", "i3"]
        # file re-read per request: enabling i2 back needs no redeploy
        f.write_text("")
        out = serving.serve(Query(user="u", num=3), [pred])
        assert len(out.itemScores) == 3

    @pytest.fixture()
    def timed_app(self, memory_storage):
        import datetime as dt
        from predictionio_tpu.data import store
        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage import App
        app_id = memory_storage.get_meta_data_apps().insert(
            App(0, "mlapp", None))
        memory_storage.get_events().init(app_id)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        rng = np.random.default_rng(5)
        evs = []
        for day in range(30):
            for u in range(4):
                i = int(rng.integers(0, 6))
                evs.append(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties=DataMap({"rating": float(rng.integers(1, 6))}),
                    event_time=t0 + dt.timedelta(days=day)))
        store.write(evs, app_id)
        return app_id, t0

    def test_sliding_eval_windows(self, memory_storage, timed_app):
        import datetime as dt
        from predictionio_tpu.examples import movielens as ml
        app_id, t0 = timed_app
        ds = ml.SlidingEvalDataSource(ml.SlidingEvalDataSourceParams(
            appName="mlapp",
            firstTrainingUntilTime=t0 + dt.timedelta(days=20),
            evalDurationSeconds=5 * 86400.0,
            evalCount=2))
        ctx = WorkflowContext(storage=memory_storage)
        sets = ds.read_eval(ctx)
        assert len(sets) == 2
        (td1, _, qa1), (td2, _, qa2) = sets
        # window 2 trains on strictly more history
        assert td2.n > td1.n
        assert td1.n == 20 * 4
        assert td2.n == 25 * 4
        # no test event leaks into its own training window
        assert qa1 and qa2


class TestStock:
    """scala-stock parity: indicators, regression strategy, backtesting."""

    @staticmethod
    def write_prices(path, days=300, seed=11):
        rng = np.random.default_rng(seed)
        # TREND has persistent upward drift (predictable); NOISE is a fair
        # coin; FLAT barely moves
        trend = 100 * np.exp(np.cumsum(rng.normal(0.002, 0.01, days)))
        noise = 100 * np.exp(np.cumsum(rng.normal(0.0, 0.02, days)))
        flat = np.full(days, 50.0) + rng.normal(0, 0.01, days)
        lines = ["date,TREND,NOISE,FLAT"]
        for d in range(days):
            lines.append(f"d{d},{trend[d]:.4f},{noise[d]:.4f},{flat[d]:.4f}")
        path.write_text("\n".join(lines) + "\n")

    def test_indicators(self):
        from predictionio_tpu.examples import stock as st
        lp = np.log(np.linspace(100, 200, 50))
        sh = st.ShiftsIndicator(5).get_training(lp)
        np.testing.assert_allclose(sh[5:], lp[5:] - lp[:-5])
        assert sh[:5].tolist() == [0.0] * 5
        # RSI of a monotonically rising series saturates at 100
        rsi = st.RSIIndicator(14).get_training(lp)
        assert rsi[-1] == pytest.approx(100.0)
        assert rsi[0] == 50.0   # neutral before enough history
        # falling series -> 0
        rsi_dn = st.RSIIndicator(14).get_training(lp[::-1].copy())
        assert rsi_dn[-1] == pytest.approx(0.0)

    def test_regression_strategy_and_backtest(self, tmp_path, memory_storage):
        from predictionio_tpu.controller import Evaluation
        from predictionio_tpu.examples import stock as st
        f = tmp_path / "prices.csv"
        self.write_prices(f)
        engine = st.engine()
        dsp = st.StockDataSourceParams(
            filepath=str(f), trainUntilIdx=250, evalInterval=10,
            evalCount=3)
        ep = EngineParams(
            data_source_params=dsp,
            algorithm_params_list=(
                ("", st.RegressionStrategyParams(shifts=(1, 5, 22))),))
        # plain train + predict
        models = engine.train(WorkflowContext(storage=memory_storage), ep)
        model = models[0]
        assert model.coef.shape == (3, 5)     # 3 shifts + RSI + intercept
        algo = st.RegressionStrategyAlgorithm(
            st.RegressionStrategyParams(shifts=(1, 5, 22)))
        pred = algo.predict(model, st.QueryDate(idx=249))
        assert set(pred.data) == {"TREND", "NOISE", "FLAT"}
        # the drift stock must get a higher predicted return than the flat
        assert pred.data["TREND"] > pred.data["FLAT"]

        class StockEval(Evaluation):
            engine = st.engine()
            metric = st.BacktestingMetric(st.BacktestingParams(
                enterThreshold=0.0005, exitThreshold=0.0,
                maxPositions=2))

        ev = StockEval()
        ctx = WorkflowContext(storage=memory_storage)
        result = run_evaluation(ctx, ev, [ep], evaluation_class="StockEval")
        bt = ev.metric.last_result
        assert bt is not None and bt.days > 0
        assert len(bt.nav) == bt.days + 1
        # NAV walk is marked to market: all positive, finite
        assert all(np.isfinite(bt.nav)) and min(bt.nav) > 0

    def test_rsi_bounded_on_mixed_series(self):
        """Mixed up/down windows must stay in [0,100] (loss magnitudes,
        not the reference's signed series which explodes the range)."""
        from predictionio_tpu.examples import stock as st
        rng = np.random.default_rng(3)
        lp = np.cumsum(rng.normal(0, 0.02, 500))
        rsi = st.RSIIndicator(14).get_training(lp)
        assert np.all(rsi >= 0.0) and np.all(rsi <= 100.0)
        assert rsi[50:].std() > 1.0     # actually varies

    def test_eval_predictions_use_query_day_history(self, tmp_path):
        """Two days in one eval window must get different predictions
        (indicators recomputed from each day's observable history)."""
        from predictionio_tpu.examples import stock as st
        f = tmp_path / "prices.csv"
        self.write_prices(f)
        dsp = st.StockDataSourceParams(
            filepath=str(f), trainUntilIdx=250, evalInterval=10,
            evalCount=1)
        ds = st.StockDataSource(dsp)
        sets = ds.read_eval(None)
        (train, _, qa) = sets[0]
        algo = st.RegressionStrategyAlgorithm()
        model = algo.train(None, train)
        p0 = algo.predict(model, qa[0][0])
        p5 = algo.predict(model, qa[5][0])
        assert p0.data != p5.data

    def test_backtest_survives_delisting(self):
        """A ticker losing its price mid-eval must not NaN the NAV walk:
        inactive days can't be entered, marks fall back to the last
        tradeable price."""
        from predictionio_tpu.examples import stock as st
        days = 30
        prices = np.full((days, 2), 100.0)
        prices[:, 1] = 50.0
        prices[20:, 1] = np.nan              # DEAD delists at day 20
        frame = st.StockTrainingData(
            tickers=["LIVE", "DEAD"], prices=prices,
            active=np.isfinite(prices) & (prices > 0))
        metric = st.BacktestingMetric(st.BacktestingParams(
            enterThreshold=0.0, exitThreshold=-1.0, maxPositions=2))
        qa = [(st.QueryDate(idx=d),
               st.StockPrediction(data={"LIVE": 0.01, "DEAD": 0.01}),
               frame) for d in range(15, 28)]
        sharpe = metric.calculate([(None, qa)])
        bt = metric.last_result
        assert all(np.isfinite(bt.nav)), bt.nav
        assert np.isfinite(bt.ret)


class TestRecommendedUser:
    """similarproduct/recommended-user parity: follow -> similar users."""

    @pytest.fixture()
    def app(self, memory_storage):
        import datetime as dt
        from predictionio_tpu.data import store
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage import App
        app_id = memory_storage.get_meta_data_apps().insert(
            App(0, "ruapp", None))
        memory_storage.get_events().init(app_id)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        evs = [Event(event="$set", entity_type="user", entity_id=f"u{k}",
                     event_time=t0) for k in range(5)]
        # u0 and u1 follow the same people (u3, u4); u2 follows only u3
        follows = [("u0", "u3"), ("u0", "u4"), ("u1", "u3"), ("u1", "u4"),
                   ("u2", "u3"), ("u0", "u3")]       # dup deduped
        for n, (a, b) in enumerate(follows):
            evs.append(Event(event="follow", entity_type="user",
                             entity_id=a, target_entity_type="user",
                             target_entity_id=b,
                             event_time=t0 + dt.timedelta(minutes=n)))
        store.write(evs, app_id)
        return app_id

    def test_similar_users(self, memory_storage, app):
        from predictionio_tpu.examples import recommended_user as ru
        engine = ru.engine()
        ep = EngineParams(
            data_source_params=ru.RUDataSourceParams("ruapp"),
            algorithm_params_list=(
                ("als", ru.RUALSParams(rank=4, numIterations=10, seed=5)),))
        ctx = WorkflowContext(storage=memory_storage)
        model = engine.train(ctx, ep)[0]
        algo = ru.RUALSAlgorithm()
        # u3 and u4 are followed by the same users -> most similar pair
        r = algo.predict(model, ru.RUQuery(users=("u3",), num=2))
        assert r.similarUserScores
        assert r.similarUserScores[0].user == "u4"
        assert all(s.user != "u3" for s in r.similarUserScores)  # excluded
        # blackList removes the top pick
        r = algo.predict(model, ru.RUQuery(users=("u3",), num=2,
                                           blackList=("u4",)))
        assert all(s.user != "u4" for s in r.similarUserScores)
        # unseen seed users -> empty
        assert algo.predict(model, ru.RUQuery(users=("zz",), num=2)
                            ).similarUserScores == ()


def test_example_engine_drives_through_engine_json(tmp_path, memory_storage):
    """Example engines must be front-door engines: engine.json factory
    resolution + typed params extraction + run_train (the reference's
    experimental engines each ship an engine.json)."""
    from predictionio_tpu.workflow import run_train
    from predictionio_tpu.workflow.workflow_utils import get_engine

    rng = np.random.default_rng(1)
    x = rng.normal(0, 1, (150, 3))
    y = x @ np.array([2.0, -1.0, 0.5]) + 0.25
    data = tmp_path / "lr_data.txt"
    np.savetxt(data, np.column_stack([y, x]), fmt="%.6f")
    variant = {
        "id": "default",
        "engineFactory": "predictionio_tpu.examples.regression:engine",
        "datasource": {"params": {"filepath": str(data), "k": 3}},
        "algorithms": [
            {"name": "SGD",
             "params": {"numIterations": 200, "stepSize": 0.5}}],
    }
    engine = get_engine(variant["engineFactory"])
    ep = engine.engine_params_from_json(variant)
    assert ep.algorithm_params_list[0][1].stepSize == 0.5
    ctx = WorkflowContext(storage=memory_storage)
    iid = run_train(ctx, engine, ep, engine_factory=variant["engineFactory"],
                    params_json=variant)
    assert memory_storage.get_model_data_models().get(iid) is not None


class TestSimilarProductVariants:
    """filterbyyear / no-set-user / add-rateevent /
    add-and-return-item-properties, composed."""

    @pytest.fixture()
    def app(self, memory_storage):
        import datetime as dt
        from predictionio_tpu.data import store
        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage import App
        app_id = memory_storage.get_meta_data_apps().insert(
            App(0, "spvapp", None))
        memory_storage.get_events().init(app_id)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        evs = []
        # NO $set user events at all (no-set-user variant)
        for i, (cats, year, title) in enumerate([
                (["a"], 2001, "Alpha"), (["a"], 1995, "Beta"),
                (["b"], 2010, "Gamma")]):
            evs.append(Event(
                event="$set", entity_type="item", entity_id=f"i{i}",
                properties=DataMap({"categories": cats, "year": year,
                                    "title": title, "date": f"{year}-01-01"}),
                event_time=t0))
        views = [("u1", "i0"), ("u1", "i1"), ("u2", "i0"), ("u2", "i1"),
                 ("u3", "i2")]
        for n, (u, i) in enumerate(views):
            evs.append(Event(
                event="view", entity_type="user", entity_id=u,
                target_entity_type="item", target_entity_id=i,
                event_time=t0 + dt.timedelta(minutes=n)))
        store.write(evs, app_id)
        return app_id

    def train(self, memory_storage):
        from predictionio_tpu.examples import similarproduct_variants as sv
        engine = sv.engine()
        ep = EngineParams(
            data_source_params=sv.VDataSourceParams(appName="spvapp"),
            algorithm_params_list=(
                ("als", sv.VALSParams(rank=4, numIterations=10, seed=3)),))
        ctx = WorkflowContext(storage=memory_storage)
        return sv, engine.train(ctx, ep)[0]

    def test_no_set_user_and_returned_properties(self, memory_storage, app):
        sv, model = self.train(memory_storage)
        algo = sv.VALSAlgorithm()
        r = algo.predict(model, sv.VQuery(items=("i0",), num=3))
        assert r.itemScores
        top = r.itemScores[0]
        assert top.item == "i1"                # co-viewed cluster
        assert top.title == "Beta" and top.year == 1995   # properties ride
        assert top.date == "1995-01-01"

    def test_year_filter(self, memory_storage, app):
        sv, model = self.train(memory_storage)
        algo = sv.VALSAlgorithm()
        # i1 is from 1995; filtering recommendFromYear=2000 removes it
        r = algo.predict(model, sv.VQuery(items=("i0",), num=3,
                                          recommendFromYear=2000))
        assert all(s.item != "i1" for s in r.itemScores)
        r = algo.predict(model, sv.VQuery(items=("i0",), num=3,
                                          recommendFromYear=1990))
        assert any(s.item == "i1" for s in r.itemScores)

    def test_rate_events_switch_to_explicit_latest_wins(
            self, memory_storage, app):
        import datetime as dt
        from predictionio_tpu.data import store
        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event
        t0 = dt.datetime(2021, 1, 2, tzinfo=dt.timezone.utc)
        evs = []
        pairs = [("u1", "i0", 5.0, 0), ("u1", "i1", 5.0, 1),
                 ("u2", "i0", 5.0, 2), ("u2", "i1", 5.0, 3),
                 ("u3", "i2", 4.0, 4),
                 ("u1", "i1", 1.0, 0)]     # EARLIER than the 5.0 -> loses
        for u, i, rt, m in pairs:
            evs.append(Event(
                event="rate", entity_type="user", entity_id=u,
                target_entity_type="item", target_entity_id=i,
                properties=DataMap({"rating": rt}),
                event_time=t0 + dt.timedelta(minutes=m)))
        store.write(evs, app)
        sv, model = self.train(memory_storage)
        algo = sv.VALSAlgorithm()
        r = algo.predict(model, sv.VQuery(items=("i0",), num=3))
        assert r.itemScores and r.itemScores[0].item == "i1"

    def test_negative_year_floor_excludes_yearless_items(
            self, memory_storage, app):
        """recommendFromYear=-1 must not resurrect items without a year
        property (the 0 sentinel is excluded explicitly)."""
        import datetime as dt
        from predictionio_tpu.data import store
        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event
        # i3: viewed by the i0 cluster's users, but NO year property
        t0 = dt.datetime(2021, 1, 1, 12, tzinfo=dt.timezone.utc)
        evs = [Event(event="$set", entity_type="item", entity_id="i3",
                     properties=DataMap({"categories": ["a"],
                                         "title": "NoYear"}),
                     event_time=t0)]
        for u in ("u1", "u2"):
            evs.append(Event(event="view", entity_type="user", entity_id=u,
                             target_entity_type="item",
                             target_entity_id="i3", event_time=t0))
        store.write(evs, app)
        sv, model = self.train(memory_storage)
        algo = sv.VALSAlgorithm()
        r = algo.predict(model, sv.VQuery(items=("i0",), num=5))
        assert any(s.item == "i3" for s in r.itemScores)   # unfiltered: in
        r = algo.predict(model, sv.VQuery(items=("i0",), num=5,
                                          recommendFromYear=-1))
        assert all(s.item != "i3" for s in r.itemScores)   # filtered: out
