"""Realtime fold-in tests (predictionio_tpu/realtime/foldin.py).

THE acceptance demo lives here: a user unseen at train time sends
events against a LIVE deploy and receives non-degraded personalized
top-k within 2 s — no restart, no /reload, 0 post-warmup recompiles,
0 dropped queries during publication — for the replicated path AND the
sharded+quantized path. Around it: the eventlog/memory incremental
cursor surfaces, solve-kernel parity against an independent numpy
half-step, crash-safe cursor resume, the headroom-exhausted /reload
fallback, the drift probe (clean + corrupted), wire parity with
fold-in off, the /reload-under-burst hot-swap contract, the doctor
fold-in line, and the standalone `pio foldin` runner.
"""

import datetime as dt
import json
import threading
import time

import numpy as np
import pytest

from predictionio_tpu.common import devicewatch
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.realtime import foldin
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig

APP = "FoldinApp"


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def _mk_event(u, i, r, minute=0, month=1):
    return Event(
        event="rate", entity_type="user", entity_id=u,
        target_entity_type="item", target_entity_id=i,
        properties=DataMap({"rating": r}),
        event_time=dt.datetime(2021, month, 1, 0, minute % 60,
                               tzinfo=dt.timezone.utc))


def _train(storage, app_name=APP):
    """Seed a parity-preference app (even users like even items) and
    train one small ALS instance; returns the engine."""
    from predictionio_tpu.controller import EngineParams
    from predictionio_tpu.models.recommendation import (
        ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
    )
    from predictionio_tpu.workflow import WorkflowContext, run_train

    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(0, app_name, None))
    storage.get_events().init(app_id)
    events = []
    for u in range(8):
        for i in range(6):
            events.append(_mk_event(
                f"u{u}", f"i{i}", 5.0 if (u % 2) == (i % 2) else 1.0,
                minute=u * 6 + i))
    storage.get_events().insert_batch(events, app_id)
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName=app_name),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=4, numIterations=4,
                                       lambda_=0.05, seed=3)),))
    run_train(WorkflowContext(storage=storage), engine, ep,
              engine_factory="foldin-test",
              params_json={
                  "datasource": {"params": {"appName": app_name}},
                  "algorithms": [{"name": "als", "params": {
                      "rank": 4, "numIterations": 4, "lambda": 0.05,
                      "seed": 3}}]})
    return engine


@pytest.fixture(scope="module")
def trained():
    """Module-scoped trained engine on memory storage: every test
    shares the same model shapes, so the AOT memo pays each compile
    once for the whole file."""
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    })
    engine = _train(storage)
    return storage, engine


@pytest.fixture(autouse=True)
def _foldin_env(monkeypatch, tmp_path):
    """Small, constant fold-in shapes: headroom is pinned per-deploy in
    the tests (constant => the AOT memo reuses every program), buckets
    and the per-user cap stay tiny so tier-1 compiles stay cheap, and
    each test gets a private cursor directory."""
    monkeypatch.setenv("PIO_FOLDIN_CURSOR_DIR", str(tmp_path / "cur"))
    monkeypatch.setenv("PIO_FOLDIN_USER_BUCKETS", "1,4")
    monkeypatch.setenv("PIO_FOLDIN_MAX_EVENTS", "16")
    monkeypatch.delenv("PIO_FOLDIN", raising=False)
    yield


HEADROOM = 16   # constant across tests => constant padded shapes


def _api(storage, engine, **kw):
    kw.setdefault("batching", "on")
    kw.setdefault("foldin", "on")
    kw.setdefault("foldin_tick_ms", 20.0)
    kw.setdefault("foldin_headroom", HEADROOM)
    return QueryAPI(storage=storage, engine=engine,
                    config=ServerConfig(**kw))


def _post(api, user, num=4):
    status, body = api.handle(
        "POST", "/queries.json",
        body=json.dumps({"user": user, "num": num}).encode())
    return status, body


def _app_id(storage):
    return storage.get_meta_data_apps().get_by_name(APP).id


# ---------------------------------------------------------------------------
# eventlog incremental cursor surface
# ---------------------------------------------------------------------------

@pytest.fixture()
def el_events(tmp_path):
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
        "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path / "el"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    })
    ev = storage.get_events()
    ev.init(1)
    return storage, ev


def test_eventlog_cursor_incremental_read(el_events):
    _storage, ev = el_events
    ev.insert_batch([_mk_event("u1", "i1", 5.0),
                     _mk_event("u2", "i2", 3.0)], 1)
    head = ev.head_cursor(1)
    assert head == {"seq": 0, "row": 2}
    assert ev.cursor_lag(1, cursor={"seq": 0, "row": 0}) == 2
    assert ev.cursor_lag(1, cursor=head) == 0
    ev.insert_batch([_mk_event("u3", "i3", 1.0)], 1)
    cur, cols = ev.read_columns_since(
        1, cursor=head, event_names=["rate", "buy"],
        entity_type="user", target_entity_type="item")
    pool = cols["pool"]
    assert [pool[c] for c in cols["entity_code"]] == ["u3"]
    assert cols["creation_ms"].shape == (1,)
    assert cur == {"seq": 0, "row": 3}
    # a full read from the zero cursor reproduces read_columns
    _c0, full = ev.read_columns_since(1, cursor=None)
    bulk = ev.read_columns(1)
    np.testing.assert_array_equal(full["entity_code"],
                                  bulk["entity_code"])
    np.testing.assert_array_equal(full["rating"], bulk["rating"])


def test_eventlog_cursor_stable_across_compaction(el_events):
    _storage, ev = el_events
    ev.insert_batch([_mk_event(f"u{j}", f"i{j}", 1.0 + j)
                     for j in range(4)], 1)
    cur, _ = ev.read_columns_since(1, cursor=None)
    ev.flush(1)   # buffer -> chunk: positions must not move
    assert ev.cursor_lag(1, cursor=cur) == 0
    _cur2, cols2 = ev.read_columns_since(1, cursor=cur)
    assert cols2["entity_code"].shape[0] == 0   # no replay
    ev.insert_batch([_mk_event("u9", "i9", 2.0)], 1)
    cur3, cols3 = ev.read_columns_since(1, cursor=cur)
    assert [cols3["pool"][c] for c in cols3["entity_code"]] == ["u9"]
    # a mid-chunk cursor sees exactly the suffix
    _c, mid = ev.read_columns_since(1, cursor={"seq": 0, "row": 3})
    assert [mid["pool"][c] for c in mid["entity_code"]] == ["u3", "u9"]
    # a cursor past the head (external reset) clamps instead of raising
    c_over, cols_over = ev.read_columns_since(
        1, cursor={"seq": 99, "row": 0})
    assert cols_over["entity_code"].shape[0] == 0
    assert c_over["seq"] <= 99
    assert ev.cursor_lag(1, cursor=cur3) == 0


@pytest.fixture()
def sq_events(tmp_path):
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_SQ_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQ_PATH": str(tmp_path / "pio.sqlite"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    })
    ev = storage.get_events()
    ev.init(1)
    return storage, ev


def test_sqlite_cursor_incremental_read(sq_events):
    """The sqlite twin of the eventlog cursor contract (ISSUE 14
    satellite; same assertions as test_eventlog_cursor_incremental_read
    modulo the backend's rowid positions): incremental windows, filters
    narrowing output but not the consumed range, creation_ms present,
    zero-cursor reproducing the bulk read."""
    _storage, ev = sq_events
    ev.insert_batch([_mk_event("u1", "i1", 5.0),
                     _mk_event("u2", "i2", 3.0)], 1)
    head = ev.head_cursor(1)
    assert head == {"seq": 0, "row": 2}
    assert ev.cursor_lag(1, cursor={"seq": 0, "row": 0}) == 2
    assert ev.cursor_lag(1, cursor=head) == 0
    ev.insert_batch([_mk_event("u3", "i3", 1.0)], 1)
    cur, cols = ev.read_columns_since(
        1, cursor=head, event_names=["rate", "buy"],
        entity_type="user", target_entity_type="item")
    pool = cols["pool"]
    assert [pool[c] for c in cols["entity_code"]] == ["u3"]
    assert cols["creation_ms"].shape == (1,)
    assert cur == {"seq": 0, "row": 3}
    # a full read from the zero cursor reproduces read_columns
    _c0, full = ev.read_columns_since(1, cursor=None)
    bulk = ev.read_columns(1)
    assert full["entity_code"].shape == bulk["entity_code"].shape
    assert sorted(full["rating"].tolist()) == \
        sorted(bulk["rating"].tolist())
    # a cursor past the head (external reset) clamps instead of raising
    c_over, cols_over = ev.read_columns_since(1, cursor={"seq": 0,
                                                         "row": 999})
    assert cols_over["entity_code"].shape[0] == 0
    assert c_over["row"] <= 3
    # filters narrow output, never the consumed range: a filtered
    # follower's cursor still converges on the head
    ev.insert_batch([_mk_event("u4", "i4", 2.0)], 1)
    cur2, cols2 = ev.read_columns_since(1, cursor=cur,
                                        event_names=["no-such-event"])
    assert cols2["entity_code"].shape[0] == 0
    assert ev.cursor_lag(1, cursor=cur2) == 0


def test_sqlite_foldin_tail_selected(sq_events):
    """The fold-in worker no longer refuses sqlite: tail_for picks the
    columnar cursor tail (the README backend matrix row)."""
    from predictionio_tpu.realtime import foldin

    _storage, ev = sq_events
    ev.insert_batch([_mk_event("u1", "i1", 5.0)], 1)
    cfg = foldin.FoldinConfig(app_name=APP)
    tail = foldin.tail_for(ev, 1, cfg)
    assert tail is not None and tail.kind == "columnar"
    cur, rows = tail.read({"seq": 0, "row": 0})
    assert rows == [("u1", "i1", "rate", 5.0, rows[0][4])]
    assert tail.lag(cur) == 0
    ev.insert_batch([_mk_event("u9", "i1", 4.0)], 1)
    assert tail.lag(cur) == 1
    cur2, rows2 = tail.read(cur)
    assert [r[0] for r in rows2] == ["u9"]


def test_memory_cursor_surface(memory_storage):
    ev = memory_storage.get_events()
    ev.init(1)
    ev.insert_batch([_mk_event("u1", "i1", 5.0)], 1)
    head = ev.head_cursor(1)
    assert head == 1 and ev.cursor_lag(1, cursor=0) == 1
    eid = ev.insert(_mk_event("u2", "i2", 3.0), 1)
    cur, evs = ev.read_events_since(1, cursor=head)
    assert cur == 2 and [e.entity_id for e in evs] == ["u2"]
    # deletes keep positions (cursor stability) but filter the result
    ev.delete(eid, 1)
    _cur, evs2 = ev.read_events_since(1, cursor=head)
    assert evs2 == []
    assert ev.head_cursor(1) == 2


@pytest.fixture()
def remote_events(el_events):
    """The eventlog store served over a live storage server, consumed
    through the `remote` driver — the fold-in backend matrix's last
    open row (ISSUE 15 satellite)."""
    from predictionio_tpu.data.storage.remote import serve_storage

    storage, ev = el_events
    server = serve_storage(storage, host="127.0.0.1", port=0)
    remote = Storage(env={
        "PIO_STORAGE_SOURCES_R_TYPE": "remote",
        "PIO_STORAGE_SOURCES_R_URL":
            f"http://127.0.0.1:{server.server_address[1]}",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "R",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "R",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "R",
    })
    yield ev, remote.get_events()
    server.shutdown()
    server.server_close()


def test_remote_cursor_tail_matches_backend(remote_events):
    """The remote driver's cursor tail (proto 3: head_cursor /
    cursor_lag DAO calls + the binary /rpc/read_columns_since route)
    answers byte-identically to the backing eventlog store."""
    backend, ev = remote_events
    assert ev.cursor_tail_supported()
    backend.insert_batch([_mk_event("u1", "i1", 5.0),
                          _mk_event("u2", "i2", 3.0)], 1)
    head = ev.head_cursor(1)
    assert head == backend.head_cursor(1)
    assert ev.cursor_lag(1, cursor={"seq": 0, "row": 0}) == 2
    assert ev.cursor_lag(1, cursor=head) == 0
    backend.insert_batch([_mk_event("u3", "i3", 1.0)], 1)
    cur, cols = ev.read_columns_since(
        1, cursor=head, event_names=["rate", "buy"],
        entity_type="user", target_entity_type="item")
    d_cur, d_cols = backend.read_columns_since(
        1, cursor=head, event_names=["rate", "buy"],
        entity_type="user", target_entity_type="item")
    assert cur == d_cur
    assert cols["pool"] == d_cols["pool"]
    for key in ("entity_code", "target_code", "event_code", "rating",
                "time_ms", "creation_ms"):
        np.testing.assert_array_equal(cols[key], d_cols[key])
    assert [cols["pool"][c] for c in cols["entity_code"]] == ["u3"]


def test_remote_foldin_tail_selected(remote_events):
    """The fold-in worker no longer refuses a remote-backed deployment:
    tail_for picks the forwarded columnar cursor tail — and an OLD
    storage server (proto < 3) still refuses cleanly at bind time."""
    from predictionio_tpu.realtime import foldin

    backend, ev = remote_events
    backend.insert_batch([_mk_event("u1", "i1", 5.0)], 1)
    cfg = foldin.FoldinConfig(app_name=APP)
    tail = foldin.tail_for(ev, 1, cfg)
    assert tail is not None and tail.kind == "columnar"
    cur, rows = tail.read({"seq": 0, "row": 0})
    assert [(r[0], r[1], r[2], r[3]) for r in rows] == \
        [("u1", "i1", "rate", 5.0)]
    assert tail.lag(cur) == 0
    backend.insert_batch([_mk_event("u9", "i1", 4.0)], 1)
    assert tail.lag(cur) == 1
    _cur2, rows2 = tail.read(cur)
    assert [r[0] for r in rows2] == ["u9"]
    # an old server: the feature probe says no, the worker refuses at
    # bind time instead of failing per tick
    ev.c._proto = 2
    assert not ev.cursor_tail_supported()
    assert foldin.tail_for(ev, 1, cfg) is None


# ---------------------------------------------------------------------------
# solve-kernel parity vs an independent numpy half-step
# ---------------------------------------------------------------------------

def test_foldin_solve_matches_numpy_half_step():
    rng = np.random.default_rng(11)
    rank, n_items = 4, 12
    V = rng.normal(size=(n_items, rank)).astype(np.float32)
    lam = 0.05
    users = [[(1, 5.0), (3, 1.0), (7, 4.0)],
             [(0, 2.0), (2, 2.5)]]
    bucket, me = 4, 16
    nnz_pad = bucket * me
    item_rows = np.zeros((nnz_pad, rank), np.float32)
    self_idx = np.full((nnz_pad,), bucket, np.int32)
    rating = np.zeros((nnz_pad,), np.float32)
    counts = np.zeros((bucket,), np.int32)
    pos = 0
    for j, ratings in enumerate(users):
        counts[j] = len(ratings)
        for ii, rv in ratings:
            item_rows[pos] = V[ii]
            self_idx[pos] = j
            rating[pos] = rv
            pos += 1
    import jax
    rows = np.asarray(jax.device_get(foldin.foldin_solve(
        item_rows, self_idx, rating, counts, np.float32(lam),
        n_self=bucket, chunk=nnz_pad)))
    for j, ratings in enumerate(users):
        Vs = np.stack([V[ii] for ii, _ in ratings])
        r = np.asarray([rv for _, rv in ratings], np.float32)
        A = Vs.T @ Vs + lam * len(ratings) * np.eye(rank)
        expect = np.linalg.solve(A, Vs.T @ r)
        np.testing.assert_allclose(rows[j], expect, rtol=2e-3, atol=1e-4)
    # padding users solve to ~zero rows
    assert np.abs(rows[len(users):]).max() < 1e-5


# ---------------------------------------------------------------------------
# THE freshness demo: live deploy, unseen user, <= 2 s, nothing dropped
# ---------------------------------------------------------------------------

def _freshness_demo(storage, engine, api_kwargs, expect_items,
                    uid, parity):
    """Shared body for the replicated and sharded+quant demos: query a
    LIVE HTTP deploy for an unseen user while a burst of concurrent
    clients hammers it; the user's events must turn into personalized
    top-k within 2 s with zero dropped queries, zero post-warmup
    recompiles, and no generation change."""
    import http.client

    from predictionio_tpu.data.api.http import make_server

    api = _api(storage, engine, **api_kwargs)
    server = make_server(api, "127.0.0.1", 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        assert api._foldin_worker is not None and \
            api._foldin_worker.supported
        recompiles_before = devicewatch.post_warmup_recompiles()
        generation_before = api.generation

        burst_errors = []
        stop = threading.Event()

        def burst(cx):
            # num=10 clamps to the DECLARED k (PIO_AOT_KS), so the
            # 0-recompiles assertion below is honest: any other num
            # would legitimately compile a lazy program (the declared-k
            # contract, same as every serving path)
            try:
                conn = http.client.HTTPConnection("127.0.0.1", port)
                while not stop.is_set():
                    conn.request(
                        "POST", "/queries.json",
                        body=json.dumps({"user": f"u{cx}", "num": 10}),
                        headers={"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    resp.read()
                    if resp.status != 200:
                        burst_errors.append(resp.status)
                        return
                conn.close()
            except Exception as e:   # a dropped query IS a failure
                burst_errors.append(e)

        clients = [threading.Thread(target=burst, args=(cx,))
                   for cx in range(4)]
        for t in clients:
            t.start()
        try:
            # the unseen user's events land mid-burst
            events = [_mk_event(uid, f"i{i}",
                                5.0 if (i % 2) == parity else 1.0)
                      for i in range(6)]
            t0 = time.perf_counter()
            storage.get_events().insert_batch(events, _app_id(storage))
            conn = http.client.HTTPConnection("127.0.0.1", port)
            body = None
            while time.perf_counter() - t0 < 2.0:
                conn.request(
                    "POST", "/queries.json",
                    body=json.dumps({"user": uid, "num": 10}),
                    headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                body = json.loads(resp.read())
                assert resp.status == 200
                if body.get("itemScores"):
                    break
                time.sleep(0.01)
            freshness_s = time.perf_counter() - t0
            conn.close()
        finally:
            stop.set()
            for t in clients:
                t.join(timeout=10)

        assert not burst_errors, burst_errors      # 0 dropped queries
        assert freshness_s <= 2.0, freshness_s     # the contract
        items = [s["item"] for s in body["itemScores"]]
        assert items, body
        # personalized, not degraded: the TOP items are the user's
        # preferred parity class, and the response carries no
        # degraded flag
        assert set(items[:3]) == expect_items, (items, body)
        assert "degraded" not in body
        assert api.generation == generation_before   # no /reload
        assert devicewatch.post_warmup_recompiles() \
            == recompiles_before                     # no recompiles
        # the worker surfaces its state on GET /
        st = api.handle("GET", "/")[1]["foldin"]
        assert st["enabled"] and st["usersFolded"] >= 1
    finally:
        server.shutdown()
        api.close()


def test_freshness_demo_replicated(trained):
    storage, engine = trained
    _freshness_demo(storage, engine, {},
                    expect_items={"i1", "i3", "i5"},
                    uid="fresh_replicated", parity=1)


def test_freshness_demo_sharded_quant(trained):
    storage, engine = trained
    _freshness_demo(storage, engine,
                    {"shard_serving": "on", "serve_quant": "on"},
                    expect_items={"i0", "i2", "i4"},
                    uid="fresh_sq", parity=0)


def test_foldin_updates_existing_user(trained):
    """A user the TRAINER knew keeps serving while fold-in re-solves
    them from new events — their ranking flips to the new signal."""
    storage, engine = trained
    api = _api(storage, engine)
    try:
        worker = api._foldin_worker
        worker.stop()   # drive ticks deterministically
        # u0 (even-liker) suddenly loves odd items, strongly — the new
        # events are strictly NEWER, so the per-user history cap keeps
        # all of them and the re-solve flips the ranking
        evs = [_mk_event("u0", f"i{i}", 5.0 if i % 2 else 0.5, month=3)
               for i in range(6)] * 2
        storage.get_events().insert_batch(evs, _app_id(storage))
        summary = worker.tick()
        assert summary["folded"] >= 1
        status, body = _post(api, "u0", num=10)
        assert status == 200
        items = [s["item"] for s in body["itemScores"]]
        assert set(items[:3]) == {"i1", "i3", "i5"}, items
    finally:
        api.close()


# ---------------------------------------------------------------------------
# wire parity off
# ---------------------------------------------------------------------------

def test_wire_parity_foldin_off(trained, monkeypatch):
    """PIO_FOLDIN=0 / --foldin off answers byte-for-byte what a
    default server answers, and GET / keeps the legacy key set."""
    storage, engine = trained
    queries = [("u1", 5), ("u3", 3), ("nobody", 4)]

    def answers(api):
        return [json.dumps(_post(api, u, n)[1], sort_keys=True)
                for u, n in queries]

    api_default = QueryAPI(storage=storage, engine=engine,
                           config=ServerConfig(batching="on"))
    try:
        baseline = answers(api_default)
        assert "foldin" not in api_default.handle("GET", "/")[1]
        assert api_default._foldin_worker is None
    finally:
        api_default.close()
    monkeypatch.setenv("PIO_FOLDIN", "0")
    api_off = QueryAPI(storage=storage, engine=engine,
                       config=ServerConfig(batching="on", foldin="on"))
    try:
        assert answers(api_off) == baseline
        assert "foldin" not in api_off.handle("GET", "/")[1]
        assert api_off._foldin_worker is None   # env override wins
    finally:
        api_off.close()


# ---------------------------------------------------------------------------
# crash-safe cursor resume + headroom fallback + drift probe
# ---------------------------------------------------------------------------

def test_cursor_resume_refolds_after_restart(trained):
    """A restarted deploy (fresh QueryAPI, same cursor dir) re-folds
    the users the previous worker folded — the persisted fold set is
    the crash-safety contract."""
    storage, engine = trained
    api1 = _api(storage, engine)
    try:
        w1 = api1._foldin_worker
        w1.stop()
        storage.get_events().insert_batch(
            [_mk_event("resumer", f"i{i}", 4.0) for i in range(4)],
            _app_id(storage))
        assert w1.tick()["appended"] == 1
        assert api1.models[0].user_vocab.get("resumer") is not None
    finally:
        api1.close()
    # "restart": a new server over the same storage + cursor dir
    api2 = _api(storage, engine)
    try:
        w2 = api2._foldin_worker
        w2.stop()
        # no new events, but the persisted fold set queues the re-fold
        assert w2.tick()["appended"] == 1
        status, body = _post(api2, "resumer", num=2)
        assert status == 200 and body["itemScores"]
    finally:
        api2.close()


def test_headroom_exhaustion_falls_back_to_reload(trained):
    """More new users than headroom: the worker journals a WARN, the
    /reload fallback bumps the generation with re-grown capacity, and
    every user is servable afterwards."""
    from predictionio_tpu.common import journal

    storage, engine = trained
    journal.clear()
    api = _api(storage, engine, foldin_headroom=2)
    try:
        worker = api._foldin_worker
        worker.stop()
        uids = [f"horde{j}" for j in range(5)]
        for uid in uids:
            storage.get_events().insert_batch(
                [_mk_event(uid, f"i{i}", 4.0) for i in range(3)],
                _app_id(storage))
        gen_before = api.generation
        summary = worker.tick()
        assert summary.get("reloaded") is True
        assert api.generation == gen_before + 1     # hot-swap happened
        # the reload restarted the worker thread; stop it again so the
        # re-fold tick below stays deterministic
        worker.stop()
        worker.tick()
        for uid in uids:
            status, body = _post(api, uid, num=2)
            assert status == 200 and body["itemScores"], uid
        warns = [e for e in journal.snapshot(level="warn")["events"]
                 if e["category"] == "foldin"]
        assert any("headroom" in e["message"] for e in warns)
    finally:
        api.close()


def test_drift_probe_clean_and_corrupted(trained, monkeypatch):
    from predictionio_tpu.common import journal

    # force the host-numpy layout so the corruption below can write
    # the published row in place
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "0")
    storage, engine = trained
    api = _api(storage, engine)
    try:
        worker = api._foldin_worker
        worker.stop()
        storage.get_events().insert_batch(
            [_mk_event("drifter", f"i{i}", 4.5 - i * 0.5)
             for i in range(5)], _app_id(storage))
        worker.tick()
        worker._drift_probe()
        st = worker.state()
        assert st["drift"]["ok"] and st["drift"]["recall"] == 1.0
        # corrupt the published row behind the probe's back: the probe
        # must notice and journal a WARN
        journal.clear()
        model = api.models[0]
        ix = model.user_vocab.get("drifter")
        model.user_factors[ix] = -model.user_factors[ix]
        worker._drift_probe()
        st = worker.state()
        assert not st["drift"]["ok"]
        warns = [e for e in journal.snapshot(level="warn")["events"]
                 if e["category"] == "foldin"]
        assert any("drift" in e["message"] for e in warns)
    finally:
        api.close()


# ---------------------------------------------------------------------------
# /reload hot-swap under a concurrent query burst (ROADMAP item 1's
# re-shard-without-restart path, previously untested under load)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("extra", [
    {},                                            # replicated
    {"shard_serving": "on"},                       # re-shard on swap
    {"shard_serving": "on", "serve_quant": "on"},  # re-quantize too
], ids=["replicated", "sharded", "sharded+quant"])
def test_reload_hot_swap_under_burst_drops_nothing(trained, extra):
    storage, engine = trained
    api = QueryAPI(storage=storage, engine=engine,
                   config=ServerConfig(batching="on", **extra))
    try:
        gen_before = api.generation
        errors = []
        stop = threading.Event()

        def burst(cx):
            try:
                while not stop.is_set():
                    status, body = _post(api, f"u{cx % 8}", num=3)
                    if status != 200 or not body.get("itemScores"):
                        errors.append((status, body))
                        return
            except Exception as e:
                errors.append(e)

        clients = [threading.Thread(target=burst, args=(cx,))
                   for cx in range(4)]
        for t in clients:
            t.start()
        try:
            status, _ = api.handle("POST", "/reload")
            assert status == 200
            deadline = time.perf_counter() + 30
            while api.generation == gen_before \
                    and time.perf_counter() < deadline:
                time.sleep(0.02)
            # keep the burst running a moment across the swap window
            time.sleep(0.2)
        finally:
            stop.set()
            for t in clients:
                t.join(timeout=10)
        assert not errors, errors[:3]          # zero dropped queries
        assert api.generation == gen_before + 1
    finally:
        api.close()


# ---------------------------------------------------------------------------
# doctor fold-in line
# ---------------------------------------------------------------------------

def _scrape_stub(metrics_text, device_body):
    blank = {"status": 404, "body": ""}
    return {
        "url": "http://x", "healthz": {"status": 200, "body": "{}"},
        "readyz": {"status": 200, "body": '{"status": "ready"}'},
        "metrics": {"status": 200, "body": metrics_text},
        "traces": {"status": 200, "body": '{"spanCount": 0}'},
        "device": {"status": 200, "body": json.dumps(device_body)},
        "slow": dict(blank), "events": dict(blank),
    }


def test_doctor_foldin_line_states():
    import datetime as _dt

    from predictionio_tpu.tools import doctor

    now = _dt.datetime.now(_dt.timezone.utc).timestamp()
    dev = {"telemetry": True,
           "foldin": {"enabled": True, "cursorLag": 3, "tickMs": 20.0,
                      "lastTickMs": 1.8, "lastTickAt": now,
                      "freshness": {"p99S": 0.12},
                      "drift": {"recall": 1.0, "ok": True}}}
    checks = {c: (s, d) for c, s, d in
              doctor.diagnose(_scrape_stub("", dev))}
    state, detail = checks["foldin"]
    assert state == doctor.OK
    assert "cursor lag 3" in detail and "freshness p99 0.12" in detail
    # stale cursor -> WARN, never RED
    dev_stale = {"telemetry": True,
                 "foldin": {"enabled": True, "cursorLag": 900,
                            "tickMs": 20.0, "lastTickMs": 1.8,
                            "lastTickAt": now - 3600}}
    state, detail = {c: (s, d) for c, s, d in doctor.diagnose(
        _scrape_stub("", dev_stale))}["foldin"]
    assert state == doctor.WARN and "STALE" in detail
    # failed drift probe -> WARN
    dev_drift = {"telemetry": True,
                 "foldin": {"enabled": True, "cursorLag": 0,
                            "tickMs": 20.0, "lastTickAt": now,
                            "drift": {"recall": 0.4, "ok": False}}}
    state, detail = {c: (s, d) for c, s, d in doctor.diagnose(
        _scrape_stub("", dev_drift))}["foldin"]
    assert state == doctor.WARN and "FAILED" in detail
    # no worker: quiet NA line
    state, detail = {c: (s, d) for c, s, d in doctor.diagnose(
        _scrape_stub("", {"telemetry": True}))}["foldin"]
    assert state == doctor.NA and "fold-in off" in detail


# ---------------------------------------------------------------------------
# standalone runner (`pio foldin`)
# ---------------------------------------------------------------------------

def test_standalone_pipeline_folds_into_local_copy(trained):
    """The `pio foldin` soak pipeline (its engine-resolution inputs
    assembled directly — the trained fixture's factory name is not
    importable): loads the persisted model, folds a new user into the
    LOCAL copy, and leaves its cursor in the standalone namespace."""
    import os

    storage, _engine = trained
    from predictionio_tpu.models.recommendation import RecommendationEngine
    from predictionio_tpu.workflow import model_io
    from predictionio_tpu.workflow.create_server import (
        ServerConfig, engine_params_from_instance, resolve_engine_instance,
    )
    instance = resolve_engine_instance(storage, ServerConfig())
    engine_params = engine_params_from_instance(
        RecommendationEngine(), instance)
    blob = storage.get_model_data_models().get(instance.id)
    models = model_io.deserialize_models(blob.models)
    cfg = foldin.config_for(engine_params, tick_ms=20.0)
    cfg.namespace = "standalone"
    prep = foldin.pad_capacity(models, 8)
    worker = foldin.FoldinWorker(storage, cfg)
    worker.bind(models[prep["index"]], generation=1, prep=prep)
    # events land AFTER the worker's head cursor — the stream it tails
    storage.get_events().insert_batch(
        [_mk_event("solo", f"i{i}", 4.0) for i in range(4)],
        _app_id(storage))
    summary = worker.tick()
    assert summary["appended"] >= 1
    assert models[prep["index"]].user_vocab.get("solo") is not None
    assert os.path.exists(worker._store.path)
    assert ".standalone." in worker._store.path


def test_pio_foldin_cli_parses():
    from predictionio_tpu.tools.cli import build_parser
    args = build_parser().parse_args(
        ["foldin", "--tick-ms", "50", "--max-ticks", "3"])
    assert args.command == "foldin" and args.tick_ms == 50.0
    args = build_parser().parse_args(
        ["deploy", "--foldin", "on", "--foldin-tick-ms", "100",
         "--foldin-headroom", "64"])
    assert args.foldin == "on" and args.foldin_headroom == 64


# ---------------------------------------------------------------------------
# AOT + journal wiring
# ---------------------------------------------------------------------------

def test_foldin_programs_registered_and_enumerated():
    from predictionio_tpu.serving import aot

    names = aot.registered_names()
    assert {"foldin_solve", "scatter_user_rows",
            "scatter_user_rows_sharded",
            "scatter_user_rows_sharded_quant",
            "scatter_user_rows_quant"} <= names
    specs = foldin.solve_program_specs(rank=4)
    assert len(specs) == len(foldin.user_buckets())
    assert all(s.name == "foldin_solve" for s in specs)


def test_worker_bind_emits_journal_and_state(trained):
    from predictionio_tpu.common import journal

    storage, engine = trained
    journal.clear()
    api = _api(storage, engine)
    try:
        infos = [e for e in journal.snapshot()["events"]
                 if e["category"] == "foldin"]
        assert any("bound to generation" in e["message"] for e in infos)
        dev = devicewatch.debug_snapshot()
        # devicewatch carries the foldin block only under telemetry;
        # the worker state itself is always live on GET /
        st = api.handle("GET", "/")[1]["foldin"]
        assert st["capacity"]["rows"] >= st["capacity"]["used"]
        assert st["backend"] == "object"
        assert isinstance(dev, dict)
    finally:
        api.close()


# ---------------------------------------------------------------------------
# item fold-in: unseen ITEMS become rankable without a retrain (the
# transposed half-step into every serving layout)
# ---------------------------------------------------------------------------

def _rate_new_item(storage, iid, parity=0, month=7):
    """Known users of one parity class rate a brand-new item highly —
    its solved factors land in that parity's item cluster."""
    evs = [_mk_event(f"u{u}", iid, 5.0, minute=u, month=month)
           for u in range(parity, 8, 2)]
    storage.get_events().insert_batch(evs, _app_id(storage))


@pytest.mark.parametrize("extra", [
    {},
    {"shard_serving": "on", "serve_quant": "on"},
], ids=["replicated", "sharded+quant"])
def test_unseen_item_servable_within_2s(trained, extra):
    """An item the trainer never saw is rated by live events and must
    rank in an even user's top-k within 2 s — no retrain, no /reload,
    vocab grown in place — on the replicated AND sharded+quantized
    layouts."""
    storage, engine = trained
    iid = f"inew_{'sq' if extra else 'rep'}"
    api = _api(storage, engine, **extra)
    try:
        worker = api._foldin_worker
        assert worker is not None and worker.supported
        generation_before = api.generation
        t0 = time.perf_counter()
        _rate_new_item(storage, iid, parity=0)
        items = []
        while time.perf_counter() - t0 < 2.0:
            status, body = _post(api, "u0", num=10)
            assert status == 200
            items = [s["item"] for s in body["itemScores"]]
            if iid in items:
                break
            time.sleep(0.01)
        assert iid in items, items
        # rankable AND ranked like the even cluster it was rated into
        assert iid in items[:4], items
        assert api.generation == generation_before   # no /reload
        st = api.handle("GET", "/")[1]["foldin"]
        assert st["itemsFolded"] >= 1
        assert st["itemCapacity"]["rows"] > st["itemCapacity"]["used"]
    finally:
        api.close()


@pytest.mark.parametrize("extra", [
    {},
    {"shard_serving": "on"},
    {"serve_quant": "on"},
    {"shard_serving": "on", "serve_quant": "on"},
], ids=["fp32", "sharded", "int8", "sharded+int8"])
def test_item_foldin_bit_parity_per_layout(trained, extra):
    """The folded item row every layout actually serves equals a fresh
    transposed half-step on the same events — bit-level: fp32 layouts
    carry the solve output verbatim, int8 layouts carry exactly its
    per-row symmetric quantization."""
    import jax

    from predictionio_tpu.ops import quant as quant_mod

    storage, engine = trained
    iid = "ipar_" + "_".join(sorted(extra)) if extra else "ipar_rep"
    api = _api(storage, engine, **extra)
    try:
        worker = api._foldin_worker
        worker.stop()   # drive the tick deterministically
        _rate_new_item(storage, iid, parity=1, month=8)
        summary = worker.tick()
        assert summary["itemsAppended"] >= 1, summary
        model = api.models[0]
        ix = model.item_vocab.get(iid)
        assert ix is not None and ix >= 6   # appended past the 6
                                            # trained items
        # the tick re-solved the rating users AFTER the item folded
        # (items fold first); re-fold the item so both sides of the
        # comparison see the same, now-stable user matrix
        folded, _appended, _deferred = worker._fold_items([iid], {})
        assert folded == 1
        ratings, unknown = worker._gather_item_ratings(
            iid, model.user_vocab)
        assert ratings and unknown == 0
        fresh = np.asarray(jax.device_get(
            worker._solve([ratings], factors=worker._user_factors)[0]),
            np.float32)
        # the worker's host mirror (the user solves' gather source)
        # carries the solve output verbatim on every layout
        np.testing.assert_array_equal(worker._item_factors[int(ix)],
                                      fresh)
        pub = worker._published_item_row(model, int(ix))
        sharding = getattr(model, "sharding", None)
        int8 = (getattr(model, "quant", None) is not None
                or (sharding is not None and sharding.dtype == "int8"))
        expect = fresh
        if int8:
            q, s = quant_mod.quantize_rows(fresh[None])
            expect = quant_mod.dequantize_rows(q, s)[0]
        np.testing.assert_array_equal(pub, expect)
    finally:
        api.close()


def test_trained_items_never_resolved_by_foldin(trained):
    """New events against an item the TRAINER knew must not overwrite
    its batch-solved row with a single half-step (the item-side
    correctness rule; users re-solve, trained items do not)."""
    storage, engine = trained
    api = _api(storage, engine)
    try:
        worker = api._foldin_worker
        worker.stop()
        model = api.models[0]
        ix = model.item_vocab.get("i0")
        before = np.array(worker._item_factors[int(ix)])
        storage.get_events().insert_batch(
            [_mk_event(f"u{u}", "i0", 1.0, month=9) for u in range(4)],
            _app_id(storage))
        summary = worker.tick()
        assert summary.get("itemsFolded", 0) == 0
        np.testing.assert_array_equal(worker._item_factors[int(ix)],
                                      before)
    finally:
        api.close()


def test_item_drift_probe_clean_and_corrupted(trained, monkeypatch):
    from predictionio_tpu.common import journal

    # host-numpy layout so the corruption below can write the
    # published row in place (same trick as the user-side probe test)
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "0")
    storage, engine = trained
    api = _api(storage, engine)
    try:
        worker = api._foldin_worker
        worker.stop()
        _rate_new_item(storage, "idrift", parity=0, month=10)
        worker.tick()
        worker._item_drift_probe()
        st = worker.state()
        assert st["itemDrift"]["ok"] and st["itemDrift"]["recall"] == 1.0
        journal.clear()
        model = api.models[0]
        ix = model.item_vocab.get("idrift")
        model.item_factors[int(ix)] = -model.item_factors[int(ix)]
        worker._item_factors[int(ix)] = \
            np.array(model.item_factors[int(ix)])
        worker._item_drift_probe()
        st = worker.state()
        assert not st["itemDrift"]["ok"]
        warns = [e for e in journal.snapshot(level="warn")["events"]
                 if e["category"] == "foldin"]
        assert any("ITEM drift" in e["message"] for e in warns)
    finally:
        api.close()
