"""Golden numerical parity: the XLA kernels vs independently-computed
reference math (VERDICT round 1 weak #6 / next #8).

MLlib itself cannot run in this image, so "reference" here is a direct
dense implementation of the published MLlib semantics, computed with plain
numpy in this file — plus one literal hand-computed case. What these pin:

- explicit ALS half-step: ALS-WR normal equations with nnz-scaled
  regularization (lambda * n_ratings(u)) and presence (not value) weighted
  Gram (MLlib ALS.train semantics as invoked by
  recommendation-engine/src/main/scala/ALSAlgorithm.scala:40-94);
- implicit ALS half-step: Hu-Koren-Volinsky A_u = Y'Y + Y'(C_u - I)Y,
  b_u = Y'C_u p_u with c-1 = alpha*|r|, p = [r > 0]
  (MLlib ALS.trainImplicit);
- multinomial NB: pi/theta smoothing exactly as
  mllib.classification.NaiveBayes.train(lambda);
- e2 CategoricalNaiveBayes: NO smoothing, score via log-likelihood maps
  (e2/.../engine/CategoricalNaiveBayes.scala:24-173).
"""

import numpy as np
import jax.numpy as jnp

from predictionio_tpu.ops import als, naive_bayes


def dense_explicit_half(V, u_of, i_of, r_of, n_users, lam, reg_scaling):
    """Straight normal-equation solve per user, dense numpy."""
    rank = V.shape[1]
    out = np.zeros((n_users, rank))
    for u in range(n_users):
        rows = [j for j, uu in enumerate(u_of) if uu == u]
        A = np.zeros((rank, rank))
        b = np.zeros(rank)
        for j in rows:
            v = V[i_of[j]]
            A += np.outer(v, v)
            b += r_of[j] * v
        reg = lam * len(rows) if reg_scaling == "count" else lam
        out[u] = np.linalg.solve(A + (reg + 1e-8) * np.eye(rank), b)
    return out


def coo_fixture(seed=0, n_users=7, n_items=5, rank=3, nnz=17):
    rng = np.random.default_rng(seed)
    # distinct (u, i) pairs so the dense reference is unambiguous
    pairs = rng.permutation(n_users * n_items)[:nnz]
    u = (pairs // n_items).astype(np.int32)
    i = (pairs % n_items).astype(np.int32)
    r = rng.uniform(0.5, 5.0, nnz).astype(np.float32)
    V = rng.normal(size=(n_items, rank)).astype(np.float32)
    return u, i, r, V


class TestExplicitALSGolden:
    def test_half_step_matches_dense_normal_equations(self):
        u, i, r, V = coo_fixture()
        data = als.prepare_ratings(u, i, r, 7, 5, chunk=8)
        bu = data.by_user
        got = als._half_step_explicit(
            jnp.asarray(V), bu.self_idx, bu.other_idx, bu.rating, bu.counts,
            7, 0.1, chunk=8, reg_scaling="count")
        want = dense_explicit_half(V, u, i, r, 7, 0.1, "count")
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4,
                                   atol=2e-5)

    def test_half_step_constant_reg(self):
        u, i, r, V = coo_fixture(seed=1)
        data = als.prepare_ratings(u, i, r, 7, 5, chunk=8)
        bu = data.by_user
        got = als._half_step_explicit(
            jnp.asarray(V), bu.self_idx, bu.other_idx, bu.rating, bu.counts,
            7, 0.5, chunk=8, reg_scaling="constant")
        want = dense_explicit_half(V, u, i, r, 7, 0.5, "constant")
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4,
                                   atol=2e-5)

    def test_hand_computed_two_by_two(self):
        """Literal hand case: user 0 rated items 0 (r=2) and 1 (r=4);
        V = [[1,0],[1,1]], lambda=0.5, count scaling => reg = 1.0.
        A = [[1,0],[0,0]] + [[1,1],[1,1]] = [[2,1],[1,1]];
        b = 2*[1,0] + 4*[1,1] = [6,4];
        solve([[3,1],[1,2]], [6,4]) = [(12-4)/5, (12-6)/5] = [1.6, 1.2]."""
        u = np.asarray([0, 0], np.int32)
        i = np.asarray([0, 1], np.int32)
        r = np.asarray([2.0, 4.0], np.float32)
        V = np.asarray([[1.0, 0.0], [1.0, 1.0]], np.float32)
        data = als.prepare_ratings(u, i, r, 1, 2, chunk=2)
        bu = data.by_user
        got = np.asarray(als._half_step_explicit(
            jnp.asarray(V), bu.self_idx, bu.other_idx, bu.rating, bu.counts,
            1, 0.5, chunk=2, reg_scaling="count"))[0]
        np.testing.assert_allclose(got, [1.6, 1.2], rtol=1e-4)


class TestImplicitALSGolden:
    def test_half_step_matches_dense_hkv(self):
        u, i, r, V = coo_fixture(seed=2)
        # include a negative (dislike) to pin the signed-preference rule
        r = r.copy()
        r[0] = -r[0]
        alpha, lam = 8.0, 0.05
        data = als.prepare_ratings(u, i, r, 7, 5, chunk=8)
        bu = data.by_user
        got = als._half_step_implicit(
            jnp.asarray(V), bu.self_idx, bu.other_idx, bu.rating, bu.counts,
            7, lam, alpha, chunk=8, reg_scaling="count")

        rank = V.shape[1]
        YtY = V.T @ V
        want = np.zeros((7, rank))
        for uu in range(7):
            rows = [j for j in range(len(u)) if u[j] == uu]
            A = YtY.copy()
            b = np.zeros(rank)
            for j in rows:
                v = V[i[j]].astype(np.float64)
                c_minus_1 = alpha * abs(float(r[j]))
                A = A + c_minus_1 * np.outer(v, v)
                p = 1.0 if r[j] > 0 else 0.0
                b = b + (1.0 + c_minus_1) * p * v
            reg = lam * len(rows)
            want[uu] = np.linalg.solve(A + (reg + 1e-8) * np.eye(rank), b)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4,
                                   atol=2e-5)


class TestNaiveBayesGolden:
    def test_matches_mllib_formulas(self):
        rng = np.random.default_rng(3)
        X = rng.integers(0, 5, (30, 4)).astype(np.float64)
        y = rng.integers(0, 3, 30)
        lam = 1.0
        model = naive_bayes.train(X, y, lambda_=lam, n_classes=3)

        # direct MLlib multinomial formulas
        want_pi = np.zeros(3)
        want_theta = np.zeros((3, 4))
        for c in range(3):
            nc = np.sum(y == c)
            want_pi[c] = np.log((nc + lam) / (len(y) + 3 * lam))
            fs = X[y == c].sum(axis=0)
            want_theta[c] = np.log((fs + lam) / (fs.sum() + 4 * lam))
        np.testing.assert_allclose(np.asarray(model.pi), want_pi, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(model.theta), want_theta,
                                   rtol=1e-5)

    def test_hand_computed_prediction(self):
        """2 classes, 2 features, lambda=0: priors 2/3 vs 1/3; class 0 has
        feature sums [3, 1], class 1 has [0, 2]. Posterior for x=[1, 0]
        must pick class 0 (class 1 has zero mass on feature 0)."""
        X = np.asarray([[2, 1], [1, 0], [0, 2]], np.float64)
        y = np.asarray([0, 0, 1])
        model = naive_bayes.train(X, y, lambda_=0.0, n_classes=2)
        pred = np.asarray(naive_bayes.predict(
            model, np.asarray([[1.0, 0.0]])))
        assert pred[0] == 0
        np.testing.assert_allclose(
            float(np.asarray(model.pi)[0]), np.log(2 / 3), rtol=1e-6)


class TestE2CategoricalNBGolden:
    def test_no_smoothing_semantics(self):
        """CategoricalNaiveBayes.scala:24-173: log P(c) + sum_j
        log P(f_j | c), with an unseen (feature, value) under class c
        scoring -inf (no Laplace smoothing)."""
        from predictionio_tpu.e2.engine import (
            CategoricalNaiveBayes, LabeledPoint,
        )

        points = [
            LabeledPoint("spam", ("casino", "win")),
            LabeledPoint("spam", ("casino", "cash")),
            LabeledPoint("ham", ("meeting", "win")),
        ]
        m = CategoricalNaiveBayes.train(points)
        # P(spam)=2/3; P(f0=casino|spam)=1, P(f1=win|spam)=1/2
        got = m.log_score(LabeledPoint("spam", ("casino", "win")))
        want = np.log(2 / 3) + np.log(1.0) + np.log(1 / 2)
        assert got is not None
        np.testing.assert_allclose(got, want, rtol=1e-6)
        # unseen value under ham -> default likelihood -inf; unknown label
        # -> None (CategoricalNaiveBayes.scala logScore semantics)
        assert m.log_score(
            LabeledPoint("ham", ("casino", "cash"))) == float("-inf")
        assert m.log_score(LabeledPoint("nolabel", ("x", "y"))) is None
        assert m.predict(("casino", "win")) == "spam"
