"""End-to-end golden TRAIN parity: full multi-iteration ALS vs a dense
numpy reference (round-3 verdict weak #5 / ask #7).

The half-step goldens in test_golden_parity.py pin one solve; these pin
the whole training LOOP — seeding, iteration wiring, regularization
scaling, and checkpoint/resume segmentation — on a ~20x10 problem small
enough to hand-solve densely. Single-device, 8-virtual-device mesh, and a
resume-mid-train variant must all land on the same factors.
"""

import numpy as np
import pytest

from predictionio_tpu.ops import als
from predictionio_tpu.parallel import als_dist
from predictionio_tpu.parallel.mesh import get_mesh
from predictionio_tpu.workflow.checkpoint import FactorCheckpointer

N_U, N_I, RANK, LAM, ITERS, ALPHA = 20, 10, 3, 0.07, 5, 1.3
_EPS = als._EPS


def make_problem(seed=13, density=0.55):
    rng = np.random.default_rng(seed)
    mask = rng.random((N_U, N_I)) < density
    # ensure no empty row/col so count-scaled reg never zeroes out
    mask[np.arange(N_U), rng.integers(0, N_I, N_U)] = True
    mask[rng.integers(0, N_U, N_I), np.arange(N_I)] = True
    ui, ii = np.nonzero(mask)
    vals = rng.uniform(0.5, 5.0, ui.shape[0]).astype(np.float32)
    return ui.astype(np.int32), ii.astype(np.int32), vals


def seed_factors():
    U0, V0 = als._seed_factors(21, N_U, N_I, RANK)
    return np.asarray(U0), np.asarray(V0)


def dense_explicit(ui, ii, vals, U, V, iterations):
    """Straight-line numpy ALS: per-row ridge solves, count-scaled reg."""
    U, V = U.copy(), V.copy()
    for _ in range(iterations):
        for u in range(N_U):
            sel = ui == u
            Vu = V[ii[sel]]
            A = Vu.T @ Vu + (LAM * sel.sum() + _EPS) * np.eye(RANK)
            U[u] = np.linalg.solve(A, Vu.T @ vals[sel])
        for i in range(N_I):
            sel = ii == i
            Uu = U[ui[sel]]
            A = Uu.T @ Uu + (LAM * sel.sum() + _EPS) * np.eye(RANK)
            V[i] = np.linalg.solve(A, Uu.T @ vals[sel])
    return U, V


def dense_implicit(ui, ii, vals, U, V, iterations):
    """Hu-Koren-Volinsky in numpy: A = YtY + Yt(C-I)Y, b = Yt C p."""
    U, V = U.copy(), V.copy()
    for _ in range(iterations):
        YtY = V.T @ V
        for u in range(N_U):
            sel = ui == u
            Vu = V[ii[sel]]
            conf = ALPHA * np.abs(vals[sel])
            pref = (vals[sel] > 0).astype(np.float64)
            A = YtY + Vu.T @ (conf[:, None] * Vu) \
                + (LAM * sel.sum() + _EPS) * np.eye(RANK)
            U[u] = np.linalg.solve(A, Vu.T @ ((1.0 + conf) * pref))
        XtX = U.T @ U
        for i in range(N_I):
            sel = ii == i
            Uu = U[ui[sel]]
            conf = ALPHA * np.abs(vals[sel])
            pref = (vals[sel] > 0).astype(np.float64)
            A = XtX + Uu.T @ (conf[:, None] * Uu) \
                + (LAM * sel.sum() + _EPS) * np.eye(RANK)
            V[i] = np.linalg.solve(A, Uu.T @ ((1.0 + conf) * pref))
    return U, V


@pytest.fixture(scope="module")
def problem():
    ui, ii, vals = make_problem()
    data = als.prepare_ratings(ui, ii, vals, N_U, N_I, chunk=32)
    return ui, ii, vals, data


def test_explicit_full_train_matches_dense(problem):
    ui, ii, vals, data = problem
    U0, V0 = seed_factors()
    want_U, want_V = dense_explicit(ui, ii, vals, U0, V0, ITERS)
    U, V = als.train_explicit(data, rank=RANK, iterations=ITERS,
                              lambda_=LAM, u0=U0, v0=V0, chunk=32)
    np.testing.assert_allclose(np.asarray(U), want_U, rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(V), want_V, rtol=2e-3, atol=2e-4)


def test_implicit_full_train_matches_dense(problem):
    ui, ii, vals, data = problem
    U0, V0 = seed_factors()
    want_U, want_V = dense_implicit(ui, ii, vals, U0, V0, ITERS)
    U, V = als.train_implicit(data, rank=RANK, iterations=ITERS,
                              lambda_=LAM, alpha=ALPHA, u0=U0, v0=V0,
                              chunk=32)
    np.testing.assert_allclose(np.asarray(U), want_U, rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(V), want_V, rtol=2e-3, atol=2e-4)


def test_sharded_full_train_matches_dense(problem):
    ui, ii, vals, data = problem
    U0, V0 = seed_factors()
    want_U, want_V = dense_explicit(ui, ii, vals, U0, V0, ITERS)
    mesh = get_mesh(8)
    U, V = als_dist.train_explicit_sharded(
        mesh, data, rank=RANK, iterations=ITERS, lambda_=LAM,
        u0=U0, v0=V0, chunk=32)
    np.testing.assert_allclose(np.asarray(U), want_U, rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(V), want_V, rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("path", ["single", "sharded"])
def test_resume_mid_train_matches_uninterrupted(problem, tmp_path, path):
    """Crash after 3 of 5 iterations (snapshot at 2), resume to 5: the
    result must equal the uninterrupted 5-iteration dense reference."""
    ui, ii, vals, data = problem
    U0, V0 = seed_factors()
    want_U, want_V = dense_explicit(ui, ii, vals, U0, V0, ITERS)

    def train(iterations, ckpt):
        if path == "single":
            return als.train_explicit(
                data, rank=RANK, iterations=iterations, lambda_=LAM,
                u0=U0, v0=V0, chunk=32, checkpoint_every=2,
                checkpointer=ckpt)
        return als_dist.train_explicit_sharded(
            get_mesh(8), data, rank=RANK, iterations=iterations,
            lambda_=LAM, u0=U0, v0=V0, chunk=32, checkpoint_every=2,
            checkpointer=ckpt)

    ckpt = FactorCheckpointer(str(tmp_path / "ck"))
    train(3, ckpt)                      # "crashed" partial run; saved step 2
    assert ckpt.latest()[0] == 2
    U, V = train(ITERS, ckpt)           # restores step 2, runs 3 more
    np.testing.assert_allclose(np.asarray(U), want_U, rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(V), want_V, rtol=2e-3, atol=2e-4)
