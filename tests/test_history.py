"""Metrics flight recorder (common/history.py) + its two consumers
(`pio monitor`, `pio incident`).

Covers the acceptance surface: ring mechanics (counter deltas with
baseline + reset semantics, histogram bucket deltas, gauge last-value,
fast->slow downsampling, bounded memory under PIO_HISTORY_MAX_SERIES),
the /debug/history.json route (param validation, WIRE PARITY with
history off — existing responses byte-identical, the endpoint answers
``enabled: false``), the SLO engine riding the shared sampler without
its burn math changing, monitor --once/--record/--replay, and the
incident e2e: a fault injected into two live daemons shows up as one
ordered timeline fusing the journal RED, the p99 change-point and the
trace's spans.
"""

import io
import json
import urllib.request
from datetime import datetime, timezone

import pytest

from journal_test_util import trained_query_api
from predictionio_tpu.common import (
    history, journal, slo, telemetry, tracing,
)
from predictionio_tpu.data.api import EventAPI
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.tools import incident, monitor
from predictionio_tpu.tools.cli import build_parser


@pytest.fixture(autouse=True)
def _clean():
    for mod in (telemetry, journal, tracing, history):
        mod.set_enabled(None)
    journal.clear()
    tracing.clear()
    history.reset()
    slo.reset()
    yield
    for mod in (telemetry, journal, tracing, history):
        mod.set_enabled(None)
    journal.clear()
    tracing.clear()
    history.reset()
    slo.reset()


@pytest.fixture()
def fresh_registry(monkeypatch):
    """An empty process registry so the rings hold exactly the series
    this test writes (the real registry is additive process-wide)."""
    reg = telemetry.MetricsRegistry()
    monkeypatch.setattr(telemetry, "REGISTRY", reg)
    return reg


def _now_ms():
    return int(datetime.now(timezone.utc).timestamp() * 1000)


# ---------------------------------------------------------------------------
# ring mechanics
# ---------------------------------------------------------------------------

def test_counter_deltas_baseline_and_reset(fresh_registry):
    """First sight baselines at 0 (the counter's past predates the
    ring); going backwards is a reset and the delta restarts from the
    new value instead of going negative."""
    rec = history.Recorder(history.HistoryConfig())
    c = telemetry.registry().counter("demo_total", "d").child()
    c.inc(10)
    rec.tick(wall_ms=1000)
    c.inc(3)
    rec.tick(wall_ms=6000)
    snap = rec.snapshot()
    assert [e["series"]["demo_total"] for e in snap["samples"]] == \
        [0.0, 3.0]
    assert snap["kinds"]["demo_total"] == "counter"
    # reset semantics, unit-level: 10 -> 13 -> 2 (restarted process)
    assert rec._counter_delta("k", 10.0) == 0.0
    assert rec._counter_delta("k", 13.0) == 3.0
    assert rec._counter_delta("k", 2.0) == 2.0


def test_histogram_bucket_deltas(fresh_registry):
    """Each tick's entry is a tiny cumulative histogram of just that
    tick's observations; the baseline tick records nothing (no prior
    pass to difference against)."""
    rec = history.Recorder(history.HistoryConfig())
    h = telemetry.registry().histogram("demo_seconds", "d").labels()
    h.observe(0.01)
    rec.tick(wall_ms=1000)
    for _ in range(5):
        h.observe(0.01)
    h.observe(1.0)
    rec.tick(wall_ms=6000)
    first, second = rec.snapshot()["samples"]
    assert "demo_seconds" not in first["series"]
    d = second["series"]["demo_seconds"]
    assert d["count"] == 6
    assert d["sum"] == pytest.approx(5 * 0.01 + 1.0)
    assert d["buckets"]["+Inf"] == 6
    # count going backwards = reset, tolerated like a counter's
    out = rec._hist_delta("k", {"buckets": {0.1: 5.0, float("inf"): 5.0},
                                "sum": 0.05, "count": 5.0})
    assert out is None                       # baseline
    out = rec._hist_delta("k", {"buckets": {0.1: 2.0, float("inf"): 2.0},
                                "sum": 0.02, "count": 2.0})
    assert out["count"] == 2.0               # not -3


def test_downsample_merge_counters_sum_gauges_last(fresh_registry):
    """A slow slot is the fold of its fast ticks: counter + histogram
    deltas SUM (a 60 s delta is the sum of its 5 s deltas), gauges keep
    the last value, and the slot is stamped with the last tick's t."""
    cfg = history.HistoryConfig(slow_every=3)
    rec = history.Recorder(cfg)
    reg = telemetry.registry()
    c = reg.counter("m_total", "d").child()
    g = reg.gauge("m_gauge", "d").child()
    h = reg.histogram("m_seconds", "d").labels()
    for i, (inc, gv, obs) in enumerate(
            [(5, 1.0, 2), (7, 2.0, 3), (9, 7.0, 4)]):
        c.inc(inc)
        g.set(gv)
        for _ in range(obs):
            h.observe(0.01)
        rec.tick(wall_ms=1000 + i * 5000)
    slow = rec.snapshot(res="slow")
    assert len(slow["samples"]) == 1
    slot = slow["samples"][0]
    assert slot["t"] == 11000
    s = slot["series"]
    assert s["m_total"] == 7.0 + 9.0         # tick 1 was the baseline
    assert s["m_gauge"] == 7.0
    assert s["m_seconds"]["count"] == 3 + 4  # baseline tick recorded none


def test_series_cap_drops_not_grows(fresh_registry):
    """PIO_HISTORY_MAX_SERIES is a hard cap: series beyond it are
    counted as dropped, never admitted (bounded memory beats complete
    coverage, KNOWN_ISSUES #20)."""
    rec = history.Recorder(history.HistoryConfig(max_series=3))
    fam = telemetry.registry().counter("many_total", "d",
                                       labelnames=("i",))
    for i in range(8):
        fam.labels(i=str(i)).inc()
    rec.tick(wall_ms=1000)
    snap = rec.snapshot()
    assert snap["seriesTotal"] == 3
    assert snap["droppedSeries"] == 5
    assert len(snap["samples"][0]["series"]) == 3


def test_snapshot_series_since_ms_and_limit_filters(fresh_registry):
    rec = history.Recorder(history.HistoryConfig())
    reg = telemetry.registry()
    a = reg.counter("aaa_total", "d").child()
    reg.gauge("bbb_gauge", "d").child().set(1.0)
    for i in range(3):
        a.inc()
        rec.tick(wall_ms=1000 + i * 5000)
    snap = rec.snapshot(series="aaa_total", since_ms=1000)
    assert [e["t"] for e in snap["samples"]] == [6000, 11000]
    assert all(set(e["series"]) == {"aaa_total"}
               for e in snap["samples"])
    assert set(snap["kinds"]) == {"aaa_total"}
    snap = rec.snapshot(limit=1)
    assert [e["t"] for e in snap["samples"]] == [11000]


# ---------------------------------------------------------------------------
# the route: validation + wire parity off
# ---------------------------------------------------------------------------

def test_history_route_param_validation(fresh_registry):
    history.install(start=False)
    st, body = telemetry.handle_route(
        "GET", "/debug/history.json", {"since_ms": "nope"})
    assert st == 400 and "since_ms" in body["message"]
    st, body = telemetry.handle_route(
        "GET", "/debug/history.json", {"res": "bogus"})
    assert st == 400 and "res must be fast or slow" in body["message"]
    st, body = telemetry.handle_route(
        "GET", "/debug/history.json", {"limit": "many"})
    assert st == 400 and "limit" in body["message"]
    # clamped, not rejected: an over-ask is a full read
    st, body = telemetry.handle_route(
        "GET", "/debug/history.json", {"limit": "999999", "res": "slow"})
    assert st == 200 and body["res"] == "slow"
    st, body = telemetry.handle_route("GET", "/debug/history.json", {})
    assert st == 200
    assert body["enabled"] is True
    assert body["retention"]["slow"]["slots"] == history.SLOW_SLOTS


def test_wire_parity_history_off(memory_storage):
    """PIO_HISTORY=0: existing endpoints' bytes are unchanged (history
    only ever ADDS /debug/history.json, which then answers
    enabled:false), and a disabled tick records nothing."""
    api = trained_query_api(memory_storage)
    server, port = serve_background(api)
    body = json.dumps({"user": "u1", "num": 3}).encode()

    def post():
        req = urllib.request.Request(
            f"http://localhost:{port}/queries.json", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req) as r:
            return r.status, r.read()

    try:
        history.set_enabled(True)
        st_on, bytes_on = post()
        history.set_enabled(False)
        st_off, bytes_off = post()
        assert st_on == st_off == 200
        assert bytes_on == bytes_off
        # off stops RECORDING; the rings keep what they had but nothing
        # new lands while disabled
        rec = history.recorder()
        assert rec is not None               # QueryAPI installed it
        ticks_before = rec.snapshot()["ticksTotal"]
        rec.tick(wall_ms=_now_ms())          # must no-op
        with urllib.request.urlopen(
                f"http://localhost:{port}/debug/history.json") as r:
            snap = json.loads(r.read())
        assert snap["enabled"] is False
        assert snap["samples"] == []
        assert rec.snapshot()["ticksTotal"] == ticks_before
    finally:
        server.shutdown()
        api.close()


# ---------------------------------------------------------------------------
# the SLO engine rides the shared sampler
# ---------------------------------------------------------------------------

def test_slo_burn_unchanged_by_sampler_snapshots(fresh_registry):
    """record_snapshot calls between scrapes (what the history sampler
    does every tick) must not change the burn verdicts — same numbers
    as test_slo.py's test_availability_burn_and_budget."""
    eng = slo.SLOEngine(slo.SLOConfig(availability=0.999,
                                      fast_window_s=60.0,
                                      slow_window_s=600.0))
    fam = telemetry.registry().counter(
        "pio_http_requests_total", "req",
        labelnames=("service", "status"))
    c_ok = fam.labels(service="H1", status="200")
    c_bad = fam.labels(service="H1", status="500")
    c_ok.inc(1000)
    eng.evaluate(now=0.0)                    # baseline snapshot
    c_ok.inc(950)
    c_bad.inc(50)
    eng.record_snapshot(now=50.0)            # sampler ticks, inside
    eng.record_snapshot(now=99.0)            # both burn windows
    v = eng.evaluate(now=100.0)["availability"]
    assert v["burn_fast"] == pytest.approx(0.05 / 0.001, rel=1e-6)
    assert v["burn_slow"] == pytest.approx(0.05 / 0.001, rel=1e-6)
    assert v["budget_remaining"] == pytest.approx(1 - 0.025 / 0.001,
                                                  rel=1e-6)


def test_history_tick_feeds_slo_rings(fresh_registry):
    """The sampler is the process's one snapshotter: a recorder tick
    appends to the installed SLO engine's windows."""
    eng = slo.install(slo.SLOConfig())
    rec = history.install(start=False)
    before = {k: len(r) for k, r in eng._history.items()}
    rec.tick(wall_ms=1000)
    for k, ring in eng._history.items():
        assert len(ring) == before[k] + 1, k


# ---------------------------------------------------------------------------
# pio monitor: --once / --record / --replay
# ---------------------------------------------------------------------------

def _ticked_daemon(memory_storage, obs_per_tick=20, ticks=2):
    """A live EventAPI whose history rings hold deterministic serve
    traffic: ``obs_per_tick`` 10 ms observations per 5 s tick."""
    api = EventAPI(storage=memory_storage)
    server, port = serve_background(api)
    history.reset()                          # drop the ctor's sampler
    rec = history.install(history.HistoryConfig(), start=False)
    h = telemetry.registry().histogram(
        "pio_serve_seconds", "serve", labelnames=("mode",)
    ).labels(mode="batched")
    t0 = _now_ms() - (ticks + 1) * 5000
    rec.tick(wall_ms=t0)                     # baseline
    for i in range(ticks):
        for _ in range(obs_per_tick):
            h.observe(0.01)
        rec.tick(wall_ms=t0 + (i + 1) * 5000)
    return api, server, port, rec


def test_monitor_once_live(memory_storage, fresh_registry):
    api, server, port, _rec = _ticked_daemon(memory_storage)
    buf = io.StringIO()
    try:
        rc = monitor.run_monitor([f"http://localhost:{port}"],
                                 once=True, out=buf)
    finally:
        server.shutdown()
    out = buf.getvalue()
    assert rc == 0
    assert f"http://localhost:{port}" in out
    # 20 obs / 5 s tick -> 4.0 qps straight off the histogram deltas
    assert "4.0" in out
    assert "DEAD" not in out


def test_monitor_record_then_replay(memory_storage, fresh_registry,
                                    tmp_path):
    rec_file = tmp_path / "fleet.jsonl"
    api, server, port, _rec = _ticked_daemon(memory_storage)
    live = io.StringIO()
    try:
        rc = monitor.run_monitor([f"http://localhost:{port}"],
                                 once=True, record=str(rec_file),
                                 out=live)
    finally:
        server.shutdown()
    assert rc == 0
    frames = [json.loads(line)
              for line in rec_file.read_text().splitlines() if line]
    assert len(frames) == 1 and frames[0]["targets"]
    # replay re-renders the identical row with the fleet long gone
    replayed = io.StringIO()
    rc = monitor.run_monitor([], replay=str(rec_file), out=replayed)
    assert rc == 0
    live_row = live.getvalue().splitlines()[2]
    replay_row = replayed.getvalue().splitlines()[2]
    assert live_row == replay_row
    # an empty recording is exit 2, like an all-dead fleet
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert monitor.run_monitor([], replay=str(empty),
                               out=io.StringIO()) == 2


def test_monitor_all_unreachable_exits_2():
    buf = io.StringIO()
    rc = monitor.run_monitor(["http://localhost:9"], once=True,
                             timeout=0.5, out=buf)
    assert rc == 2
    assert "DEAD" in buf.getvalue()


def test_cli_wires_monitor_and_incident():
    parser = build_parser()
    args = parser.parse_args(["monitor", "--targets", "http://a", "--once"])
    assert args.command == "monitor" and args.once
    args = parser.parse_args(["incident", "--targets", "http://a",
                              "--window", "5m", "--trace", "cafe"])
    assert args.command == "incident" and args.window == "5m"
    assert args.trace == "cafe"


# ---------------------------------------------------------------------------
# pio incident: change-point math + the e2e timeline
# ---------------------------------------------------------------------------

def test_parse_window():
    assert incident.parse_window("10m") == 600.0
    assert incident.parse_window("90s") == 90.0
    assert incident.parse_window("1h") == 3600.0
    assert incident.parse_window("600") == 600.0
    with pytest.raises(ValueError):
        incident.parse_window("tenminutes")


def test_change_points_flags_steps_not_jitter():
    flat = [(i * 1000, 10.0) for i in range(12)]
    assert incident.change_points(flat) == []
    # near-zero MAD + the relative floor: 10% wiggle stays quiet
    wiggle = [(i * 1000, 10.0 + (0.5 if i % 2 else -0.5))
              for i in range(12)]
    assert incident.change_points(wiggle) == []
    # a held step reports ONCE, at the edge
    step = [(i * 1000, 10.0 if i < 8 else 80.0) for i in range(12)]
    cps = incident.change_points(step)
    assert len(cps) == 1
    assert cps[0]["t"] == 8000 and cps[0]["direction"] == "up"


def test_incident_e2e_two_daemons(memory_storage, fresh_registry):
    """The acceptance e2e: a fault injected into a live two-daemon
    fleet — breaker RED in the journal (with a live trace), a p99 step
    in the rings — assembles over HTTP into one ordered timeline."""
    telemetry.set_enabled(True)
    tracing.set_enabled(True)
    journal.set_enabled(True)
    history.set_enabled(True)
    api1 = EventAPI(storage=memory_storage)
    api2 = EventAPI(storage=memory_storage)
    s1, p1 = serve_background(api1)
    s2, p2 = serve_background(api2)
    history.reset()
    rec = history.install(history.HistoryConfig(), start=False)
    h = telemetry.registry().histogram(
        "pio_serve_seconds", "serve", labelnames=("mode",)
    ).labels(mode="batched")

    # the fault: a RED journal event emitted under a live trace
    ctx = tracing.new_context()
    with tracing.activate(ctx):
        tracing.record_span("query.predict", tracing.current(), 0.048,
                            service="engine")
        journal.emit("breaker", "storage breaker OPEN", level="red")

    # the signal: 7 healthy ticks then 2 ticks of 100x latency
    now = _now_ms()
    t0 = now - 60_000
    rec.tick(wall_ms=t0)
    for i in range(9):
        lat = 0.002 if i < 7 else 0.2
        for _ in range(20):
            h.observe(lat)
        rec.tick(wall_ms=t0 + (i + 1) * 5000)

    targets = [f"http://localhost:{p1}", f"http://localhost:{p2}"]
    try:
        result = incident.assemble(targets, window_s=600.0)
        buf = io.StringIO()
        rc = incident.run_incident(targets, window="10m", out=buf)
    finally:
        s1.shutdown()
        s2.shutdown()

    assert not result["errors"]
    kinds = [e["kind"] for e in result["entries"]]
    assert "RED" in kinds and "STEP" in kinds and "SPAN" in kinds
    # the trace was discovered FROM the journal event, not handed in
    assert ctx.trace_id in result["trace_ids"]
    red = next(e for e in result["entries"] if e["kind"] == "RED")
    assert "breaker: storage breaker OPEN" in red["detail"]
    step = next(e for e in result["entries"] if e["kind"] == "STEP")
    assert "p99 rose" in step["detail"]
    span = next(e for e in result["entries"] if e["kind"] == "SPAN")
    assert "query.predict" in span["detail"]
    # one timeline, oldest first
    ts = [e["ts_ms"] for e in result["entries"]]
    assert ts == sorted(ts)

    assert rc == 1                           # incident evidence found
    out = buf.getvalue()
    assert "VERDICT" in out and "RED event(s)" in out
    assert "STEP" in out and "SPAN" in out


def test_incident_all_unreachable_exits_2():
    buf = io.StringIO()
    rc = incident.run_incident(["http://localhost:9"], window="1m",
                               timeout=0.5, out=buf)
    assert rc == 2
    assert "unreachable" in buf.getvalue()
