"""Flight recorder (common/journal.py): the operational-event journal.

Covers the acceptance surface: emit/snapshot mechanics (monotonic seq,
``since_seq`` pagination, category + minimum-level filters, bounded
eviction that never renumbers), the ``/debug/events.json`` route on all
three daemons, WIRE PARITY (journal off -> existing responses byte-
identical, the endpoint answers ``enabled: false``), and every wired
emitter: breaker transitions, retry exhaustion, degraded flips, WAL
torn-tail repair, group-commit stalls, model load/reload generations,
drain begin/end, quant fallback, AOT prebuild failures, post-warmup
recompiles, and SLO burn-rate crossings — the chaos-suite shapes
(breaker open, WAL repair) asserted through the wire surface of all
three daemons.
"""

import io
import json
import os
import urllib.request

import pytest

from predictionio_tpu.common import (
    journal, resilience, telemetry, tracing,
)
from predictionio_tpu.common.resilience import CircuitBreaker, RetryPolicy
from predictionio_tpu.data.api import EventAPI
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import Storage
from predictionio_tpu.data.storage.remote import StorageRPCAPI


@pytest.fixture(autouse=True)
def _clean_journal():
    journal.set_enabled(None)
    journal.clear()
    telemetry.set_enabled(None)
    tracing.set_enabled(None)
    tracing.clear()
    yield
    journal.set_enabled(None)
    journal.clear()
    telemetry.set_enabled(None)
    tracing.set_enabled(None)
    tracing.clear()


# ---------------------------------------------------------------------------
# journal mechanics
# ---------------------------------------------------------------------------

def test_emit_and_snapshot_basics():
    s1 = journal.emit("breaker", "opened", level=journal.RED,
                      endpoint="ep")
    s2 = journal.emit("wal", "repaired", level=journal.WARN, bytes=12)
    s3 = journal.emit("lifecycle", "gen 1 live")
    assert (s1, s2, s3) == (1, 2, 3)
    snap = journal.snapshot()
    assert snap["enabled"] is True
    assert snap["lastSeq"] == 3
    assert [e["seq"] for e in snap["events"]] == [1, 2, 3]
    first = snap["events"][0]
    assert first["category"] == "breaker" and first["level"] == "red"
    assert first["fields"] == {"endpoint": "ep"}
    assert "at" in first and "ts" in first


def test_since_seq_pagination_and_filters():
    journal.emit("breaker", "opened", level=journal.RED)
    journal.emit("wal", "stall", level=journal.WARN)
    journal.emit("lifecycle", "gen 1 live")     # info
    # since_seq: strictly-greater cursor — the follower contract
    assert [e["seq"] for e in
            journal.snapshot(since_seq=1)["events"]] == [2, 3]
    assert not journal.snapshot(since_seq=3)["events"]
    # category narrows to one subsystem
    assert [e["category"] for e in
            journal.snapshot(category="wal")["events"]] == ["wal"]
    # level is a MINIMUM severity: warn returns warn+red
    assert [e["level"] for e in
            journal.snapshot(level="warn")["events"]] == ["red", "warn"]
    assert [e["level"] for e in
            journal.snapshot(level="red")["events"]] == ["red"]
    # limit keeps the NEWEST records
    assert [e["seq"] for e in
            journal.snapshot(limit=2)["events"]] == [2, 3]


def test_bounded_eviction_keeps_seq_monotonic(monkeypatch):
    monkeypatch.setenv("PIO_JOURNAL_BUFFER", "16")
    for k in range(40):
        journal.emit("lifecycle", f"event {k}")
    snap = journal.snapshot()
    assert snap["capacity"] == 16
    assert len(snap["events"]) == 16
    # old records fell off; seq NEVER renumbers (cursors stay valid)
    assert [e["seq"] for e in snap["events"]] == list(range(25, 41))
    assert snap["lastSeq"] == 40


def test_disabled_journal_records_nothing(monkeypatch):
    journal.set_enabled(False)
    assert journal.emit("breaker", "opened") is None
    snap = journal.snapshot()
    assert snap["enabled"] is False and snap["events"] == []
    journal.set_enabled(None)
    monkeypatch.setenv("PIO_JOURNAL", "0")
    assert journal.emit("breaker", "opened") is None
    assert not journal.snapshot()["events"]


def test_emit_captures_and_pins_active_trace():
    tracing.set_enabled(True)
    ctx = tracing.new_context()
    with tracing.activate(ctx):
        journal.emit("wal", "repaired", level=journal.WARN)
    snap = journal.snapshot()
    assert snap["events"][-1]["traceId"] == ctx.trace_id
    # the journal reference pinned the trace in the tail ring
    assert f"journal:wal" in tracing._tail.reasons_for(ctx.trace_id)


def test_emit_metric_gated_on_telemetry():
    telemetry.set_enabled(True)
    journal.emit("wal", "stall", level=journal.WARN)
    reg = telemetry.registry()
    fam = reg._families.get("pio_journal_events_total")
    assert fam is not None
    val = fam.labels(category="wal", level="warn").value
    assert val >= 1


# ---------------------------------------------------------------------------
# the wire surface: /debug/events.json on every daemon
# ---------------------------------------------------------------------------

def _mk_event(eid="u1", iid="i1"):
    return Event(event="rate", entity_type="user", entity_id=eid,
                 target_entity_type="item", target_entity_id=iid,
                 properties=DataMap({"rating": 2.0}))


def test_events_route_params_and_validation(memory_storage):
    api = EventAPI(storage=memory_storage)
    journal.emit("breaker", "opened", level=journal.RED)
    journal.emit("wal", "stall", level=journal.WARN)
    st, snap = api.handle("GET", "/debug/events.json")
    assert st == 200 and len(snap["events"]) == 2
    st, snap = api.handle("GET", "/debug/events.json",
                          {"since_seq": "1"})
    assert st == 200 and [e["seq"] for e in snap["events"]] == [2]
    st, snap = api.handle("GET", "/debug/events.json",
                          {"category": "breaker"})
    assert st == 200 and len(snap["events"]) == 1
    st, snap = api.handle("GET", "/debug/events.json", {"level": "red"})
    assert st == 200 and len(snap["events"]) == 1
    st, err = api.handle("GET", "/debug/events.json",
                         {"since_seq": "bogus"})
    assert st == 400
    st, err = api.handle("GET", "/debug/events.json", {"level": "loud"})
    assert st == 400
    st, err = api.handle("GET", "/debug/events.json", {"limit": "x"})
    assert st == 400


def test_chaos_shapes_visible_on_all_three_daemons(memory_storage,
                                                   tmp_path):
    """THE acceptance read: a breaker-open and a WAL torn-tail repair
    (the chaos suite's injected shapes) show up in /debug/events.json
    on the query, event, AND storage daemons."""
    from journal_test_util import trained_query_api

    # breaker open: drive a shared breaker over its error threshold
    br = CircuitBreaker("evlog", window_s=30, error_threshold=0.5,
                        min_calls=4, open_s=5)
    for _ in range(4):
        br.record(False)
    assert br.state == CircuitBreaker.OPEN

    # WAL torn-tail repair: tear the WAL mid-record, then reopen+insert
    env = {
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
        "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path / "el"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    }
    s1 = Storage(env=env)
    from predictionio_tpu.data.storage import App
    app_id = s1.get_meta_data_apps().insert(App(0, "JApp"))
    ev1 = s1.get_events()
    ev1.init(app_id)
    ev1.insert_batch([_mk_event("u1"), _mk_event("u2")], app_id)
    sh = ev1._shard(app_id, None)
    wal = sh.wal_path_for(sh.next_seq)
    size = os.path.getsize(wal)
    with open(wal, "r+b") as f:
        f.truncate(size - 10)
    s2 = Storage(env=env)
    s2.get_events().insert(_mk_event("u3"), app_id)   # repairs the tail

    query_api = trained_query_api(memory_storage)
    event_api = EventAPI(storage=memory_storage)
    storage_api = StorageRPCAPI(memory_storage, key="sekrit")
    try:
        for api in (query_api, event_api, storage_api):
            st, snap = api.handle("GET", "/debug/events.json",
                                  {"level": "warn"})
            assert st == 200, type(api).__name__
            cats = {e["category"] for e in snap["events"]}
            assert "breaker" in cats, (type(api).__name__, snap)
            assert "wal" in cats, (type(api).__name__, snap)
            opened = [e for e in snap["events"]
                      if e["category"] == "breaker"
                      and e["fields"].get("to") == "open"]
            assert opened and opened[0]["level"] == "red"
            repaired = [e for e in snap["events"]
                        if e["category"] == "wal"
                        and "torn" in e["message"]]
            assert repaired
    finally:
        query_api.close()


def test_wire_parity_journal_off(memory_storage):
    """PIO_JOURNAL=0: existing endpoints' bytes are unchanged (the
    journal only ever ADDS /debug/events.json, which then answers
    enabled:false with no events)."""
    from journal_test_util import trained_query_api
    api = trained_query_api(memory_storage)
    server, port = serve_background(api)
    body = json.dumps({"user": "u1", "num": 3}).encode()

    def post():
        req = urllib.request.Request(
            f"http://localhost:{port}/queries.json", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req) as r:
            return r.status, r.read()

    try:
        journal.set_enabled(True)
        st_on, bytes_on = post()
        journal.set_enabled(False)
        st_off, bytes_off = post()
        assert st_on == st_off == 200
        assert bytes_on == bytes_off
        # off stops RECORDING (history already buffered stays readable);
        # nothing new lands while disabled
        last = journal.snapshot()["lastSeq"]
        journal.emit("lifecycle", "must not record")
        with urllib.request.urlopen(
                f"http://localhost:{port}/debug/events.json") as r:
            snap = json.loads(r.read())
        assert snap["enabled"] is False
        assert snap["lastSeq"] == last
        assert all(e["message"] != "must not record"
                   for e in snap["events"])
    finally:
        server.shutdown()
        api.close()


# ---------------------------------------------------------------------------
# emitters: one test per wired subsystem
# ---------------------------------------------------------------------------

def test_breaker_lifecycle_emits_transitions():
    clock = [0.0]
    br = CircuitBreaker("ep1", window_s=30, error_threshold=0.5,
                        min_calls=2, open_s=5,
                        clock=lambda: clock[0])
    br.record(False)
    br.record(False)            # -> open (red)
    clock[0] += 6.0
    br.allow()                  # -> half-open probe admitted (warn)
    br.record(True)             # -> closed (info)
    events = [e for e in journal.snapshot(category="breaker")["events"]
              if e["fields"].get("endpoint") == "ep1"]
    assert [(e["fields"]["to"], e["level"]) for e in events] == [
        ("open", "red"), ("half-open", "warn"), ("closed", "info")]


def test_retry_exhaustion_emits():
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.0)

    def always_fails():
        raise ConnectionError("nope")

    with pytest.raises(ConnectionError):
        policy.call(always_fails, sleep=lambda s: None)
    events = journal.snapshot(category="retry")["events"]
    assert len(events) == 1
    assert events[0]["level"] == "warn"
    assert events[0]["fields"]["attempts"] == 3


def test_first_try_failure_is_not_journaled():
    """A no-retry policy failing its only attempt is the caller's
    ordinary error path, not retry exhaustion."""
    policy = RetryPolicy(max_attempts=1)
    with pytest.raises(ConnectionError):
        policy.call(lambda: (_ for _ in ()).throw(ConnectionError("x")),
                    sleep=lambda s: None)
    assert not journal.snapshot(category="retry")["events"]


def test_degraded_flip_emits():
    resilience.reset_degraded()
    resilience.note_degraded("side-channel lookup failed")
    events = journal.snapshot(category="degraded")["events"]
    assert events and events[-1]["level"] == "warn"
    assert "side-channel" in events[-1]["fields"]["reason"]
    resilience.pop_degraded()


def test_wal_group_commit_stall_emits(monkeypatch, tmp_path):
    from predictionio_tpu.data.storage import eventlog as el
    monkeypatch.setattr(el, "_WAL_STALL_S", 0.0)   # every commit stalls
    monkeypatch.setenv("PIO_WAL_GROUP_MS", "1")
    env = {
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
        "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path / "el"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    }
    s = Storage(env=env)
    from predictionio_tpu.data.storage import App
    app_id = s.get_meta_data_apps().insert(App(0, "StallApp"))
    s.get_events().init(app_id)
    s.get_events().insert_batch([_mk_event()], app_id)
    events = journal.snapshot(category="wal")["events"]
    assert any("stall" in e["message"] for e in events), events


def test_lifecycle_generation_reload_and_drain(memory_storage):
    from journal_test_util import trained_query_api
    api = trained_query_api(memory_storage)
    try:
        life = journal.snapshot(category="lifecycle")["events"]
        gens = [e for e in life if "generation" in e["fields"]]
        assert gens and gens[-1]["fields"]["generation"] == 1
        assert gens[-1]["fields"]["reload"] is False
        assert api.generation == 1
        api._reload()      # synchronous hot-swap
        life = journal.snapshot(category="lifecycle")["events"]
        gens = [e for e in life if "generation" in e["fields"]
                and e["fields"].get("reload") is True]
        assert gens and gens[-1]["fields"]["generation"] == 2
        api.drain(grace_s=5.0)
        msgs = [e["message"] for e in
                journal.snapshot(category="lifecycle")["events"]]
        assert any("drain begin" in m for m in msgs)
        assert any("drain complete" in m for m in msgs)
    finally:
        api.close()


def test_quant_fallback_emits():
    from predictionio_tpu.ops import quant
    quant.note_fallback("ranking-parity probe below the floor",
                        recall=0.95, floor=0.99)
    events = journal.snapshot(category="quant")["events"]
    assert events and events[-1]["level"] == "warn"
    assert events[-1]["fields"]["recall"] == 0.95


def test_aot_prebuild_failure_emits():
    from predictionio_tpu.serving import aot

    def boom():
        raise RuntimeError("no such kernel")

    spec = aot.ProgramSpec(name="journal_test_kernel",
                           key=("journal_test_kernel", 1),
                           lower=boom, prime=boom)
    report = aot.prebuild([spec], threads=1)
    assert any(status == "failed" for _k, status, _s in report.programs)
    events = journal.snapshot(category="aot")["events"]
    assert events and events[-1]["level"] == "warn"
    assert "journal_test_kernel" in events[-1]["fields"]["program"]


def test_post_warmup_recompile_emits():
    from predictionio_tpu.common import devicewatch
    telemetry.set_enabled(True)
    devicewatch._note_post_warmup("serve_flush", "flush:n=3,k=10", 0.4)
    events = journal.snapshot(category="recompile")["events"]
    assert events and events[-1]["level"] == "red"
    assert events[-1]["fields"]["signature"] == "flush:n=3,k=10"


def test_slo_crossing_emits_edges_not_levels():
    from predictionio_tpu.common.slo import SLOEngine
    eng = SLOEngine()
    hot = {"availability": {"burn_fast": 20.0, "burn_slow": 1.0}}
    eng._note_crossings(hot)
    eng._note_crossings(hot)     # sustained burn: NO second event
    events = journal.snapshot(category="slo")["events"]
    assert len(events) == 1 and events[0]["level"] == "red"
    cool = {"availability": {"burn_fast": 0.1, "burn_slow": 1.0}}
    eng._note_crossings(cool)    # recovery edge
    events = journal.snapshot(category="slo")["events"]
    assert len(events) == 2 and events[1]["level"] == "info"
    assert "subsided" in events[1]["message"]


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
