"""Typed JSON codec tests (ref: core/src/test/scala/.../JsonExtractorSuite)."""

import dataclasses
from typing import Optional, Tuple

import pytest

from predictionio_tpu.workflow.json_extractor import (
    extract, extract_query, to_json_obj,
)


@dataclasses.dataclass(frozen=True)
class Inner:
    name: str
    weight: float = 1.0


@dataclasses.dataclass(frozen=True)
class Q:
    user: str
    num: int
    items: Optional[Tuple[str, ...]] = None
    inner: Optional[Inner] = None


def test_extract_nested_and_defaults():
    q = extract(Q, {"user": "u1", "num": 3,
                    "items": ["a", "b"],
                    "inner": {"name": "x"}})
    assert q == Q("u1", 3, ("a", "b"), Inner("x", 1.0))
    # int widening to float
    assert extract(Inner, {"name": "x", "weight": 2}).weight == 2.0


def test_extract_rejects_bad_input():
    with pytest.raises(ValueError, match="required"):
        extract(Q, {"user": "u1"})
    with pytest.raises(ValueError, match="unknown field"):
        extract(Q, {"user": "u1", "num": 1, "zzz": 2})
    with pytest.raises(ValueError, match="expected int"):
        extract(Q, {"user": "u1", "num": "3"})
    with pytest.raises(ValueError, match="expected int"):
        extract(Q, {"user": "u1", "num": True})
    # null for a required non-Optional field is rejected
    with pytest.raises(ValueError, match="null"):
        extract(Q, {"user": None, "num": 3})
    # null for Optional passes
    assert extract(Q, {"user": "u", "num": 1, "items": None}).items is None


def test_extract_pep604_union():
    @dataclasses.dataclass(frozen=True)
    class Modern:
        name: str
        inner: Inner | None = None
        count: int | str = 0

    m = extract(Modern, {"name": "a", "inner": {"name": "i"}})
    assert m.inner == Inner("i")  # validated, not a raw dict
    with pytest.raises(ValueError):
        extract(Modern, {"name": "a", "inner": {"nope": 1}})
    assert extract(Modern, {"name": "a", "count": "x"}).count == "x"
    with pytest.raises(ValueError, match="null"):
        extract(Modern, {"name": None})


def test_to_json_obj_drops_none_fields():
    assert to_json_obj(Q("u", 2)) == {"user": "u", "num": 2}
    assert to_json_obj(Q("u", 2, ("i",), Inner("x"))) == {
        "user": "u", "num": 2, "items": ["i"],
        "inner": {"name": "x", "weight": 1.0}}


def test_extract_query_bytes():
    assert extract_query(Q, b'{"user": "u", "num": 1}') == Q("u", 1)
    assert extract_query(None, b'{"free": 1}') == {"free": 1}
