"""Round-5 ETL caches: repeat trains over an unchanged event store skip
the device layout (process-wide content-fingerprint cache) and the hybrid
prep (identity-keyed cache); any data change invalidates both."""

import numpy as np
import pytest

from predictionio_tpu.ops import als


@pytest.fixture(autouse=True)
def _clear_caches():
    from predictionio_tpu.models.recommendation import als_algorithm
    als_algorithm._BIG_LAYOUT_CACHE.clear()
    als._HYBRID_CACHE.clear()
    yield
    als_algorithm._BIG_LAYOUT_CACHE.clear()
    als._HYBRID_CACHE.clear()


def _mk_td(seed=0, n=4000):
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.models.recommendation.data_source import (
        TrainingData,
    )
    rng = np.random.default_rng(seed)
    n_u, n_i = 60, 40
    return TrainingData(
        user_idx=rng.integers(0, n_u, n).astype(np.int32),
        item_idx=rng.integers(0, n_i, n).astype(np.int32),
        rating=rng.uniform(0.5, 5, n).astype(np.float32),
        user_vocab=BiMap.string_int(f"u{k}" for k in range(n_u)),
        item_vocab=BiMap.string_int(f"i{k}" for k in range(n_i)),
    )


def test_big_layout_cache_hits_and_invalidates(monkeypatch):
    from predictionio_tpu.models.recommendation.als_algorithm import (
        ALSAlgorithm, ALSAlgorithmParams,
    )
    monkeypatch.setenv("PIO_ALS_BIG_LAYOUT_MIN", "100")  # force big path
    calls = []
    real = als.prepare_ratings
    monkeypatch.setattr(als, "prepare_ratings",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=3, numIterations=2, seed=1))
    td1 = _mk_td(seed=0)
    m1 = algo.train(None, type("P", (), {"ratings": td1})())
    assert len(calls) == 1
    # same CONTENT in a fresh TrainingData object -> layout reused
    m2 = algo.train(None, type("P", (), {"ratings": _mk_td(seed=0)})())
    assert len(calls) == 1
    np.testing.assert_array_equal(np.asarray(m1.user_factors),
                                  np.asarray(m2.user_factors))
    # one changed rating -> fingerprint differs -> rebuild
    td3 = _mk_td(seed=0)
    td3.rating[0] += 1.0
    algo.train(None, type("P", (), {"ratings": td3})())
    assert len(calls) == 2


def test_layout_digest_distinguishes_same_shape_different_content():
    """Two different-content/same-shape event sets can never share a
    cache entry: the cheap meta prefix (nnz, vocab sizes) collides by
    construction, so only the blake2b content digest separates them —
    the 128-bit guarantee the PR 1 fingerprint change bought (the old
    32-bit CRC left a ~2^-32 silent-stale-layout window)."""
    from predictionio_tpu.models.recommendation import als_algorithm
    td_a = _mk_td(seed=0)
    td_b = _mk_td(seed=1)     # same n/vocab shapes, different contents
    assert (als_algorithm._layout_meta(td_a, False)
            == als_algorithm._layout_meta(td_b, False))
    assert (als_algorithm._layout_crc(td_a)
            != als_algorithm._layout_crc(td_b))
    # and the digest is 16 bytes of blake2b, not a 4-byte CRC
    assert len(als_algorithm._layout_crc(td_a)) == 16


def test_big_layout_cache_disabled(monkeypatch):
    from predictionio_tpu.models.recommendation.als_algorithm import (
        ALSAlgorithm, ALSAlgorithmParams,
    )
    monkeypatch.setenv("PIO_ALS_BIG_LAYOUT_MIN", "100")
    monkeypatch.setenv("PIO_ALS_LAYOUT_CACHE", "0")
    calls = []
    real = als.prepare_ratings
    monkeypatch.setattr(als, "prepare_ratings",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=3, numIterations=2, seed=1))
    algo.train(None, type("P", (), {"ratings": _mk_td()})())
    algo.train(None, type("P", (), {"ratings": _mk_td()})())
    assert len(calls) == 2


def test_hybrid_prep_cache_identity_keyed(monkeypatch):
    monkeypatch.setenv("PIO_ALS_HOT_K", "16")
    monkeypatch.setenv("PIO_ALS_DENSE_MIN_COUNT", "4")
    rng = np.random.default_rng(2)
    n_u, n_i, nnz = 120, 80, 4000
    ui = rng.integers(0, n_u, nnz).astype(np.int32)
    ii = rng.integers(0, n_i, nnz).astype(np.int32)
    vals = rng.uniform(0.5, 5, nnz).astype(np.float32)
    data = als.prepare_ratings(ui, ii, vals, n_u, n_i, chunk=1024)
    calls = []
    real = als._hybrid_prepare
    monkeypatch.setattr(als, "_hybrid_prepare",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    U1, V1 = als.train_explicit(data, rank=3, iterations=2, lambda_=0.05,
                                seed=5, chunk=1024, kernel="hybrid")
    assert len(calls) == 1
    # same ALSData object -> prep reused; warm-start continues training
    U2, _ = als.train_explicit(data, rank=3, iterations=1, lambda_=0.05,
                               u0=U1, v0=V1, chunk=1024, kernel="hybrid")
    assert len(calls) == 1
    # different ALSData object -> rebuilt
    data2 = als.prepare_ratings(ui, ii, vals, n_u, n_i, chunk=1024)
    als.train_explicit(data2, rank=3, iterations=1, lambda_=0.05,
                       chunk=1024, kernel="hybrid")
    assert len(calls) == 2
