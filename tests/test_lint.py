"""`pio lint` (tools/analyze): the KNOWN_ISSUES invariants as passes.

Three layers, all tier-1:

1. **The repo is clean**: one entry point runs every pass over the real
   tree exactly like `pio lint` and requires exit 0 — THE static-analysis
   gate. Any new violation anywhere in `predictionio_tpu/`, `bench.py`
   or `diagnostics/` fails this test with file:line + rule + fix hint.
2. **The passes are live**: each rule is proven to fire on a seeded
   defect (a `block_until_ready` clock boundary, an unclipped padded
   gather, an implicit device->host sync, a `time.time()` inside a
   jitted body, a lock-order inversion, an undocumented `PIO_*` read,
   an unregistered serving jit, a private debug path) — a lint that
   can't fail is documentation, not enforcement.
3. **No coverage was lost in the re-homing**: the hand-maintained
   module lists of the three pre-framework lints are asserted to be
   SUBSETS of what the shared walker / structural scopes discover, so
   the old opt-in coverage is provably contained in the new opt-out
   coverage.

Plus the suppression-baseline contract (new findings fail; baselined
findings don't; stale baseline entries fail until deleted) and the
runtime lock-order monitor the chaos tests install.
"""

import ast
import json
import os
import threading

import pytest

from predictionio_tpu.tools.analyze import runner, runtime, walker
from predictionio_tpu.tools.analyze.findings import Baseline, Finding
from predictionio_tpu.tools.analyze.passes import (
    all_passes, aot_registration, debug_surface, declarations, host_sync,
    jit_purity, lock_order, timing,
)

ROOT = walker.repo_root()


def _mod(src, rel="predictionio_tpu/fake/mod.py"):
    """An in-memory Module for seeding defects into a pass."""
    return walker.Module(path=os.path.join(ROOT, rel), rel=rel,
                         source=src, tree=ast.parse(src))


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# 1. the gate: the repo itself lints clean
# ---------------------------------------------------------------------------

def test_repo_lint_clean():
    """THE tier-1 entry point: `pio lint` over the real repo, exit 0."""
    result = runner.run_lint()
    assert not result.internal_errors, result.internal_errors
    assert result.exit_code == 0, "\n" + result.render_text()
    # the walk covers the whole repo-of-record, not an opt-in list
    assert result.modules_analyzed > 100
    assert len(result.passes_run) == len(all_passes())


def test_lint_json_schema():
    """The --json object carries the documented fields (README schema)."""
    d = runner.run_lint().as_dict()
    for key in ("exit", "modulesAnalyzed", "passes", "findings",
                "suppressed", "staleBaselineKeys", "internalErrors",
                "counts"):
        assert key in d, key
    assert d["counts"] == {"findings": len(d["findings"]),
                           "suppressed": len(d["suppressed"]),
                           "stale": len(d["staleBaselineKeys"])}
    json.dumps(d)                      # JSON-serializable end to end


# ---------------------------------------------------------------------------
# 2. every pass fires on a seeded defect
# ---------------------------------------------------------------------------

def test_timing_pass_fires_on_block_until_ready_clock_boundary():
    src = (
        "import time\n"
        "import jax\n"
        "def timed(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = x + 1\n"
        "    jax.block_until_ready(y)\n"     # the KNOWN_ISSUES #3 shape
        "    return time.perf_counter() - t0\n")
    assert _rules(timing.run([_mod(src)])) == ["timing-block-until-ready"]


def test_timing_pass_fires_on_wall_clock():
    src = "import time as t\nx = t.time()\nfrom time import time\ny = time()\n"
    found = timing.run([_mod(src)])
    assert _rules(found) == ["timing-wall-clock"]
    assert sorted(f.line for f in found) == [2, 4]
    # perf_counter does not trip it
    assert not timing.run([_mod("import time\nx = time.perf_counter()\n")])


def test_timing_pass_respects_pragma_opt_out():
    src = ("import jax\n"
           "# dispatch barrier, nothing timed behind it\n"
           "jax.block_until_ready(0)  "
           "# pio-lint: allow=timing-block-until-ready\n")
    assert not timing.run([_mod(src)])


def test_host_sync_pass_fires_on_unclipped_gather():
    src = ("import jax.numpy as jnp\n"
           "def f(x, idx):\n"
           "    return jnp.take(x, idx, axis=0)\n")
    assert _rules(host_sync.run([_mod(src)])) == ["gather-clip"]


def test_host_sync_pass_accepts_clipped_and_contracted_gathers():
    clipped = ("import jax.numpy as jnp\n"
               "def f(x, idx, n):\n"
               "    idx = jnp.clip(idx, 0, n - 1)\n"
               "    return jnp.take(x, idx, axis=0)\n")
    mode = ("import jax.numpy as jnp\n"
            "def f(x, idx):\n"
            "    return jnp.take(x, idx, axis=0, mode='clip')\n")
    contract = ("import jax.numpy as jnp\n"
                "def f(x, idx):\n"
                '    """idx must be in-bounds (callers clip)."""\n'
                "    return jnp.take(x, idx, axis=0)\n")
    for src in (clipped, mode, contract):
        assert not host_sync.run([_mod(src)]), src


def test_host_sync_pass_fires_on_implicit_sync():
    src = ("import jax.numpy as jnp\n"
           "def serve(q):\n"
           "    scores = jnp.dot(q, q)\n"
           "    return float(scores)\n")       # implicit device->host sync
    assert _rules(host_sync.run([_mod(src)])) == ["hostsync-implicit"]
    # the sanctioned explicit transfer is NOT flagged
    ok = ("import jax\nimport jax.numpy as jnp\n"
          "def serve(q):\n"
          "    return float(jax.device_get(jnp.dot(q, q)))\n")
    assert not host_sync.run([_mod(ok)])


def test_host_sync_pass_fires_inside_registered_jit_bodies():
    """A conversion inside a register_jit-reachable body is flagged even
    with no local jax provenance — the argument IS a tracer there."""
    src = ("import jax.numpy as jnp\n"
           "from predictionio_tpu.serving.aot import register_jit\n"
           "def kernel(x, k):\n"
           "    return jnp.sum(x) * int(k)\n"
           "register_jit('kernel', kernel)\n")
    assert _rules(host_sync.run([_mod(src)])) == ["hostsync-implicit"]


def test_jit_purity_pass_fires_on_wall_clock_in_jit():
    src = ("import time\nimport jax\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    return x + time.time()\n")     # baked in at trace time
    assert _rules(jit_purity.run([_mod(src)])) == ["jit-wall-clock"]


def test_jit_purity_pass_fires_on_rng_io_and_global_mutation():
    src = ("import random\nimport jax\n"
           "STATE = {}\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    global STATE\n"
           "    print(x)\n"
           "    return x * random.random()\n")
    assert _rules(jit_purity.run([_mod(src)])) == [
        "jit-global-mutation", "jit-io", "jit-nondeterminism"]
    # jax.random with an explicit key is the traced alternative: legal
    ok = ("import jax\n"
          "@jax.jit\n"
          "def f(key, x):\n"
          "    return x + jax.random.normal(key, x.shape)\n")
    assert not jit_purity.run([_mod(ok)])


def test_jit_purity_ignores_unjitted_functions():
    src = ("import time\nimport jax\n"
           "def eager(x):\n"
           "    return x + time.time()\n")     # wrong-clock maybe, but
    assert not jit_purity.run([_mod(src)])     # not a jit-purity issue


def test_lock_order_pass_fires_on_inversion():
    src = (
        "import threading\n"
        "a_lock = threading.Lock()\n"
        "b_lock = threading.Lock()\n"
        "def path_one():\n"
        "    with a_lock:\n"
        "        with b_lock:\n"
        "            pass\n"
        "def path_two():\n"
        "    with b_lock:\n"
        "        with a_lock:\n"
        "            pass\n")
    found = lock_order.run([_mod(src)])
    assert _rules(found) == ["lock-order-inversion"]
    assert "a_lock" in found[0].message and "b_lock" in found[0].message


def test_lock_order_pass_accepts_consistent_order():
    src = (
        "import threading\n"
        "a_lock = threading.Lock()\n"
        "b_lock = threading.Lock()\n"
        "def path_one():\n"
        "    with a_lock:\n"
        "        with b_lock:\n"
        "            pass\n"
        "def path_two():\n"
        "    with a_lock:\n"
        "        with b_lock:\n"
        "            pass\n")
    assert not lock_order.run([_mod(src)])


def test_lock_order_distinguishes_classes():
    """self._lock of two different classes are different nodes."""
    src = (
        "class A:\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            with self._cond:\n"
        "                pass\n"
        "class B:\n"
        "    def g(self):\n"
        "        with self._cond:\n"
        "            with self._lock:\n"
        "                pass\n")
    # A._lock->A._cond and B._cond->B._lock: four distinct nodes, no pair
    assert not lock_order.run([_mod(src)])
    graph = lock_order.build_graph([_mod(src)])
    assert len(graph) == 2


def test_declarations_pass_fires_on_undocumented_env_read():
    src = "import os\nx = os.environ.get('PIO_NOT_A_REAL_KNOB_XYZ', '')\n"
    found = [f for f in declarations.run([_mod(src)], readme_text="")
             if f.path != declarations._DECL_REL]
    assert _rules(found) == ["env-undeclared"]
    assert "PIO_NOT_A_REAL_KNOB_XYZ" in found[0].message


def test_declarations_pass_fires_on_unregistered_metric():
    src = ("from predictionio_tpu.common import telemetry\n"
           "c = telemetry.registry.counter('pio_ghost_series_total', 'x')\n")
    found = [f for f in declarations.run([_mod(src)], readme_text="")
             if f.rule == "metric-undeclared"]
    assert len(found) == 1 and "pio_ghost_series_total" in found[0].message


def test_declarations_pass_fires_on_undeclared_journal_category():
    """The journal-category half of the declarations triangle: an emit
    call site whose category is not in JOURNAL_CATEGORIES is a typo'd
    timeline and fails the lint."""
    src = ("from predictionio_tpu.common import journal\n"
           "journal.emit('not_a_real_category_xyz', 'boom')\n")
    found = [f for f in declarations.run([_mod(src)], readme_text="")
             if f.rule == "journal-undeclared"]
    assert len(found) == 1
    assert "not_a_real_category_xyz" in found[0].message
    # keyword spelling is caught too
    src_kw = ("from predictionio_tpu.common import journal\n"
              "journal.emit(category='also_bogus_xyz', message='x')\n")
    found = [f for f in declarations.run([_mod(src_kw)], readme_text="")
             if f.rule == "journal-undeclared"]
    assert len(found) == 1 and "also_bogus_xyz" in found[0].message


def test_declarations_pass_accepts_declared_journal_category():
    src = ("from predictionio_tpu.common import journal\n"
           "journal.emit('wal', 'repaired', level=journal.WARN)\n")
    assert not [f for f in declarations.run([_mod(src)], readme_text="")
                if f.rule == "journal-undeclared"]


def test_declarations_pass_fires_on_undeclared_tenant_metric():
    """The multi-tenant subsystem is inside the declarations triangle:
    a tenant-labeled family NOT in METRICS fails the pass, while the
    registered pio_tenant_* families, the PIO_TENANT_* env knobs, and
    the 'tenant' journal category all pass."""
    src = ("from predictionio_tpu.common import telemetry\n"
           "c = telemetry.registry().counter(\n"
           "    'pio_tenant_evictions_total', 'x',\n"
           "    labelnames=('tenant',))\n")
    found = [f for f in declarations.run(
        [_mod(src, rel="predictionio_tpu/serving/registry.py")],
        readme_text="") if f.rule == "metric-undeclared"]
    assert len(found) == 1
    assert "pio_tenant_evictions_total" in found[0].message

    ok = ("import os\n"
          "from predictionio_tpu.common import journal, telemetry\n"
          "r = os.environ.get('PIO_TENANT_RATE', '')\n"
          "h = os.environ.get('PIO_TENANT_HBM_HARD_CAP_MB', '')\n"
          "c = telemetry.registry().counter(\n"
          "    'pio_tenant_requests_total', 'x',\n"
          "    labelnames=('tenant', 'outcome'))\n"
          "journal.emit('tenant', 'over budget', level=journal.WARN)\n")
    found = declarations.run(
        [_mod(ok, rel="predictionio_tpu/serving/registry.py")],
        readme_text="")
    assert not [f for f in found if f.rule in (
        "metric-undeclared", "env-undeclared", "journal-undeclared")]


def test_declarations_pass_covers_history_knobs_and_metrics():
    """The metrics flight recorder is inside the declarations triangle:
    an undeclared PIO_HISTORY_* knob and a ghost pio_history_* family
    each fire exactly one finding, while the real knobs and the
    sampler's registered families pass clean."""
    bad_env = ("import os\n"
               "x = os.environ.get('PIO_HISTORY_BOGUS_KNOB', '')\n")
    found = [f for f in declarations.run([_mod(bad_env)], readme_text="")
             if f.path != declarations._DECL_REL]
    assert _rules(found) == ["env-undeclared"]
    assert "PIO_HISTORY_BOGUS_KNOB" in found[0].message

    bad_metric = (
        "from predictionio_tpu.common import telemetry\n"
        "c = telemetry.registry().counter(\n"
        "    'pio_history_bogus_total', 'x')\n")
    found = [f for f in declarations.run(
        [_mod(bad_metric, rel="predictionio_tpu/common/history.py")],
        readme_text="") if f.rule == "metric-undeclared"]
    assert len(found) == 1
    assert "pio_history_bogus_total" in found[0].message

    ok = ("import os\n"
          "from predictionio_tpu.common import telemetry\n"
          "t = os.environ.get('PIO_HISTORY_TICK_S', '5')\n"
          "m = os.environ.get('PIO_HISTORY_MAX_SERIES', '512')\n"
          "e = os.environ.get('PIO_HISTORY', '1')\n"
          "c = telemetry.registry().counter(\n"
          "    'pio_history_ticks_total', 'x')\n"
          "g = telemetry.registry().gauge('pio_history_series', 'x')\n")
    found = declarations.run(
        [_mod(ok, rel="predictionio_tpu/common/history.py")],
        readme_text="")
    assert not [f for f in found if f.rule in (
        "metric-undeclared", "env-undeclared")]


def test_declarations_pass_covers_partition_and_cache_families():
    """The partition-routing + response-cache subsystem is inside the
    declarations triangle: a ghost cache metric and an undeclared
    PIO_ROUTER_CACHE_* knob both fail the pass, while the real env
    knobs and metric families registered by router/create_server
    pass clean."""
    bad_metric = (
        "from predictionio_tpu.common import telemetry\n"
        "c = telemetry.registry().counter(\n"
        "    'pio_router_cache_ghost_total', 'x')\n")
    found = [f for f in declarations.run(
        [_mod(bad_metric, rel="predictionio_tpu/workflow/router.py")],
        readme_text="") if f.rule == "metric-undeclared"]
    assert len(found) == 1
    assert "pio_router_cache_ghost_total" in found[0].message

    bad_env = ("import os\n"
               "x = os.environ.get('PIO_ROUTER_CACHE_GHOST_KNOB', '')\n")
    found = [f for f in declarations.run(
        [_mod(bad_env, rel="predictionio_tpu/workflow/router.py")],
        readme_text="") if f.path != declarations._DECL_REL]
    assert _rules(found) == ["env-undeclared"]

    ok = ("import os\n"
          "from predictionio_tpu.common import journal, telemetry\n"
          "a = os.environ.get('PIO_ROUTER_CACHE', 'off')\n"
          "b = os.environ.get('PIO_ROUTER_CACHE_MB', '16')\n"
          "c = os.environ.get('PIO_ROUTER_CACHE_TTL_MS', '5000')\n"
          "d = os.environ.get('PIO_DEPLOY_PARTITION', '')\n"
          "reg = telemetry.registry()\n"
          "reg.counter('pio_router_cache_hits_total', 'x')\n"
          "reg.counter('pio_router_cache_misses_total', 'x')\n"
          "reg.counter('pio_router_cache_evictions_total', 'x')\n"
          "reg.gauge('pio_router_cache_hit_ratio', 'x')\n"
          "reg.counter('pio_router_partition_requests_total', 'x',\n"
          "            labelnames=('outcome',))\n"
          "reg.gauge('pio_router_partition_width', 'x')\n"
          "journal.emit('router', 'partition map live',\n"
          "             level=journal.INFO)\n")
    found = declarations.run(
        [_mod(ok, rel="predictionio_tpu/workflow/router.py")],
        readme_text="")
    assert not [f for f in found if f.rule in (
        "metric-undeclared", "env-undeclared", "journal-undeclared")]


def test_declarations_pass_fires_on_undeclared_category_in_realtime():
    """The new realtime subsystem is inside the journal-undeclared
    scope like everything else: a fold-in emitter with a typo'd
    category fails the lint, and its real `foldin` category passes."""
    src = ("from predictionio_tpu.common import journal\n"
           "journal.emit('fold_in_typo_xyz', 'headroom gone',\n"
           "             level=journal.WARN)\n")
    found = [f for f in declarations.run(
        [_mod(src, rel="predictionio_tpu/realtime/foldin.py")],
        readme_text="") if f.rule == "journal-undeclared"]
    assert len(found) == 1 and "fold_in_typo_xyz" in found[0].message
    ok = ("from predictionio_tpu.common import journal\n"
          "journal.emit('foldin', 'worker bound',\n"
          "             level=journal.INFO)\n")
    assert not [f for f in declarations.run(
        [_mod(ok, rel="predictionio_tpu/realtime/foldin.py")],
        readme_text="") if f.rule == "journal-undeclared"]


def test_declarations_pass_covers_autopilot_families():
    """ISSUE 18 seeded defect: the autopilot subsystem sits inside the
    declarations triangle like every other — an undeclared
    pio_autopilot_* metric fires exactly one finding, while the real
    autopilot metrics, PIO_AUTOPILOT_* knobs, and the `autopilot`
    journal category all pass."""
    bad = ("from predictionio_tpu.common import telemetry\n"
           "c = telemetry.registry().counter(\n"
           "    'pio_autopilot_bogus_total', 'x',\n"
           "    labelnames=('action',))\n")
    found = [f for f in declarations.run(
        [_mod(bad, rel="predictionio_tpu/workflow/autopilot.py")],
        readme_text="") if f.rule == "metric-undeclared"]
    assert len(found) == 1
    assert "pio_autopilot_bogus_total" in found[0].message

    ok = ("import os\n"
          "from predictionio_tpu.common import journal, telemetry\n"
          "a = os.environ.get('PIO_AUTOPILOT_COOLDOWN_S', '30')\n"
          "b = os.environ.get('PIO_AUTOPILOT_UTIL_HIGH', '0.85')\n"
          "reg = telemetry.registry()\n"
          "reg.counter('pio_autopilot_actions_total', 'x',\n"
          "            labelnames=('action', 'outcome'))\n"
          "reg.gauge('pio_autopilot_state', 'x')\n"
          "reg.gauge('pio_autopilot_last_action_age_seconds', 'x')\n"
          "journal.emit('autopilot', 'shed widened',\n"
          "             level=journal.WARN)\n")
    found = declarations.run(
        [_mod(ok, rel="predictionio_tpu/workflow/autopilot.py")],
        readme_text="")
    assert not [f for f in found if f.rule in (
        "metric-undeclared", "env-undeclared", "journal-undeclared")]


def test_declarations_pass_clean_on_real_repo_and_readme():
    """Every PIO_* read, pio_* metric, and journal.emit category in the
    real tree is declared in common/declarations.py and (env/metric)
    documented in README.md."""
    modules = [m for m in walker.discover(ROOT)]
    assert not declarations.run(modules)


def test_aot_pass_fires_on_unregistered_serving_jit():
    src = ("import jax\n"
           "@jax.jit\n"
           "def brand_new_kernel(x):\n"
           "    return x\n")
    found = aot_registration.run(
        [_mod(src, rel="predictionio_tpu/serving/newmod.py")])
    assert _rules(found) == ["aot-unregistered-jit"]
    assert found[0].detail == "brand_new_kernel"


def test_aot_pass_scope_is_structural_not_a_list():
    """A module OUTSIDE serving/ that registers kernels is pulled into
    scope automatically — the PR 8 hand-extension becomes unnecessary."""
    src = ("import jax\n"
           "from predictionio_tpu.serving.aot import register_jit\n"
           "@jax.jit\n"
           "def registered(x):\n"
           "    return x\n"
           "@jax.jit\n"
           "def forgotten(x):\n"
           "    return x\n"
           "register_jit('registered', registered)\n")
    found = aot_registration.run(
        [_mod(src, rel="predictionio_tpu/parallel/newdist.py")])
    assert [f.detail for f in found] == ["forgotten"]


def test_aot_pass_fires_on_unregistered_quant_kernel():
    """ISSUE 11 seeded defect: a quantized serving module that registers
    one kernel but forgets its fused sibling — the forgotten one would
    compile lazily on the first quantized request, exactly the cliff
    the AOT pass exists to catch."""
    src = ("import jax\n"
           "from predictionio_tpu.serving.aot import register_jit\n"
           "@jax.jit\n"
           "def topk_quant(x):\n"
           "    return x\n"
           "@jax.jit\n"
           "def topk_quant_fused(x):\n"
           "    return x\n"
           "register_jit('topk_quant', topk_quant)\n")
    found = aot_registration.run(
        [_mod(src, rel="predictionio_tpu/ops/quant_v2.py")])
    assert _rules(found) == ["aot-unregistered-jit"]
    assert [f.detail for f in found] == ["topk_quant_fused"]


def test_aot_scope_covers_quant_modules_automatically():
    """ops/quant.py and ops/topk_pallas.py enter the AOT lint scope via
    register_jit reachability — no hand-maintained list was touched."""
    modules = walker.discover(ROOT)
    scope = {m.rel for m in aot_registration.serving_scope(modules)}
    assert "predictionio_tpu/ops/quant.py" in scope
    assert "predictionio_tpu/ops/topk_pallas.py" in scope


def test_debug_surface_pass_fires_on_private_path():
    telemetry_src = "DEBUG_PATHS = ('/debug/slow.json',)\n"
    offender = "PATH = '/debug/private.json'\n"
    mods = [_mod(telemetry_src, rel="predictionio_tpu/common/telemetry.py"),
            _mod(offender, rel="predictionio_tpu/data/api/service.py")]
    found = debug_surface.run(mods)
    assert "debug-path-unshared" in _rules(found)
    # shared paths and their query-bearing forms stay legal
    ok = "PATH = '/debug/slow.json?limit=3'\n"
    mods[1] = _mod(ok, rel="predictionio_tpu/data/api/service.py")
    assert "debug-path-unshared" not in _rules(debug_surface.run(mods))


# ---------------------------------------------------------------------------
# 3. re-homing lost no coverage: old opt-in lists ⊂ new opt-out scopes
# ---------------------------------------------------------------------------

#: the hand-maintained scope lists of the three pre-framework lints,
#: frozen as they stood before the re-homing (tests/test_timing_lint.py
#: and tests/test_aot.py at PR 8). They exist here ONLY to prove
#: containment — the passes themselves carry no lists.
_OLD_TIMED_MODULES = (
    "common/telemetry.py", "common/tracing.py", "common/devicewatch.py",
    "common/waterfall.py", "common/profiling.py", "common/slo.py",
    "serving/batcher.py", "serving/aot.py", "parallel/serve_dist.py",
    "workflow/context.py", "workflow/core_workflow.py",
    "workflow/create_server.py", "data/store.py", "ops/staging.py",
    "models/recommendation/als_algorithm.py",
    "tools/benchtrend.py", "tools/doctor.py", "tools/profile.py",
)
_OLD_AOT_MODULES = ("ops/topk.py", "parallel/serve_dist.py")  # + serving/*
_OLD_DAEMON_MODULES = (
    "workflow/create_server.py", "data/api/service.py",
    "data/storage/remote.py",
    # PR 15: the fleet router is a fourth daemon with the same shared
    # debug surface contract
    "workflow/router.py",
    # PR 20: the eval dashboard + admin server joined the contract so
    # `pio monitor` can scrape all six daemons without a key
    "tools/dashboard.py", "tools/admin.py",
)


def test_timing_coverage_superset_of_old_list():
    discovered = {m.rel for m in walker.discover(ROOT)}
    old = {f"predictionio_tpu/{rel}" for rel in _OLD_TIMED_MODULES}
    assert old <= discovered, sorted(old - discovered)
    # and strictly more: bench.py + diagnostics/ joined the walk
    assert "bench.py" in discovered
    assert any(r.startswith("diagnostics/") for r in discovered)


def test_aot_scope_superset_of_old_list():
    modules = walker.discover(ROOT)
    scope = {m.rel for m in aot_registration.serving_scope(modules)}
    old = {f"predictionio_tpu/{rel}" for rel in _OLD_AOT_MODULES}
    old |= {m.rel for m in modules
            if m.rel.startswith("predictionio_tpu/serving/")}
    assert old <= scope, sorted(old - scope)
    # the training-kernel module register_jit resolves into is in scope
    # too — the old lint never covered it
    assert "predictionio_tpu/ops/als.py" in scope


def test_debug_daemon_set_matches_old_list():
    assert {f"predictionio_tpu/{rel}" for rel in _OLD_DAEMON_MODULES} == set(
        debug_surface.DAEMON_MODULES)


def test_registered_jit_defs_resolve_cross_module():
    """The purity/host-sync jit scope follows register_jit into other
    modules (ops/als.py's training kernels are traced bodies too)."""
    modules = walker.discover(ROOT)
    regs = {(m.rel, fn.name) for m, fn in walker.registered_jit_defs(modules)}
    assert ("predictionio_tpu/ops/als.py", "_train_hybrid_jit") in regs
    assert any(rel == "predictionio_tpu/ops/topk.py" for rel, _ in regs)


# ---------------------------------------------------------------------------
# suppression baseline: the debt contract
# ---------------------------------------------------------------------------

def test_baseline_suppresses_known_and_fails_new(tmp_path):
    known = Finding(rule="r", path="a.py", line=3, message="m", detail="tok")
    new = Finding(rule="r", path="b.py", line=9, message="m", detail="tok2")
    path = tmp_path / "base.json"
    Baseline(path=str(path)).write(findings=[known])
    base = Baseline.load(str(path))
    active, suppressed, stale = base.apply([known, new])
    assert [f.key for f in active] == [new.key]
    assert [f.key for f in suppressed] == [known.key]
    assert stale == []


def test_baseline_key_survives_line_drift(tmp_path):
    """Keys are detail-token based, not line based: an edit above the
    accepted site must not resurrect the finding."""
    before = Finding(rule="r", path="a.py", line=3, message="m", detail="t")
    after = Finding(rule="r", path="a.py", line=47, message="m", detail="t")
    path = tmp_path / "base.json"
    Baseline(path=str(path)).write(findings=[before])
    active, suppressed, _ = Baseline.load(str(path)).apply([after])
    assert not active and [f.key for f in suppressed] == [after.key]


def test_stale_baseline_entry_fails_the_lint(tmp_path):
    gone = Finding(rule="r", path="a.py", line=3, message="m", detail="t")
    path = tmp_path / "base.json"
    Baseline(path=str(path)).write(findings=[gone])
    active, suppressed, stale = Baseline.load(str(path)).apply([])
    assert stale == [gone.key]
    # the runner turns stale keys into failing findings
    from predictionio_tpu.tools.analyze.findings import stale_findings
    rendered = stale_findings(stale, str(path))
    assert rendered and rendered[0].rule == "baseline-stale"


def test_checked_in_baseline_entries_all_match():
    """Every entry in conf/lint_baseline.json still matches a live
    finding (no stale debt) and carries a real reason."""
    result = runner.run_lint()
    assert result.stale == []
    with open(os.path.join(ROOT, "conf", "lint_baseline.json"),
              encoding="utf-8") as f:
        payload = json.load(f)
    for entry in payload["entries"]:
        assert entry["reason"], entry["key"]
        assert entry["reason"] != "accepted pre-existing finding", (
            f"placeholder reason on {entry['key']} — say WHY the debt "
            "is accepted")


def test_runner_reports_parse_failures_as_internal_error(tmp_path):
    """A file that doesn't parse is coverage loss = exit 2, not exit 0."""
    root = tmp_path
    pkg = root / "predictionio_tpu"
    pkg.mkdir()
    (pkg / "broken.py").write_text("def f(:\n")
    result = runner.run_lint(root=str(root),
                             baseline_path=str(root / "base.json"))
    assert result.exit_code == 2
    assert any("broken.py" in e for e in result.internal_errors)


def test_pragma_lives_on_line_or_line_above():
    src_same = "import time as t\nx = t.time()  # pio-lint: allow=timing-wall-clock\n"
    src_above = ("import time as t\n"
                 "# pio-lint: allow=timing-wall-clock\n"
                 "x = t.time()\n")
    assert not timing.run([_mod(src_same)])
    assert not timing.run([_mod(src_above)])
    # and a pragma for a DIFFERENT rule does not suppress
    src_wrong = "import time as t\nx = t.time()  # pio-lint: allow=gather-clip\n"
    assert timing.run([_mod(src_wrong)])


# ---------------------------------------------------------------------------
# runtime lock-order monitor (the chaos tests' dynamic half)
# ---------------------------------------------------------------------------

def test_runtime_monitor_detects_inversion():
    mon = runtime.LockOrderMonitor()
    a = mon.wrap(threading.Lock(), "a")
    b = mon.wrap(threading.Lock(), "b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert mon.inversions() == [("a", "b")]
    mon.reset()
    assert mon.inversions() == []


def test_runtime_monitor_consistent_order_is_clean_across_threads():
    mon = runtime.LockOrderMonitor()
    a = mon.wrap(threading.Lock(), "a")
    b = mon.wrap(threading.Lock(), "b")

    def worker():
        for _ in range(50):
            with a:
                with b:
                    pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert mon.inversions() == []
    assert mon.edges()[("a", "b")] == 200


def test_runtime_monitor_reentrant_acquire_is_not_an_edge():
    mon = runtime.LockOrderMonitor()
    r = mon.wrap(threading.RLock(), "r")
    with r:
        with r:
            pass
    assert mon.edges() == {}


def test_runtime_monitor_wraps_condition():
    """A wrapped Condition keeps wait/notify working (proxied through)."""
    mon = runtime.LockOrderMonitor()
    cond = mon.wrap(threading.Condition(), "cond")
    hits = []

    def waiter():
        with cond:
            while not hits:
                cond.wait(timeout=5)

    t = threading.Thread(target=waiter)
    t.start()
    with cond:
        hits.append(1)
        cond.notify()
    t.join(timeout=5)
    assert not t.is_alive()


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_lint_exit_codes(capsys):
    assert runner.main([]) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out


def test_cli_lint_list_names_every_pass(capsys):
    assert runner.main(["--list"]) == 0
    out = capsys.readouterr().out
    for p in all_passes():
        assert p.name in out


def test_cli_lint_finds_seeded_defect_in_tree(tmp_path, capsys):
    pkg = tmp_path / "predictionio_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text("import time\nx = time.time()\n")
    rc = runner.main(["--root", str(tmp_path),
                      "--baseline", str(tmp_path / "base.json")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "timing-wall-clock" in out and "bad.py:2" in out
    # --update-baseline accepts it; the re-run is clean; fixing the file
    # makes the baseline entry stale and the lint fails again
    assert runner.main(["--root", str(tmp_path),
                        "--baseline", str(tmp_path / "base.json"),
                        "--update-baseline"]) == 0
    capsys.readouterr()
    assert runner.main(["--root", str(tmp_path),
                        "--baseline", str(tmp_path / "base.json")]) == 0
    capsys.readouterr()
    (pkg / "bad.py").write_text("import time\nx = time.perf_counter()\n")
    rc = runner.main(["--root", str(tmp_path),
                      "--baseline", str(tmp_path / "base.json")])
    out = capsys.readouterr().out
    assert rc == 1 and "baseline-stale" in out


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
