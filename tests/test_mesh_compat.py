"""`parallel/mesh.py::shard_map_compat` across both API spellings.

The shim picked up 29 tests in PR 8 by accepting whichever shard_map
the running jax exposes — `jax.shard_map` (newer, `check_vma=`) or
`jax.experimental.shard_map.shard_map` (0.4.x, `check_rep=`). Only the
spelling the installed jax happens to ship was ever exercised; here the
OTHER branch is forced via import-shim monkeypatching so a jax upgrade
(or downgrade) can't silently break the path nobody ran.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from predictionio_tpu.parallel import mesh as mesh_mod


def _psum_through(compat_result):
    """Run the wrapped kernel on a 1-device mesh and return the sum."""
    return np.asarray(compat_result(jnp.arange(8, dtype=jnp.float32)))


def _kernel(x):
    return jax.lax.psum(jnp.sum(x), "block")


def test_shard_map_compat_native_spelling(monkeypatch):
    """`jax.shard_map` present -> used, with the check_vma spelling."""
    calls = {}

    def fake_shard_map(f, mesh, in_specs, out_specs, **kwargs):
        calls.update(kwargs, mesh=mesh, in_specs=in_specs)
        # delegate to the real implementation so the wrapped kernel is
        # genuinely executable — the fake only asserts the call shape
        from jax.experimental.shard_map import shard_map
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

    monkeypatch.setattr(jax, "shard_map", fake_shard_map, raising=False)
    m = mesh_mod.get_mesh(1)
    wrapped = mesh_mod.shard_map_compat(_kernel, m, (P("block"),), P())
    assert calls["check_vma"] is False          # the new-API spelling
    assert "check_rep" not in calls
    assert calls["mesh"] is m
    assert calls["in_specs"] == (P("block"),)   # sequence normalized
    assert _psum_through(wrapped) == pytest.approx(28.0)


def test_shard_map_compat_experimental_fallback(monkeypatch):
    """No `jax.shard_map` -> the jax.experimental spelling, check_rep."""
    monkeypatch.delattr(jax, "shard_map", raising=False)
    assert not hasattr(jax, "shard_map")

    import jax.experimental.shard_map as exp_mod
    real = exp_mod.shard_map
    calls = {}

    def spying_shard_map(*args, **kwargs):
        # jax re-enters shard_map positionally during tracing — record
        # only the shim's call (check_rep passed by keyword), forward all
        if "check_rep" in kwargs:
            calls.update(kwargs)
        return real(*args, **kwargs)

    monkeypatch.setattr(exp_mod, "shard_map", spying_shard_map)
    m = mesh_mod.get_mesh(1)
    wrapped = mesh_mod.shard_map_compat(_kernel, m, [P("block")], P())
    assert calls["check_rep"] is False          # the 0.4.x spelling
    assert "check_vma" not in calls
    assert _psum_through(wrapped) == pytest.approx(28.0)


def test_shard_map_compat_branches_agree(monkeypatch):
    """Both spellings produce the same numbers for the same kernel."""
    m = mesh_mod.get_mesh(1)
    via_fallback = _psum_through(
        mesh_mod.shard_map_compat(_kernel, m, (P("block"),), P()))

    def native(f, mesh, in_specs, out_specs, check_vma):
        from jax.experimental.shard_map import shard_map
        assert check_vma is False
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

    monkeypatch.setattr(jax, "shard_map", native, raising=False)
    via_native = _psum_through(
        mesh_mod.shard_map_compat(_kernel, m, (P("block"),), P()))
    np.testing.assert_array_equal(via_fallback, via_native)


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
