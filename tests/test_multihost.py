"""Multi-host (DCN) emulation: two REAL processes, one global mesh.

The reference's cluster story is spawning against a Spark cluster
(tools/.../Runner.scala:185-307); ours is JAX's multi-controller runtime
(parallel.mesh.init_distributed). This test proves the sharded ALS
trainer's collectives actually cross process boundaries: two OS processes
each own 4 virtual CPU devices, jax.distributed stitches them into one
8-device mesh, and both must produce factors that match a single-process
8-device run of the same seed bit-for-bit (same device count => same
reduction order).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np

_WORKER = r"""
import json, os, sys
import numpy as np

pid = int(sys.argv[1])
port = sys.argv[2]
out_path = sys.argv[3]

# the environment preloads jax pinned to its own platform; as in
# tests/conftest.py the backend is not initialized yet, so config applies
import jax
jax.config.update("jax_platforms", "cpu")

from predictionio_tpu.parallel.mesh import get_mesh, init_distributed
init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())  # 2 hosts x 4 local

from predictionio_tpu.ops import als
from predictionio_tpu.parallel import als_dist

rng = np.random.default_rng(77)           # identical data on both hosts
n_u, n_i, nnz = 120, 60, 2500
u = rng.integers(0, n_u, nnz).astype(np.int32)
i = rng.integers(0, n_i, nnz).astype(np.int32)
r = rng.uniform(0.5, 5.0, nnz).astype(np.float32)
data = als.prepare_ratings(u, i, r, n_u, n_i)

mesh = get_mesh()                          # all 8 GLOBAL devices
try:
    U, V = als_dist.train_explicit_sharded(mesh, data, rank=5, iterations=4,
                                           lambda_=0.05, seed=9)
except Exception as e:  # capability gate, not error handling: some
    # backends (jaxlib 0.4.x CPU) cannot RUN computations that span
    # processes at all — report the capability gap to the parent so it
    # can skip with the reason instead of failing the suite
    if "Multiprocess computations aren't implemented" in str(e):
        with open(out_path, "w") as f:
            json.dump({"unsupported": str(e).splitlines()[-1]}, f)
        sys.exit(0)
    raise

# hybrid kernel across the same two-process mesh: the dense-hot psum and
# per-device D shards must also work over DCN (K lowered so the split
# engages at this scale)
os.environ["PIO_ALS_HOT_K"] = "8"
os.environ["PIO_ALS_DENSE_MIN_COUNT"] = "4"
Uh, Vh = als_dist.train_explicit_sharded(mesh, data, rank=5, iterations=4,
                                         lambda_=0.05, seed=9,
                                         kernel="hybrid")
with open(out_path, "w") as f:
    json.dump({"U": np.asarray(U).tolist(), "V": np.asarray(V).tolist(),
               "Uh": np.asarray(Uh).tolist(),
               "Vh": np.asarray(Vh).tolist(),
               "process_count": jax.process_count()}, f)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_matches_single_process(tmp_path, monkeypatch):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
           "PYTHONPATH": os.pathsep.join(
               [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
               + os.environ.get("PYTHONPATH", "").split(os.pathsep)),
           }
    outs = [tmp_path / "out0.json", tmp_path / "out1.json"]
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(pid), str(port), str(outs[pid])],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in (0, 1)]
    try:
        logs = [p.communicate(timeout=280)[0].decode(errors="replace")
                for p in procs]
    finally:
        for p in procs:   # a deadlocked worker must not outlive the test
            if p.poll() is None:
                p.kill()
    for pid, p in enumerate(procs):
        assert p.returncode == 0, f"worker {pid} failed:\n{logs[pid][-3000:]}"

    got = [json.loads(o.read_text()) for o in outs]
    unsupported = [g["unsupported"] for g in got if "unsupported" in g]
    if unsupported:
        import pytest
        pytest.skip("backend does not support multiprocess computations "
                    f"(two-process DCN leg needs a real multi-host "
                    f"platform here): {unsupported[0]}")
    assert got[0]["process_count"] == 2
    # both processes computed (and can read) the SAME replicated factors
    np.testing.assert_array_equal(np.asarray(got[0]["U"]),
                                  np.asarray(got[1]["U"]))
    np.testing.assert_array_equal(np.asarray(got[0]["V"]),
                                  np.asarray(got[1]["V"]))

    # and they match a single-process run over the same 8-device mesh
    from predictionio_tpu.ops import als
    from predictionio_tpu.parallel import als_dist
    from predictionio_tpu.parallel.mesh import get_mesh

    rng = np.random.default_rng(77)
    n_u, n_i, nnz = 120, 60, 2500
    u = rng.integers(0, n_u, nnz).astype(np.int32)
    i = rng.integers(0, n_i, nnz).astype(np.int32)
    r = rng.uniform(0.5, 5.0, nnz).astype(np.float32)
    data = als.prepare_ratings(u, i, r, n_u, n_i)
    U, V = als_dist.train_explicit_sharded(get_mesh(8), data, rank=5,
                                           iterations=4, lambda_=0.05,
                                           seed=9)
    np.testing.assert_allclose(np.asarray(got[0]["U"]), np.asarray(U),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got[0]["V"]), np.asarray(V),
                               rtol=1e-5, atol=1e-6)

    # hybrid leg: two-process result matches a single-process 8-device
    # hybrid run (same K/min-count env as the workers). Tolerance is
    # looser than the csrb leg: the split-bf16 dense partials reduce via
    # psum, and a 2-process (DCN) reduction tree orders the f32 adds
    # differently than the single-program one — ~1e-5 drift is reduction
    # order, not divergence (iterated 4x through the solve).
    monkeypatch.setenv("PIO_ALS_HOT_K", "8")
    monkeypatch.setenv("PIO_ALS_DENSE_MIN_COUNT", "4")
    Uh, Vh = als_dist.train_explicit_sharded(
        get_mesh(8), data, rank=5, iterations=4, lambda_=0.05, seed=9,
        kernel="hybrid")
    np.testing.assert_array_equal(np.asarray(got[0]["Uh"]),
                                  np.asarray(got[1]["Uh"]))
    np.testing.assert_allclose(np.asarray(got[0]["Uh"]), np.asarray(Uh),
                               rtol=1e-4, atol=5e-5)
    np.testing.assert_allclose(np.asarray(got[0]["Vh"]), np.asarray(Vh),
                               rtol=1e-4, atol=5e-5)
