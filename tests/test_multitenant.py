"""Multi-tenant serving tests (serving/registry.py + the --engines
deploy path): registry generations + HBM budgets, per-access-key
admission (401/429), one process serving N engine instances with
per-key wire routing, per-tenant saturation isolation, shared-AOT
compile flatness, and legacy single-tenant wire parity."""

import dataclasses
import json
import threading
import time

import pytest

from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import AccessKey, App
from predictionio_tpu.serving import registry as registry_mod
from predictionio_tpu.serving.registry import (
    AdmissionController, AdmissionError, ModelRegistry, ServableModel,
    TenantSpec, load_engines_conf, model_hbm_bytes, parse_tenant_specs,
)
from predictionio_tpu.workflow import WorkflowContext, run_train
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig


# ---------------------------------------------------------------------------
# training helpers: N independent apps, each its own trained instance
# ---------------------------------------------------------------------------

def _train_als(storage, app_name, key, invert=False):
    """One ALS app + COMPLETED instance + access key. ``invert`` flips
    the parity signal so two tenants' models give DIFFERENT answers to
    the same query — the wire-isolation assertion needs that."""
    import datetime as dt

    from predictionio_tpu.data import store
    from predictionio_tpu.models.recommendation import (
        ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
    )
    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(0, app_name, None))
    storage.get_events().init(app_id)
    storage.get_meta_data_access_keys().insert(AccessKey(key, app_id, ()))
    events = []
    minute = 0
    for u in range(8):
        for i in range(6):
            minute += 1
            match = (u % 2) == (i % 2)
            if invert:
                match = not match
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": 5.0 if match else 1.0}),
                event_time=dt.datetime(2021, 1, 1, 0, minute % 60,
                                       tzinfo=dt.timezone.utc)))
    store.write(events, app_id, storage=storage)
    ep = EngineParams(
        data_source_params=DataSourceParams(appName=app_name),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=4, numIterations=5,
                                       lambda_=0.05, seed=3)),))
    iid = run_train(
        WorkflowContext(storage=storage), RecommendationEngine(), ep,
        engine_factory=("predictionio_tpu.models.recommendation"
                        ":RecommendationEngine"),
        params_json={
            "datasource": {"params": {"appName": app_name}},
            "algorithms": [{"name": "als", "params": {
                "rank": 4, "numIterations": 5, "lambda": 0.05,
                "seed": 3}}]})
    return app_id, iid


def _train_cls(storage, app_name, key):
    """One classification app + instance + key — the host-served
    template tenant (NaiveBayes has no batched predict, so `auto`
    batching keeps the inline path for it)."""
    import datetime as dt

    from predictionio_tpu.data import store
    from predictionio_tpu.models.classification import (
        ClassificationEngine, DataSourceParams, NaiveBayesAlgorithmParams,
    )
    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(0, app_name, None))
    storage.get_events().init(app_id)
    storage.get_meta_data_access_keys().insert(AccessKey(key, app_id, ()))
    events = []
    for n in range(20):
        plan = n % 2
        lo, hi = 0.0 + (n % 3), 8.0 + (n % 3)
        events.append(Event(
            event="$set", entity_type="user", entity_id=f"u{n}",
            properties=DataMap({
                "plan": float(plan),
                "attr0": hi if plan == 0 else lo,
                "attr1": 2.0,
                "attr2": lo if plan == 0 else hi}),
            event_time=dt.datetime(2021, 1, 1, 0, n % 60,
                                   tzinfo=dt.timezone.utc)))
    store.write(events, app_id, storage=storage)
    ep = EngineParams(
        data_source_params=DataSourceParams(appName=app_name),
        algorithm_params_list=(
            ("naive", NaiveBayesAlgorithmParams(lambda_=1.0)),))
    iid = run_train(
        WorkflowContext(storage=storage), ClassificationEngine(), ep,
        engine_factory=("predictionio_tpu.models.classification"
                        ":ClassificationEngine"),
        params_json={
            "datasource": {"params": {"appName": app_name}},
            "algorithms": [{"name": "naive", "params": {"lambda": 1.0}}]})
    return app_id, iid


@pytest.fixture()
def mt_trained(memory_storage):
    """Two ALS tenants (opposite parity signals) + one host-served
    classification tenant, each with its own app and access key."""
    a = _train_als(memory_storage, "TenantA", "key-a")
    b = _train_als(memory_storage, "TenantB", "key-b", invert=True)
    c = _train_cls(memory_storage, "TenantC", "key-c")
    return memory_storage, {"a": a, "b": b, "c": c}


def _specs(tenants, **overrides):
    """TenantSpecs for the trained fixture, one per tenant name."""
    out = []
    for name, (_app_id, iid) in tenants.items():
        extra = overrides.get(name, {})
        out.append(TenantSpec(
            name=name, access_key=f"key-{name}",
            engine_instance_id=iid, **extra))
    return tuple(out)


def _resp(api, body, key=None):
    query = {"accessKey": key} if key else None
    r = api.handle("POST", "/queries.json", query=query,
                   body=json.dumps(body).encode())
    status, payload = r[0], r[1]
    headers = r[2] if len(r) == 3 else {}
    return status, payload, headers


# ---------------------------------------------------------------------------
# conf parsing
# ---------------------------------------------------------------------------

class TestEnginesConf:
    def test_parse_shapes(self):
        specs = parse_tenant_specs([{"name": "a"}, {"name": "b"}])
        assert [s.name for s in specs] == ["a", "b"]
        specs = parse_tenant_specs({"tenants": [
            {"name": "a", "accessKey": "k", "batchMaxQueue": 8,
             "hbmBudgetMb": 128, "rate": 10, "burst": 20}]})
        s = specs[0]
        assert s.access_key == "k" and s.batch_max_queue == 8
        assert s.hbm_budget_mb == 128 and s.rate == 10 and s.burst == 20

    @pytest.mark.parametrize("bad,match", [
        ([], "non-empty list"),
        ({"tenants": {}}, "non-empty list"),
        (["x"], "not an object"),
        ([{"name": "a", "hbmBudget": 1}], "unknown key"),
        ([{"name": ""}], "has no name"),
        ([{"accessKey": "k"}], "has no name"),
        ([{"name": "a"}, {"name": "a"}], "not unique"),
        ([{"name": "a", "accessKey": "k"},
          {"name": "b", "accessKey": "k"}], "keys are not unique"),
    ])
    def test_parse_rejects(self, bad, match):
        with pytest.raises(ValueError, match=match):
            parse_tenant_specs(bad)

    def test_load_conf_file(self, tmp_path):
        p = tmp_path / "engines.json"
        p.write_text(json.dumps([{"name": "a"}, {"name": "b"}]))
        assert len(load_engines_conf(str(p))) == 2
        p.write_text("{nope")
        with pytest.raises(ValueError, match="not valid JSON"):
            load_engines_conf(str(p))


# ---------------------------------------------------------------------------
# registry: generations, budgets, hard cap
# ---------------------------------------------------------------------------

class _Inst:
    def __init__(self, iid):
        self.id = iid


def _servable(name, model_bytes=0, budget_mb=None):
    return ServableModel(
        name=name,
        spec=TenantSpec(name=name, hbm_budget_mb=budget_mb),
        instance=_Inst(f"i-{name}"), engine=None, engine_params=None,
        algorithms=[], models=[], serving=None,
        model_bytes=model_bytes)


class TestModelRegistry:
    def test_per_tenant_generations(self):
        reg = ModelRegistry(hard_cap_mb=None)
        assert reg.install(_servable("a")) is None
        reg.install(_servable("b"))
        assert reg.generations() == {"a": 1, "b": 1}
        prior = reg.install(_servable("a"))      # hot-swap a only
        assert prior is not None and prior.generation == 1
        assert reg.generations() == {"a": 2, "b": 1}
        assert reg.names() == ["a", "b"] and len(reg) == 2

    def test_soft_budget_flags_not_refuses(self):
        reg = ModelRegistry(hard_cap_mb=None)
        s = _servable("fat", model_bytes=3 * 1024 * 1024, budget_mb=2)
        reg.install(s)                           # serves anyway
        assert s.over_budget and reg.oversubscribed() == ["fat"]
        state = reg.get("fat").state()
        assert state["overBudget"] and state["budgetMb"] == 2

    def test_hard_cap_refuses_and_keeps_prior(self):
        reg = ModelRegistry(hard_cap_mb=4)
        first = _servable("a", model_bytes=3 * 1024 * 1024)
        reg.install(first)
        with pytest.raises(ValueError, match="hard HBM cap"):
            reg.install(_servable("b", model_bytes=2 * 1024 * 1024))
        assert reg.names() == ["a"]              # b never published
        # a reload of `a` itself that grows past the cap is refused too
        # and generation 1 keeps serving
        with pytest.raises(ValueError, match="hard HBM cap"):
            reg.install(_servable("a", model_bytes=5 * 1024 * 1024))
        assert reg.get("a") is first and first.generation == 1

    def test_model_hbm_bytes_walks_arrays(self):
        import numpy as np

        class M:
            def __init__(self):
                self.x = np.zeros((4, 4), dtype=np.float32)
                self.d = {"y": np.zeros(8, dtype=np.float64)}
                self.t = (np.zeros(2, dtype=np.int32),)
                self.alias = self.x              # same array: not double-counted
                self.s = "not-an-array"

        assert model_hbm_bytes([M()]) == 4 * 4 * 4 + 8 * 8 + 2 * 4
        assert model_hbm_bytes([None]) == 0


# ---------------------------------------------------------------------------
# admission: 401 / 429
# ---------------------------------------------------------------------------

class TestAdmission:
    def _controller(self, storage, tenants, **kw):
        by_appid = {app_id: name
                    for name, (app_id, _iid) in tenants.items()}
        return AdmissionController(storage, by_appid, **kw)

    def test_resolve_and_401(self, mt_trained):
        storage, tenants = mt_trained
        adm = self._controller(storage, tenants)
        assert adm.admit("key-a") == "a"
        assert adm.admit("key-b") == "b"
        with pytest.raises(AdmissionError) as ei:
            adm.admit(None)
        assert ei.value.status == 401 and "Missing" in ei.value.message
        with pytest.raises(AdmissionError) as ei:
            adm.admit("nope")
        assert ei.value.status == 401 and "Invalid" in ei.value.message

    def test_key_created_after_deploy_works(self, mt_trained):
        storage, tenants = mt_trained
        adm = self._controller(storage, tenants)
        with pytest.raises(AdmissionError):
            adm.admit("late-key")
        app_id = tenants["a"][0]
        storage.get_meta_data_access_keys().insert(
            AccessKey("late-key", app_id, ()))
        assert adm.admit("late-key") == "a"      # no negative cache

    def test_rate_limit_429_retry_after(self, mt_trained):
        storage, tenants = mt_trained
        adm = self._controller(
            storage, tenants,
            tenant_limits={"a": (1.0, 1.0), "b": (None, None)})
        assert adm.admit("key-a") == "a"         # burst of 1
        with pytest.raises(AdmissionError) as ei:
            adm.admit("key-a")
        assert ei.value.status == 429
        assert ei.value.retry_after_s >= 1
        # tenant b is unlimited (rate 0 default): the flood on a never
        # touches b's bucket
        for _ in range(20):
            assert adm.admit("key-b") == "b"


# ---------------------------------------------------------------------------
# the tentpole: one process, three engines, per-key wire routing
# ---------------------------------------------------------------------------

class TestMultiTenantDeploy:
    def test_three_engines_wire_isolation(self, mt_trained):
        storage, tenants = mt_trained
        api = QueryAPI(storage=storage, config=ServerConfig(
            tenants=_specs(tenants)))
        try:
            # tenant a: trained so even users prefer even items
            status, body, headers = _resp(
                api, {"user": "u2", "num": 3}, key="key-a")
            assert status == 200
            assert headers.get("X-PIO-Tenant") == "a"
            top_a = body["itemScores"][0]["item"]
            assert top_a in {"i0", "i2", "i4"}
            # tenant b: the SAME query body through b's key hits the
            # inverted model — even users prefer odd items. Same wire,
            # different model: per-key routing proven at the response.
            status, body, headers = _resp(
                api, {"user": "u2", "num": 3}, key="key-b")
            assert status == 200
            assert headers.get("X-PIO-Tenant") == "b"
            assert body["itemScores"][0]["item"] in {"i1", "i3", "i5"}
            # tenant c: a different engine TEMPLATE entirely
            # (classification, host-served inline path)
            status, body, headers = _resp(
                api, {"features": [9.0, 2.0, 1.0]}, key="key-c")
            assert status == 200 and body["label"] == 0.0
            assert headers.get("X-PIO-Tenant") == "c"
            # no key / unknown key: admission 401s before any model work
            status, body, _ = _resp(api, {"user": "u2", "num": 3})
            assert status == 401 and "Missing" in body["message"]
            status, body, _ = _resp(api, {"user": "u2", "num": 3},
                                    key="bogus")
            assert status == 401 and "Invalid" in body["message"]
        finally:
            api.close()

    def test_status_and_readyz_per_tenant(self, mt_trained):
        storage, tenants = mt_trained
        api = QueryAPI(storage=storage, config=ServerConfig(
            tenants=_specs(tenants)))
        try:
            status, info = api.handle("GET", "/")
            assert status == 200
            assert set(info["tenants"]) == {"a", "b", "c"}
            assert info["generations"] == {"a": 1, "b": 1, "c": 1}
            assert info["generation"] == 1
            for name, block in info["tenants"].items():
                assert block["generation"] == 1
                assert block["instanceId"] == tenants[name][1]
                assert "queueDepth" in block and "modelBytes" in block
            assert info["modelBytesTotal"] == sum(
                t["modelBytes"] for t in info["tenants"].values())
            status, ready = api.handle("GET", "/readyz")
            assert status == 200 and ready["status"] == "ready"
            assert ready["generations"] == {"a": 1, "b": 1, "c": 1}
            assert ready["modelLoaded"] is True
        finally:
            api.close()

    def test_rate_limited_tenant_429_on_wire(self, mt_trained):
        storage, tenants = mt_trained
        api = QueryAPI(storage=storage, config=ServerConfig(
            tenants=_specs(tenants, a={"rate": 1.0, "burst": 1.0})))
        try:
            status, _, _ = _resp(api, {"user": "u1", "num": 2}, key="key-a")
            assert status == 200
            r = api.handle("POST", "/queries.json",
                           query={"accessKey": "key-a"},
                           body=json.dumps({"user": "u1", "num": 2}).encode())
            assert r[0] == 429 and int(r[2]["Retry-After"]) >= 1
            # b is untouched by a's limit
            status, _, _ = _resp(api, {"user": "u1", "num": 2}, key="key-b")
            assert status == 200
        finally:
            api.close()

    def test_hard_cap_refuses_deploy(self, mt_trained, monkeypatch):
        storage, tenants = mt_trained
        monkeypatch.setenv("PIO_TENANT_HBM_HARD_CAP_MB", "0.0001")
        with pytest.raises(ValueError, match="hard HBM cap"):
            QueryAPI(storage=storage, config=ServerConfig(
                tenants=_specs(tenants)))

    def test_soft_budget_reported_oversubscribed(self, mt_trained):
        storage, tenants = mt_trained
        api = QueryAPI(storage=storage, config=ServerConfig(
            tenants=_specs(tenants, a={"hbm_budget_mb": 1e-6})))
        try:
            status, info = api.handle("GET", "/")
            assert info["oversubscribed"] == ["a"]
            assert info["tenants"]["a"]["overBudget"] is True
            # over budget is a WARN, not an outage: a still serves
            status, _, _ = _resp(api, {"user": "u1", "num": 2}, key="key-a")
            assert status == 200
        finally:
            api.close()

    def test_duplicate_app_resolution_refused(self, mt_trained):
        storage, tenants = mt_trained
        iid_a = tenants["a"][1]
        specs = (TenantSpec(name="a", access_key="key-a",
                            engine_instance_id=iid_a),
                 # same instance, no key: falls back to the datasource
                 # appName -> the SAME app -> ambiguous per-key routing
                 TenantSpec(name="a2", engine_instance_id=iid_a))
        with pytest.raises(ValueError, match="both resolve to app id"):
            QueryAPI(storage=storage, config=ServerConfig(tenants=specs))


# ---------------------------------------------------------------------------
# noisy neighbor: saturation isolation at the wire
# ---------------------------------------------------------------------------

def _gate_tenant_batcher(api, name):
    """tests/test_create_server.py's _gated_batcher, aimed at one
    tenant's OWN batcher."""
    entered = threading.Event()
    gate = threading.Event()
    batcher = api.registry.get(name).batcher
    real = batcher._flush_fn

    def gated(items):
        entered.set()
        gate.wait(30)
        return real(items)

    batcher._flush_fn = gated
    return gate, entered


def test_tenant_saturation_is_isolated(mt_trained):
    """Flooding tenant a 503s tenant a ONLY: b keeps answering 200 from
    its own queue while a's 1-slot queue rejects — the per-tenant
    batcher claim asserted at the wire."""
    storage, tenants = mt_trained
    api = QueryAPI(storage=storage, config=ServerConfig(
        batching="on", batch_max_size=1, batch_max_delay_ms=1.0,
        tenants=_specs(tenants, a={"batch_max_queue": 1})))
    gate, entered = _gate_tenant_batcher(api, "a")
    try:
        threads = [threading.Thread(
            target=_resp, args=(api, {"user": "u1", "num": 2}, "key-a"))]
        threads[0].start()
        assert entered.wait(10)          # a's worker provably mid-flush
        t = threading.Thread(
            target=_resp, args=(api, {"user": "u1", "num": 2}, "key-a"))
        t.start()
        threads.append(t)                # fills a's 1-slot queue
        batcher = api.registry.get("a").batcher
        deadline = time.time() + 10
        while time.time() < deadline:
            with batcher._cond:
                if len(batcher._q) >= 1:
                    break
            time.sleep(0.01)
        status, body, headers = _resp(api, {"user": "u1", "num": 2},
                                      key="key-a")
        assert status == 503 and "saturated" in body["message"]
        assert int(headers["Retry-After"]) >= 1
        # tenant b — same process, same moment — is untouched
        for _ in range(3):
            status, body, _ = _resp(api, {"user": "u1", "num": 2},
                                    key="key-b")
            assert status == 200 and body["itemScores"]
        # and the host-served tenant c too
        status, body, _ = _resp(api, {"features": [1.0, 2.0, 9.0]},
                                key="key-c")
        assert status == 200 and body["label"] == 1.0
        gate.set()
        for t in threads:
            t.join(30)
    finally:
        gate.set()
        api.close()


# ---------------------------------------------------------------------------
# shared AOT: compile count flat as tenants multiply
# ---------------------------------------------------------------------------

def test_aot_compile_count_flat_across_tenants(mt_trained):
    """Three ALS tenants pad onto ONE (bucket x template x k) program
    set: tenant 1 compiles, tenants 2..N memoize — the total compiled
    count equals a single-tenant deploy's."""
    from predictionio_tpu.serving import aot

    storage, tenants = mt_trained
    third = _train_als(storage, "TenantD", "key-d")
    all_als = {"a": tenants["a"], "b": tenants["b"], "d": third}

    def deploy(names):
        aot.reset_memo()
        specs = _specs({n: all_als[n] for n in names})
        api = QueryAPI(storage=storage, config=ServerConfig(
            batching="on", aot="on", tenants=specs))
        try:
            states = [api.registry.get(n).aot_state for n in names]
            assert all(s and s.get("enabled") for s in states)
            return states
        finally:
            api.close()

    solo = deploy(["a"])
    compiled_solo = solo[0]["compiled"]
    assert compiled_solo > 0

    states = deploy(["a", "b", "d"])
    compiled_total = sum(s["compiled"] for s in states)
    assert compiled_total == compiled_solo, (
        f"compile count grew with tenant count: "
        f"{compiled_total} != {compiled_solo}")
    # the later tenants' programs were memo hits, not new compiles
    assert states[1]["compiled"] == 0 and states[2]["compiled"] == 0
    assert states[1]["memoized"] == compiled_solo
    assert states[2]["memoized"] == compiled_solo


# ---------------------------------------------------------------------------
# legacy parity: no --engines => the exact single-tenant wire shape
# ---------------------------------------------------------------------------

def test_legacy_wire_shape_without_engines_conf(mt_trained):
    """A deploy WITHOUT tenants keeps the exact legacy key set on
    `GET /` and /readyz — no tenants/generations leakage — and
    /queries.json answers the legacy 2-tuple (no X-PIO-Tenant)."""
    storage, tenants = mt_trained
    api = QueryAPI(storage=storage, config=ServerConfig(
        engine_instance_id=tenants["a"][1]))
    try:
        status, info = api.handle("GET", "/")
        assert status == 200
        assert set(info) == {
            "status", "engineInstance", "algorithms", "requestCount",
            "avgServingSec", "lastServingSec", "degradedCount",
            "draining", "serverStartTime", "generation", "batching",
            "aot"}
        status, ready = api.handle("GET", "/readyz")
        assert status == 200
        assert "generations" not in ready and "queueDepths" not in ready
        r = api.handle("POST", "/queries.json",
                       body=json.dumps({"user": "u1", "num": 2}).encode())
        assert r[0] == 200 and len(r) == 2
        # the registry still tracks the model internally (under the
        # reserved 'default' name) without leaking onto the wire
        assert api.registry.names() == [registry_mod.DEFAULT_TENANT]
    finally:
        api.close()
