"""Multinomial NB parity with the MLlib formulation (classification template)."""

import numpy as np

from predictionio_tpu.ops import naive_bayes as nb


def test_closed_form_parity():
    X = np.array([[1, 0, 2], [2, 1, 0], [0, 3, 1], [1, 1, 1]], dtype=np.float32)
    y = np.array([0, 0, 1, 1], dtype=np.int32)
    lam = 1.0
    model = nb.train(X, y, lambda_=lam)

    for c in range(2):
        sel = y == c
        expected_pi = np.log((sel.sum() + lam) / (len(y) + 2 * lam))
        np.testing.assert_allclose(float(model.pi[c]), expected_pi, rtol=1e-5)
        fsum = X[sel].sum(axis=0)
        expected_theta = np.log((fsum + lam) / (fsum.sum() + 3 * lam))
        np.testing.assert_allclose(np.asarray(model.theta)[c], expected_theta,
                                   rtol=1e-5)


def test_predict_separable():
    rng = np.random.default_rng(0)
    # class 0 heavy on features 0-1, class 1 heavy on features 2-3
    n = 200
    X0 = rng.poisson([5, 5, 0.5, 0.5], size=(n, 4))
    X1 = rng.poisson([0.5, 0.5, 5, 5], size=(n, 4))
    X = np.vstack([X0, X1]).astype(np.float32)
    y = np.array([0] * n + [1] * n, dtype=np.int32)
    model = nb.train(X, y, lambda_=1.0)
    acc = (np.asarray(nb.predict(model, X)) == y).mean()
    assert acc > 0.95


def test_predict_proba_normalized():
    X = np.array([[1.0, 2.0]], dtype=np.float32)
    model = nb.train(np.array([[1, 0], [0, 1]], dtype=np.float32),
                     np.array([0, 1], dtype=np.int32))
    p = np.asarray(nb.predict_proba(model, X))
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-6)
    assert p.shape == (1, 2)


def test_single_sample_predict():
    model = nb.train(np.array([[3, 0], [0, 3]], dtype=np.float32),
                     np.array([0, 1], dtype=np.int32))
    assert int(nb.predict(model, np.array([5.0, 0.0]))[0]) == 0
    assert int(nb.predict(model, np.array([0.0, 5.0]))[0]) == 1
