"""Multinomial NB parity with the MLlib formulation (classification template)."""

import numpy as np

from predictionio_tpu.ops import naive_bayes as nb


def test_closed_form_parity():
    X = np.array([[1, 0, 2], [2, 1, 0], [0, 3, 1], [1, 1, 1]], dtype=np.float32)
    y = np.array([0, 0, 1, 1], dtype=np.int32)
    lam = 1.0
    model = nb.train(X, y, lambda_=lam)

    for c in range(2):
        sel = y == c
        expected_pi = np.log((sel.sum() + lam) / (len(y) + 2 * lam))
        np.testing.assert_allclose(float(model.pi[c]), expected_pi, rtol=1e-5)
        fsum = X[sel].sum(axis=0)
        expected_theta = np.log((fsum + lam) / (fsum.sum() + 3 * lam))
        np.testing.assert_allclose(np.asarray(model.theta)[c], expected_theta,
                                   rtol=1e-5)


def test_predict_separable():
    rng = np.random.default_rng(0)
    # class 0 heavy on features 0-1, class 1 heavy on features 2-3
    n = 200
    X0 = rng.poisson([5, 5, 0.5, 0.5], size=(n, 4))
    X1 = rng.poisson([0.5, 0.5, 5, 5], size=(n, 4))
    X = np.vstack([X0, X1]).astype(np.float32)
    y = np.array([0] * n + [1] * n, dtype=np.int32)
    model = nb.train(X, y, lambda_=1.0)
    acc = (np.asarray(nb.predict(model, X)) == y).mean()
    assert acc > 0.95


def test_predict_proba_normalized():
    X = np.array([[1.0, 2.0]], dtype=np.float32)
    model = nb.train(np.array([[1, 0], [0, 1]], dtype=np.float32),
                     np.array([0, 1], dtype=np.int32))
    p = np.asarray(nb.predict_proba(model, X))
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-6)
    assert p.shape == (1, 2)


def test_single_sample_predict():
    model = nb.train(np.array([[3, 0], [0, 3]], dtype=np.float32),
                     np.array([0, 1], dtype=np.int32))
    assert int(nb.predict(model, np.array([5.0, 0.0]))[0]) == 0
    assert int(nb.predict(model, np.array([0.0, 5.0]))[0]) == 1


class TestRandomForest:
    """add-algorithm tutorial's RandomForestAlgorithm variant."""

    @staticmethod
    def xor_data(n=400, seed=0):
        """XOR-ish: NB (linear in log space) cannot separate; trees can."""
        rng = np.random.default_rng(seed)
        x = rng.uniform(-1, 1, (n, 4))
        y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.float64) * 2 + 1.0
        return x, y     # labels 1.0 / 3.0 (plan-id style floats)

    def make_td(self, x, y):
        from predictionio_tpu.models.classification.data_source import (
            LabeledPoint, TrainingData)
        return TrainingData(labeled_points=[
            LabeledPoint(label=float(lbl),
                         features=tuple(float(v) for v in row))
            for row, lbl in zip(x, y)])

    def test_forest_learns_xor_and_nb_cannot(self):
        from predictionio_tpu.models.classification.engine import Query
        from predictionio_tpu.models.classification.random_forest import (
            RandomForestAlgorithm, RandomForestAlgorithmParams)
        x, y = self.xor_data()
        td = self.make_td(x, y)
        algo = RandomForestAlgorithm(RandomForestAlgorithmParams(
            numClasses=2, numTrees=15, maxDepth=6, seed=3))
        model = algo.train(None, td)
        xt, yt = self.xor_data(n=200, seed=1)
        preds = np.array([algo.predict(model, Query(tuple(row))).label
                          for row in xt])
        acc = float((preds == yt).mean())
        assert acc > 0.9, acc
        # labels round-trip as the original floats
        assert set(preds.tolist()) <= {1.0, 3.0}
        # the contrast in the name: NB's linear decision stays near chance
        from predictionio_tpu.models.classification.nb_algorithm import (
            NaiveBayesAlgorithm, NaiveBayesAlgorithmParams)
        nb = NaiveBayesAlgorithm(NaiveBayesAlgorithmParams(lambda_=1.0))
        nb_model = nb.train(None, td)
        nb_preds = np.array([nb.predict(nb_model, Query(tuple(row))).label
                             for row in xt])
        nb_acc = float((nb_preds == yt).mean())
        assert nb_acc < 0.7, nb_acc

    def test_params_surface_matches_reference(self):
        from predictionio_tpu.models.classification.random_forest import (
            RandomForestAlgorithmParams)
        p = RandomForestAlgorithmParams(
            numClasses=3, numTrees=5, featureSubsetStrategy="sqrt",
            impurity="entropy", maxDepth=4, maxBins=16)
        assert (p.numClasses, p.numTrees, p.impurity) == (3, 5, "entropy")

    def test_single_tree_auto_uses_all_features(self):
        from predictionio_tpu.models.classification.random_forest import (
            _n_features_per_split)
        assert _n_features_per_split("auto", 9, 1) == 9      # MLlib rule
        assert _n_features_per_split("auto", 9, 10) == 3
        assert _n_features_per_split("log2", 9, 10) == 3

    def test_batch_predict_matches_predict(self):
        from predictionio_tpu.models.classification.engine import Query
        from predictionio_tpu.models.classification.random_forest import (
            RandomForestAlgorithm, RandomForestAlgorithmParams)
        x, y = self.xor_data(n=200, seed=4)
        td = self.make_td(x, y)
        algo = RandomForestAlgorithm(RandomForestAlgorithmParams(
            numClasses=2, numTrees=7, maxDepth=5, seed=9))
        model = algo.train(None, td)
        xt, _ = self.xor_data(n=40, seed=5)
        queries = [(qi, Query(tuple(row))) for qi, row in enumerate(xt)]
        batch = dict(algo.batch_predict(model, queries))
        for qi, q in queries:
            assert batch[qi].label == algo.predict(model, q).label
        assert algo.batch_predict(model, []) == []
