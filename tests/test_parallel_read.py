"""Parallel, overlapped bulk-read pipeline (ISSUE 2).

The chunk decode of eventlog.read_columns runs on a thread pool and the
shard lock shrinks to the refresh + snapshot — so:

- results must be BYTE-identical at any worker count (tombstones, a WAL
  tail, and string-coded ratings included), with PIO_READ_THREADS=1
  reproducing the serial path exactly;
- concurrent ingest into the same shard must proceed (and neither side
  corrupt) while a multi-second scan is in flight;
- the device-staged mirrors (ops/staging.py) must match the host columns
  bit for bit and train to identical factors;
- the eval grid must build each fold's device layout once, shared across
  rank-compatible variants (fast_eval.prepare_shared_layouts).
"""

import datetime as dt
import threading

import numpy as np
import pytest

from predictionio_tpu.data import store
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.data.storage import eventlog as el_mod

UTC = dt.timezone.utc

COLS = ("entity_code", "target_code", "event_code", "rating", "time_ms")


def el_storage(tmp_path):
    s = Storage(env={
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
        "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path / "el"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    })
    app_id = s.get_meta_data_apps().insert(App(0, "app"))
    s.get_events().init(app_id)
    return s, app_id


def seed_messy_store(tmp_path, monkeypatch, n=240, flush_at=60):
    """Multi-chunk store with buy events, string-coded ratings, a WAL tail
    and tombstones in both a chunk and the tail."""
    monkeypatch.setattr(el_mod, "_FLUSH_AT", flush_at)
    s, app_id = el_storage(tmp_path)
    ev = s.get_events()
    rng = np.random.default_rng(0)
    evs = []
    for j in range(n):
        name = "buy" if j % 5 == 0 else "rate"
        if name == "buy":
            props = {}
        elif j % 7 == 0:
            props = {"rating": f"{rng.integers(1, 10) / 2}"}  # string-coded
        else:
            props = {"rating": float(rng.integers(2, 11) / 2)}
        evs.append(Event(
            event=name, entity_type="user", entity_id=f"u{j % 17}",
            target_entity_type="item", target_entity_id=f"i{j % 11}",
            properties=DataMap(props),
            event_time=dt.datetime(2021, 1, 1, tzinfo=UTC)
            + dt.timedelta(seconds=j)))
    ids = []
    for lo in range(0, n, flush_at):     # one chunk per batch
        ids.extend(ev.insert_batch(evs[lo:lo + flush_at], app_id))
    tail = [Event(event="rate", entity_type="user", entity_id=f"u{k}",
                  target_entity_type="item", target_entity_id="i1",
                  properties=DataMap({"rating": "3.5"}))
            for k in range(5)]
    tail_ids = ev.insert_batch(tail, app_id)   # unflushed WAL tail
    ev.delete(ids[3], app_id)        # tombstone in a chunk
    ev.delete(tail_ids[2], app_id)   # tombstone in the tail
    sh = ev._shard(app_id, None)
    assert len(sh.chunk_seqs()) >= 3 and sh.buffer
    return s, app_id


def test_parallel_read_byte_identical(tmp_path, monkeypatch):
    s, app_id = seed_messy_store(tmp_path, monkeypatch)
    ev = s.get_events()
    kw = dict(event_names=["rate", "buy"], entity_type="user",
              target_entity_type="item")
    serial = ev.read_columns(app_id, read_threads=1, **kw)
    for threads in (2, 4, 7):
        par = ev.read_columns(app_id, read_threads=threads, **kw)
        assert par["pool"] == serial["pool"]
        for k in COLS:
            assert par[k].tobytes() == serial[k].tobytes(), (threads, k)
    # env knob routes the same way as the argument
    monkeypatch.setenv("PIO_READ_THREADS", "3")
    par = ev.read_columns(app_id, **kw)
    for k in COLS:
        assert par[k].tobytes() == serial[k].tobytes()
    # string-coded + tail ratings actually got coerced (not NaN-dropped)
    assert np.isfinite(serial["rating"]).sum() > 0
    n_rate = int((serial["rating"] == 3.5).sum())
    assert n_rate >= 4   # the 5 tail events minus 1 tombstone contribute


def test_streamed_chunks_concatenate_to_read_columns(tmp_path, monkeypatch):
    s, app_id = seed_messy_store(tmp_path, monkeypatch)
    ev = s.get_events()
    whole = ev.read_columns(app_id, event_names=["rate"])
    pool, chunks = ev.read_columns_streamed(app_id, event_names=["rate"],
                                            read_threads=4)
    parts = list(chunks)
    assert pool == whole["pool"]
    for k in COLS:
        got = (np.concatenate([p[k] for p in parts]) if parts
               else np.empty(0))
        assert got.tobytes() == whole[k].tobytes()


def test_insert_during_long_read_no_deadlock(tmp_path, monkeypatch):
    """The shard lock is released during chunk decode: an insert landing
    mid-scan completes promptly, the in-flight read returns its snapshot,
    and a follow-up read sees the new row."""
    s, app_id = seed_messy_store(tmp_path, monkeypatch)
    ev = s.get_events()
    pre = ev.read_columns(app_id, event_names=["rate", "buy"])

    started, release = threading.Event(), threading.Event()
    orig = el_mod.EventlogEvents._decode_chunk_columns

    def slow_decode(self, sh, seq, *a, **kw):
        started.set()
        assert release.wait(timeout=10), "reader stuck waiting for release"
        return orig(self, sh, seq, *a, **kw)

    monkeypatch.setattr(el_mod.EventlogEvents, "_decode_chunk_columns",
                        slow_decode)
    result = {}

    def reader():
        result["cols"] = ev.read_columns(app_id,
                                         event_names=["rate", "buy"])

    rt = threading.Thread(target=reader)
    rt.start()
    assert started.wait(timeout=10), "read never reached chunk decode"

    ins_done = threading.Event()

    def insert():
        ev.insert(Event(event="rate", entity_type="user",
                        entity_id="u-mid-read",
                        target_entity_type="item", target_entity_id="i0",
                        properties=DataMap({"rating": 5.0})), app_id)
        ins_done.set()

    it = threading.Thread(target=insert)
    it.start()
    # the insert must NOT have to wait for the multi-chunk scan
    assert ins_done.wait(timeout=10), \
        "insert blocked behind an in-flight bulk read"
    release.set()
    rt.join(timeout=30)
    it.join(timeout=5)
    assert not rt.is_alive()
    # the in-flight read returned its pre-insert snapshot, uncorrupted
    for k in COLS:
        assert result["cols"][k].tobytes() == pre[k].tobytes()
    monkeypatch.setattr(el_mod.EventlogEvents, "_decode_chunk_columns", orig)
    post = ev.read_columns(app_id, event_names=["rate", "buy"])
    assert post["rating"].shape[0] == pre["rating"].shape[0] + 1
    assert "u-mid-read" in post["pool"]


def test_streamed_torn_wal_tail_mid_stream(tmp_path, monkeypatch):
    """A torn (crash-interrupted) WAL tail met by a STREAMED scan: the
    unacknowledged partial record is dropped, every acknowledged row
    survives, and the streamed result still concatenates to the bulk
    read byte for byte (the bulk path has this test; this is the
    streamed twin — ISSUE 14 satellite)."""
    s, app_id = seed_messy_store(tmp_path, monkeypatch)
    ev = s.get_events()
    sh = ev._shard(app_id, None)
    wal = sh.wal_path_for(sh.next_seq)
    with open(wal, "ab") as f:
        f.write(b'{"event": "rate", "entityTy')   # torn mid-record
    # fresh DAO: a reader that has never seen the clean tail
    ev2 = type(ev)(ev.client, None)
    whole = ev2.read_columns(app_id, event_names=["rate", "buy"])
    pool, chunks = ev2.read_columns_streamed(
        app_id, event_names=["rate", "buy"], read_threads=3)
    parts = list(chunks)
    assert pool == whole["pool"]
    for k in COLS:
        got = (np.concatenate([p[k] for p in parts]) if parts
               else np.empty(0))
        assert got.tobytes() == whole[k].tobytes()
    # the acknowledged tail rows all made it (5 inserted, 1 tombstoned)
    assert int((whole["rating"] == 3.5).sum()) >= 4


def test_streamed_concurrent_ingest_snapshot(tmp_path, monkeypatch):
    """Ingest landing BETWEEN streamed chunks: the shard lock is only
    held for the snapshot, so inserts proceed mid-scan, the in-flight
    iterator keeps its point-in-time view (no new rows, no dupes), and
    a follow-up read sees everything."""
    s, app_id = seed_messy_store(tmp_path, monkeypatch)
    ev = s.get_events()
    pre = ev.read_columns(app_id, event_names=["rate", "buy"])
    pool, chunks = ev.read_columns_streamed(
        app_id, event_names=["rate", "buy"], read_threads=2)
    it = iter(chunks)
    parts = [next(it)]
    # the scan is mid-flight; this insert must neither block nor leak
    # into the snapshot
    ev.insert(Event(event="rate", entity_type="user",
                    entity_id="u-mid-stream", target_entity_type="item",
                    target_entity_id="i0",
                    properties=DataMap({"rating": 1.5})), app_id)
    parts.extend(it)
    for k in COLS:
        got = np.concatenate([p[k] for p in parts])
        assert got.tobytes() == pre[k].tobytes(), k
    post = ev.read_columns(app_id, event_names=["rate", "buy"])
    assert post["rating"].shape[0] == pre["rating"].shape[0] + 1
    assert "u-mid-stream" in post["pool"]


def test_streamed_compaction_race(tmp_path, monkeypatch):
    """Chunk compaction firing while a streamed scan is mid-iteration:
    the snapshot's buffer tail was copied under the lock and published
    chunks are immutable, so the in-flight iterator yields every
    pre-compaction row exactly once — the rows that just became a chunk
    come from the snapshot copy, never double-counted from the new
    chunk file (and the compaction's WAL GC cannot disturb the decode,
    which reads chunk files only)."""
    s, app_id = seed_messy_store(tmp_path, monkeypatch)
    ev = s.get_events()
    sh = ev._shard(app_id, None)
    assert sh.buffer, "test needs an unflushed tail"
    pre = ev.read_columns(app_id, event_names=["rate", "buy"])
    pool, chunks = ev.read_columns_streamed(
        app_id, event_names=["rate", "buy"], read_threads=2)
    it = iter(chunks)
    parts = [next(it)]
    n_chunks_before = len(sh.chunk_seqs())
    ev.flush(app_id)          # buffer -> chunk mid-stream
    assert len(sh.chunk_seqs()) == n_chunks_before + 1
    parts.extend(it)
    for k in COLS:
        got = np.concatenate([p[k] for p in parts])
        assert got.tobytes() == pre[k].tobytes(), k
    # and a FRESH streamed read over the compacted store agrees too
    pool2, chunks2 = ev.read_columns_streamed(
        app_id, event_names=["rate", "buy"], read_threads=2)
    parts2 = list(chunks2)
    for k in COLS:
        got = np.concatenate([p[k] for p in parts2])
        assert got.tobytes() == pre[k].tobytes(), k


def test_streamed_decode_ahead_bounded(tmp_path, monkeypatch):
    """The decode-ahead window is BOUNDED: with a slow consumer, at most
    O(workers) chunks are decoded beyond what was consumed — a dataset
    much larger than the window can stream through O(chunk) host memory
    (ISSUE 14 tentpole; before this, every decoded chunk buffered in
    completed futures)."""
    monkeypatch.setattr(el_mod, "_FLUSH_AT", 12)
    s, app_id = el_storage(tmp_path)
    ev = s.get_events()
    for lo in range(0, 30 * 12, 12):     # 30 chunks
        ev.insert_batch([
            Event(event="rate", entity_type="user", entity_id=f"u{j%7}",
                  target_entity_type="item", target_entity_id=f"i{j%5}",
                  properties=DataMap({"rating": 3.0}))
            for j in range(lo, lo + 12)], app_id)
    ev.flush(app_id)
    decoded = []
    orig = el_mod.EventlogEvents._decode_chunk_columns

    def counting_decode(self, sh, seq, *a, **kw):
        decoded.append(seq)
        return orig(self, sh, seq, *a, **kw)

    monkeypatch.setattr(el_mod.EventlogEvents, "_decode_chunk_columns",
                        counting_decode)
    threads = 2
    pool, chunks = ev.read_columns_streamed(app_id, event_names=["rate"],
                                            read_threads=threads)
    it = iter(chunks)
    next(it)                      # consume ONE chunk, then stall
    import time
    time.sleep(0.3)               # give eager decode every chance
    window = 2 * threads
    assert len(decoded) <= 1 + window + threads, (
        f"decode-ahead ran {len(decoded)} chunks past a stalled "
        f"consumer (window {window})")
    rest = list(it)
    assert 1 + len(rest) == 30    # everything still arrives, in order


def test_overlap_off_matches_overlap_on(tmp_path, monkeypatch):
    s, app_id = seed_messy_store(tmp_path, monkeypatch)
    kw = dict(event_names=["rate", "buy"], entity_type="user",
              target_entity_type="item", storage=s)
    monkeypatch.setenv("PIO_READ_OVERLAP", "0")
    off = store.find_columnar("app", **kw)
    monkeypatch.setenv("PIO_READ_OVERLAP", "1")
    on = store.find_columnar("app", **kw)
    for attr in ("entity_idx", "target_idx", "event_name_idx", "rating",
                 "event_time_ms"):
        assert getattr(on, attr).tobytes() == getattr(off, attr).tobytes()
    assert on.entity_ids.to_dict() == off.entity_ids.to_dict()
    assert on.target_ids.to_dict() == off.target_ids.to_dict()
    assert on.event_names == off.event_names


def test_staged_mirrors_match_host_columns(tmp_path, monkeypatch):
    s, app_id = seed_messy_store(tmp_path, monkeypatch)
    col = store.find_columnar(
        "app", event_names=["rate", "buy"], entity_type="user",
        target_entity_type="item", storage=s, stage=True)
    assert col.staged is not None and col.staged.n == col.n
    np.testing.assert_array_equal(np.asarray(col.staged.entity_idx),
                                  col.entity_idx)
    np.testing.assert_array_equal(np.asarray(col.staged.target_idx),
                                  col.target_idx)
    np.testing.assert_array_equal(np.asarray(col.staged.event_name_idx),
                                  col.event_name_idx)
    assert np.asarray(col.staged.rating).tobytes() == col.rating.tobytes()
    # the template's device-side buy mapping mirrors the host one
    from predictionio_tpu.models.recommendation.data_source import (
        training_data_from_columnar,
    )
    td = training_data_from_columnar(col)
    u_d, i_d, r_d = td._staged_coo
    np.testing.assert_array_equal(np.asarray(u_d), td.user_idx)
    np.testing.assert_array_equal(np.asarray(i_d), td.item_idx)
    assert np.asarray(r_d).tobytes() == td.rating.tobytes()


def test_staged_and_host_train_identically(tmp_path, monkeypatch):
    from predictionio_tpu.models.recommendation.als_algorithm import (
        ALSAlgorithm, ALSAlgorithmParams,
    )
    from predictionio_tpu.models.recommendation.data_source import (
        training_data_from_columnar,
    )
    s, app_id = seed_messy_store(tmp_path, monkeypatch)
    kw = dict(event_names=["rate", "buy"], entity_type="user",
              target_entity_type="item", storage=s)
    td_staged = training_data_from_columnar(
        store.find_columnar("app", stage=True, **kw))
    td_host = training_data_from_columnar(
        store.find_columnar("app", stage=False, **kw))
    assert hasattr(td_staged, "_staged_coo")
    assert not hasattr(td_host, "_staged_coo")
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=3, numIterations=2, seed=7))
    m_staged = algo.train(None, type("P", (), {"ratings": td_staged})())
    m_host = algo.train(None, type("P", (), {"ratings": td_host})())
    np.testing.assert_array_equal(np.asarray(m_staged.user_factors),
                                  np.asarray(m_host.user_factors))
    np.testing.assert_array_equal(np.asarray(m_staged.item_factors),
                                  np.asarray(m_host.item_factors))


def test_stage_kill_switch(tmp_path, monkeypatch):
    s, app_id = seed_messy_store(tmp_path, monkeypatch)
    monkeypatch.setenv("PIO_READ_STAGE", "0")
    col = store.find_columnar(
        "app", event_names=["rate", "buy"], entity_type="user",
        target_entity_type="item", storage=s, stage=True)
    assert col.staged is None


def test_staging_wanted_skips_warm_retrain(monkeypatch):
    from predictionio_tpu.models.recommendation import als_algorithm
    monkeypatch.setattr(als_algorithm, "_BIG_LAYOUT_CACHE", [])
    assert als_algorithm.staging_wanted()
    # a populated content-fingerprint cache means a warm retrain is likely
    # to hit — don't pay the staged transfer
    monkeypatch.setattr(als_algorithm, "_BIG_LAYOUT_CACHE",
                        [("meta", b"crc", object())])
    assert not als_algorithm.staging_wanted()
    monkeypatch.setenv("PIO_ALS_LAYOUT_CACHE", "0")
    assert als_algorithm.staging_wanted()   # cache disabled -> cold rebuild
    monkeypatch.setenv("PIO_READ_STAGE", "0")
    assert not als_algorithm.staging_wanted()


def test_sqlite_columnar_matches_object_path(tmp_path):
    """sqlite's new read_columns: find_columnar's vectorized path must
    agree with the per-event path event for event (same treatment as the
    eventlog, ISSUE 2 tentpole pt. 1 'sqlite/remote backends')."""
    from tests.test_eventlog_ingestion import seed_events, triples

    sql_env = {
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_SQ_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQ_PATH": str(tmp_path / "pio.sqlite"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    }
    s_sql = Storage(env=sql_env)
    app_id = s_sql.get_meta_data_apps().insert(App(0, "app"))
    s_sql.get_events().init(app_id)
    mem_env = {
        "PIO_STORAGE_SOURCES_T_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "T",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "T",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "T",
    }
    s_mem = Storage(env=mem_env)
    s_mem.get_meta_data_apps().insert(App(0, "app"))

    rng = np.random.default_rng(3)
    evs = seed_events(rng)
    evs.append(Event(event="rate", entity_type="user", entity_id="u1",
                     target_entity_type="item", target_entity_id="i1",
                     properties=DataMap({"rating": "4.5"}),   # string-coded
                     event_time=dt.datetime(2021, 1, 3, tzinfo=UTC)))
    s_sql.get_events().insert_batch(evs, app_id)
    s_mem.get_events().insert_batch(evs, 1)

    assert hasattr(s_sql.get_events(), "read_columns")
    kw = dict(event_names=["rate", "buy"], entity_type="user",
              target_entity_type="item")
    fast = store.find_columnar("app", storage=s_sql, **kw)
    slow = store.find_columnar("app", storage=s_mem, **kw)
    assert fast.n == slow.n
    assert triples(fast) == triples(slow)
    assert set(fast.entity_ids.to_dict()) == set(slow.entity_ids.to_dict())
    assert set(fast.target_ids.to_dict()) == set(slow.target_ids.to_dict())
    # no-target events survive as -1 codes through the raw contract
    raw = s_sql.get_events().read_columns(app_id)
    assert (raw["target_code"] == -1).sum() == 3   # the $set events


def test_eval_grid_builds_layout_once_per_fold(memory_storage):
    """prepare_shared_layouts hoists the fold layouts out of the
    per-variant loop: a 2-variant grid over one data source builds
    prepare_ratings once per fold, and every variant train is a reuse
    hit."""
    from unittest import mock

    from predictionio_tpu.models.recommendation import als_algorithm
    from predictionio_tpu.models.recommendation.evaluation import (
        RecommendationEvaluation,
    )
    from predictionio_tpu.ops import als
    from predictionio_tpu.workflow import WorkflowContext, run_evaluation
    from tests.test_evaluation import grid, rated_app  # noqa: F401

    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "MyApp1", None))
    memory_storage.get_events().init(app_id)
    evs = []
    rng = np.random.default_rng(4)
    for j in range(160):
        evs.append(Event(
            event="rate", entity_type="user", entity_id=f"u{j % 11}",
            target_entity_type="item", target_entity_id=f"i{j % 9}",
            properties=DataMap({"rating": float(rng.integers(1, 6))}),
            event_time=dt.datetime(2021, 1, 1, tzinfo=UTC)
            + dt.timedelta(minutes=j)))
    store.write(evs, app_id, storage=memory_storage)

    als_algorithm._BIG_LAYOUT_CACHE.clear()
    params = grid(ranks=(2, 3), iters=(2,))   # 2 variants, kFold=3
    builds = []
    real = als.prepare_ratings
    with mock.patch.object(
            als, "prepare_ratings",
            side_effect=lambda *a, **k: builds.append(1) or real(*a, **k)):
        hits0 = als_algorithm.LAYOUT_STATS["hits"]
        run_evaluation(WorkflowContext(storage=memory_storage),
                       RecommendationEvaluation(), params,
                       evaluation_class="RecommendationEvaluation")
        hits = als_algorithm.LAYOUT_STATS["hits"] - hits0
    assert len(builds) == 3          # one layout per fold, NOT per variant
    assert hits == 6                 # 2 variants x 3 folds all reused


def test_cli_read_flags(monkeypatch):
    from predictionio_tpu.tools.cli import _apply_read_env, build_parser

    args = build_parser().parse_args(
        ["train", "--read-threads", "3", "--read-overlap", "off"])
    assert args.read_threads == 3 and args.read_overlap == "off"
    # register the keys with monkeypatch BEFORE the direct writes, so
    # teardown restores the pre-test state (a trailing delenv on a key
    # first touched AFTER the write would "restore" the written value —
    # that exact leak once poisoned every later staging-dependent test)
    for k in ("PIO_READ_THREADS", "PIO_READ_OVERLAP", "PIO_READ_STAGE"):
        monkeypatch.setenv(k, "pre")
    import os
    _apply_read_env(args)
    assert os.environ["PIO_READ_THREADS"] == "3"
    assert os.environ["PIO_READ_OVERLAP"] == "0"
    assert os.environ["PIO_READ_STAGE"] == "0"
