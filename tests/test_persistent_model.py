"""PersistentModel SPI roundtrip (ref: controller/PersistentModel.scala:67-115,
LocalFileSystemPersistentModel.scala:39-77, Engine.makeSerializableModels
:286-304, prepareDeploy :199-269)."""

import dataclasses

import pytest

from predictionio_tpu.controller import (
    Algorithm, DataSource, EngineParams, Engine, FirstServing,
    LocalFileSystemPersistentModel, Params, Preparator,
)
from predictionio_tpu.controller.persistent_model import PersistentModelManifest
from predictionio_tpu.workflow import WorkflowContext, run_train
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig


@dataclasses.dataclass
class SelfSavingModel(LocalFileSystemPersistentModel):
    weights: tuple = (1.0, 2.0)


class _DS(DataSource):
    def read_training(self, ctx):
        return "td"


class _Prep(Preparator):
    def prepare(self, ctx, td):
        return td


@dataclasses.dataclass(frozen=True)
class _AlgoParams(Params):
    scale: float = 2.0


class _Algo(Algorithm):
    params_class = _AlgoParams

    def __init__(self, params: _AlgoParams = _AlgoParams()):
        self.params = params

    def train(self, ctx, pd):
        return SelfSavingModel(weights=(self.params.scale, 2.0))

    def predict(self, model, query):
        return {"w": list(model.weights)}


def _engine():
    return Engine(_DS, _Prep, {"algo": _Algo}, FirstServing)


def test_persistent_model_train_deploy_roundtrip(memory_storage, tmp_path,
                                                 monkeypatch):
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
    ctx = WorkflowContext(storage=memory_storage)
    ep = EngineParams(
        algorithm_params_list=(("algo", _AlgoParams(scale=7.0)),))
    iid = run_train(ctx, _engine(), ep,
                    engine_factory="tests.test_persistent_model:_engine",
                    params_json={"algorithms": [
                        {"name": "algo", "params": {"scale": 7.0}}]})
    # the blob must hold a manifest, not the model
    import pickle
    blob = memory_storage.get_model_data_models().get(iid).models
    stored = pickle.loads(blob)
    assert isinstance(stored[0], PersistentModelManifest)
    assert stored[0].module_name.endswith("test_persistent_model")

    api = QueryAPI(storage=memory_storage, engine=_engine())
    status, body = api.handle("POST", "/queries.json", body=b"{}")
    assert status == 200 and body == {"w": [7.0, 2.0]}


def test_unimportable_persistent_model_fails_at_save(memory_storage, tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))

    class LocalModel(LocalFileSystemPersistentModel):  # <locals> qualname
        pass

    class BadAlgo(_Algo):
        def train(self, ctx, pd):
            return LocalModel()

    engine = Engine(_DS, _Prep, {"algo": BadAlgo}, FirstServing)
    ctx = WorkflowContext(storage=memory_storage)
    ep = EngineParams(algorithm_params_list=(("algo", _AlgoParams()),))
    with pytest.raises(ValueError, match="not importable"):
        run_train(ctx, engine, ep, engine_factory="x")
    # the failed run is recorded as ERROR, so deploy refuses it
    rows = memory_storage.get_meta_data_engine_instances().get_all()
    assert rows and all(r.status == "ERROR" for r in rows)
