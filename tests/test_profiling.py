"""On-demand device-profiling tests (common/profiling.py + `pio
profile`).

Acceptance: `pio profile` against a live in-process daemon produces a
non-empty trace artifact; captures are bounded (hard max duration),
single-concurrent (409 while one runs), and listed by
`GET /debug/profile` on every daemon.
"""

import io
import json
import time
import urllib.request

import jax.numpy as jnp
import pytest

from predictionio_tpu.common import profiling
from predictionio_tpu.data.api import EventAPI
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.tools.profile import run_profile


@pytest.fixture(autouse=True)
def _clean_profiling(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_PROFILE_DIR", str(tmp_path / "profiles"))
    profiling.reset()
    yield
    # never leave a dangling jax trace behind for the next test
    deadline = time.perf_counter() + 15.0
    while profiling.list_captures()["active"] is not None:
        if time.perf_counter() > deadline:
            pytest.fail("profiling capture never finished")
        time.sleep(0.05)
    profiling.reset()


def _wait_done(capture_id, timeout=15.0):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        c = profiling.get_capture(capture_id)
        if c is not None and c.get("state") != "running":
            return c
        time.sleep(0.05)
    pytest.fail(f"capture {capture_id} never completed")


def test_capture_is_bounded_single_and_listed(monkeypatch):
    monkeypatch.setenv("PIO_PROFILE_MAX_MS", "300")
    entry = profiling.start_capture(ms=60_000)   # clamped to 300
    assert entry["requestedMs"] == 300
    # single concurrent capture: a second start is refused
    with pytest.raises(profiling.CaptureBusy):
        profiling.start_capture(ms=100)
    # some device work lands inside the capture window
    float(jnp.ones((32, 32)).sum())
    done = _wait_done(entry["id"])
    assert done["state"] == "done"
    assert done["files"], "capture produced no artifact files"
    assert done["bytes"] > 0
    import os
    assert os.path.exists(os.path.join(done["dir"], "capture.json"))
    # the hard max really bounded it (60 s requested, ~0.3 s ran)
    assert done["durationMs"] < 10_000
    listing = profiling.list_captures()
    assert listing["active"] is None
    assert listing["captures"][0]["id"] == entry["id"]
    # the slot is free again
    e2 = profiling.start_capture(ms=50)
    _wait_done(e2["id"])


def test_capture_rejects_bad_ms():
    with pytest.raises(ValueError):
        profiling.start_capture(ms=0)


def test_debug_profile_route_get_and_post(memory_storage):
    api = EventAPI(storage=memory_storage)
    st, listing = api.handle("GET", "/debug/profile")
    assert st == 200 and "captures" in listing and "maxMs" in listing
    st, payload = api.handle("POST", "/debug/profile",
                             query={"ms": "bogus"})
    assert st == 400
    st, payload = api.handle("POST", "/debug/profile",
                             query={"ms": "100"})
    assert st == 202
    cap = payload["capture"]
    assert cap["state"] == "running"
    # second POST while running: 409, not a corrupted first capture
    st, busy = api.handle("POST", "/debug/profile", query={"ms": "100"})
    assert st == 409
    done = _wait_done(cap["id"])
    assert done["state"] in ("done", "empty")


def test_pio_profile_cli_against_live_daemon(memory_storage, tmp_path):
    """The acceptance path: `pio profile <url>` against a live
    in-process daemon yields a non-empty trace artifact. `-o` names a
    subdirectory under the server's PIO_PROFILE_DIR."""
    api = EventAPI(storage=memory_storage)
    server, port = serve_background(api, "127.0.0.1", 0)
    try:
        # concurrent device work so the profiler window sees dispatches
        float(jnp.ones((64, 64)).sum())
        buf = io.StringIO()
        rc = run_profile(f"http://127.0.0.1:{port}", ms=400,
                         out_dir="cli-capture", out=buf)
        text = buf.getvalue()
        assert rc == 0, text
        assert "capture done" in text
        assert "file(s)" in text
        # artifact landed under the requested server-side subdir,
        # confined to the profile base
        listing = profiling.list_captures()
        assert listing["captures"][0]["dir"].startswith(
            str(tmp_path / "profiles" / "cli-capture"))
        assert listing["captures"][0]["files"]
    finally:
        server.shutdown()


def test_debug_profile_dir_confined_to_base(memory_storage, tmp_path):
    """The unauthenticated POST must never write outside the
    operator-configured profile base: absolute paths, `..` hops, and
    anything else resolving outside PIO_PROFILE_DIR answer 400 with no
    capture started; a path inside the base is accepted."""
    api = EventAPI(storage=memory_storage)
    for bad in (str(tmp_path / "evil"), "../evil", "a/../../evil",
                "/etc/cron.d"):
        st, payload = api.handle("POST", "/debug/profile",
                                 query={"ms": "100", "dir": bad})
        assert st == 400, (bad, st, payload)
        assert "profile base" in payload["message"]
        assert profiling.list_captures()["active"] is None
        assert not (tmp_path / "evil").exists()
    # in-base override (relative, or absolute under the base) is fine
    st, payload = api.handle("POST", "/debug/profile",
                             query={"ms": "50", "dir": "sub"})
    assert st == 202
    assert payload["capture"]["dir"].startswith(
        str(tmp_path / "profiles" / "sub"))
    _wait_done(payload["capture"]["id"])


def test_debug_profile_post_kill_switch(memory_storage, monkeypatch):
    """PIO_PROFILE_ENABLE=0 turns the POST surface off (403) while the
    GET listing keeps answering."""
    monkeypatch.setenv("PIO_PROFILE_ENABLE", "0")
    api = EventAPI(storage=memory_storage)
    st, payload = api.handle("POST", "/debug/profile",
                             query={"ms": "100"})
    assert st == 403 and "PIO_PROFILE_ENABLE" in payload["message"]
    assert profiling.list_captures()["active"] is None
    st, listing = api.handle("GET", "/debug/profile")
    assert st == 200 and "captures" in listing


def test_pio_profile_cli_unreachable_exits_2():
    buf = io.StringIO()
    assert run_profile("http://127.0.0.1:1", ms=100, out=buf) == 2
    assert "unreachable" in buf.getvalue()


def test_cli_profile_subcommand_wiring(memory_storage, tmp_path):
    from predictionio_tpu.tools.cli import main as cli_main
    api = EventAPI(storage=memory_storage)
    server, port = serve_background(api, "127.0.0.1", 0)
    try:
        float(jnp.ones((64, 64)).sum())
        rc = cli_main(["profile", f"http://127.0.0.1:{port}",
                       "--ms", "300", "-o", "sub-capture"])
        assert rc == 0
    finally:
        server.shutdown()


def test_train_profile_shares_capture_format(memory_storage, tmp_path):
    """profiling.trace (the `pio train --profile DIR` path) writes the
    same capture.json + xprof layout and shares the single-capture
    guard."""
    out = tmp_path / "train-prof"
    with profiling.trace(str(out), label="train"):
        with pytest.raises(profiling.CaptureBusy):
            profiling.start_capture(ms=100)
        float(jnp.ones((32, 32)).sum())
    meta = json.loads((out / "capture.json").read_text())
    assert meta["label"] == "train" and meta["state"] == "done"
    assert meta["files"], "train capture listed no artifact files"
    listing = profiling.list_captures()
    assert listing["captures"][0]["label"] == "train"
    assert listing["captures"][0]["files"]


def test_profile_over_http_query_params(memory_storage):
    """End-to-end over real HTTP: POST with query params, poll GET."""
    api = EventAPI(storage=memory_storage)
    server, port = serve_background(api, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{port}"
    try:
        req = urllib.request.Request(f"{base}/debug/profile?ms=150",
                                     data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 202
            cap = json.loads(r.read().decode())["capture"]
        float(jnp.ones((32, 32)).sum())
        _wait_done(cap["id"])
        with urllib.request.urlopen(f"{base}/debug/profile",
                                    timeout=10) as r:
            listing = json.loads(r.read().decode())
        assert any(c["id"] == cap["id"] for c in listing["captures"])
    finally:
        server.shutdown()
