"""Quantized serving (ops/quant.py + ops/topk_pallas.py).

The acceptance surface of ISSUE 11: int8 per-row-scale quantization of
both factor matrices with dequantize-free int8 x int8 scoring; the
fused Pallas score->mask->per-tile-top-k kernel bit-identical (in
interpret mode, on CPU) to the XLA fallback AND to the sharded int8
kernel, ties included; the ranking-parity contract (recall@k >= 0.99,
exact-match@1 >= 0.999 vs fp32 on a trained model — KNOWN_ISSUES #12);
PIO_SERVE_QUANT=off wire-byte identical to the pre-quant server
(replicated and sharded); AOT-prebuilt quant programs keeping
post_warmup_recompiles at 0 with quant+fused on; and the doctor /
deploy-state surfaces, including the requested-but-fell-back WARN.
"""

import datetime as dt
import json

import numpy as np
import pytest

import jax

from predictionio_tpu.common import devicewatch, telemetry
from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.ops import quant, topk, topk_pallas
from predictionio_tpu.parallel import serve_dist
from predictionio_tpu.workflow import WorkflowContext, model_io, run_train
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("PIO_SERVE_QUANT", raising=False)
    monkeypatch.delenv("PIO_SERVE_FUSED", raising=False)
    monkeypatch.delenv("PIO_SERVE_FUSED_TILE", raising=False)
    yield
    quant.record_state(None)
    serve_dist.record_state(None)
    telemetry.set_enabled(None)


def _factors(n_users=33, n_items=1100, rank=10, seed=0):
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(n_users, rank)).astype(np.float32)
    V = rng.normal(size=(n_items, rank)).astype(np.float32)
    return U, V


# ---------------------------------------------------------------------------
# quantization properties
# ---------------------------------------------------------------------------

def test_quantize_rows_properties():
    M = np.array([[1.0, -2.0, 0.5],
                  [0.0, 0.0, 0.0],          # all-zero row: scale 1.0
                  [127.0, -127.0, 63.5],
                  [1e-6, -1e-6, 0.0]], dtype=np.float32)
    q, s = quant.quantize_rows(M)
    assert q.dtype == np.int8 and s.dtype == np.float32
    assert np.abs(q).max() <= 127
    assert s[1] == 1.0 and not q[1].any()
    # max round-trip error per element is half a quantization step
    deq = quant.dequantize_rows(q, s)
    assert np.all(np.abs(deq - M) <= s[:, None] / 2 + 1e-9)
    # the row max always hits +/-127 exactly (symmetric per-row scale)
    assert np.abs(q[0]).max() == 127 and np.abs(q[2]).max() == 127


def test_quantized_factors_bytes():
    U, V = _factors()
    qf = quant.QuantizedFactors.from_factors(U, V)
    assert qf.n_users == 33 and qf.n_items == 1100 and qf.rank == 10
    assert qf.fp32_bytes() == (33 + 1100) * 10 * 4
    assert qf.int8_bytes() == (33 + 1100) * 10 + (33 + 1100) * 4
    # the int8 MATRICES are exactly 0.25x of fp32
    assert ((33 + 1100) * 10) / qf.fp32_bytes() == 0.25


# ---------------------------------------------------------------------------
# kernel parity: fused Pallas (interpret) == XLA fallback == sharded int8
# ---------------------------------------------------------------------------

def _build_serving(qf, fused: str, tile: str, monkeypatch):
    monkeypatch.setenv("PIO_SERVE_FUSED", fused)
    monkeypatch.setenv("PIO_SERVE_FUSED_TILE", tile)
    return quant.QuantizedServing.build(qf)


def test_fused_interpret_matches_fallback_bit_identical(monkeypatch):
    """Constructed ties (duplicated item rows quantize identically), k
    below/at/above the tile, bucket sizes down to 1."""
    U, V = _factors()
    V[707] = V[3]
    V[13] = V[3]
    qf = quant.QuantizedFactors.from_factors(U, V)
    fb = _build_serving(qf, "0", "256", monkeypatch)
    fu = _build_serving(qf, "1", "256", monkeypatch)
    assert fu.fused and fu.interpret and not fb.fused
    for ixs in (np.arange(16, dtype=np.int32),
                np.asarray([7], dtype=np.int32)):
        for k in (1, 5, 10, 300):   # 300 > the 256 tile
            fv, fi = jax.device_get(fu.topk(ixs, k))
            bv, bi = jax.device_get(fb.topk(ixs, k))
            np.testing.assert_array_equal(
                fv.view(np.int32), bv.view(np.int32),
                err_msg=f"k={k} b={len(ixs)}")
            np.testing.assert_array_equal(fi, bi, err_msg=f"k={k}")
    # the tie rule itself: clones of item 3 rank lowest-index first
    _fv, fi = jax.device_get(fu.topk(np.arange(8, dtype=np.int32), 1100))
    for row in fi:
        pos = [int(np.flatnonzero(row == c)[0]) for c in (3, 13, 707)]
        assert pos == sorted(pos), pos


def test_inline_quant_matches_batched_row(monkeypatch):
    U, V = _factors(seed=1)
    qf = quant.QuantizedFactors.from_factors(U, V)
    qs = _build_serving(qf, "0", "512", monkeypatch)
    iv, ii = jax.device_get(qs.topk_one(np.int32(7), 10))
    bv, bi = jax.device_get(qs.topk(np.asarray([7], np.int32), 10))
    np.testing.assert_array_equal(iv.view(np.int32),
                                  bv[0].view(np.int32))
    np.testing.assert_array_equal(ii, bi[0])


def test_sharded_quant_matches_replicated_quant_bit_identical(monkeypatch):
    """8 int8 shards vs the replicated quant kernel: exact integer
    scores + elementwise rescale leave no room for drift."""
    U, V = _factors(seed=2)
    V[1099] = V[5]     # cross-shard tie with the clone in shard 0
    qf = quant.QuantizedFactors.from_factors(U, V)
    qs = _build_serving(qf, "0", "512", monkeypatch)
    sharded = serve_dist.shard_factors(U, V, quant=qf)
    assert sharded.dtype == "int8" and sharded.n_shards == 8
    ixs = np.array([0, 5, 12, 0, 31], dtype=np.int32)
    for k in (1, 10, 200):
        sv, si = jax.device_get(sharded.topk(ixs, k))
        rv, ri = jax.device_get(qs.topk(ixs, k))
        np.testing.assert_array_equal(sv.view(np.int32),
                                      rv.view(np.int32), err_msg=f"k={k}")
        np.testing.assert_array_equal(si, ri, err_msg=f"k={k}")


def test_sharded_quant_per_shard_bytes_quartered():
    U, V = _factors(rank=64, seed=3)
    qf = quant.QuantizedFactors.from_factors(U, V)
    int8 = serve_dist.shard_factors(U, V, quant=qf)
    fp32 = serve_dist.shard_factors(U, V)
    ratio = int8.per_shard_bytes() / fp32.per_shard_bytes()
    assert ratio <= 0.30, ratio
    assert int8.summary()["dtype"] == "int8"
    assert "dtype" not in fp32.summary()     # fp32 keeps the PR 8 keys


# ---------------------------------------------------------------------------
# mode / fused resolution
# ---------------------------------------------------------------------------

def test_mode_resolution(monkeypatch):
    # bare defaults: auto + CPU backend -> fp32
    assert quant.configured_mode() == "auto"
    assert not quant.serving_enabled()
    with quant.deploy_scope("on"):
        assert quant.serving_enabled()
    with quant.deploy_scope("off"):
        assert not quant.serving_enabled()
    # env wins over the config scope
    monkeypatch.setenv("PIO_SERVE_QUANT", "0")
    with quant.deploy_scope("on"):
        assert not quant.serving_enabled()
    monkeypatch.setenv("PIO_SERVE_QUANT", "1")
    with quant.deploy_scope("off"):
        assert quant.serving_enabled()
    monkeypatch.delenv("PIO_SERVE_QUANT")
    # auto engages on accelerator backends
    monkeypatch.setattr(quant, "_accelerator_platform", lambda: True)
    with quant.deploy_scope("auto"):
        assert quant.serving_enabled()
    with pytest.raises(ValueError):
        with quant.deploy_scope("sideways"):
            pass


def test_fused_choice(monkeypatch):
    # CPU backend: auto -> XLA fallback; on -> interpret; off -> fallback
    monkeypatch.delenv("PIO_SERVE_FUSED", raising=False)
    assert topk_pallas.fused_choice() == (False, False)
    monkeypatch.setenv("PIO_SERVE_FUSED", "1")
    assert topk_pallas.fused_choice() == (True, True)
    monkeypatch.setenv("PIO_SERVE_FUSED", "0")
    assert topk_pallas.fused_choice() == (False, False)


def test_accept_parity(monkeypatch):
    low = {"k": 10, "recall": 0.5, "exact1": 0.5}
    high = {"k": 10, "recall": 1.0, "exact1": 1.0}
    with quant.deploy_scope("auto"):
        assert not quant.accept_parity(low)
        assert quant.accept_parity(high)
    with quant.deploy_scope("on"):
        assert quant.accept_parity(low)      # operator's explicit call
    monkeypatch.setenv("PIO_SERVE_QUANT_RECALL_MIN", "0.4")
    with quant.deploy_scope("auto"):
        assert quant.accept_parity(low)


# ---------------------------------------------------------------------------
# the ranking-parity contract on a TRAINED model
# ---------------------------------------------------------------------------

def _ladder_storage():
    """A trained model with real top-10 structure: each user rates a
    12-item preference ladder (5.0 stepping down by 0.3) over a 1.0
    background — trained score margins comfortably exceed the int8
    quantization noise, which is what the contract requires of a model
    before quantized serving makes sense (KNOWN_ISSUES #12)."""
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    })
    app_id = storage.get_meta_data_apps().insert(App(0, "QuantApp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(7)
    n_u, n_i = 60, 48
    events = []
    for u in range(n_u):
        rated = {}
        for j in range(12):
            rated[(u * 7 + j * 3) % n_i] = 5.0 - 0.3 * j
        for i in range(n_i):
            if i not in rated and rng.random() < 0.5:
                rated[i] = 1.0
        for i, r in rated.items():
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": r}),
                event_time=dt.datetime(2021, 2, 3, 0, (u + i) % 60,
                                       tzinfo=dt.timezone.utc)))
    storage.get_events().insert_batch(events, app_id)
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="QuantApp"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=10, numIterations=12,
                                       lambda_=0.03, seed=5)),))
    run_train(WorkflowContext(storage=storage), engine, ep,
              engine_factory="quant-test",
              params_json={
                  "datasource": {"params": {"appName": "QuantApp"}},
                  "algorithms": [{"name": "als", "params": {
                      "rank": 10, "numIterations": 12,
                      "lambda": 0.03, "seed": 5}}]})
    return storage, engine


@pytest.fixture(scope="module")
def trained():
    return _ladder_storage()


def _trained_factors(storage):
    instance = storage.get_meta_data_engine_instances() \
        .get_latest_completed("default", "NOT_USED", "default")
    blob = storage.get_model_data_models().get(instance.id)
    m = model_io.deserialize_models(blob.models)[0]
    return np.asarray(m.user_factors), np.asarray(m.item_factors)


def test_trained_model_ranking_parity_contract(trained):
    """THE contract: recall@k >= 0.99 and exact-match@1 >= 0.999 vs the
    fp32 path on a trained model."""
    storage, _engine = trained
    U, V = _trained_factors(storage)
    qf = quant.QuantizedFactors.from_factors(U, V)
    parity = quant.ranking_parity(U, V, qf, k=10)
    assert parity["recall"] >= 0.99, parity
    assert parity["exact1"] >= 0.999, parity
    # and the deploy gate accepts it in auto mode
    with quant.deploy_scope("auto"):
        assert quant.accept_parity(parity)


def _post(api, user, num=10):
    status, body = api.handle(
        "POST", "/queries.json",
        body=json.dumps({"user": user, "num": num}).encode())
    assert status == 200, body
    return json.dumps(body, sort_keys=True)


def _items(payload: str):
    return [s["item"] for s in json.loads(payload).get("itemScores", [])]


def test_quant_server_ranking_parity_at_the_wire(trained, monkeypatch):
    """Two live servers over the SAME trained model — fp32 vs int8 —
    compared at the wire: recall@10 >= 0.99, exact-match@1 >= 0.999."""
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")
    storage, engine = trained
    queries = [(f"u{u}", 10) for u in range(60)]

    api_fp = QueryAPI(storage=storage, engine=engine,
                      config=ServerConfig(batching="on",
                                          serve_quant="off"))
    try:
        fp = [_post(api_fp, u, n) for u, n in queries]
    finally:
        api_fp.close()
    api_q = QueryAPI(storage=storage, engine=engine,
                     config=ServerConfig(batching="on",
                                         serve_quant="on"))
    try:
        qn = [_post(api_q, u, n) for u, n in queries]
        status = api_q.handle("GET", "/")[1]
    finally:
        api_q.close()
    recalls, top1 = [], []
    for a, b in zip(fp, qn):
        ia, ib = _items(a), _items(b)
        recalls.append(len(set(ia) & set(ib)) / max(len(ia), 1))
        top1.append(1.0 if ia[0] == ib[0] else 0.0)
    assert np.mean(recalls) >= 0.99, np.mean(recalls)
    assert np.mean(top1) >= 0.999, np.mean(top1)
    # the deploy recorded its own probe on the quant surface
    q = status["quant"]
    assert q["enabled"] and q["dtype"] == "int8"
    assert q["recall"] >= 0.99 and q["exact1"] >= 0.999


# ---------------------------------------------------------------------------
# deployed server: wire parity off, surfaces, sharding composition, AOT
# ---------------------------------------------------------------------------

def test_quant_off_wire_byte_identical(trained, monkeypatch):
    """PIO_SERVE_QUANT=off (and the auto default on CPU) answers
    byte-for-byte what a pre-quant server answers — replicated AND
    sharded — and keeps the legacy GET / key set."""
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")
    storage, engine = trained
    queries = [("u1", 5), ("u3", 9), ("nobody", 5), ("u7", 1)]
    for shard in ("off", "on"):
        api_off = QueryAPI(storage=storage, engine=engine,
                           config=ServerConfig(batching="on",
                                               shard_serving=shard,
                                               serve_quant="off"))
        try:
            off_answers = [_post(api_off, u, n) for u, n in queries]
            assert "quant" not in api_off.handle("GET", "/")[1]
        finally:
            api_off.close()
        api_default = QueryAPI(storage=storage, engine=engine,
                               config=ServerConfig(batching="on",
                                                   shard_serving=shard))
        try:
            assert [_post(api_default, u, n)
                    for u, n in queries] == off_answers
            assert "quant" not in api_default.handle("GET", "/")[1]
        finally:
            api_default.close()


def test_quant_sharded_server_matches_quant_replicated(trained,
                                                       monkeypatch):
    """quant x sharding compose, and because the int8 kernels are
    exact, the two layouts answer byte-identically at the wire."""
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")
    storage, engine = trained
    queries = [("u1", 5), ("u3", 10), ("nobody", 5), ("u7", 1)]
    api_rep = QueryAPI(storage=storage, engine=engine,
                       config=ServerConfig(batching="on",
                                           serve_quant="on"))
    try:
        rep = [_post(api_rep, u, n) for u, n in queries]
    finally:
        api_rep.close()
    api_sh = QueryAPI(storage=storage, engine=engine,
                      config=ServerConfig(batching="on",
                                          shard_serving="on",
                                          serve_quant="on"))
    try:
        sh = [_post(api_sh, u, n) for u, n in queries]
        status = api_sh.handle("GET", "/")[1]
        assert status["sharding"]["dtype"] == "int8"
        assert status["sharding"]["shards"] == 8
        q = status["quant"]
        assert q["enabled"] and q["sharded"] and q["dtype"] == "int8"
        model = api_sh.models[0]
        assert model.sharding is not None and model.sharding.dtype == "int8"
    finally:
        api_sh.close()
    assert rep == sh


def test_quant_gauges_recorded(trained, monkeypatch):
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")
    storage, engine = trained
    api = QueryAPI(storage=storage, engine=engine,
                   config=ServerConfig(batching="on", serve_quant="on"))
    try:
        reg = telemetry.registry()
        assert reg.gauge("pio_serve_quant_mode", "x").labels().value == 1.0
        i8 = reg.gauge("pio_serve_factor_bytes", "x",
                       labelnames=("dtype",)).labels(dtype="int8").value
        f32 = reg.gauge("pio_serve_factor_bytes", "x",
                        labelnames=("dtype",)).labels(dtype="fp32").value
        assert 0 < i8 < f32
        rec = reg.gauge("pio_serve_quant_recall", "x",
                        labelnames=("metric",)).labels(
                            metric="recall").value
        assert rec >= 0.99
    finally:
        api.close()
    # a fresh fp32 deploy clears the mode gauge
    api2 = QueryAPI(storage=storage, engine=engine,
                    config=ServerConfig(batching="on", serve_quant="off"))
    try:
        assert telemetry.registry().gauge(
            "pio_serve_quant_mode", "x").labels().value == 0.0
    finally:
        api2.close()


def test_quant_fused_programs_prebuilt_no_post_warmup_recompiles(
        trained, monkeypatch):
    """With quant + the fused kernel on (interpret mode on CPU), every
    (bucket x k) program is primed before ready: a post-AOT serving
    burst must compile NOTHING."""
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")
    monkeypatch.setenv("PIO_SERVE_FUSED", "1")
    storage, engine = trained
    telemetry.set_enabled(True)
    devicewatch.install()
    devicewatch.reset_watchdog()
    api = QueryAPI(storage=storage, engine=engine,
                   config=ServerConfig(batching="on", serve_quant="on"))
    try:
        assert api.models[0].quant is not None
        assert api.models[0].quant.fused
        assert devicewatch.serving_warmup_done()    # AOT marked it
        before = devicewatch.post_warmup_recompiles()
        for q in range(6):
            _post(api, f"u{q}", 10)
        assert devicewatch.post_warmup_recompiles() == before
    finally:
        api.close()
        devicewatch.reset_watchdog()


def test_auto_mode_falls_back_below_recall_floor(trained, monkeypatch):
    """auto + accelerator + a failing probe -> fp32 serving, an explicit
    fellBack record on GET /, and answers identical to serve_quant=off."""
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")
    monkeypatch.setattr(quant, "_accelerator_platform", lambda: True)
    monkeypatch.setattr(
        quant, "ranking_parity",
        lambda *a, **k: {"k": 10, "sampledUsers": 4,
                         "recall": 0.5, "exact1": 0.5})
    storage, engine = trained
    api = QueryAPI(storage=storage, engine=engine,
                   config=ServerConfig(batching="on", serve_quant="auto"))
    try:
        status = api.handle("GET", "/")[1]
        assert status["quant"] == {"enabled": False, "fellBack": True}
        assert api.models[0].quant is None
        fell_back = _post(api, "u1", 5)
    finally:
        api.close()
    api_off = QueryAPI(storage=storage, engine=engine,
                       config=ServerConfig(batching="on",
                                           serve_quant="off"))
    try:
        assert _post(api_off, "u1", 5) == fell_back
    finally:
        api_off.close()


# ---------------------------------------------------------------------------
# doctor: the quant line + the hbm note
# ---------------------------------------------------------------------------

def _scrape_stub(metrics_text, device_body):
    blank = {"status": None, "body": ""}
    return {
        "url": "http://x", "healthz": {"status": 200, "body": "{}"},
        "readyz": {"status": 200, "body": '{"status": "ready"}'},
        "metrics": {"status": 200, "body": metrics_text},
        "traces": {"status": 200, "body": '{"spanCount": 0}'},
        "device": {"status": 200, "body": json.dumps(device_body)},
        "slow": dict(blank),
    }


def test_doctor_quant_line_states():
    from predictionio_tpu.tools import doctor

    dev = {"telemetry": True,
           "quant": {"enabled": True, "dtype": "int8", "fused": True,
                     "int8Bytes": 14 * 2**20, "fp32Bytes": 40 * 2**20,
                     "recall": 0.9975}}
    metrics = "pio_serve_quant_mode 1\n"
    checks = {c: (s, d) for c, s, d in
              doctor.diagnose(_scrape_stub(metrics, dev))}
    state, detail = checks["quant"]
    assert state == doctor.OK
    assert "int8" in detail and "0.35x" in detail
    assert "recall gate 0.9975" in detail
    assert "fused Pallas" in detail
    # requested but fell back -> WARN naming the cost
    dev_fb = {"telemetry": True, "quant": {"enabled": False,
                                           "fellBack": True}}
    state, detail = {c: (s, d) for c, s, d in doctor.diagnose(
        _scrape_stub("", dev_fb))}["quant"]
    assert state == doctor.WARN and "fell back" in detail
    # fp32 daemon: quiet NA line
    state, detail = {c: (s, d) for c, s, d in doctor.diagnose(
        _scrape_stub("", {"telemetry": True}))}["quant"]
    assert state == doctor.NA and "fp32" in detail


def test_doctor_hbm_line_reflects_quant_footprint():
    from predictionio_tpu.tools import doctor

    dev = {"telemetry": True,
           "quant": {"enabled": True, "dtype": "int8",
                     "int8Bytes": 10 * 2**20, "fp32Bytes": 40 * 2**20}}
    metrics = ('pio_hbm_bytes_in_use{device="tpu:0"} 1073741824\n'
               'pio_hbm_bytes_limit{device="tpu:0"} 17179869184\n')
    checks = {c: (s, d) for c, s, d in
              doctor.diagnose(_scrape_stub(metrics, dev))}
    state, detail = checks["hbm"]
    assert state == doctor.OK
    assert "int8 factors save 30.0 MiB" in detail


# ---------------------------------------------------------------------------
# persistence + footprint accounting (workflow/model_io.py)
# ---------------------------------------------------------------------------

def test_quantized_factors_survive_model_io_roundtrip():
    U, V = _factors(n_users=6, n_items=9, rank=4, seed=4)
    qf = quant.QuantizedFactors.from_factors(U, V)
    qf.recall = 1.0
    blob = model_io.serialize_models([qf])
    back = model_io.deserialize_models(blob)[0]
    assert back.u_q.dtype == np.int8
    np.testing.assert_array_equal(back.u_q, qf.u_q)
    np.testing.assert_array_equal(back.v_scale, qf.v_scale)
    assert back.recall == 1.0


def test_factor_bytes_by_dtype_accounting():
    U, V = _factors(n_users=6, n_items=9, rank=4, seed=4)
    qf = quant.QuantizedFactors.from_factors(U, V)
    by = model_io.factor_bytes_by_dtype(qf)
    assert by["int8"] == (6 + 9) * 4          # the two int8 matrices
    assert by["float32"] == (6 + 9) * 4       # the two scale vectors
    assert model_io.factor_bytes_by_dtype({"U": U, "V": V}) == {
        "float32": (6 + 9) * 4 * 4}


# ---------------------------------------------------------------------------
# the quantized HBM-ceiling demonstration (bench leg, on the 8-device
# tier-1 mesh)
# ---------------------------------------------------------------------------

def test_quant_hbm_ceiling_serves_past_fp32_sharded_budget(monkeypatch):
    import bench

    monkeypatch.setenv("BENCH_SHARD_BUDGET_MB", "1")
    out = bench._quant_hbm_ceiling_demo()
    assert "skipped" not in out
    assert out["n_devices"] == 8
    assert not out["fp32_sharded_fits_budget"]
    assert out["int8_sharded_fits_budget"]
    assert out["catalog_vs_fp32_ceiling"] >= 3.0
    assert out["quant_sharded_served_ok"]


# ---------------------------------------------------------------------------
# tier-1 Pallas coverage: the ALS solver's interpret path (satellite —
# until now its only coverage rode inside test_als.py's solver A/B)
# ---------------------------------------------------------------------------

def test_solve_pallas_interpret_matches_solve_factors():
    from predictionio_tpu.ops import als
    from predictionio_tpu.ops.solve_pallas import solve_factors_pallas

    rng = np.random.default_rng(11)
    n, r = 70, 6
    G = rng.normal(size=(n, r, r)).astype(np.float32)
    A = np.einsum("nij,nkj->nik", G, G)       # PSD batch
    b = rng.normal(size=(n, r)).astype(np.float32)
    reg = np.full((n,), 0.05, dtype=np.float32)
    got = np.asarray(solve_factors_pallas(
        jax.numpy.asarray(A), jax.numpy.asarray(b),
        jax.numpy.asarray(reg), interpret=True))
    want = np.asarray(als.solve_factors(
        jax.numpy.asarray(A), jax.numpy.asarray(b),
        jax.numpy.asarray(reg)))
    # fp32 elimination-order differences between the in-VMEM kernel and
    # the XLA sweep leave ~1e-4 relative drift on marginally-conditioned
    # rows; the ALS A/B in test_als.py holds the tighter end-to-end bar
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)
