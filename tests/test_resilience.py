"""Unit tests for common/resilience.py: retry policy schedule, circuit
breaker state machine (with an injected clock), fault-injector spec
parsing and determinism, and the request-scoped degradation flag."""

import threading

import pytest

from predictionio_tpu.common import resilience
from predictionio_tpu.common.resilience import (
    CircuitBreaker, CircuitOpenError, FaultInjector, FaultSpecError,
    InjectedFault, RetryPolicy,
)


# ------------------------------------------------------------- RetryPolicy
def test_default_policy_is_legacy_single_reconnect():
    """Zero-config must reproduce the historical transport behavior: one
    extra attempt, no sleep, no deadline, and `configured` False so the
    opt-in behaviors (5xx retry, deadline header) stay off."""
    p = RetryPolicy.from_env(prefix="PIO_TEST_UNSET")
    assert p.max_attempts == 2
    assert p.base_delay_s == 0.0
    assert p.total_deadline_s is None
    assert p.configured is False
    assert p.backoff_s(0) == 0.0 and p.backoff_s(5) == 0.0


def test_from_env_and_properties(monkeypatch):
    monkeypatch.setenv("PIO_T1_RETRIES", "3")
    monkeypatch.setenv("PIO_T1_BACKOFF_MS", "10")
    p = RetryPolicy.from_env(prefix="PIO_T1")
    assert p.max_attempts == 4 and p.base_delay_s == 0.01
    assert p.configured is True
    # config properties win over env
    p2 = RetryPolicy.from_env(prefix="PIO_T1",
                              properties={"RETRIES": "0"})
    assert p2.max_attempts == 1 and p2.configured is True


def test_backoff_full_jitter_bounded_and_floor():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.1, max_delay_s=0.3)
    for attempt, cap in ((0, 0.1), (1, 0.2), (2, 0.3), (5, 0.3)):
        for _ in range(20):
            assert 0.0 <= p.backoff_s(attempt) <= cap
    # a server Retry-After hint floors the pause
    assert p.backoff_s(0, floor=2.5) == 2.5


def test_call_retries_then_succeeds_and_exhausts():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("boom")
        return "ok"

    p = RetryPolicy(max_attempts=3)
    assert p.call(flaky, sleep=lambda s: None) == "ok"
    assert len(calls) == 3

    calls.clear()
    with pytest.raises(ConnectionError):
        RetryPolicy(max_attempts=2).call(flaky, sleep=lambda s: None)
    assert len(calls) == 2  # bounded: first try + one retry


def test_total_deadline_stops_retries():
    t = [0.0]
    p = RetryPolicy(max_attempts=10, total_deadline_s=1.0)
    deadline = p.deadline_from_now(clock=lambda: t[0])
    assert p.may_retry(0, deadline, clock=lambda: t[0])
    t[0] = 1.5  # budget spent
    assert not p.may_retry(0, deadline, clock=lambda: t[0])


# ---------------------------------------------------------- CircuitBreaker
def _breaker(**kw):
    t = [0.0]
    kw.setdefault("min_calls", 4)
    kw.setdefault("error_threshold", 0.5)
    kw.setdefault("open_s", 10.0)
    br = CircuitBreaker("test:1", clock=lambda: t[0], **kw)
    return br, t


def test_breaker_stays_closed_below_volume():
    br, _t = _breaker()
    for _ in range(3):   # below min_calls: even 100% errors don't trip
        br.allow()
        br.record(False)
    assert br.state == CircuitBreaker.CLOSED


def test_breaker_stays_closed_below_error_rate():
    br, _t = _breaker()
    for _ in range(20):  # plenty of volume, low error rate
        br.allow()
        br.record(True)
    br.allow()
    br.record(False)
    assert br.state == CircuitBreaker.CLOSED


def test_breaker_opens_fast_fails_half_opens_and_recovers():
    br, t = _breaker()
    for ok in (True, False, False, False):   # 75% errors over 4 calls
        br.allow()
        br.record(ok)
    assert br.state == CircuitBreaker.OPEN
    with pytest.raises(CircuitOpenError):
        br.allow()
    assert br.stats()["fastFails"] == 1
    # after open_s: half-open admits ONE probe, fast-fails the second
    t[0] = 10.5
    br.allow()
    with pytest.raises(CircuitOpenError):
        br.allow()
    br.record(True)   # probe succeeded -> closed, window reset
    assert br.state == CircuitBreaker.CLOSED
    br.allow()
    br.record(True)


def test_breaker_reopens_on_failed_probe():
    br, t = _breaker()
    for _ in range(4):
        br.allow()
        br.record(False)
    t[0] = 10.5
    br.allow()          # the half-open probe
    br.record(False)    # ...fails
    assert br.state == CircuitBreaker.OPEN
    with pytest.raises(CircuitOpenError):
        br.allow()
    # and the clock must advance ANOTHER open_s before the next probe
    t[0] = 20.6
    br.allow()
    br.record(True)
    assert br.state == CircuitBreaker.CLOSED


def test_breaker_registry_disabled_by_default(monkeypatch):
    monkeypatch.delenv("PIO_BREAKER_ENABLED", raising=False)
    assert CircuitBreaker.for_endpoint("a:1") is None
    monkeypatch.setenv("PIO_BREAKER_ENABLED", "1")
    CircuitBreaker.reset_registry()
    try:
        b1 = CircuitBreaker.for_endpoint("a:1")
        assert b1 is not None
        assert CircuitBreaker.for_endpoint("a:1") is b1   # shared
        assert CircuitBreaker.for_endpoint("b:2") is not b1
    finally:
        CircuitBreaker.reset_registry()


# ----------------------------------------------------------- FaultInjector
def test_fault_spec_parsing_rejects_garbage():
    for bad in ("explode:0.5", "drop", "drop:nan", "drop:1.5", "drop:-1"):
        with pytest.raises(FaultSpecError):
            FaultInjector(bad)
    inj = FaultInjector("drop:0.5, error:0.1:502 @server, latency:1:5")
    kinds = [f.kind for f in inj.faults]
    assert kinds == ["drop", "error", "latency"]
    assert inj.faults[1].scope == "server"


def test_injector_drop_and_scope():
    inj = FaultInjector("drop:1@client")
    with pytest.raises(InjectedFault):
        inj.before_send("client", "POST /rpc")
    # scope mismatch: server boundary unaffected
    inj.before_send("server", "POST /rpc")
    assert inj.fired.get("drop") == 1


def test_injector_drop_max_fires_one_shot():
    """drop_rx:1:1 — exactly one lost response, then healed: the
    deterministic shape of a mid-request server kill."""
    inj = FaultInjector("drop_rx:1:1")
    with pytest.raises(InjectedFault):
        inj.after_send("client", "POST /rpc/read_columns")
    inj.after_send("client", "POST /rpc/read_columns")  # healed
    assert inj.fired["drop_rx"] == 1


def test_injector_error_and_truncate():
    inj = FaultInjector("error:1:503")
    status, payload = inj.on_response("client", "POST /rpc", 200, b"{}")
    assert status == 503 and b"injected" in payload
    inj = FaultInjector("truncate:1")
    status, payload = inj.on_response("client", "GET /x", 200, b"A" * 100)
    assert status == 200 and len(payload) == 50


def test_injector_deterministic_with_seed():
    a = FaultInjector("drop:0.5", seed=42)
    b = FaultInjector("drop:0.5", seed=42)

    def decisions(inj):
        out = []
        for _ in range(50):
            try:
                inj.before_send("client", "GET /")
                out.append(False)
            except InjectedFault:
                out.append(True)
        return out

    seq = decisions(a)
    assert seq == decisions(b)
    assert any(seq) and not all(seq)


def test_install_clear_and_env_activation(monkeypatch):
    resilience.clear()
    monkeypatch.delenv("PIO_FAULT_SPEC", raising=False)
    assert resilience.active() is None
    inj = resilience.install("drop:1")
    assert resilience.active() is inj
    resilience.clear()
    assert resilience.active() is None
    monkeypatch.setenv("PIO_FAULT_SPEC", "latency:1:1")
    env_inj = resilience.active()
    assert env_inj is not None
    assert resilience.active() is env_inj   # cached per spec value
    monkeypatch.delenv("PIO_FAULT_SPEC")
    assert resilience.active() is None


# ---------------------------------------------------------- degraded flag
def test_degraded_flag_scoped_per_thread():
    resilience.reset_degraded()
    resilience.note_degraded("a")
    resilience.note_degraded("b")
    assert resilience.pop_degraded() == ("a", "b")
    assert resilience.pop_degraded() == ()   # scope cleared

    # another thread's scope is independent
    seen = {}

    def other():
        resilience.reset_degraded()
        seen["other"] = resilience.pop_degraded()

    resilience.reset_degraded()
    resilience.note_degraded("mine")
    t = threading.Thread(target=other)
    t.start()
    t.join(5)
    assert seen["other"] == ()
    assert resilience.pop_degraded() == ("mine",)


def test_note_degraded_outside_scope_only_counts():
    before = resilience.degraded_total()
    resilience.pop_degraded()           # ensure no scope on this thread
    resilience.note_degraded("orphan")  # must not raise
    assert resilience.degraded_total() == before + 1
