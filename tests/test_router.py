"""`pio router` — the replica-fleet front door (workflow/router.py).

The contracts under test:

- membership is health-driven: a dead replica is ejected and re-admitted
  when its readiness probe recovers, with journal events on every
  transition;
- a replica dying mid-burst yields ZERO non-503 client errors — the
  idempotent /queries.json failover retries once on another replica;
- load shedding: an empty rotation or a spent deadline answers
  503 + Retry-After / 504 immediately, never an unbounded queue;
- the coordinated /reload barrier: a fleet never serves two model
  generations to one client (per-client responses are generation-
  monotonic) and zero queries drop during the swap;
- injected latency on ONE replica opens its breaker and shifts traffic
  (tier-1 shape via a delegating slow wrapper; the subprocess twin with
  a real PIO_FAULT_SPEC env rides the slow chaos suite);
- the router is a first-class fleet member: doctor line (membership,
  breakers, added-latency, generation skew), /debug/events.json,
  trace pass-through so `pio trace` assembles router→replica trees.

Tests marked ONLY `chaos` are the tier-1 smoke subset; the subprocess
SIGKILL / fault-spec legs carry chaos+slow and run with `-m chaos`.
"""

import datetime as dt
import http.client
import io
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from predictionio_tpu.common import journal
from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.api.http import make_server, serve_background
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.workflow import WorkflowContext, run_train
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig
from predictionio_tpu.workflow.router import (
    RouterAPI, RouterConfig, _parse_backend,
)

UTC = dt.timezone.utc

#: an importable factory so subprocess replicas can deploy without an
#: engine dir (get_engine resolves module:attr)
FACTORY = "predictionio_tpu.models.recommendation:RecommendationEngine"


def _train_seeded(storage, app_name="RouterApp", seed=3, fresh_app=True):
    """Seed ratings (once) + train one small ALS instance with this
    seed; different seeds give byte-distinguishable answers — the
    reload-barrier test's generation marker."""
    apps = storage.get_meta_data_apps()
    if fresh_app:
        app_id = apps.insert(App(0, app_name, None))
        storage.get_events().init(app_id)
        events = []
        for u in range(8):
            for i in range(6):
                events.append(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties=DataMap(
                        {"rating": 5.0 if (u % 2) == (i % 2) else 1.0}),
                    event_time=dt.datetime(2021, 1, 1, 0,
                                           (u * 6 + i) % 60, tzinfo=UTC)))
        storage.get_events().insert_batch(events, app_id)
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName=app_name),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=4, numIterations=3,
                                       lambda_=0.05, seed=seed)),))
    run_train(WorkflowContext(storage=storage), engine, ep,
              engine_factory=FACTORY,
              params_json={
                  "datasource": {"params": {"appName": app_name}},
                  "algorithms": [{"name": "als", "params": {
                      "rank": 4, "numIterations": 3, "lambda": 0.05,
                      "seed": seed}}]})
    return engine


def _replica(storage, engine, port=0):
    """One query-server replica on the async transport (its shutdown
    severs keep-alive connections — the in-process stand-in for a
    killed process). AOT off: router semantics don't depend on it, and
    ~15 prebuilt deploys of compiled-program memos would bloat the
    shared test process (the PR 14 RSS smoke runs in this process)."""
    api = QueryAPI(storage=storage, engine=engine,
                   config=ServerConfig(batching="on", aot="off"))
    server = make_server(api, "127.0.0.1", port, transport="async")
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return api, server, server.server_address[1]


def _router(ports, **kw):
    kw.setdefault("health_ms", 100.0)
    router = RouterAPI(RouterConfig(
        backends=tuple(f"http://127.0.0.1:{p}" for p in ports), **kw))
    server, rport = serve_background(router)
    return router, server, rport


def _post_query(conn, user="u1", num=3, headers=None):
    body = json.dumps({"user": user, "num": num})
    hdrs = {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    conn.request("POST", "/queries.json", body=body, headers=hdrs)
    resp = conn.getresponse()
    return resp.status, resp.read(), {k.lower(): v
                                      for k, v in resp.getheaders()}


# ---------------------------------------------------------------------------
# construction + shedding + deadline (no fleet needed)
# ---------------------------------------------------------------------------

def test_router_config_validation():
    with pytest.raises(ValueError):
        RouterAPI(RouterConfig(backends=()))
    with pytest.raises(ValueError):
        RouterAPI(RouterConfig(backends=("http://a:1", "http://a:1/")))
    with pytest.raises(ValueError):
        _parse_backend("https://sec.example:1")
    with pytest.raises(ValueError):
        _parse_backend("no-port")
    assert _parse_backend("http://h:8000/") == ("h", 8000)
    assert _parse_backend("h:8000") == ("h", 8000)


def test_router_sheds_with_no_backend_in_rotation():
    """Every backend dead => readyz 503 and /queries.json answers the
    existing 503 + Retry-After contract immediately."""
    # an unused ephemeral port: nothing listens there
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    router = RouterAPI(RouterConfig(
        backends=(f"http://127.0.0.1:{dead_port}",), health_ms=50.0))
    try:
        status, payload = router.handle("GET", "/readyz")
        assert status == 503 and payload["backendsInRotation"] == 0
        out = router.handle("POST", "/queries.json",
                            body=b'{"user": "u1", "num": 1}')
        assert out[0] == 503
        assert out[2]["Retry-After"]
        st = router.handle("GET", "/")[1]
        assert st["router"] is True and st["shedCount"] >= 1
    finally:
        router.close()


def test_router_spent_deadline_504s_instead_of_retrying(memory_storage):
    engine = _train_seeded(memory_storage)
    api, server, port = _replica(memory_storage, engine)
    router, rserver, rport = _router([port])
    try:
        conn = http.client.HTTPConnection("127.0.0.1", rport)
        status, payload, _ = _post_query(
            conn, headers={"X-PIO-Deadline-Ms": "0"})
        assert status == 504, payload
        # and an intact budget serves fine through the same router
        status, payload, _ = _post_query(conn)
        assert status == 200, payload
        conn.close()
    finally:
        rserver.shutdown()
        router.close()
        server.shutdown()
        api.close()


def test_router_inflight_admission_bound(memory_storage):
    """max_inflight=0-available => immediate 503 + Retry-After (the
    bound is structural; no queue grows behind it)."""
    engine = _train_seeded(memory_storage)
    api, server, port = _replica(memory_storage, engine)
    router, rserver, rport = _router([port], max_inflight=1)
    try:
        # exhaust the only slot from under the handler (the admission
        # count is a plain lock-guarded counter so the autopilot's shed
        # ladder can shrink the bound under load)
        with router._lock:
            router._inflight_count += 1
        out = router.handle("POST", "/queries.json",
                            body=b'{"user": "u1", "num": 1}')
        assert out[0] == 503 and out[2]["Retry-After"]
        with router._lock:
            router._inflight_count -= 1
        assert router.handle(
            "POST", "/queries.json",
            body=b'{"user": "u1", "num": 1}')[0] == 200
    finally:
        rserver.shutdown()
        router.close()
        server.shutdown()
        api.close()


# ---------------------------------------------------------------------------
# tenant-aware routing (PR 16): key forwarding, learned labels, per-tenant
# shedding, and per-tenant generation skew
# ---------------------------------------------------------------------------

class _MTStubAPI:
    """A minimal multi-tenant replica double: /readyz reports the
    per-tenant ``generations`` dict, /queries.json records the
    accessKey the router forwarded and answers with the backend's
    X-PIO-Tenant resolution header — the two wire surfaces the
    router's tenant awareness is built on."""

    KEYMAP = {"shop-key": "shop", "news-key": "news"}

    def __init__(self, generations):
        self.generations = dict(generations)
        self.seen_keys = []

    def handle(self, method, path, query=None, body=b"", headers=None):
        path = (path or "/").rstrip("/") or "/"
        if method == "GET" and path in ("/", "/healthz", "/readyz"):
            return 200, {"status": "ready",
                         "generation": max(self.generations.values()),
                         "generations": dict(self.generations)}
        if method == "POST" and path == "/queries.json":
            key = (query or {}).get("accessKey")
            self.seen_keys.append(key)
            if key is None:
                return 200, {"legacy": True}
            tenant = self.KEYMAP.get(key)
            if tenant is None:
                return 401, {"message": "Invalid accessKey."}
            return 200, {"tenant": tenant}, {"X-PIO-Tenant": tenant}
        return 404, {"message": "Not Found"}


def _wait_rotation(router, n, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if router.handle("GET", "/")[1]["inRotation"] == n:
            return
        time.sleep(0.02)
    raise AssertionError(f"fleet never reached {n} backends in rotation")


def _post_keyed(rport, key=None):
    conn = http.client.HTTPConnection("127.0.0.1", rport)
    try:
        path = "/queries.json"
        if key:
            path += f"?accessKey={key}"
        conn.request("POST", path, body=b'{"user": "u1", "num": 1}',
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}"), \
            {k.lower(): v for k, v in resp.getheaders()}
    finally:
        conn.close()


def test_router_tenant_forwarding_learning_and_skew():
    """The access key rides the forwarded URL (the backend resolves
    the SAME key the client presented), the router learns key->tenant
    from X-PIO-Tenant, and per-tenant generation skew across the fleet
    is surfaced by name — while key-less queries keep the bare
    legacy path byte for byte."""
    stub0 = _MTStubAPI({"shop": 3, "news": 4})
    stub1 = _MTStubAPI({"shop": 3, "news": 5})   # news lags: skew
    server0, port0 = serve_background(stub0)
    server1, port1 = serve_background(stub1)
    router, rserver, rport = _router([port0, port1])
    try:
        _wait_rotation(router, 2)
        # keyed query: forwarded WITH the key, answered, learned
        status, payload, _ = _post_keyed(rport, "shop-key")
        assert status == 200 and payload["tenant"] == "shop"
        assert (stub0.seen_keys + stub1.seen_keys) == ["shop-key"]
        assert router._tenant_by_key == {"shop-key": "shop"}
        # key-less query: bare legacy path, no tenant involvement
        status, payload, _ = _post_keyed(rport)
        assert status == 200 and payload == {"legacy": True}
        assert None in (stub0.seen_keys + stub1.seen_keys)
        # an unknown key's 401 passes through untouched
        assert _post_keyed(rport, "wrong")[0] == 401
        # fleet status: per-tenant generations + the skewed tenant named
        st = router.handle("GET", "/")[1]
        assert st["tenantGenerations"] == {"news": [4, 5], "shop": [3]}
        assert st["tenantGenerationSkew"] == ["news"]
    finally:
        rserver.shutdown()
        router.close()
        server0.shutdown()
        server1.shutdown()


def test_router_tenant_inflight_cap_sheds_one_tenant_only():
    """PIO_ROUTER_TENANT_MAX_INFLIGHT: a saturated tenant sheds 503 at
    the front door while other tenants and key-less queries ride on —
    and the cap charges the LEARNED tenant name, not the raw key."""
    stub = _MTStubAPI({"shop": 1, "news": 1})
    server, port = serve_background(stub)
    router, rserver, rport = _router([port], tenant_max_inflight=1)
    try:
        _wait_rotation(router, 1)
        # prime the learned mapping
        assert _post_keyed(rport, "shop-key")[0] == 200
        assert router._tenant_by_key["shop-key"] == "shop"
        # saturate tenant shop from under the handler
        with router._lock:
            router._tenant_inflight["shop"] = 1
        status, payload, headers = _post_keyed(rport, "shop-key")
        assert status == 503
        assert "tenant 'shop' is saturated" in payload["message"]
        assert headers["retry-after"]
        # ...while news and key-less traffic are untouched
        assert _post_keyed(rport, "news-key")[0] == 200
        assert _post_keyed(rport)[0] == 200
        # releasing the slot re-admits shop (no sticky state)
        with router._lock:
            router._tenant_inflight.pop("shop", None)
        assert _post_keyed(rport, "shop-key")[0] == 200
    finally:
        rserver.shutdown()
        router.close()
        server.shutdown()


# ---------------------------------------------------------------------------
# failover + membership (tier-1 chaos smoke)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_replica_kill_under_burst_zero_non_503(memory_storage):
    """THE fleet robustness contract, in-process shape: kill one of two
    replicas under a concurrent burst through the router — zero client
    errors that are not 503 (here: zero errors at all, failover covers
    the torn requests), the dead backend is ejected, and a restart on
    the same port re-admits it."""
    journal.clear()
    engine = _train_seeded(memory_storage)
    api0, server0, port0 = _replica(memory_storage, engine)
    api1, server1, port1 = _replica(memory_storage, engine)
    router, rserver, rport = _router([port0, port1])
    n_clients, per_client = 4, 30
    errors, lock = [], threading.Lock()
    statuses = []
    kill_at = threading.Event()

    def client(cx):
        conn = http.client.HTTPConnection("127.0.0.1", rport)
        my = []
        try:
            for q in range(per_client):
                if cx == 0 and q == 5:
                    kill_at.set()
                status, payload, _ = _post_query(conn, user=f"u{q % 8}")
                my.append(status)
                if status not in (200, 503):
                    raise AssertionError(
                        f"non-503 client error {status}: {payload[:200]}")
        except Exception as e:
            with lock:
                errors.append(e)
        finally:
            conn.close()
            with lock:
                statuses.extend(my)

    threads = [threading.Thread(target=client, args=(cx,))
               for cx in range(n_clients)]
    try:
        for t in threads:
            t.start()
        assert kill_at.wait(10)
        server0.shutdown()     # the in-process "kill": connections sever
        server0.server_close()
        api0.close()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert statuses.count(200) == n_clients * per_client, (
            statuses.count(200), statuses.count(503))
        # ejected...
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            st = router.handle("GET", "/")[1]
            rot = {b["url"]: b["inRotation"] for b in st["backends"]}
            if not rot[f"http://127.0.0.1:{port0}"]:
                break
            time.sleep(0.05)
        assert not rot[f"http://127.0.0.1:{port0}"], rot
        assert rot[f"http://127.0.0.1:{port1}"]
        # ...journaled...
        ev = journal.snapshot(category="router")
        assert any("ejected" in e["message"] for e in ev["events"])
        # ...and re-admitted on restart at the same port
        api2, server2, _ = _replica(memory_storage, engine, port=port0)
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                st = router.handle("GET", "/")[1]
                if all(b["inRotation"] for b in st["backends"]):
                    break
                time.sleep(0.05)
            assert all(b["inRotation"] for b in st["backends"]), st
            ev = journal.snapshot(category="router")
            assert any("re-admitted" in e["message"]
                       for e in ev["events"])
        finally:
            server2.shutdown()
            api2.close()
    finally:
        rserver.shutdown()
        router.close()
        server1.shutdown()
        api1.close()


@pytest.mark.chaos
def test_latency_on_one_replica_opens_breaker_and_shifts_traffic(
        memory_storage, monkeypatch):
    """One slow replica (the in-process stand-in for PIO_FAULT_SPEC
    latency — the env-spec twin rides the slow suite): first attempts
    against it time out inside the reserved half-budget, its breaker
    opens after min_calls failures, traffic shifts to the healthy
    replica, and tail latency recovers."""
    monkeypatch.setenv("PIO_BREAKER_MIN_CALLS", "3")
    engine = _train_seeded(memory_storage)
    api0, server0, port0 = _replica(memory_storage, engine)

    class SlowAPI:
        """Delegates to a real QueryAPI, adding 0.5 s to every query."""

        def __init__(self, inner):
            self._inner = inner

        def handle(self, method, path, query=None, body=b"",
                   headers=None):
            if path.rstrip("/") == "/queries.json":
                time.sleep(0.5)
            return self._inner.handle(method, path, query, body, headers)

    api1 = QueryAPI(storage=memory_storage, engine=engine,
                    config=ServerConfig(batching="on", aot="off"))
    server1 = make_server(SlowAPI(api1), "127.0.0.1", 0,
                          transport="async")
    threading.Thread(target=server1.serve_forever, daemon=True).start()
    port1 = server1.server_address[1]
    router, rserver, rport = _router([port0, port1], deadline_ms=600.0)
    slow_name = f"127.0.0.1:{port1}"
    try:
        conn = http.client.HTTPConnection("127.0.0.1", rport)
        slow = next(b for b in router.backends if b.name == slow_name)
        # burst until the breaker converges: each time the health
        # poller re-admits the slow replica, the next request pays a
        # half-budget timeout and records another breaker failure
        deadline = time.monotonic() + 30
        q = 0
        while time.monotonic() < deadline \
                and slow.breaker.state == "closed":
            status, payload, _ = _post_query(conn, user=f"u{q % 8}")
            assert status == 200, payload
            q += 1
        assert slow.breaker.state in ("open", "half-open"), \
            slow.breaker.stats()
        # traffic shifted: with the breaker open, requests no longer
        # pay the slow replica's timeout — the tail recovered (an
        # occasional half-open probe may still pay one, so median)
        post = []
        for q in range(10):
            t0 = time.perf_counter()
            status, payload, _ = _post_query(conn, user=f"u{q % 8}")
            post.append(time.perf_counter() - t0)
            assert status == 200, payload
        conn.close()
        assert sorted(post)[len(post) // 2] < 0.25, post
        assert router.failover_count > 0
    finally:
        rserver.shutdown()
        router.close()
        server0.shutdown()
        api0.close()
        server1.shutdown()
        api1.close()


# ---------------------------------------------------------------------------
# the coordinated hot-swap barrier
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_reload_barrier_zero_drops_and_monotone_generations(
        memory_storage):
    """THE barrier e2e: two replicas serve model A under a live burst;
    a second instance (different seed => byte-distinguishable answers)
    trains; POST /reload on the ROUTER swaps the fleet. Zero queries
    drop, and no client ever observes new-then-old — per-client
    responses are generation-monotonic, so one client never sees two
    model generations interleaved."""
    engine = _train_seeded(memory_storage, seed=3)
    api0, server0, port0 = _replica(memory_storage, engine)
    api1, server1, port1 = _replica(memory_storage, engine)
    router, rserver, rport = _router([port0, port1], health_ms=60.0)
    probe = json.dumps({"user": "u1", "num": 4})

    def answer(port):
        conn = http.client.HTTPConnection("127.0.0.1", port)
        conn.request("POST", "/queries.json", body=probe,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = resp.read()
        conn.close()
        assert resp.status == 200, out
        return out

    answer_a = answer(port0)
    assert answer_a == answer(port1)

    stop = threading.Event()
    errors, lock = [], threading.Lock()
    sequences = {}

    def client(cx):
        conn = http.client.HTTPConnection("127.0.0.1", rport)
        seq = []
        try:
            while not stop.is_set():
                conn.request("POST", "/queries.json", body=probe,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = resp.read()
                if resp.status != 200:
                    raise AssertionError(
                        f"dropped query: {resp.status} {payload[:200]}")
                seq.append(payload)
        except Exception as e:
            with lock:
                errors.append(e)
        finally:
            conn.close()
            with lock:
                sequences[cx] = seq

    threads = [threading.Thread(target=client, args=(cx,))
               for cx in range(4)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.3)
        # model B: same data, different factor seed
        _train_seeded(memory_storage, seed=4, fresh_app=False)
        conn = http.client.HTTPConnection("127.0.0.1", rport)
        conn.request("POST", "/reload?wait=1", body=b"")
        resp = conn.getresponse()
        reload_out = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        assert reload_out["reload"].get("ok") is True, reload_out
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        answer_b = answer(port0)
        assert answer_b != answer_a
        assert answer_b == answer(port1)
        swaps = 0
        for cx, seq in sequences.items():
            assert seq, f"client {cx} served nothing"
            kinds = []
            for payload in seq:
                assert payload in (answer_a, answer_b), payload[:200]
                kinds.append("A" if payload == answer_a else "B")
            # generation-monotonic: A...AB...B, never B after A resumed
            assert "BA" not in "".join(kinds), "".join(kinds)
            swaps += "B" in kinds
        assert swaps == len(sequences), "no client observed the swap"
        st = router.handle("GET", "/")[1]
        assert st["generations"] == [2] and not st["generationSkew"]
    finally:
        stop.set()
        rserver.shutdown()
        router.close()
        server0.shutdown()
        api0.close()
        server1.shutdown()
        api1.close()


def test_reload_barrier_single_backend_in_place(memory_storage):
    """N=1 degenerates to the replica's own zero-downtime in-process
    hot-swap: the lone backend never leaves rotation."""
    engine = _train_seeded(memory_storage)
    api, server, port = _replica(memory_storage, engine)
    router, rserver, rport = _router([port])
    try:
        _train_seeded(memory_storage, seed=9, fresh_app=False)
        status, payload = router.handle("POST", "/reload",
                                        query={"wait": "1"})[:2]
        assert status == 200 and payload["reload"]["ok"] is True
        st = router.handle("GET", "/")[1]
        assert st["backends"][0]["generation"] == 2
        assert st["backends"][0]["inRotation"]
    finally:
        rserver.shutdown()
        router.close()
        server.shutdown()
        api.close()


def test_concurrent_reload_barriers_409(memory_storage):
    engine = _train_seeded(memory_storage)
    api, server, port = _replica(memory_storage, engine)
    router, rserver, rport = _router([port])
    try:
        assert router._reload_lock.acquire(blocking=False)
        try:
            status, payload = router.handle("POST", "/reload")
            assert status == 409, payload
        finally:
            router._reload_lock.release()
    finally:
        rserver.shutdown()
        router.close()
        server.shutdown()
        api.close()


# ---------------------------------------------------------------------------
# fleet-member surfaces: doctor, journal, traces
# ---------------------------------------------------------------------------

def test_router_doctor_line_and_fleet_targets(memory_storage):
    from predictionio_tpu.tools.doctor import run_doctor, run_doctor_fleet

    engine = _train_seeded(memory_storage)
    api, server, port = _replica(memory_storage, engine)
    router, rserver, rport = _router([port])
    try:
        buf = io.StringIO()
        rc = run_doctor(f"http://127.0.0.1:{rport}", out=buf)
        text = buf.getvalue()
        assert rc in (0, 1), text   # reachable; other suites may have
        # left process-wide registry alarms (recompiles, failed AOT
        # builds) that redden UNRELATED checks on this shared /metrics
        router_lines = [ln for ln in text.splitlines()
                        if ln.strip().startswith("router")]
        assert router_lines and "1/1 in rotation" in router_lines[0], text
        assert " ok " in router_lines[0], text
        # --targets: router + replica in one sweep, worst code wins
        buf = io.StringIO()
        rc = run_doctor_fleet([f"http://127.0.0.1:{rport}",
                               f"http://127.0.0.1:{port}"], out=buf)
        assert rc in (0, 1), buf.getvalue()
        assert buf.getvalue().count("pio doctor —") == 2
        # a dead member turns the fleet verdict to 2 (unreachable)
        import socket
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead = s.getsockname()[1]
        s.close()
        buf = io.StringIO()
        rc = run_doctor_fleet([f"http://127.0.0.1:{rport}",
                               f"http://127.0.0.1:{dead}"], out=buf)
        assert rc == 2
    finally:
        rserver.shutdown()
        router.close()
        server.shutdown()
        api.close()


def test_router_doctor_generation_skew_warns():
    """A constructed scrape with two generations in the fleet WARNs on
    the router line (the aborted-barrier shape, KNOWN_ISSUES #15)."""
    from predictionio_tpu.tools.doctor import diagnose

    ok = {"status": 200, "body": '{"status": "ok"}'}
    scraped = {
        "url": "http://t", "healthz": dict(ok),
        "readyz": {"status": 200, "body": '{"status": "ready"}'},
        "root": {"status": 200, "body": json.dumps({
            "status": "alive", "router": True,
            "backends": [
                {"url": "http://a:1", "inRotation": True,
                 "generation": 1, "breaker": "closed"},
                {"url": "http://b:2", "inRotation": True,
                 "generation": 2, "breaker": "closed"}],
            "generations": [1, 2], "generationSkew": True,
            "shedCount": 0})},
        "metrics": {"status": 200, "body": ""},
        "traces": {"status": 200, "body": '{"spanCount": 0}'},
        "device": {"status": 200, "body": '{"telemetry": false}'},
        "slow": {"status": 200, "body": '{"enabled": false}'},
        "events": {"status": 200, "body":
                   '{"enabled": true, "events": []}'},
    }
    checks = {c: (s, d) for c, s, d in diagnose(scraped)}
    state, detail = checks["router"]
    assert state == "WARN" and "GENERATION SKEW" in detail


def test_router_journal_rides_debug_events(memory_storage):
    """The router's own /debug/events.json serves the `router` journal
    category — `pio events --targets <router>` treats it as one more
    fleet member with zero new plumbing."""
    journal.clear()
    engine = _train_seeded(memory_storage)
    api, server, port = _replica(memory_storage, engine)
    router, rserver, rport = _router([port])
    try:
        conn = http.client.HTTPConnection("127.0.0.1", rport)
        conn.request("GET", "/debug/events.json?category=router")
        resp = conn.getresponse()
        payload = json.loads(resp.read())
        conn.close()
        assert resp.status == 200 and payload["enabled"]
        assert any(e["category"] == "router" for e in payload["events"])
        # and `pio events --targets <router>,<replica>` merge-tails it
        # like any other fleet member
        from predictionio_tpu.common.traceview import run_events
        buf = io.StringIO()
        rc = run_events([f"http://127.0.0.1:{rport}",
                         f"http://127.0.0.1:{port}"],
                        category="router", out=buf)
        assert rc == 0
        assert "router" in buf.getvalue(), buf.getvalue()
    finally:
        rserver.shutdown()
        router.close()
        server.shutdown()
        api.close()


def test_router_trace_passthrough(memory_storage):
    """An incoming X-PIO-Trace is adopted by the router's transport and
    propagated to the chosen replica: both daemons buffer spans under
    the SAME trace id — the raw material `pio trace` assembles."""
    engine = _train_seeded(memory_storage)
    api, server, port = _replica(memory_storage, engine)
    router, rserver, rport = _router([port])
    trace_id = "00000000deadbeef"
    try:
        conn = http.client.HTTPConnection("127.0.0.1", rport)
        status, payload, _ = _post_query(
            conn, headers={"X-PIO-Trace": f"{trace_id}-0000000000000001"})
        assert status == 200, payload
        conn.request("GET", f"/traces.json?trace_id={trace_id}")
        router_spans = json.loads(conn.getresponse().read())
        conn.close()
        conn = http.client.HTTPConnection("127.0.0.1", port)
        conn.request("GET", f"/traces.json?trace_id={trace_id}")
        replica_spans = json.loads(conn.getresponse().read())
        conn.close()
        r_names = {s["name"] for t in router_spans.get("traces", [])
                   for s in t.get("spans", [])}
        b_names = {s["name"] for t in replica_spans.get("traces", [])
                   for s in t.get("spans", [])}
        assert "route" in r_names, router_spans
        assert any(n.startswith("server:") for n in b_names), replica_spans
    finally:
        rserver.shutdown()
        router.close()
        server.shutdown()
        api.close()


# ---------------------------------------------------------------------------
# subprocess fleet: real SIGKILL + real PIO_FAULT_SPEC (chaos + slow)
# ---------------------------------------------------------------------------

_REPLICA_SCRIPT = """\
import sys
port, url = int(sys.argv[1]), sys.argv[2]
from predictionio_tpu.data.storage import Storage
from predictionio_tpu.workflow.create_server import (
    QueryAPI, ServerConfig, serve,
)
storage = Storage(env={
    "PIO_STORAGE_SOURCES_R_TYPE": "remote",
    "PIO_STORAGE_SOURCES_R_URL": url,
    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "R",
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "R",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "R",
})
api = QueryAPI(storage=storage,
               config=ServerConfig(batching="on", aot="off"))
serve(api, host="127.0.0.1", port=port)
"""


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_replica(tmp_path, port, storage_url, extra_env=None):
    script = tmp_path / "replica.py"
    script.write_text(_REPLICA_SCRIPT)
    # sys.path[0] of a script run is the SCRIPT's directory — the repo
    # root must ride PYTHONPATH for the child to import the package
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pythonpath = repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": pythonpath.rstrip(os.pathsep),
           **(extra_env or {})}
    proc = subprocess.Popen(
        [sys.executable, str(script), str(port), storage_url],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    return proc


def _wait_ready(port, timeout=180.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=2.0)
            conn.request("GET", "/readyz")
            ok = conn.getresponse().status == 200
            conn.close()
            if ok:
                return True
        except OSError:
            pass
        time.sleep(0.25)
    return False


@pytest.fixture()
def _fleet_storage(tmp_path):
    """A file/HTTP-backed fleet substrate: the parent trains into a
    local store and serves it over a storage server; subprocess
    replicas deploy through the `remote` driver."""
    from predictionio_tpu.data.storage.remote import serve_storage

    backing = Storage(env={
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
        "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path / "el"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    })
    server = serve_storage(backing, host="127.0.0.1", port=0)
    url = f"http://127.0.0.1:{server.server_address[1]}"
    yield backing, url
    server.shutdown()
    server.server_close()


@pytest.mark.chaos
@pytest.mark.slow
def test_sigkill_replica_under_burst(tmp_path, _fleet_storage):
    """The real thing: two replica PROCESSES behind the router; SIGKILL
    one mid-burst — zero non-503 client errors, ejection, and
    re-admission when a fresh process takes the port back."""
    backing, url = _fleet_storage
    _train_seeded(backing)
    ports = [_free_port(), _free_port()]
    procs = [_spawn_replica(tmp_path, p, url) for p in ports]
    router = rserver = None
    try:
        for p in ports:
            assert _wait_ready(p), f"replica on {p} never became ready"
        router, rserver, rport = _router(ports)
        errors, statuses, lock = [], [], threading.Lock()
        killed = threading.Event()

        def client(cx):
            conn = http.client.HTTPConnection("127.0.0.1", rport)
            try:
                for q in range(25):
                    if cx == 0 and q == 4:
                        procs[0].kill()          # SIGKILL, mid-burst
                        procs[0].wait(timeout=10)
                        killed.set()
                    status, payload, _ = _post_query(conn,
                                                     user=f"u{q % 8}")
                    with lock:
                        statuses.append(status)
                    if status not in (200, 503):
                        raise AssertionError(
                            f"non-503 error {status}: {payload[:200]}")
            except Exception as e:
                with lock:
                    errors.append(e)
            finally:
                conn.close()

        threads = [threading.Thread(target=client, args=(cx,))
                   for cx in range(4)]
        for t in threads:
            t.start()
        assert killed.wait(30)
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert statuses.count(200) == len(statuses), statuses
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            st = router.handle("GET", "/")[1]
            rot = {b["url"]: b["inRotation"] for b in st["backends"]}
            if not rot[f"http://127.0.0.1:{ports[0]}"]:
                break
            time.sleep(0.1)
        assert not rot[f"http://127.0.0.1:{ports[0]}"], rot
        # a fresh process re-takes the port: re-admission is automatic
        procs[0] = _spawn_replica(tmp_path, ports[0], url)
        assert _wait_ready(ports[0])
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            st = router.handle("GET", "/")[1]
            if all(b["inRotation"] for b in st["backends"]):
                break
            time.sleep(0.1)
        assert all(b["inRotation"] for b in st["backends"]), st
    finally:
        if rserver is not None:
            rserver.shutdown()
        if router is not None:
            router.close()
        for proc in procs:
            proc.kill()


@pytest.mark.chaos
@pytest.mark.slow
def test_fault_spec_latency_shifts_traffic(tmp_path, _fleet_storage,
                                           monkeypatch):
    """PIO_FAULT_SPEC latency injected in ONE replica process: the
    router's reserved half-budget times the slow attempts out, the
    backend's breaker opens, traffic shifts, and the tail recovers."""
    monkeypatch.setenv("PIO_BREAKER_MIN_CALLS", "3")
    backing, url = _fleet_storage
    _train_seeded(backing)
    ports = [_free_port(), _free_port()]
    procs = [
        _spawn_replica(tmp_path, ports[0], url),
        _spawn_replica(
            tmp_path, ports[1], url,
            extra_env={"PIO_FAULT_SPEC": "latency:1:500@/queries.json"}),
    ]
    router = rserver = None
    try:
        for p in ports:
            assert _wait_ready(p), f"replica on {p} never became ready"
        router, rserver, rport = _router(ports, deadline_ms=600.0)
        conn = http.client.HTTPConnection("127.0.0.1", rport)
        slow = next(b for b in router.backends
                    if b.name == f"127.0.0.1:{ports[1]}")
        # burst until the breaker converges (see the in-process twin)
        deadline = time.monotonic() + 30
        q = 0
        while time.monotonic() < deadline \
                and slow.breaker.state == "closed":
            status, payload, _ = _post_query(conn, user=f"u{q % 8}")
            assert status == 200, payload
            q += 1
        assert slow.breaker.state in ("open", "half-open"), \
            slow.breaker.stats()
        post = []
        for q in range(10):
            t0 = time.perf_counter()
            status, payload, _ = _post_query(conn, user=f"u{q % 8}")
            post.append(time.perf_counter() - t0)
            assert status == 200, payload
        conn.close()
        assert sorted(post)[len(post) // 2] < 0.3, post
    finally:
        if rserver is not None:
            rserver.shutdown()
        if router is not None:
            router.close()
        for proc in procs:
            proc.kill()
