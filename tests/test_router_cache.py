"""The router's front-door response cache (workflow/router.py
`_ResponseCache`, `PIO_ROUTER_CACHE*`) + the zipfian bench sampler.

The contracts under test:

- the LRU unit: hit/miss accounting, TTL expiry and byte-budget
  evictions both counted, oversize bodies never stored;
- a hot key is answered WITHOUT touching a replica (the backend's
  request count stands still on a hit) and only 200s are stored;
- the key carries the PER-TENANT model generation (the PR 16
  `generations` dict, not the process scalar): one tenant's /reload
  invalidates exactly that tenant's entries — the other tenant keeps
  serving cached answers, and the invalidation is journaled;
- per-tenant generation SKEW across the fleet bypasses the cache
  entirely (neither lookup nor store) rather than serve either
  generation's answer for the other;
- cache off (the default) is advertisement-free: GET / has no
  `cache` key (wire parity is asserted in test_router_partition.py);
- `data/synthetic.query_keys` (the bench's zipfian sampler, built on
  the same `_zipf_cdf` the synthetic ratings use): deterministic per
  seed, properly skewed, bounded to the pool.
"""

import http.client
import json
import time

import numpy as np

from predictionio_tpu.common import journal
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.data.synthetic import query_keys
from predictionio_tpu.workflow.router import (
    RouterAPI, RouterConfig, _ResponseCache,
)


# ---------------------------------------------------------------------------
# the LRU unit (no fleet needed)
# ---------------------------------------------------------------------------

def test_response_cache_hit_miss_and_lru_eviction():
    cache = _ResponseCache(max_bytes=256, ttl_s=60.0)
    assert cache.get(("t", ("s", 1), b"q1")) is None          # miss
    assert cache.put(("t", ("s", 1), b"q1"), 200,
                     {"itemScores": []}, None) == 0
    hit = cache.get(("t", ("s", 1), b"q1"))
    assert hit is not None and hit[0] == 200                   # hit
    st = cache.stats()
    assert st["hits"] == 1 and st["misses"] == 1
    assert st["entries"] == 1 and 0 < st["bytes"] <= 256
    # a different generation is a different key — no false hit
    assert cache.get(("t", ("s", 2), b"q1")) is None
    # byte budget: inserting past it evicts the LEAST recently used
    evicted = 0
    for n in range(2, 30):
        evicted += cache.put(("t", ("s", 1), b"q%d" % n), 200,
                             {"itemScores": [], "n": n}, None)
    assert evicted > 0
    st = cache.stats()
    assert st["bytes"] <= 256 and st["evictions"] == evicted
    assert cache.get(("t", ("s", 1), b"q1")) is None           # aged out
    # oversize bodies are never stored (no eviction storm either)
    big = _ResponseCache(max_bytes=64, ttl_s=60.0)
    big.put(("t", ("s", 1), b"q"), 200, {"pad": "x" * 500}, None)
    assert big.stats()["entries"] == 0


def test_response_cache_ttl_expiry_counts_as_eviction():
    cache = _ResponseCache(max_bytes=1 << 20, ttl_s=0.05)
    cache.put(("t", ("s", 1), b"q"), 200, {"a": 1}, None)
    assert cache.get(("t", ("s", 1), b"q")) is not None
    time.sleep(0.08)
    assert cache.get(("t", ("s", 1), b"q")) is None
    st = cache.stats()
    assert st["entries"] == 0 and st["evictions"] == 1
    assert st["misses"] == 1 and st["hits"] == 1


def test_response_cache_invalidate_tenant_is_scoped():
    cache = _ResponseCache(max_bytes=1 << 20, ttl_s=60.0)
    cache.put(("shop", ("t", 1), b"a"), 200, {"s": 1}, None)
    cache.put(("shop", ("t", 1), b"b"), 200, {"s": 2}, None)
    cache.put(("news", ("t", 1), b"a"), 200, {"n": 1}, None)
    assert cache.invalidate_tenant("shop") == 2
    assert cache.get(("shop", ("t", 1), b"a")) is None
    assert cache.get(("news", ("t", 1), b"a")) is not None
    assert cache.stats()["evictions"] == 2


# ---------------------------------------------------------------------------
# through the router: hits skip the replica, generations scope the key
# ---------------------------------------------------------------------------

class _CountingStub:
    """A single-tenant replica double that counts /queries.json work
    and can answer non-200 on demand — the surface the cache fronts."""

    def __init__(self, generation=1):
        self.generation = generation
        self.query_count = 0

    def handle(self, method, path, query=None, body=b"", headers=None):
        path = (path or "/").rstrip("/") or "/"
        if method == "GET" and path in ("/", "/healthz", "/readyz"):
            return 200, {"status": "ready", "generation": self.generation}
        if method == "POST" and path == "/queries.json":
            self.query_count += 1
            req = json.loads(body or b"{}")
            if req.get("user") == "boom":
                return 503, {"message": "synthetic unavailability"}
            return 200, {"itemScores": [], "served": self.query_count}
        return 404, {"message": "Not Found"}


class _MTStub:
    """A multi-tenant replica double: /readyz carries the per-tenant
    ``generations`` dict, /queries.json resolves the access key and
    answers with X-PIO-Tenant — the surfaces the per-tenant cache
    keying reads."""

    KEYMAP = {"shop-key": "shop", "news-key": "news"}

    def __init__(self, generations):
        self.generations = dict(generations)
        self.query_count = 0

    def handle(self, method, path, query=None, body=b"", headers=None):
        path = (path or "/").rstrip("/") or "/"
        if method == "GET" and path in ("/", "/healthz", "/readyz"):
            return 200, {"status": "ready",
                         "generation": max(self.generations.values()),
                         "generations": dict(self.generations)}
        if method == "POST" and path == "/queries.json":
            self.query_count += 1
            tenant = self.KEYMAP.get((query or {}).get("accessKey"))
            if tenant is None:
                return 401, {"message": "Invalid accessKey."}
            return 200, {"tenant": tenant, "served": self.query_count}, \
                {"X-PIO-Tenant": tenant}
        return 404, {"message": "Not Found"}


def _cached_router(ports, **kw):
    kw.setdefault("health_ms", 60.0)
    kw.setdefault("cache", "on")
    kw.setdefault("cache_mb", 1)
    kw.setdefault("cache_ttl_ms", 60_000.0)
    router = RouterAPI(RouterConfig(
        backends=tuple(f"http://127.0.0.1:{p}" for p in ports), **kw))
    server, rport = serve_background(router)
    deadline = time.monotonic() + 10
    while (time.monotonic() < deadline
           and router.handle("GET", "/")[1]["inRotation"] != len(ports)):
        time.sleep(0.02)
    return router, server, rport


def _post(rport, body, key=None):
    conn = http.client.HTTPConnection("127.0.0.1", rport)
    try:
        path = "/queries.json" + (f"?accessKey={key}" if key else "")
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def test_cache_hit_skips_replica_and_skips_non_200():
    stub = _CountingStub()
    server, port = serve_background(stub)
    router, rserver, rport = _cached_router([port])
    try:
        body = json.dumps({"user": "u1", "num": 3}).encode()
        first = _post(rport, body)
        assert first[0] == 200
        served = stub.query_count
        # the hot key is answered at the front door: same bytes, the
        # replica's counter stands still
        for _ in range(3):
            assert _post(rport, body) == first
        assert stub.query_count == served
        # a different body is a different key
        assert _post(rport, json.dumps(
            {"user": "u2", "num": 3}).encode())[0] == 200
        assert stub.query_count == served + 1
        # non-200s pass through and are never stored
        boom = json.dumps({"user": "boom"}).encode()
        assert _post(rport, boom)[0] == 503
        assert _post(rport, boom)[0] == 503
        assert stub.query_count == served + 3
        st = router.handle("GET", "/")[1]["cache"]
        assert st["enabled"] and st["entries"] == 2
        assert st["hits"] == 3 and st["hitRatio"] > 0
    finally:
        rserver.shutdown()
        router.close()
        server.shutdown()


def test_tenant_reload_invalidates_only_that_tenant():
    """THE satellite contract: two tenants cached; bumping ONE
    tenant's generation (its /reload) drops exactly its entries —
    the other tenant's next query is still a front-door hit — and
    the invalidation rides the router journal."""
    journal.clear()
    stub = _MTStub({"shop": 1, "news": 1})
    server, port = serve_background(stub)
    router, rserver, rport = _cached_router([port])
    try:
        body = json.dumps({"user": "u1", "num": 3}).encode()
        # prime both tenants twice: learn the label, then store
        for key in ("shop-key", "news-key"):
            assert _post(rport, body, key)[0] == 200
            assert _post(rport, body, key)[0] == 200
        shop_answer = _post(rport, body, "shop-key")
        news_answer = _post(rport, body, "news-key")
        served = stub.query_count
        # both hot now: replica untouched
        assert _post(rport, body, "shop-key") == shop_answer
        assert _post(rport, body, "news-key") == news_answer
        assert stub.query_count == served

        # news reloads: generation 1 -> 2 on the backend
        stub.generations["news"] = 2
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if router.handle("GET", "/")[1]["cache"]["evictions"] >= 1:
                break
            time.sleep(0.03)
        # shop still answers from cache...
        assert _post(rport, body, "shop-key") == shop_answer
        assert stub.query_count == served
        # ...news goes back to the replica (fresh served counter)
        status, payload = _post(rport, body, "news-key")
        assert status == 200 and payload != news_answer[1]
        assert stub.query_count == served + 1
        ev = journal.snapshot(category="router")
        assert any("response cache invalidated for tenant 'news'"
                   in e["message"] for e in ev["events"]), \
            [e["message"] for e in ev["events"]]
        assert not any("tenant 'shop'" in e["message"]
                       for e in ev["events"])
    finally:
        rserver.shutdown()
        router.close()
        server.shutdown()


def test_generation_skew_bypasses_cache():
    """Two backends disagreeing on a tenant's generation (mid-barrier
    skew): that tenant's queries bypass the cache entirely — every
    request reaches a replica, nothing is stored — while an agreed
    tenant keeps caching."""
    stub0 = _MTStub({"shop": 1, "news": 7})
    stub1 = _MTStub({"shop": 2, "news": 7})   # shop: split vote
    server0, port0 = serve_background(stub0)
    server1, port1 = serve_background(stub1)
    router, rserver, rport = _cached_router([port0, port1])
    try:
        body = json.dumps({"user": "u1", "num": 3}).encode()
        for _ in range(4):
            assert _post(rport, body, "shop-key")[0] == 200
        shop_hits = stub0.query_count + stub1.query_count
        assert shop_hits == 4          # every one touched a replica
        # news agrees across the fleet: second query is a hit
        assert _post(rport, body, "news-key")[0] == 200
        assert _post(rport, body, "news-key")[0] == 200
        assert _post(rport, body, "news-key")[0] == 200
        assert stub0.query_count + stub1.query_count <= shop_hits + 2
        st = router.handle("GET", "/")[1]["cache"]
        # only news entries made it in
        assert st["entries"] == 1, st
    finally:
        rserver.shutdown()
        router.close()
        server0.shutdown()
        server1.shutdown()


def test_cache_off_is_advertisement_free():
    stub = _CountingStub()
    server, port = serve_background(stub)
    router = RouterAPI(RouterConfig(
        backends=(f"http://127.0.0.1:{port}",), health_ms=60.0))
    rserver, rport = serve_background(router)
    try:
        body = json.dumps({"user": "u1", "num": 3}).encode()
        assert _post(rport, body)[0] == 200
        assert _post(rport, body)[0] == 200
        assert stub.query_count == 2   # no front-door answering
        assert "cache" not in router.handle("GET", "/")[1]
    finally:
        rserver.shutdown()
        router.close()
        server.shutdown()


# ---------------------------------------------------------------------------
# the bench's zipfian key sampler
# ---------------------------------------------------------------------------

def test_query_keys_deterministic_and_skewed():
    a = query_keys(5000, seed=7, exponent=1.1, pool=64)
    b = query_keys(5000, seed=7, exponent=1.1, pool=64)
    assert np.array_equal(a, b)                      # seeded => replay
    assert a.dtype == np.int32
    assert a.min() >= 0 and a.max() < 64
    assert not np.array_equal(a, query_keys(5000, seed=8,
                                            exponent=1.1, pool=64))
    # zipf skew: the hottest key draws far more than the uniform share
    counts = np.bincount(a, minlength=64)
    assert counts.max() > 4 * (5000 / 64)
    # a steeper exponent concentrates harder
    steep = np.bincount(query_keys(5000, seed=7, exponent=2.0, pool=64),
                        minlength=64)
    assert steep.max() > counts.max()
    assert query_keys(0, seed=1).size == 0
