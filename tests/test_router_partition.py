"""Partition-routed serving (`pio deploy --partition i/N` +
workflow/router.py scatter/merge).

The contracts under test:

- `parse_partition` / `partition_rows`: the contiguous, order-
  preserving row split — slices tile [0, n) exactly, sizes within 1;
- `merge_candidates` is the HOST twin of the device all-gather merge:
  bit-identical values/indices to ``lax.sort((-v, g), num_keys=2)``
  for every k, cross-partition ties included (lowest global index
  wins), and merging per-partition top-k candidate lists reproduces
  the global top-k (the coverage guarantee the scatter relies on);
- a partition replica advertises its owned range on /readyz and
  annotates answers with global item indices; the router assembles a
  servable map and a partition fleet's merged answers over live HTTP
  are BYTE-identical to a single full-model replica — including the
  naturally-tied scores that straddle the partition boundary;
- coverage incomplete (one partition ejected) => 503 + Retry-After,
  never a partial merge, and the map loss is journaled RED;
- the default config (no --partition, cache off) advertises nothing
  new: GET / carries neither `partitions` nor `cache`, and routed
  bytes equal the replica's own — the PR 16 wire, untouched;
- `--partition` refuses to compose with `--engines` multi-tenancy;
- `pio doctor` turns a coverage gap RED and a cold enabled cache WARN
  from the scraped surfaces alone.
"""

import datetime as dt
import http.client
import json
import threading
import time

import numpy as np
import pytest

from predictionio_tpu.common import journal
from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.api.http import make_server, serve_background
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.parallel.serve_dist import (
    merge_candidates, parse_partition, partition_rows,
)
from predictionio_tpu.workflow import WorkflowContext, run_train
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig
from predictionio_tpu.workflow.router import RouterAPI, RouterConfig

UTC = dt.timezone.utc
FACTORY = "predictionio_tpu.models.recommendation:RecommendationEngine"


# ---------------------------------------------------------------------------
# the row split + the host merge twin (no fleet needed)
# ---------------------------------------------------------------------------

def test_parse_partition():
    assert parse_partition("0/2") == (0, 2)
    assert parse_partition("3/4") == (3, 4)
    assert parse_partition(" 1/2 ") == (1, 2)
    for bad in ("", "2/2", "4/3", "-1/2", "0/0", "0/-1", "a/b", "1",
                "1/2/3", "1.5/2"):
        with pytest.raises(ValueError):
            parse_partition(bad)


def test_partition_rows_tile_exactly():
    for n in (0, 1, 5, 6, 7, 64, 1000):
        for count in (1, 2, 3, 5, 8):
            slices = [partition_rows(n, i, count) for i in range(count)]
            # contiguous, order-preserving, tiling [0, n) exactly
            assert slices[0][0] == 0 and slices[-1][1] == n
            for (alo, ahi), (blo, bhi) in zip(slices, slices[1:]):
                assert ahi == blo
            sizes = [hi - lo for lo, hi in slices]
            assert sum(sizes) == n
            assert max(sizes) - min(sizes) <= 1


def test_merge_candidates_bit_parity_with_device_sort():
    """The host merge must land on EXACTLY the device rule: two-key
    sort, score descending then lowest global index — values
    bit-identical, ties (planted across the would-be partition
    boundary) resolved identically."""
    from jax import lax
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    v = rng.standard_normal(40).astype(np.float32)
    # cross-partition ties: equal float32 scores at far-apart gids
    v[3] = v[29] = np.float32(1.5)
    v[7] = v[21] = v[33] = np.float32(0.25)
    g = np.arange(40, dtype=np.int32)
    neg, sid = lax.sort((-jnp.asarray(v), jnp.asarray(g)),
                        num_keys=2, dimension=-1)
    dev_v, dev_g = -np.asarray(neg), np.asarray(sid)
    for k in (1, 2, 5, 17, 40):
        mv, mg, order = merge_candidates(v, g, k)
        assert mv.tobytes() == dev_v[:k].tobytes()
        assert np.array_equal(mg, dev_g[:k])
        assert len(order) == k
    # the tie rule, spelled out: among equal scores the LOWEST global
    # index comes first (both planted groups)
    mv, mg, _ = merge_candidates(v, g, 40)
    for tied in (np.int32(3), np.int32(7)):
        group = mg[mv == v[tied]]
        assert list(group) == sorted(group)


def test_merge_of_per_partition_topk_equals_global_topk():
    """The coverage guarantee: each partition contributing its LOCAL
    top-k (same two-key rule) is enough — merging the candidate lists
    reproduces the global top-k bit for bit. This is exactly what the
    router does with N replicas' answers."""
    rng = np.random.default_rng(7)
    n, k = 101, 10
    v = rng.standard_normal(n).astype(np.float32)
    v[4] = v[77] = np.float32(2.25)        # a tie straddling partitions
    g = np.arange(n, dtype=np.int32)
    want_v, want_g, _ = merge_candidates(v, g, k)
    for count in (2, 3, 5):
        cand_v, cand_g = [], []
        for i in range(count):
            lo, hi = partition_rows(n, i, count)
            lv, lg, _ = merge_candidates(v[lo:hi], g[lo:hi], k)
            cand_v.append(lv)
            cand_g.append(lg)
        got_v, got_g, _ = merge_candidates(
            np.concatenate(cand_v), np.concatenate(cand_g), k)
        assert got_v.tobytes() == want_v.tobytes(), count
        assert np.array_equal(got_g, want_g), count


def test_partition_refuses_multitenancy(memory_storage):
    with pytest.raises(ValueError):
        QueryAPI(storage=memory_storage,
                 config=ServerConfig(partition="0/2",
                                     tenants=("shop",)))


# ---------------------------------------------------------------------------
# the live fleet: byte parity, coverage gap, default-config wire parity
# ---------------------------------------------------------------------------

def _train_seeded(storage, app_name="PartitionApp", seed=3):
    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(0, app_name, None))
    storage.get_events().init(app_id)
    events = []
    for u in range(8):
        for i in range(6):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap(
                    {"rating": 5.0 if (u % 2) == (i % 2) else 1.0}),
                event_time=dt.datetime(2021, 1, 1, 0,
                                       (u * 6 + i) % 60, tzinfo=UTC)))
    storage.get_events().insert_batch(events, app_id)
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName=app_name),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=4, numIterations=3,
                                       lambda_=0.05, seed=seed)),))
    run_train(WorkflowContext(storage=storage), engine, ep,
              engine_factory=FACTORY,
              params_json={
                  "datasource": {"params": {"appName": app_name}},
                  "algorithms": [{"name": "als", "params": {
                      "rank": 4, "numIterations": 3, "lambda": 0.05,
                      "seed": seed}}]})
    return engine


def _replica(storage, engine, partition=""):
    api = QueryAPI(storage=storage, engine=engine,
                   config=ServerConfig(batching="on", aot="off",
                                       partition=partition))
    server = make_server(api, "127.0.0.1", 0, transport="async")
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return api, server, server.server_address[1]


def _raw_query(port, body):
    conn = http.client.HTTPConnection("127.0.0.1", port)
    try:
        conn.request("POST", "/queries.json", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read(), {k.lower(): v for k, v
                                          in resp.getheaders()}
    finally:
        conn.close()


def test_partition_fleet_wire_byte_identical(memory_storage):
    """THE tentpole contract over live HTTP: one full replica vs a
    router over two partition replicas of the SAME trained model —
    every (user, num) answer byte-identical, the parity-patterned
    data guaranteeing tied scores that straddle the partition
    boundary; then a killed partition turns the fleet into a clean
    503 coverage gap, never a partial merge."""
    journal.clear()
    engine = _train_seeded(memory_storage)
    api_full, s_full, p_full = _replica(memory_storage, engine)
    api0, s0, p0 = _replica(memory_storage, engine, partition="0/2")
    api1, s1, p1 = _replica(memory_storage, engine, partition="1/2")
    router = RouterAPI(RouterConfig(
        backends=(f"http://127.0.0.1:{p0}", f"http://127.0.0.1:{p1}"),
        health_ms=80.0))
    rserver, rport = serve_background(router)
    try:
        # replicas advertise the owned range on /readyz
        conn = http.client.HTTPConnection("127.0.0.1", p0)
        conn.request("GET", "/readyz")
        ready = json.loads(conn.getresponse().read())
        conn.close()
        assert ready["partition"]["index"] == 0
        assert ready["partition"]["count"] == 2
        assert ready["partition"]["nItems"] == 6
        boundary = ready["partition"]["hi"]

        # the router assembles a complete servable map
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and router._pmap is None:
            time.sleep(0.05)
        st = router.handle("GET", "/")[1]
        parts = st["partitions"]
        assert parts["complete"] and parts["count"] == 2, parts
        assert set(parts["owners"]) == {"0", "1"}

        # byte parity on EVERY user at several k, including k > rows
        # per partition and k > the whole catalog
        mismatches = []
        for u in range(8):
            for num in (1, 3, 6, 10):
                body = json.dumps({"user": f"u{u}", "num": num})
                full = _raw_query(p_full, body)
                routed = _raw_query(rport, body)
                if full[:2] != routed[:2]:
                    mismatches.append((u, num, full[0], routed[0]))
        assert not mismatches, mismatches

        # the parity data really does tie ACROSS the boundary: the
        # full answer at num=6 has equal scores on both sides
        payload = json.loads(_raw_query(
            p_full, json.dumps({"user": "u1", "num": 6}))[1])
        scores = [(s["score"], int(s["item"][1:]))
                  for s in payload["itemScores"]]
        straddles = any(
            sa == sb and (ia < boundary) != (ib < boundary)
            for x, (sa, ia) in enumerate(scores)
            for sb, ib in scores[x + 1:])
        assert straddles, scores

        # merged answers never leak the replica-side partition block
        assert b'"partition"' not in _raw_query(
            rport, json.dumps({"user": "u1", "num": 3}))[1]

        # a malformed body propagates the replica's own error verbatim
        assert _raw_query(rport, b'{"num": 1}')[0] == \
            _raw_query(p_full, b'{"num": 1}')[0]

        # kill one partition: the map is LOST (journaled RED) and the
        # fleet answers 503 + Retry-After — never a 1-partition merge
        s1.shutdown()
        api1.close()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and router._pmap is not None:
            time.sleep(0.05)
        assert router._pmap is None
        status, payload, headers = _raw_query(
            rport, json.dumps({"user": "u1", "num": 3}))
        assert status == 503, payload
        assert b"coverage" in payload
        assert headers["retry-after"]
        st = router.handle("GET", "/")[1]
        assert st["partitions"]["complete"] is False
        ev = journal.snapshot(category="router")
        assert any("partition map LOST" in e["message"]
                   and e["level"] == "red" for e in ev["events"]), \
            [e["message"] for e in ev["events"]]
        assert any("partition map live" in e["message"]
                   for e in ev["events"])
    finally:
        rserver.shutdown()
        router.close()
        s_full.shutdown()
        api_full.close()
        s0.shutdown()
        api0.close()
        s1.shutdown()
        api1.close()


def test_default_config_wire_unchanged(memory_storage):
    """No --partition, cache off: the router advertises neither
    `partitions` nor `cache` on GET / and routed bytes equal the
    replica's own — the pre-partition wire, byte for byte."""
    engine = _train_seeded(memory_storage, app_name="PlainApp")
    api, server, port = _replica(memory_storage, engine)
    router = RouterAPI(RouterConfig(
        backends=(f"http://127.0.0.1:{port}",), health_ms=80.0))
    rserver, rport = serve_background(router)
    try:
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and router.handle("GET", "/")[1]["inRotation"] != 1):
            time.sleep(0.02)
        st = router.handle("GET", "/")[1]
        assert "partitions" not in st
        assert "cache" not in st
        body = json.dumps({"user": "u1", "num": 4})
        assert _raw_query(rport, body)[:2] == _raw_query(port, body)[:2]
    finally:
        rserver.shutdown()
        router.close()
        server.shutdown()
        api.close()


# ---------------------------------------------------------------------------
# doctor: coverage gap RED, cold cache WARN (constructed scrapes)
# ---------------------------------------------------------------------------

def _router_scrape(root_extra):
    root = {"status": "alive", "router": True,
            "backends": [{"url": "http://a:1", "inRotation": True,
                          "generation": 1, "breaker": "closed"}],
            "generations": [1], "generationSkew": False,
            "shedCount": 0, **root_extra}
    return {
        "url": "http://t",
        "healthz": {"status": 200, "body": '{"status": "ok"}'},
        "readyz": {"status": 200, "body": '{"status": "ready"}'},
        "root": {"status": 200, "body": json.dumps(root)},
        "metrics": {"status": 200, "body": ""},
        "traces": {"status": 200, "body": '{"spanCount": 0}'},
        "device": {"status": 200, "body": '{"telemetry": false}'},
        "slow": {"status": 200, "body": '{"enabled": false}'},
        "events": {"status": 200,
                   "body": '{"enabled": true, "events": []}'},
    }


def test_doctor_partition_coverage_gap_is_red():
    from predictionio_tpu.tools.doctor import diagnose

    scraped = _router_scrape({"partitions": {
        "complete": False, "count": None, "generation": None,
        "nItems": None, "owners": {"0": [
            {"backend": "http://a:1", "lo": 0, "hi": 3}]}}})
    checks = {c: (s, d) for c, s, d in diagnose(scraped)}
    state, detail = checks["router"]
    assert state == "RED" and "COVERAGE GAP" in detail
    assert "503" in detail


def test_doctor_partition_map_rides_ok_detail():
    from predictionio_tpu.tools.doctor import diagnose

    scraped = _router_scrape({"partitions": {
        "complete": True, "count": 2, "generation": 3, "nItems": 6,
        "owners": {"0": [{"backend": "http://a:1", "lo": 0, "hi": 3}],
                   "1": [{"backend": "http://b:2", "lo": 3, "hi": 6}]}}})
    checks = {c: (s, d) for c, s, d in diagnose(scraped)}
    state, detail = checks["router"]
    assert state == "ok", detail
    assert "partition map 2 wide" in detail
    assert "p0=[0,3)x1" in detail and "p1=[3,6)x1" in detail


def test_doctor_cold_enabled_cache_warns():
    from predictionio_tpu.tools.doctor import diagnose

    cold = _router_scrape({"cache": {
        "enabled": True, "entries": 40, "bytes": 1000,
        "maxBytes": 1 << 20, "ttlMs": 5000.0,
        "hits": 0, "misses": 40, "evictions": 0, "hitRatio": 0.0}})
    checks = {c: (s, d) for c, s, d in diagnose(cold)}
    state, detail = checks["router"]
    assert state == "WARN" and "cache" in detail
    assert "0.0%" in detail
    # a warm cache (or one without traffic yet) stays OK
    for stats in ({"hits": 30, "misses": 10, "hitRatio": 0.75},
                  {"hits": 0, "misses": 3, "hitRatio": 0.0}):
        warm = _router_scrape({"cache": {
            "enabled": True, "entries": 5, "bytes": 100,
            "maxBytes": 1 << 20, "ttlMs": 5000.0, "evictions": 0,
            **stats}})
        checks = {c: (s, d) for c, s, d in diagnose(warm)}
        assert checks["router"][0] == "ok", checks["router"]
