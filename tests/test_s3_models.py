"""Object-store Models backend (S3Models.scala:36-95 parity) against an
in-process S3-compatible fake: full Storage wiring, roundtrip, overwrite,
missing-get, delete, error surfacing, and SigV4 header shape."""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from predictionio_tpu.data.storage import Model, Storage


class _FakeS3(BaseHTTPRequestHandler):
    store: dict = {}
    seen_headers: list = []
    fail_next: list = []       # status codes to force, consumed in order

    def _respond(self, status, body=b""):
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):
        self.seen_headers.append(dict(self.headers.items()))
        if self.fail_next:
            return self._respond(self.fail_next.pop(0))
        n = int(self.headers.get("Content-Length") or 0)
        self.store[self.path] = self.rfile.read(n)
        self._respond(200)

    def do_GET(self):
        if self.fail_next:
            return self._respond(self.fail_next.pop(0))
        if self.path in self.store:
            self._respond(200, self.store[self.path])
        else:
            self._respond(404)

    def do_DELETE(self):
        self.store.pop(self.path, None)
        self._respond(204)

    def log_message(self, *a):
        pass


@pytest.fixture()
def s3_storage():
    handler = type("H", (_FakeS3,), {"store": {}, "seen_headers": [],
                                     "fail_next": []})
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    port = server.server_address[1]
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_S3_TYPE": "s3",
        "PIO_STORAGE_SOURCES_S3_ENDPOINT": f"http://127.0.0.1:{port}",
        "PIO_STORAGE_SOURCES_S3_BUCKET_NAME": "pio-models",
        "PIO_STORAGE_SOURCES_S3_BASE_PATH": "prod/models",
        "PIO_STORAGE_SOURCES_S3_ACCESS_KEY_ID": "AKIDEXAMPLE",
        "PIO_STORAGE_SOURCES_S3_SECRET_ACCESS_KEY": "secret",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "S3",
    })
    try:
        yield storage, handler
    finally:
        server.shutdown()


def test_roundtrip_overwrite_delete(s3_storage):
    storage, handler = s3_storage
    models = storage.get_model_data_models()
    models.insert(Model(id="inst1", models=b"\x00blob-one"))
    got = models.get("inst1")
    assert got is not None and got.models == b"\x00blob-one"
    # key layout: /<bucket>/<BASE_PATH>/<namespace>-<id>
    assert "/pio-models/prod/models/pio_modeldata-inst1" in handler.store
    # overwrite wins
    models.insert(Model(id="inst1", models=b"blob-two"))
    assert models.get("inst1").models == b"blob-two"
    assert models.get("missing") is None
    models.delete("inst1")
    assert models.get("inst1") is None


def test_sigv4_headers_present(s3_storage):
    storage, handler = s3_storage
    storage.get_model_data_models().insert(Model(id="x", models=b"y"))
    hdrs = handler.seen_headers[-1]
    auth = hdrs.get("authorization", "")
    assert auth.startswith("AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/")
    assert "SignedHeaders=host;x-amz-content-sha256;x-amz-date" in auth
    assert "Signature=" in auth
    assert hdrs.get("x-amz-content-sha256")


def test_put_failure_raises(s3_storage):
    storage, handler = s3_storage
    handler.fail_next.append(500)
    with pytest.raises(IOError, match="PUT"):
        storage.get_model_data_models().insert(Model(id="z", models=b"b"))


def test_missing_bucket_rejected():
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_S3_TYPE": "s3",
        "PIO_STORAGE_SOURCES_S3_ENDPOINT": "http://127.0.0.1:1",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "S3",
    })
    with pytest.raises((ValueError, RuntimeError), match="BUCKET_NAME"):
        storage.get_model_data_models()
