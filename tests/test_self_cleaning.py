"""SelfCleaningDataSource tests (ref: core/src/test/scala/.../
SelfCleaningDataSourceTest semantics)."""

import datetime as dt

import pytest

from predictionio_tpu.controller.self_cleaning import (
    EventWindow, SelfCleaningDataSource, parse_duration,
)
from predictionio_tpu.data import store
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App

UTC = dt.timezone.utc
NOW = dt.datetime(2021, 6, 10, tzinfo=UTC)


def ev(name, entity, props=None, day=1, **kw):
    return Event(
        event=name, entity_type="user", entity_id=entity,
        properties=DataMap(props or {}),
        event_time=dt.datetime(2021, 6, day, tzinfo=UTC), **kw)


class _DS(SelfCleaningDataSource):
    app_name = "CleanApp"

    def __init__(self, window):
        self.event_window = window


@pytest.fixture()
def app(memory_storage):
    app_id = memory_storage.get_meta_data_apps().insert(App(0, "CleanApp"))
    memory_storage.get_events().init(app_id)
    return app_id


def test_parse_duration():
    assert parse_duration("3 days") == dt.timedelta(days=3)
    assert parse_duration("12h") == dt.timedelta(hours=12)
    assert parse_duration("90 seconds") == dt.timedelta(seconds=90)
    with pytest.raises(ValueError):
        parse_duration("sideways")


def test_window_keeps_recent_and_set_events(memory_storage, app):
    store.write([
        ev("buy", "u1", day=1),          # old -> dropped
        ev("buy", "u1", day=9),          # recent -> kept
        ev("$set", "u1", {"a": 1}, day=1),   # $set always kept
    ], app, storage=memory_storage)
    ds = _DS(EventWindow(duration="3 days"))
    cleaned = ds.clean_events(storage=memory_storage, now=NOW)
    assert {(e.event, e.event_time.day) for e in cleaned} == {
        ("buy", 9), ("$set", 1)}
    # no window -> everything
    assert len(_DS(None).clean_events(storage=memory_storage, now=NOW)) == 3


def test_compress_properties_per_entity(memory_storage, app):
    store.write([
        ev("$set", "u1", {"a": 1, "b": 2}, day=1),
        ev("$unset", "u1", {"b": None}, day=2),
        ev("$set", "u1", {"c": 3}, day=3),
        ev("$set", "u2", {"x": 9}, day=2),
        ev("buy", "u1", day=4),
    ], app, storage=memory_storage)
    ds = _DS(EventWindow(compress_properties=True))
    cleaned = ds.clean_events(storage=memory_storage, now=NOW)
    sets = {e.entity_id: e for e in cleaned if e.event == "$set"}
    assert sets["u1"].properties.to_dict() == {"a": 1, "c": 3}
    assert sets["u1"].event_time.day == 3  # last write's time
    assert sets["u2"].properties.to_dict() == {"x": 9}
    assert sum(1 for e in cleaned if e.event == "buy") == 1


def test_compress_chain_starting_with_unset(memory_storage, app):
    """A chain whose first event is $unset must still compress to a $set
    of the surviving fields, not a mislabeled $unset."""
    store.write([
        ev("$unset", "u1", {"b": None}, day=1),
        ev("$set", "u1", {"a": 1}, day=2),
    ], app, storage=memory_storage)
    ds = _DS(EventWindow(compress_properties=True))
    cleaned = ds.clean_events(storage=memory_storage, now=NOW)
    assert len(cleaned) == 1
    assert cleaned[0].event == "$set"
    assert cleaned[0].properties.to_dict() == {"a": 1}


def test_remove_duplicates_keeps_first(memory_storage, app):
    store.write([
        ev("buy", "u1", {"q": 1}, day=2),
        ev("buy", "u1", {"q": 1}, day=5),    # duplicate (times differ)
        ev("buy", "u1", {"q": 2}, day=5),    # different properties -> kept
    ], app, storage=memory_storage)
    ds = _DS(EventWindow(remove_duplicates=True))
    cleaned = ds.clean_events(storage=memory_storage, now=NOW)
    assert len(cleaned) == 2
    kept = [e for e in cleaned if e.properties.to_dict() == {"q": 1}]
    assert kept[0].event_time.day == 2  # earliest kept


def test_clean_persisted_events_rewrites_store(memory_storage, app):
    store.write([
        ev("$set", "u1", {"a": 1}, day=1),
        ev("$set", "u1", {"b": 2}, day=2),
        ev("buy", "u1", day=3),
        ev("buy", "u1", day=3),
    ], app, storage=memory_storage)
    ds = _DS(EventWindow(compress_properties=True, remove_duplicates=True))
    ds.clean_persisted_events(storage=memory_storage, now=NOW)
    after = list(store.find("CleanApp", storage=memory_storage))
    sets = [e for e in after if e.event == "$set"]
    buys = [e for e in after if e.event == "buy"]
    assert len(sets) == 1 and sets[0].properties.to_dict() == {"a": 1, "b": 2}
    assert len(buys) == 1
    # idempotent second run
    ds.clean_persisted_events(storage=memory_storage, now=NOW)
    assert len(list(store.find("CleanApp", storage=memory_storage))) == 2
