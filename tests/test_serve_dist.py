"""Sharded serving (parallel/serve_dist.py) on the 8-device virtual mesh.

The acceptance surface of ISSUE 8: sharded and replicated serving return
BIT-identical (values, indices) top-k — at 1 device and at 8 simulated
devices, including constructed score ties across shard boundaries — the
mode knob resolves config/env/auto correctly (auto falls back on /reload
hot-swap), the deployed server's wire bytes are unchanged by sharding,
and the sharded (bucket x k) programs are AOT-prebuilt so
post_warmup_recompiles stays 0 with sharding on.
"""

import datetime as dt
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from predictionio_tpu.common import devicewatch, telemetry
from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.ops import topk
from predictionio_tpu.parallel import serve_dist
from predictionio_tpu.workflow import WorkflowContext, run_train
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig


@pytest.fixture(autouse=True)
def _clean():
    yield
    serve_dist.record_state(None)
    telemetry.set_enabled(None)


def _factors(n_users=13, n_items=45, rank=5, seed=0):
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(n_users, rank)).astype(np.float32)
    V = rng.normal(size=(n_items, rank)).astype(np.float32)
    return U, V


def _replicated(U, V, ixs, k):
    return jax.device_get(topk.topk_for_users(
        jnp.asarray(U), jnp.asarray(V), np.asarray(ixs, np.int32), k=k))


# ---------------------------------------------------------------------------
# kernel parity: bit-identical to the replicated path
# ---------------------------------------------------------------------------

def test_sharded_matches_replicated_bit_identical():
    """8 shards, n_items NOT divisible by the device count (padding rows
    on the last shard), k spanning below/at/above rows-per-shard."""
    U, V = _factors()
    sharded = serve_dist.shard_factors(U, V)
    assert sharded.n_shards == 8
    ixs = np.array([0, 5, 12, 0, 7], dtype=np.int32)
    for k in (1, 3, 6, 20, 45):     # rows_dev_i = 6: 20 and 45 exceed it
        sv, si = jax.device_get(sharded.topk(ixs, k))
        rv, ri = _replicated(U, V, ixs, k)
        # bit-identical, not allclose: view as int32 so -0.0 vs 0.0 or a
        # ulp of drift would fail loudly
        np.testing.assert_array_equal(sv.view(np.int32),
                                      rv.view(np.int32), err_msg=f"k={k}")
        np.testing.assert_array_equal(si, ri, err_msg=f"k={k}")


def test_sharded_single_device_mesh_parity():
    U, V = _factors(seed=1)
    sharded = serve_dist.shard_factors(U, V, n_shards=1)
    assert sharded.n_shards == 1
    ixs = np.array([2, 2, 9], dtype=np.int32)
    sv, si = jax.device_get(sharded.topk(ixs, 7))
    rv, ri = _replicated(U, V, ixs, 7)
    np.testing.assert_array_equal(sv.view(np.int32), rv.view(np.int32))
    np.testing.assert_array_equal(si, ri)


def test_tie_across_shard_boundaries():
    """Duplicated item rows in different shards score identically; both
    paths must rank the clones lowest-global-index first."""
    U, V = _factors(n_items=40, seed=2)
    V[39] = V[3]      # last shard
    V[20] = V[3]      # middle shard
    sharded = serve_dist.shard_factors(U, V)
    ixs = np.arange(8, dtype=np.int32)
    sv, si = jax.device_get(sharded.topk(ixs, 40))
    rv, ri = _replicated(U, V, ixs, 40)
    np.testing.assert_array_equal(sv.view(np.int32), rv.view(np.int32))
    np.testing.assert_array_equal(si, ri)
    # the rule itself, not just parity: clone 3 outranks 20 outranks 39
    for row in si:
        pos = [int(np.flatnonzero(row == c)[0]) for c in (3, 20, 39)]
        assert pos == sorted(pos), pos


def test_all_equal_scores_rank_by_global_index():
    """Total tie (zero item factors): the top-k must be exactly the k
    lowest global indices on both paths — the strongest cross-shard
    tie-break case there is."""
    U, _ = _factors(seed=3)
    V = np.zeros((37, U.shape[1]), dtype=np.float32)
    sharded = serve_dist.shard_factors(U, V)
    ixs = np.array([1, 4], dtype=np.int32)
    sv, si = jax.device_get(sharded.topk(ixs, 9))
    rv, ri = _replicated(U, V, ixs, 9)
    np.testing.assert_array_equal(si, np.tile(np.arange(9), (2, 1)))
    np.testing.assert_array_equal(si, ri)
    np.testing.assert_array_equal(sv.view(np.int32), rv.view(np.int32))


def test_more_users_and_items_than_one_shard_row():
    """n_users < n_dev (some shards own no real user rows) still gathers
    correctly through the psum."""
    U, V = _factors(n_users=3, n_items=11, seed=4)
    sharded = serve_dist.shard_factors(U, V)
    ixs = np.array([0, 1, 2, 2], dtype=np.int32)
    sv, si = jax.device_get(sharded.topk(ixs, 11))
    rv, ri = _replicated(U, V, ixs, 11)
    np.testing.assert_array_equal(sv.view(np.int32), rv.view(np.int32))
    np.testing.assert_array_equal(si, ri)


# ---------------------------------------------------------------------------
# mode resolution
# ---------------------------------------------------------------------------

def test_mode_resolution(monkeypatch):
    monkeypatch.delenv("PIO_SERVE_SHARD", raising=False)
    # bare defaults: auto + virtual CPU devices -> replicated
    assert serve_dist.configured_mode() == "auto"
    assert not serve_dist.serving_enabled()
    with serve_dist.deploy_scope("on"):
        assert serve_dist.serving_enabled()
    with serve_dist.deploy_scope("off"):
        assert not serve_dist.serving_enabled()
    # env wins over the config scope (the PIO_AOT override shape)
    monkeypatch.setenv("PIO_SERVE_SHARD", "0")
    with serve_dist.deploy_scope("on"):
        assert not serve_dist.serving_enabled()
    monkeypatch.setenv("PIO_SERVE_SHARD", "1")
    with serve_dist.deploy_scope("off"):
        assert serve_dist.serving_enabled()


def test_auto_falls_back_on_reload_and_cpu(monkeypatch):
    monkeypatch.delenv("PIO_SERVE_SHARD", raising=False)
    # auto on a "real" multi-device mesh: sharded...
    monkeypatch.setattr(serve_dist, "_multi_device_platform", lambda: True)
    with serve_dist.deploy_scope("auto"):
        assert serve_dist.serving_enabled()
    # ...but not during a /reload hot-swap
    with serve_dist.deploy_scope("auto", reload=True):
        assert not serve_dist.serving_enabled()
    # "on" stays sharded even across a reload (explicit operator call)
    with serve_dist.deploy_scope("on", reload=True):
        assert serve_dist.serving_enabled()
    # virtual CPU devices: auto stays replicated
    monkeypatch.setattr(serve_dist, "_multi_device_platform",
                        lambda: False)
    with serve_dist.deploy_scope("auto"):
        assert not serve_dist.serving_enabled()


def test_bad_mode_rejected():
    with pytest.raises(ValueError):
        with serve_dist.deploy_scope("sideways"):
            pass
    with pytest.raises(ValueError):
        serve_dist.configured_mode("sideways")


# ---------------------------------------------------------------------------
# deployed server: wire parity, status surface, AOT coverage
# ---------------------------------------------------------------------------

def _train_engine(storage, n_items=9, rank=3):
    app_id = storage.get_meta_data_apps().insert(App(0, "ShardApp"))
    storage.get_events().init(app_id)
    events = []
    for u in range(8):
        for i in range(n_items):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap(
                    {"rating": 5.0 if (u % 3) == (i % 3) else 1.5}),
                event_time=dt.datetime(2021, 2, 3, 0, (u + i) % 60,
                                       tzinfo=dt.timezone.utc)))
    storage.get_events().insert_batch(events, app_id)
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="ShardApp"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=rank, numIterations=2,
                                       lambda_=0.05, seed=5)),))
    run_train(WorkflowContext(storage=storage), engine, ep,
              engine_factory="shard-test",
              params_json={
                  "datasource": {"params": {"appName": "ShardApp"}},
                  "algorithms": [{"name": "als", "params": {
                      "rank": rank, "numIterations": 2,
                      "lambda": 0.05, "seed": 5}}]})
    return engine


def _post(api, user, num=5):
    status, body = api.handle(
        "POST", "/queries.json",
        body=json.dumps({"user": user, "num": num}).encode())
    assert status == 200, body
    return json.dumps(body, sort_keys=True)


def test_query_api_sharded_wire_parity(memory_storage, monkeypatch):
    """A sharded deploy answers byte-for-byte what the replicated deploy
    answers, exposes its layout on GET / + the gauge, and keeps the
    legacy key set when replicated."""
    # pin the replicated leg to the device path: the parity contract is
    # sharded-vs-replicated DEVICE kernels (host BLAS accumulates in a
    # different order) and the probe must not flip it on a slow CI host
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")
    engine = _train_engine(memory_storage)
    queries = [("u1", 5), ("u3", 9), ("nobody", 5), ("u7", 1)]

    api_off = QueryAPI(storage=memory_storage, engine=engine,
                       config=ServerConfig(batching="on",
                                           shard_serving="off"))
    try:
        off_answers = [_post(api_off, u, n) for u, n in queries]
        off_status = api_off.handle("GET", "/")[1]
        assert "sharding" not in off_status     # legacy key set intact
    finally:
        api_off.close()

    api_on = QueryAPI(storage=memory_storage, engine=engine,
                      config=ServerConfig(batching="on",
                                          shard_serving="on"))
    try:
        on_answers = [_post(api_on, u, n) for u, n in queries]
        on_status = api_on.handle("GET", "/")[1]
        sh = on_status["sharding"]
        assert sh["enabled"] and sh["shards"] == 8
        assert sh["merge"] == serve_dist.MERGE_STRATEGY
        gauge = telemetry.registry().gauge(
            "pio_serve_shards", "x").labels()
        assert gauge.value == 8.0
        model = api_on.models[0]
        assert model.sharding is not None
    finally:
        api_on.close()
    assert on_answers == off_answers


def test_reload_falls_back_to_replicated_on_auto(memory_storage,
                                                 monkeypatch):
    monkeypatch.delenv("PIO_SERVE_SHARD", raising=False)
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "1e9")
    monkeypatch.setattr(serve_dist, "_multi_device_platform",
                        lambda: True)
    engine = _train_engine(memory_storage, n_items=8)
    api = QueryAPI(storage=memory_storage, engine=engine,
                   config=ServerConfig(batching="on",
                                       shard_serving="auto"))
    try:
        assert api.handle("GET", "/")[1]["sharding"]["shards"] == 8
        before = _post(api, "u2", 4)
        api._reload()                       # hot-swap: auto -> replicated
        assert "sharding" not in api.handle("GET", "/")[1]
        assert getattr(api.models[0], "sharding", None) is None
        assert _post(api, "u2", 4) == before
        # the gauge reflects the fallback
        assert telemetry.registry().gauge(
            "pio_serve_shards", "x").labels().value == 0.0
    finally:
        api.close()


def test_sharded_programs_prebuilt_no_post_warmup_recompiles(
        memory_storage):
    """With sharding on, every (bucket x k) sharded program is primed
    before ready: a post-AOT serving burst must compile NOTHING."""
    telemetry.set_enabled(True)
    devicewatch.install()
    devicewatch.reset_watchdog()
    engine = _train_engine(memory_storage, n_items=10, rank=4)
    api = QueryAPI(storage=memory_storage, engine=engine,
                   config=ServerConfig(batching="on",
                                       shard_serving="on"))
    try:
        assert devicewatch.serving_warmup_done()    # AOT marked it
        before = devicewatch.post_warmup_recompiles()
        for q in range(6):
            _post(api, f"u{q}", 10)                 # k=10 clamps to 10
        assert devicewatch.post_warmup_recompiles() == before
    finally:
        api.close()
        devicewatch.reset_watchdog()


def test_sharded_program_specs_cover_inline_bucket():
    U, V = _factors(seed=6)
    sharded = serve_dist.shard_factors(U, V)
    specs = serve_dist.sharded_program_specs(sharded, (4, 16), (10,))
    buckets = sorted({s.key[-2] for s in specs})
    assert buckets == [1, 4, 16]      # bucket 1 forced in for inline
    assert all(s.name == "topk_for_users_sharded" for s in specs)
    # a spec is genuinely AOT-compilable from declared (sharded) shapes
    specs[0].build()


def test_hbm_ceiling_demo_shards_past_one_device_budget(monkeypatch):
    """The bench's HBM-ceiling leg on the 8-device mesh: a factor matrix
    sized past one device's (demonstration) budget serves only sharded —
    replicated placement exceeds the budget, each shard fits, and the
    sharded top-k actually answers."""
    import bench

    monkeypatch.setenv("BENCH_SHARD_BUDGET_MB", "1")
    out = bench._shard_hbm_ceiling_demo()
    assert "skipped" not in out
    assert out["n_devices"] == 8
    assert out["factor_bytes"] > out["budget_bytes"]
    assert not out["replicated_fits_budget"]
    assert out["sharded_fits_budget"]
    assert out["per_shard_bytes"] < out["factor_bytes"] // 4
    assert out["sharded_served_ok"]


# ---------------------------------------------------------------------------
# doctor: the sharding line
# ---------------------------------------------------------------------------

def _scrape_stub(metrics_text, device_body):
    blank = {"status": None, "body": ""}
    return {
        "url": "http://x", "healthz": {"status": 200, "body": "{}"},
        "readyz": {"status": 200, "body": '{"status": "ready"}'},
        "metrics": {"status": 200, "body": metrics_text},
        "traces": {"status": 200, "body": '{"spanCount": 0}'},
        "device": {"status": 200, "body": json.dumps(device_body)},
        "slow": dict(blank),
    }


def test_doctor_sharding_line_states():
    from predictionio_tpu.tools import doctor

    dev = {"telemetry": True,
           "sharding": {"shards": 8, "merge": "all_gather",
                        "perShardFactorBytes": 2 * 2**20}}
    # healthy headroom on every device
    metrics = ("pio_serve_shards 8\n"
               'pio_hbm_bytes_in_use{device="tpu:0"} 100\n'
               'pio_hbm_bytes_limit{device="tpu:0"} 1000\n'
               'pio_hbm_bytes_in_use{device="tpu:1"} 300\n'
               'pio_hbm_bytes_limit{device="tpu:1"} 1000\n')
    checks = {c: (s, d) for c, s, d in
              doctor.diagnose(_scrape_stub(metrics, dev))}
    state, detail = checks["sharding"]
    assert state == doctor.OK
    assert "8 shard(s), all_gather merge" in detail
    assert "headroom 70%" in detail
    # one shard within 10% of HBM -> WARN names the fix
    metrics_hot = metrics.replace(
        'pio_hbm_bytes_in_use{device="tpu:1"} 300',
        'pio_hbm_bytes_in_use{device="tpu:1"} 950')
    state, detail = {c: (s, d) for c, s, d in doctor.diagnose(
        _scrape_stub(metrics_hot, dev))}["sharding"]
    assert state == doctor.WARN and "within 10%" in detail
    # replicated daemon: informational NA-ish OK line, never noisy
    state, detail = {c: (s, d) for c, s, d in doctor.diagnose(
        _scrape_stub("", {"telemetry": True}))}["sharding"]
    assert state == doctor.NA and "replicated" in detail
