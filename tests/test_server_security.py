"""Daemon security: shared-key auth (KeyAuthentication.scala parity) and
TLS (SSLConfiguration.scala parity) on the dashboard/admin daemons."""

import json
import ssl
import subprocess
import threading

import pytest

from predictionio_tpu.tools.admin import AdminAPI
from predictionio_tpu.tools.dashboard import DashboardAPI


def test_admin_key_auth(memory_storage):
    api = AdminAPI(storage=memory_storage, server_key="tok")
    status, body = api.handle("GET", "/", headers={})
    assert status == 401
    # header form
    status, _ = api.handle("GET", "/", headers={"X-PIO-Server-Key": "tok"})
    assert status == 200
    # accessKey query-param form (reference ParamAuth)
    status, _ = api.handle("GET", "/", query={"accessKey": "tok"})
    assert status == 200
    status, _ = api.handle("GET", "/", query={"accessKey": "wrong"})
    assert status == 401


def test_dashboard_key_auth(memory_storage):
    api = DashboardAPI(storage=memory_storage, server_key="tok")
    assert api.handle("GET", "/", headers={})[0] == 401
    assert api.handle("GET", "/",
                      headers={"x-pio-server-key": "tok"})[0] == 200


def test_no_key_means_open(memory_storage):
    assert AdminAPI(storage=memory_storage).handle("GET", "/")[0] == 200


def test_tls_end_to_end(memory_storage, tmp_path, monkeypatch):
    """Self-signed cert -> https round-trip against the admin daemon."""
    cert = tmp_path / "srv.crt"
    key = tmp_path / "srv.key"
    try:
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", "/CN=localhost"],
            check=True, capture_output=True, timeout=60)
    except (OSError, subprocess.CalledProcessError):
        pytest.skip("openssl unavailable")
    monkeypatch.setenv("PIO_SSL_CERTFILE", str(cert))
    monkeypatch.setenv("PIO_SSL_KEYFILE", str(key))

    from predictionio_tpu.data.api.http import make_server

    server = make_server(AdminAPI(storage=memory_storage, server_key="tok"),
                         "127.0.0.1", 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        import http.client

        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        conn = http.client.HTTPSConnection("127.0.0.1", port, context=ctx)
        conn.request("GET", "/", headers={"X-PIO-Server-Key": "tok"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["status"] == "alive"
        # plaintext client against the TLS port must fail
        plain = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        with pytest.raises(Exception):
            plain.request("GET", "/")
            r = plain.getresponse()
            assert r.status == 200  # unreachable
    finally:
        server.shutdown()


def test_remote_backend_over_tls(memory_storage, tmp_path, monkeypatch):
    """ADVICE r2: a TLS-enabled storage server must be reachable from the
    `remote` backend via an https:// URL (scheme honored, not stripped)."""
    cert = tmp_path / "srv.crt"
    key = tmp_path / "srv.key"
    try:
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", "/CN=localhost",
             "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1"],
            check=True, capture_output=True, timeout=60)
    except (OSError, subprocess.CalledProcessError):
        pytest.skip("openssl unavailable")
    monkeypatch.setenv("PIO_SSL_CERTFILE", str(cert))
    monkeypatch.setenv("PIO_SSL_KEYFILE", str(key))

    from predictionio_tpu.data.api.http import make_server
    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.data.storage.remote import StorageRPCAPI

    server = make_server(StorageRPCAPI(memory_storage, key="sekrit"),
                         "127.0.0.1", 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        monkeypatch.delenv("PIO_SSL_CERTFILE")
        monkeypatch.delenv("PIO_SSL_KEYFILE")
        client = Storage(env={
            "PIO_STORAGE_SOURCES_R_TYPE": "remote",
            "PIO_STORAGE_SOURCES_R_URL": f"https://127.0.0.1:{port}",
            "PIO_STORAGE_SOURCES_R_KEY": "sekrit",
            "PIO_STORAGE_SOURCES_R_CAFILE": str(cert),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "R",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "R",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "R",
        })
        from predictionio_tpu.data.storage.base import App
        app_id = client.get_meta_data_apps().insert(App(0, "tlsapp"))
        assert client.get_meta_data_apps().get(app_id).name == "tlsapp"
    finally:
        server.shutdown()
