"""Micro-batcher semantics (serving/batcher.py + serving/protocol.py):
flush-on-size, flush-on-timeout, admission control, error propagation,
padding-bucket policy, and the predict_batch protocol fallback."""

import threading
import time

import pytest

from predictionio_tpu.serving import (
    MicroBatcher, ServerSaturated, batch_capable, bucket_for, pad_buckets,
)


# ------------------------------------------------------------------ buckets
def test_bucket_for_rounds_up():
    assert bucket_for(1, (1, 4, 16, 64)) == 1
    assert bucket_for(2, (1, 4, 16, 64)) == 4
    assert bucket_for(4, (1, 4, 16, 64)) == 4
    assert bucket_for(17, (1, 4, 16, 64)) == 64
    # beyond the top bucket: exact size (overflow escape hatch)
    assert bucket_for(65, (1, 4, 16, 64)) == 65


def test_pad_buckets_env_override(monkeypatch):
    monkeypatch.setenv("PIO_SERVE_BUCKETS", "8, 2,32")
    assert pad_buckets() == (2, 8, 32)
    monkeypatch.setenv("PIO_SERVE_BUCKETS", "0,-3")
    with pytest.raises(ValueError):
        pad_buckets()
    monkeypatch.delenv("PIO_SERVE_BUCKETS")
    assert pad_buckets() == (1, 4, 16, 64)
    assert pad_buckets((16, 4, 4)) == (4, 16)


# ---------------------------------------------------------------- batching
def _collecting_batcher(**kw):
    batches = []

    def flush(items):
        batches.append(list(items))
        return [f"r:{x}" for x in items]

    return MicroBatcher(flush, **kw), batches


def test_flush_on_size():
    """A full batch flushes immediately, without waiting out the timer."""
    b, batches = _collecting_batcher(max_batch_size=4, max_delay_ms=60_000)
    try:
        results = [None] * 4

        def hit(k):
            results[k] = b.submit(k)

        threads = [threading.Thread(target=hit, args=(k,)) for k in range(4)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert time.monotonic() - t0 < 30  # far below the 60 s timer
        assert sorted(results) == ["r:0", "r:1", "r:2", "r:3"]
        assert len(batches) == 1 and sorted(batches[0]) == [0, 1, 2, 3]
        stats = b.stats()
        assert stats["batches"] == 1 and stats["queries"] == 4
        assert stats["batchSizeHist"] == {"4": 1}
        assert stats["bucketHist"] == {"4": 1}
    finally:
        b.close()


def test_flush_on_timeout():
    """A lone request is served after ~max_delay_ms, not held forever."""
    b, batches = _collecting_batcher(max_batch_size=64, max_delay_ms=30.0)
    try:
        t0 = time.monotonic()
        assert b.submit("only") == "r:only"
        dt = time.monotonic() - t0
        assert dt < 5.0            # seconds, not the 64-item wait
        assert batches == [["only"]]
    finally:
        b.close()


def test_timer_anchored_on_oldest():
    """A steady trickle of new arrivals must not starve the head request:
    the flush deadline comes from the FIRST enqueued item."""
    b, batches = _collecting_batcher(max_batch_size=64, max_delay_ms=120.0)
    try:
        done = threading.Event()
        out = []

        def first():
            out.append(b.submit("head"))
            done.set()

        threading.Thread(target=first).start()
        # trickle younger items in while the head waits
        trickle = []
        for k in range(3):
            time.sleep(0.03)
            t = threading.Thread(target=lambda k=k: b.submit(k))
            t.start()
            trickle.append(t)
        assert done.wait(10)
        assert out == ["r:head"]
        assert batches[0][0] == "head"
        for t in trickle:
            t.join(10)
    finally:
        b.close()


def test_greedy_mode_self_clocks():
    """max_delay_ms=0: a lone request flushes immediately, but arrivals
    during a busy flush still coalesce into the next batch."""
    gate = threading.Event()
    batches = []

    def flush(items):
        batches.append(list(items))
        if len(batches) == 1:
            gate.wait(30)    # hold the first batch on the "device"
        return list(items)

    b = MicroBatcher(flush, max_batch_size=64, max_delay_ms=0.0)
    try:
        threads = [threading.Thread(target=b.submit, args=("head",))]
        threads[0].start()
        while not batches:          # first batch is in flight
            time.sleep(0.005)
        for k in range(3):          # these arrive while the device is busy
            t = threading.Thread(target=b.submit, args=(k,))
            t.start()
            threads.append(t)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with b._cond:
                if len(b._q) == 3:
                    break
            time.sleep(0.005)
        gate.set()
        for t in threads:
            t.join(10)
        assert batches[0] == ["head"]
        assert len(batches) == 2 and sorted(batches[1]) == [0, 1, 2]
    finally:
        gate.set()
        b.close()


def test_admission_control_503():
    """Beyond max_queue pending items, submit raises ServerSaturated with
    a Retry-After hint >= 1s; the backlog still drains correctly."""
    entered = threading.Event()
    gate = threading.Event()

    def flush(items):
        entered.set()
        gate.wait(30)
        return list(items)

    b = MicroBatcher(flush, max_batch_size=1, max_delay_ms=1.0, max_queue=2)
    try:
        # 1 provably in-flight (the worker is inside flush) ...
        threads = [threading.Thread(target=b.submit, args=(0,))]
        threads[0].start()
        assert entered.wait(10)
        # ... + exactly max_queue queued behind it
        for k in (1, 2):
            t = threading.Thread(target=b.submit, args=(k,))
            t.start()
            threads.append(t)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with b._cond:
                depth = len(b._q)
            if depth >= b.max_queue:
                break
            time.sleep(0.01)
        assert depth == b.max_queue
        with pytest.raises(ServerSaturated) as ei:
            b.submit("overflow")
        assert ei.value.retry_after_s >= 1
        assert b.stats()["rejected"] == 1
        gate.set()
        for t in threads:
            t.join(10)
        assert b.stats()["queries"] == 3
    finally:
        gate.set()
        b.close()


def test_flush_error_propagates_to_every_waiter():
    def flush(items):
        raise RuntimeError("device fell over")

    b = MicroBatcher(flush, max_batch_size=8, max_delay_ms=1.0)
    try:
        errs = []

        def hit(k):
            try:
                b.submit(k)
            except RuntimeError as e:
                errs.append(str(e))

        threads = [threading.Thread(target=hit, args=(k,)) for k in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert errs == ["device fell over"] * 3
    finally:
        b.close()


def test_wrong_result_count_is_an_error():
    b = MicroBatcher(lambda items: [1, 2, 3], max_batch_size=1,
                     max_delay_ms=1.0)
    try:
        with pytest.raises(RuntimeError, match="flush returned"):
            b.submit("x")
    finally:
        b.close()


def test_close_drains_then_rejects():
    b, batches = _collecting_batcher(max_batch_size=8, max_delay_ms=50.0)
    results = []
    t = threading.Thread(target=lambda: results.append(b.submit("last")))
    t.start()
    time.sleep(0.05)
    b.close()
    t.join(10)
    assert results == ["r:last"]    # close() drained the pending item
    with pytest.raises(RuntimeError, match="closed"):
        b.submit("late")


# --------------------------------------------------------------- protocol
def test_batch_capable_detects_real_overrides():
    from predictionio_tpu.controller.base import Algorithm

    class Plain(Algorithm):
        def train(self, ctx, pd):
            return None

        def predict(self, model, q):
            return ("p", q)

    class Batched(Plain):
        def predict_batch(self, model, queries):
            return [("b", q) for q in queries]

    assert not batch_capable(Plain())
    assert batch_capable(Batched())
    # the base fallback maps predict, preserving order
    assert Plain().predict_batch(None, [1, 2]) == [("p", 1), ("p", 2)]
    assert Batched().predict_batch(None, [1, 2]) == [("b", 1), ("b", 2)]
