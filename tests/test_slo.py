"""SLO engine tests (common/slo.py + the `pio doctor` SLO line).

Burn-rate math over synthetic registry counters, the scrape-time
collector's wire parity (no series until PIO_TELEMETRY=1), ServerConfig
target plumbing, and the doctor verdict (RED when the fast window is
alight, WARN on slow burn, NA with the opt-in hint when telemetry is
off).
"""

import json

import pytest

from predictionio_tpu.common import slo, telemetry
from predictionio_tpu.tools import doctor


@pytest.fixture(autouse=True)
def _clean():
    telemetry.set_enabled(None)
    slo.reset()
    yield
    telemetry.set_enabled(None)
    slo.reset()


@pytest.fixture()
def fresh_registry(monkeypatch):
    """An empty process registry so the burn math sees exactly the
    counters this test writes (the real registry is additive across
    the whole test process)."""
    reg = telemetry.MetricsRegistry()
    monkeypatch.setattr(telemetry, "REGISTRY", reg)
    return reg


def _http_counter():
    return telemetry.registry().counter(
        "pio_http_requests_total", "req", labelnames=("service", "status"))


def _serve_hist():
    return telemetry.registry().histogram(
        "pio_serve_seconds", "serve", labelnames=("mode",))


# ---------------------------------------------------------------------------
# burn-rate math
# ---------------------------------------------------------------------------

def test_availability_burn_and_budget(fresh_registry):
    eng = slo.SLOEngine(slo.SLOConfig(availability=0.999,
                                      fast_window_s=60.0,
                                      slow_window_s=600.0))
    c_ok = _http_counter().labels(service="T1", status="200")
    c_bad = _http_counter().labels(service="T1", status="500")
    base_ok = 1000.0
    c_ok.inc(base_ok)
    eng.evaluate(now=0.0)                      # baseline snapshot
    # 5% of the next window's traffic fails: 50x the 0.1% allowance
    c_ok.inc(950)
    c_bad.inc(50)
    v = eng.evaluate(now=100.0)["availability"]
    assert v["burn_fast"] == pytest.approx(0.05 / 0.001, rel=1e-6)
    assert v["burn_slow"] == pytest.approx(0.05 / 0.001, rel=1e-6)
    # lifetime budget: 50 bad / 2000 total = 2.5% bad vs 0.1% allowed
    assert v["budget_remaining"] == pytest.approx(1 - 0.025 / 0.001,
                                                  rel=1e-6)


def test_burn_rate_windows_are_independent(fresh_registry):
    eng = slo.SLOEngine(slo.SLOConfig(availability=0.99,
                                      fast_window_s=60.0,
                                      slow_window_s=600.0))
    c_ok = _http_counter().labels(service="T2", status="200")
    c_bad = _http_counter().labels(service="T2", status="503")
    eng.evaluate(now=0.0)
    # old errors, then a long clean stretch
    c_bad.inc(10)
    c_ok.inc(90)
    eng.evaluate(now=100.0)
    c_ok.inc(900)
    v = eng.evaluate(now=650.0)
    # fast window (last 60 s): only clean traffic -> burn 0
    assert v["availability"]["burn_fast"] == 0.0
    # slow window still remembers the bad stretch
    assert v["availability"]["burn_slow"] > 0.0


def test_latency_objective_reads_serve_histogram(fresh_registry):
    eng = slo.SLOEngine(slo.SLOConfig(latency_ms=25.0,
                                      latency_target=0.99,
                                      fast_window_s=60.0,
                                      slow_window_s=600.0))
    h = _serve_hist().labels(mode="batched")
    eng.evaluate(now=0.0)
    for _ in range(99):
        h.observe(0.001)          # well under 25 ms
    h.observe(1.0)                # one slow outlier: exactly on target
    v = eng.evaluate(now=30.0)["latency"]
    assert v["total"] >= 100
    assert v["burn_fast"] == pytest.approx(1.0, rel=0.2)


def test_idle_windows_burn_zero(fresh_registry):
    eng = slo.SLOEngine(slo.SLOConfig())
    v = eng.evaluate(now=0.0)
    for s in ("availability", "latency"):
        assert v[s]["burn_fast"] == 0.0
        assert v[s]["burn_slow"] == 0.0
        assert v[s]["budget_remaining"] == 1.0


def test_burn_crossing_journal_full_recovery_cycle(fresh_registry):
    """The full cross-up -> sustain -> cross-down cycle journals edges
    ONLY: one event when a window goes hot, silence while it stays hot,
    one recovery event when it subsides. The autopilot's ladder (and a
    paged human) both key off these edges — a per-scrape repeat would
    re-trigger every cooldown."""
    from predictionio_tpu.common import journal
    journal.clear()
    eng = slo.SLOEngine(slo.SLOConfig(availability=0.999,
                                      fast_window_s=60.0,
                                      slow_window_s=600.0))
    c_ok = _http_counter().labels(service="RC", status="200")
    c_bad = _http_counter().labels(service="RC", status="500")
    c_ok.inc(1000)
    eng.evaluate(now=0.0)                       # baseline snapshot
    # 5% failures = 50x the 0.1% allowance: both windows cross up
    c_ok.inc(950)
    c_bad.inc(50)
    eng.evaluate(now=100.0)
    ev = journal.snapshot(category="slo")["events"]
    reds = [e for e in ev if e["level"] == "red"]
    warns = [e for e in ev if e["level"] == "warn"]
    assert len(reds) == 1
    assert "burn rate" in reds[0]["message"]
    assert "over the fast window" in reds[0]["message"]
    assert len(warns) == 1
    assert "over the slow window" in warns[0]["message"]
    # sustained burn: another hot evaluate emits NOTHING new
    c_ok.inc(950)
    c_bad.inc(50)
    eng.evaluate(now=130.0)
    assert len(journal.snapshot(category="slo")["events"]) == 2
    # recovery: a long clean stretch pushes the errors out of both
    # windows -> exactly one subsided event per window, INFO
    c_ok.inc(5000)
    eng.evaluate(now=800.0)
    ev = journal.snapshot(category="slo")["events"]
    subsided = [e for e in ev if "burn subsided" in e["message"]]
    assert len(subsided) == 2
    assert all(e["level"] == "info" for e in subsided)
    assert {("fast-window" in e["message"], "slow-window" in e["message"])
            for e in subsided} == {(True, False), (False, True)}
    # and the cycle is re-armed: a NEW burst crosses up again
    c_ok.inc(950)
    c_bad.inc(50)
    eng.evaluate(now=900.0)
    ev = journal.snapshot(category="slo")["events"]
    assert sum(e["level"] == "red" for e in ev) == 2


# ---------------------------------------------------------------------------
# collector + wire parity
# ---------------------------------------------------------------------------

def test_collector_emits_nothing_with_telemetry_off():
    eng = slo.install()
    telemetry.set_enabled(False)
    assert list(eng.collect()) == []
    assert "pio_slo_" not in telemetry.registry().exposition()


def test_collector_series_with_telemetry_on():
    eng = slo.install()
    telemetry.set_enabled(True)
    lines = list(eng.collect())
    text = "\n".join(lines)
    samples = doctor.parse_metrics(text)
    assert 'pio_slo_target' in samples
    assert len(samples["pio_slo_burn_rate"]) == 4   # 2 slos x 2 windows
    assert len(samples["pio_slo_error_budget_remaining"]) == 2
    # and the full registry scrape carries them too
    assert "pio_slo_burn_rate" in telemetry.registry().exposition()


def test_server_config_targets_override_env(memory_storage):
    from predictionio_tpu.workflow.create_server import ServerConfig
    cfg = ServerConfig(slo_availability=0.95, slo_latency_ms=5.0)
    # mirror QueryAPI's install call without a full engine deploy
    slo.install(slo.SLOConfig.from_env(
        availability=cfg.slo_availability,
        latency_ms=cfg.slo_latency_ms,
        latency_target=cfg.slo_latency_target))
    eng = slo.engine()
    assert eng.config.availability == 0.95
    assert eng.config.latency_ms == 5.0
    # a later default install (event server in the same process) must
    # not clobber the configured targets
    slo.install()
    assert slo.engine().config.availability == 0.95


def test_env_defaults(monkeypatch):
    monkeypatch.setenv("PIO_SLO_AVAILABILITY", "0.9995")
    monkeypatch.setenv("PIO_SLO_LATENCY_MS", "12.5")
    cfg = slo.SLOConfig.from_env()
    assert cfg.availability == 0.9995
    assert cfg.latency_ms == 12.5
    assert cfg.latency_target == 0.99


# ---------------------------------------------------------------------------
# pio doctor SLO line
# ---------------------------------------------------------------------------

def _scraped(metrics_body="", device=None):
    ok = {"status": 200, "body": json.dumps({"status": "ok"})}
    return {
        "url": "http://t", "healthz": dict(ok), "readyz": dict(ok),
        "metrics": {"status": 200, "body": metrics_body},
        "traces": {"status": 404, "body": ""},
        "device": {"status": 200,
                   "body": json.dumps(device or {"telemetry": True})},
    }


def _check(checks, name):
    return next(c for c in checks if c[0] == name)


def test_doctor_slo_green_within_budget():
    body = ('pio_slo_burn_rate{slo="availability",window="fast"} 0.5\n'
            'pio_slo_burn_rate{slo="availability",window="slow"} 0.2\n'
            'pio_slo_burn_rate{slo="latency",window="fast"} 0\n'
            'pio_slo_burn_rate{slo="latency",window="slow"} 0\n'
            'pio_slo_error_budget_remaining{slo="availability"} 0.98\n'
            'pio_slo_error_budget_remaining{slo="latency"} 1\n')
    check = _check(doctor.diagnose(_scraped(body)), "slo")
    assert check[1] == doctor.OK
    assert "budget" in check[2]


def test_doctor_slo_red_when_fast_burn_alight():
    body = ('pio_slo_burn_rate{slo="availability",window="fast"} 20\n'
            'pio_slo_burn_rate{slo="availability",window="slow"} 15\n'
            'pio_slo_error_budget_remaining{slo="availability"} 0.4\n')
    checks = doctor.diagnose(_scraped(body))
    check = _check(checks, "slo")
    assert check[1] == doctor.RED
    assert "availability" in check[2] and "20.0x" in check[2]
    # a RED slo check fails the verdict
    assert any(s == doctor.RED for _c, s, _d in checks)


def test_doctor_slo_warn_on_slow_burn_only():
    body = ('pio_slo_burn_rate{slo="latency",window="fast"} 2\n'
            'pio_slo_burn_rate{slo="latency",window="slow"} 8\n'
            'pio_slo_error_budget_remaining{slo="latency"} 0.7\n')
    check = _check(doctor.diagnose(_scraped(body)), "slo")
    assert check[1] == doctor.WARN
    assert "latency" in check[2]


def test_doctor_distinguishes_telemetry_off_from_missing_stats():
    """The satellite: {"telemetry": false} means PIO_TELEMETRY is
    unset — doctor prints the opt-in hint, not the misleading
    'no device memory stats (CPU)' line; with telemetry ON and still no
    HBM series, the genuine CPU/unsupported line stays."""
    off = doctor.diagnose(_scraped("", device={"telemetry": False}))
    for name in ("hbm", "slo", "serving"):
        check = _check(off, name)
        assert check[1] == doctor.NA
        assert "PIO_TELEMETRY=1" in check[2], (name, check)
        assert "KNOWN_ISSUES" not in check[2]
    on = doctor.diagnose(_scraped("", device={"telemetry": True}))
    hbm = _check(on, "hbm")
    assert hbm[1] == doctor.NA
    assert "CPU / unsupported" in hbm[2]
    assert "PIO_TELEMETRY" not in hbm[2]


def test_doctor_waterfall_line():
    slow_ok = {"status": 200, "body": json.dumps({
        "enabled": True, "capacity": 32, "sampleEvery": 1,
        "requests": [{"traceId": "ab12", "mode": "batched",
                      "totalMs": 8.2,
                      "stages": {"dispatch": 1.0, "pad": 6.5}}]})}
    scraped = _scraped()
    scraped["slow"] = slow_ok
    check = _check(doctor.diagnose(scraped), "waterfall")
    assert check[1] == doctor.OK
    assert "pad" in check[2] and "ab12" in check[2]
    # sampling off -> NA with the opt-in hint
    scraped["slow"] = {"status": 200,
                       "body": json.dumps({"enabled": False,
                                           "requests": []})}
    check = _check(doctor.diagnose(scraped), "waterfall")
    assert check[1] == doctor.NA
    assert "PIO_WATERFALL=1" in check[2]
    # legacy daemon without the route at all
    check = _check(doctor.diagnose(_scraped()), "waterfall")
    assert check[1] == doctor.NA
