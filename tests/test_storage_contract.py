"""Storage-backend contract suite: one behavioral spec, N backends.

This is the reference's storage test pattern (SURVEY.md §4 tier 2 —
LEventsSpec/PEventsSpec repeated per driver, e.g.
storage/jdbc/src/test/.../LEventsSpec.scala) applied to the memory and
sqlite backends.
"""

import datetime as dt

import pytest

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import (
    AccessKey, App, Channel, EngineInstance, EvaluationInstance, Model,
    NONE_FILTER, Storage,
)

APP = 1
UTC = dt.timezone.utc


def t(minute):
    return dt.datetime(2021, 1, 1, 0, minute, tzinfo=UTC)


def mk(event="rate", entity_id="u1", target=None, minute=0, props=None):
    return Event(
        event=event, entity_type="user", entity_id=entity_id,
        target_entity_type="item" if target else None,
        target_entity_id=target,
        properties=DataMap(props or {}),
        event_time=t(minute),
    )


@pytest.fixture(params=["memory", "sqlite", "eventlog", "remote"])
def storage(request, tmp_path):
    if request.param == "remote":
        # the networked backend: a storage server wrapping sqlite, with the
        # `remote` client driver pointed at it over real HTTP + key auth
        from predictionio_tpu.data.storage.remote import serve_storage
        backing = Storage(env={
            "PIO_STORAGE_SOURCES_B_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_B_PATH": str(tmp_path / "backing.sqlite"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "B",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "B",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "B",
        })
        server = serve_storage(backing, host="127.0.0.1", port=0,
                               key="sekrit")
        port = server.server_address[1]
        yield Storage(env={
            "PIO_STORAGE_SOURCES_R_TYPE": "remote",
            "PIO_STORAGE_SOURCES_R_URL": f"http://127.0.0.1:{port}",
            "PIO_STORAGE_SOURCES_R_KEY": "sekrit",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "R",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "R",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "R",
        })
        server.shutdown()
        return
    if request.param == "memory":
        env = {
            "PIO_STORAGE_SOURCES_T_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "T",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "T",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "T",
        }
    elif request.param == "sqlite":
        env = {
            "PIO_STORAGE_SOURCES_T_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_T_PATH": str(tmp_path / "t.sqlite"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "T",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "T",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "T",
        }
    else:
        # columnar event log provides EVENTDATA only (the HBase role);
        # metadata/models ride the memory backend
        env = {
            "PIO_STORAGE_SOURCES_M_TYPE": "memory",
            "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
            "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path / "eventlog"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        }
    yield Storage(env=env)


class TestEventsContract:
    def test_insert_get_delete(self, storage):
        ev = storage.get_events()
        ev.init(APP)
        eid = ev.insert(mk(), APP)
        got = ev.get(eid, APP)
        assert got is not None and got.event_id == eid and got.entity_id == "u1"
        assert ev.delete(eid, APP) is True
        assert ev.delete(eid, APP) is False
        assert ev.get(eid, APP) is None

    def test_insert_batch(self, storage):
        ev = storage.get_events()
        ev.init(APP)
        ids = ev.insert_batch([mk(minute=i) for i in range(5)], APP)
        assert len(set(ids)) == 5
        assert len(list(ev.find(APP))) == 5

    def test_channel_isolation(self, storage):
        ev = storage.get_events()
        ev.init(APP)
        ev.init(APP, 7)
        ev.insert(mk(entity_id="default"), APP)
        ev.insert(mk(entity_id="ch"), APP, 7)
        assert [e.entity_id for e in ev.find(APP)] == ["default"]
        assert [e.entity_id for e in ev.find(APP, 7)] == ["ch"]
        ev.remove(APP, 7)
        assert list(ev.find(APP, 7)) == []
        assert [e.entity_id for e in ev.find(APP)] == ["default"]

    def test_app_isolation(self, storage):
        ev = storage.get_events()
        ev.init(1)
        ev.init(2)
        ev.insert(mk(entity_id="a1"), 1)
        ev.insert(mk(entity_id="a2"), 2)
        assert [e.entity_id for e in ev.find(1)] == ["a1"]
        assert ev.get(next(ev.find(2)).event_id, 1) is None

    def test_find_time_range_and_order(self, storage):
        ev = storage.get_events()
        ev.init(APP)
        for m in (3, 1, 2, 0):
            ev.insert(mk(entity_id=f"u{m}", minute=m), APP)
        got = [e.entity_id for e in ev.find(APP, start_time=t(1), until_time=t(3))]
        assert got == ["u1", "u2"]  # ascending, start inclusive, until exclusive
        rev = [e.entity_id for e in ev.find(APP, reversed_=True)]
        assert rev == ["u3", "u2", "u1", "u0"]
        limited = [e.entity_id for e in ev.find(APP, limit=2)]
        assert limited == ["u0", "u1"]

    def test_find_filters(self, storage):
        ev = storage.get_events()
        ev.init(APP)
        ev.insert(mk(event="rate", entity_id="u1", target="i1"), APP)
        ev.insert(mk(event="buy", entity_id="u1", target="i2", minute=1), APP)
        ev.insert(mk(event="$set", entity_id="u2", minute=2,
                     props={"a": 1}), APP)
        assert len(list(ev.find(APP, event_names=["rate"]))) == 1
        assert len(list(ev.find(APP, event_names=["rate", "buy"]))) == 2
        assert len(list(ev.find(APP, entity_id="u1"))) == 2
        assert len(list(ev.find(APP, entity_type="user"))) == 3
        assert len(list(ev.find(APP, target_entity_id="i2"))) == 1
        # Some(None)-style filter: only events with NO target entity
        none_target = list(ev.find(APP, target_entity_type=NONE_FILTER))
        assert [e.entity_id for e in none_target] == ["u2"]

    def test_aggregate_properties_through_backend(self, storage):
        ev = storage.get_events()
        ev.init(APP)
        ev.insert(mk(event="$set", entity_id="u1", props={"a": 1, "b": 2}), APP)
        ev.insert(mk(event="$unset", entity_id="u1", minute=1, props={"a": 0}), APP)
        ev.insert(mk(event="$set", entity_id="u2", minute=1, props={"c": 9}), APP)
        ev.insert(mk(event="$delete", entity_id="u3", minute=1), APP)
        out = ev.aggregate_properties(APP, entity_type="user")
        assert out["u1"].to_dict() == {"b": 2}
        assert out["u2"].to_dict() == {"c": 9}
        assert "u3" not in out
        req = ev.aggregate_properties(APP, entity_type="user", required=["c"])
        assert set(req) == {"u2"}
        single = ev.aggregate_properties_of_entity(
            APP, entity_type="user", entity_id="u1")
        assert single.to_dict() == {"b": 2}

    def test_event_document_fidelity(self, storage):
        ev = storage.get_events()
        ev.init(APP)
        original = Event(
            event="rate", entity_type="user", entity_id="u1",
            target_entity_type="item", target_entity_id="i9",
            properties=DataMap({"rating": 4.5, "nested": {"x": [1, 2]}}),
            event_time=t(5), tags=["t1", "t2"], pr_id="pr7",
        )
        eid = ev.insert(original, APP)
        got = ev.get(eid, APP)
        assert got.properties.to_dict() == {"rating": 4.5, "nested": {"x": [1, 2]}}
        assert list(got.tags) == ["t1", "t2"] and got.pr_id == "pr7"
        assert got.event_time == t(5)


class TestMetadataContract:
    def test_apps(self, storage):
        apps = storage.get_meta_data_apps()
        app_id = apps.insert(App(0, "myapp", "desc"))
        assert app_id and apps.get(app_id).name == "myapp"
        assert apps.get_by_name("myapp").id == app_id
        assert apps.insert(App(0, "myapp", None)) is None  # duplicate name
        second = apps.insert(App(0, "other", None))
        assert second != app_id
        assert {a.name for a in apps.get_all()} == {"myapp", "other"}
        apps.update(App(app_id, "renamed", None))
        assert apps.get(app_id).name == "renamed"
        apps.delete(second)
        assert apps.get(second) is None

    def test_access_keys(self, storage):
        keys = storage.get_meta_data_access_keys()
        k = keys.insert(AccessKey("", 1, ["rate"]))
        assert k and len(k) == 64
        assert keys.get(k).events == ("rate",)
        k2 = keys.insert(AccessKey("explicit", 2, []))
        assert k2 == "explicit"
        assert {x.key for x in keys.get_by_appid(2)} == {"explicit"}
        keys.delete(k)
        assert keys.get(k) is None

    def test_channels(self, storage):
        chans = storage.get_meta_data_channels()
        cid = chans.insert(Channel(0, "ch-1", 1))
        assert chans.get(cid).name == "ch-1"
        assert [c.id for c in chans.get_by_appid(1)] == [cid]
        with pytest.raises(ValueError):
            Channel(0, "bad name!", 1)
        with pytest.raises(ValueError):
            Channel(0, "x" * 17, 1)
        chans.delete(cid)
        assert chans.get(cid) is None

    def test_engine_instances(self, storage):
        eis = storage.get_meta_data_engine_instances()
        def inst(iid, status, minute):
            return EngineInstance(
                id=iid, status=status, start_time=t(minute), end_time=t(minute),
                engine_id="e", engine_version="1", engine_variant="v",
                engine_factory="f")
        i1 = eis.insert(inst("", "INIT", 0))
        eis.update(EngineInstance(**{**eis.get(i1).__dict__, "status": "COMPLETED"}))
        i2 = eis.insert(inst("", "COMPLETED", 5))
        eis.insert(inst("", "INIT", 9))
        latest = eis.get_latest_completed("e", "1", "v")
        assert latest.id == i2  # later start_time wins
        assert len(eis.get_completed("e", "1", "v")) == 2
        assert eis.get_latest_completed("e", "1", "other") is None
        eis.delete(i1)
        assert eis.get(i1) is None

    def test_evaluation_instances(self, storage):
        evis = storage.get_meta_data_evaluation_instances()
        i1 = evis.insert(EvaluationInstance(status="INIT", start_time=t(0)))
        evis.update(EvaluationInstance(
            **{**evis.get(i1).__dict__, "status": "EVALCOMPLETED",
               "evaluator_results": "score=1"}))
        assert evis.get_completed()[0].evaluator_results == "score=1"
        assert evis.get(i1).status == "EVALCOMPLETED"

    def test_models(self, storage):
        models = storage.get_model_data_models()
        models.insert(Model("m1", b"\x00\x01binary\xff"))
        assert models.get("m1").models == b"\x00\x01binary\xff"
        models.delete("m1")
        assert models.get("m1") is None

    def test_verify_all_data_objects(self, storage):
        storage.verify_all_data_objects()


def test_localfs_models(tmp_path):
    env = {
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
        "PIO_STORAGE_SOURCES_FS_PATH": str(tmp_path / "models"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
    }
    storage = Storage(env=env)
    models = storage.get_model_data_models()
    models.insert(Model("abc", b"hello"))
    assert models.get("abc").models == b"hello"
    assert models.get("missing") is None
    models.delete("abc")
    assert models.get("abc") is None


def test_default_env_uses_sqlite(tmp_path, monkeypatch):
    storage = Storage(env={"PIO_FS_BASEDIR": str(tmp_path / "store")})
    storage.verify_all_data_objects()
    assert (tmp_path / "store" / "pio.sqlite").exists()


def test_remote_columnar_and_binary_models(tmp_path):
    """remote driver fast paths: read_columns rides the binary npz route
    (JDBCPEvents.scala:91-150 role), model blobs ride raw octet routes
    (S3Models.scala:36-95 role), find pages instead of one giant reply."""
    import numpy as np

    from predictionio_tpu.data.storage.base import Model
    from predictionio_tpu.data.storage.remote import serve_storage

    backing = Storage(env={
        "PIO_STORAGE_SOURCES_B_TYPE": "eventlog",
        "PIO_STORAGE_SOURCES_B_PATH": str(tmp_path / "el"),
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "B",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    })
    server = serve_storage(backing, host="127.0.0.1", port=0, key="k2")
    port = server.server_address[1]
    try:
        remote = Storage(env={
            "PIO_STORAGE_SOURCES_R_TYPE": "remote",
            "PIO_STORAGE_SOURCES_R_URL": f"http://127.0.0.1:{port}",
            "PIO_STORAGE_SOURCES_R_KEY": "k2",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "R",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "R",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "R",
        })
        ev = remote.get_events()
        ev.init(7)
        events = [Event(event="rate", entity_type="user", entity_id=f"u{k%5}",
                        target_entity_type="item", target_entity_id=f"i{k%3}",
                        properties=DataMap({"rating": float(k % 5) + 1.0}),
                        event_time=t(k))
                  for k in range(30)]
        ev.insert_batch(events, 7)

        # columnar bulk read over the binary route
        cols = ev.read_columns(7, event_names=["rate"], entity_type="user",
                               target_entity_type="item")
        assert int(np.sum(cols["event_code"] >= 0)) == 30
        pool = cols["pool"]
        got = sorted(
            (pool[e], pool[t], float(r))
            for e, t, r in zip(cols["entity_code"], cols["target_code"],
                               cols["rating"]))
        want = sorted((f"u{k%5}", f"i{k%3}", float(k % 5) + 1.0)
                      for k in range(30))
        assert got == want

        # find pages across boundaries (force a tiny page size)
        ev.PAGE = 7
        found = list(ev.find(app_id=7))
        assert len(found) == 30
        limited = list(ev.find(app_id=7, limit=13))
        assert len(limited) == 13

        # binary model blobs round-trip raw (8 MB, incompressible)
        blob = np.random.default_rng(0).integers(
            0, 256, 8 << 20, dtype=np.uint8).tobytes()
        models = remote.get_model_data_models()
        models.insert(Model(id="big/one?x=1", models=blob))
        back = models.get("big/one?x=1")
        assert back is not None and back.models == blob
        assert models.get("missing") is None

    finally:
        server.shutdown()


def test_remote_find_pages_through_timestamp_ties(tmp_path):
    """Forward cursor paging skips already-seen rows at the boundary
    timestamp via offset — including the pathological case of one
    timestamp carrying more rows than a whole page."""
    backing = Storage(env={
        "PIO_STORAGE_SOURCES_B_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_B_PATH": str(tmp_path / "b.sqlite"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "B",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "B",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "B",
    })
    from predictionio_tpu.data.storage.remote import serve_storage
    server = serve_storage(backing, host="127.0.0.1", port=0)
    port = server.server_address[1]
    try:
        remote = Storage(env={
            "PIO_STORAGE_SOURCES_R_TYPE": "remote",
            "PIO_STORAGE_SOURCES_R_URL": f"http://127.0.0.1:{port}",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "R",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "R",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "R",
        })
        ev = remote.get_events()
        ev.init(9)
        # 12 events at ONE timestamp + 7 spread out, paged 5 at a time
        evs = [Event(event="e", entity_type="u", entity_id=f"tie{k}",
                     event_time=t(10)) for k in range(12)]
        evs += [Event(event="e", entity_type="u", entity_id=f"later{k}",
                      event_time=t(20 + k)) for k in range(7)]
        ev.insert_batch(evs, 9)
        ev.PAGE = 5
        got = [e.entity_id for e in ev.find(app_id=9)]
        assert len(got) == 19
        assert sorted(got) == sorted(
            [f"tie{k}" for k in range(12)] + [f"later{k}" for k in range(7)])
        # no duplicates across page boundaries
        assert len(set(got)) == 19
    finally:
        server.shutdown()
