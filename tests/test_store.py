"""Store façades + columnar TPU ingestion (ref: data/.../store/)."""

import numpy as np
import pytest

from predictionio_tpu.data import store
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import AccessKey, App, Channel


@pytest.fixture()
def app(memory_storage):
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "testapp", None))
    memory_storage.get_events().init(app_id)
    return app_id


def rate(u, i, r, minute=0):
    import datetime as dt
    return Event(
        event="rate", entity_type="user", entity_id=u,
        target_entity_type="item", target_entity_id=i,
        properties=DataMap({"rating": r}),
        event_time=dt.datetime(2021, 1, 1, 0, minute, tzinfo=dt.timezone.utc),
    )


def test_find_by_app_name(memory_storage, app):
    store.write([rate("u1", "i1", 4.0)], app)
    got = list(store.find("testapp"))
    assert len(got) == 1 and got[0].entity_id == "u1"
    with pytest.raises(store.StoreError):
        list(store.find("nonexistent"))


def test_channel_resolution(memory_storage, app):
    cid = memory_storage.get_meta_data_channels().insert(Channel(0, "mobile", app))
    memory_storage.get_events().init(app, cid)
    store.write([rate("u9", "i9", 1.0)], app, cid)
    got = list(store.find("testapp", channel_name="mobile"))
    assert [e.entity_id for e in got] == ["u9"]
    assert list(store.find("testapp")) == []
    with pytest.raises(store.StoreError):
        list(store.find("testapp", channel_name="nope"))


def test_find_by_entity_latest_first(memory_storage, app):
    store.write([rate("u1", "i1", 1.0, minute=0),
                 rate("u1", "i2", 2.0, minute=1),
                 rate("u2", "i3", 3.0, minute=2)], app)
    got = store.find_by_entity("testapp", "user", "u1", limit=1)
    assert len(got) == 1 and got[0].target_entity_id == "i2"  # latest


def test_aggregate_properties_facade(memory_storage, app):
    import datetime as dt
    store.write([
        Event(event="$set", entity_type="item", entity_id="i1",
              properties=DataMap({"cat": "a"}),
              event_time=dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)),
    ], app)
    out = store.aggregate_properties("testapp", "item")
    assert out["i1"].get_str("cat") == "a"


def test_find_columnar(memory_storage, app):
    store.write([
        rate("u1", "i1", 4.0, 0),
        rate("u2", "i1", 3.0, 1),
        rate("u1", "i2", 5.0, 2),
    ], app)
    col = store.find_columnar("testapp", event_names=["rate"])
    assert col.n == 3
    assert len(col.entity_ids) == 2 and len(col.target_ids) == 2
    u1, i1 = col.entity_ids("u1"), col.target_ids("i1")
    np.testing.assert_array_equal(col.entity_idx[:2], [u1, col.entity_ids("u2")])
    assert col.target_idx[0] == i1
    np.testing.assert_allclose(col.rating, [4.0, 3.0, 5.0])
    assert col.event_names == ["rate"]
    assert col.entity_idx.dtype == np.int32


def test_find_columnar_fixed_vocab_drops_unseen(memory_storage, app):
    store.write([rate("u1", "i1", 4.0), rate("uX", "i1", 2.0, 1)], app)
    vocab = BiMap.string_int(["u1"])
    col = store.find_columnar("testapp", event_names=["rate"],
                              entity_vocab=vocab)
    assert col.n == 1  # uX dropped under fixed vocab
    assert col.entity_ids is vocab


def test_find_columnar_missing_rating_nan(memory_storage, app):
    import datetime as dt
    store.write([
        Event(event="view", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1",
              event_time=dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)),
    ], app)
    col = store.find_columnar("testapp", event_names=["view"])
    assert np.isnan(col.rating[0])


def test_extract_entity_map(memory_storage, app):
    import datetime as dt
    from predictionio_tpu.data.bimap import EntityMap

    def setp(eid, props, minute):
        return Event(
            event="$set", entity_type="item", entity_id=eid,
            properties=DataMap(props),
            event_time=dt.datetime(2021, 1, 1, 0, minute,
                                   tzinfo=dt.timezone.utc))
    store.write([
        setp("i1", {"price": 9.5, "cat": "a"}, 0),
        setp("i2", {"price": 3.0, "cat": "b"}, 1),
        setp("i3", {"cat": "c"}, 2),          # missing price -> required drops
    ], app)
    em = store.extract_entity_map(
        "testapp", "item",
        lambda dm: (dm.get_float("price"), dm.get_str("cat")),
        required=["price"])
    assert isinstance(em, EntityMap) and len(em) == 2
    assert em.data("i1") == (9.5, "a")
    # dense ix round-trips positionally
    assert em.data(em.id_to_ix("i2")) == (3.0, "b")
    # extraction failure names the entity
    with pytest.raises(store.StoreError, match="i1|i2"):
        store.extract_entity_map("testapp", "item",
                                 lambda dm: dm.get_float("nope"))
