"""Observability subsystem tests (common/telemetry.py + common/tracing.py).

Covers the acceptance surface: registry thread-safety under concurrent
writers, histogram bucket correctness, `GET /metrics` parsing as
Prometheus text exposition on all three daemons, X-PIO-Trace propagation
query-server → storage-server with admission/flush/dispatch/storage
spans, the degraded batches-vs-queries distinction (KNOWN_ISSUES #6),
and WIRE PARITY: with telemetry off (the default) responses and RPC
headers are byte-identical to the pre-telemetry code.
"""

import json
import re
import threading
import urllib.request

import pytest

from predictionio_tpu.common import resilience, telemetry, tracing
from predictionio_tpu.common.telemetry import (
    Counter, Histogram, MetricsRegistry,
)
from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.api import EventAPI
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.data.storage.remote import StorageRPCAPI
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.models.recommendation.als_algorithm import ALSAlgorithm
from predictionio_tpu.workflow import WorkflowContext, run_train
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """No telemetry override, trace override, or recorded spans leak
    between tests (the process registry is additive by design — families
    persist — so tests assert on deltas or fresh label children)."""
    telemetry.set_enabled(None)
    tracing.set_enabled(None)
    tracing.clear()
    yield
    telemetry.set_enabled(None)
    tracing.set_enabled(None)
    tracing.clear()


# ---------------------------------------------------------------------------
# registry primitives
# ---------------------------------------------------------------------------

def test_counter_thread_safety_under_concurrent_writers():
    c = Counter()
    n_threads, per_thread = 8, 5000

    def pump():
        for _ in range(per_thread):
            c.inc()

    threads = [threading.Thread(target=pump) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per_thread


def test_histogram_thread_safety_and_totals():
    h = Histogram(buckets=(1.0, 10.0))
    n_threads, per_thread = 8, 2000

    def pump(v):
        for _ in range(per_thread):
            h.observe(v)

    threads = [threading.Thread(target=pump, args=(0.5 if k % 2 else 5.0,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = h.snapshot()
    total = n_threads * per_thread
    assert snap["count"] == total
    assert snap["buckets"][1.0] == total // 2          # the 0.5 observes
    assert snap["buckets"][10.0] == total              # cumulative
    assert snap["buckets"][float("inf")] == total
    assert snap["sum"] == pytest.approx(total // 2 * 0.5 + total // 2 * 5.0)


def test_histogram_bucket_edges():
    h = Histogram(buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.2, 1.0, 2.0, 100.0):
        h.observe(v)
    snap = h.snapshot()
    # le buckets are INCLUSIVE upper bounds, cumulative
    assert snap["buckets"][0.1] == 2       # 0.05, 0.1
    assert snap["buckets"][1.0] == 4       # + 0.2, 1.0
    assert snap["buckets"][10.0] == 5      # + 2.0
    assert snap["buckets"][float("inf")] == 6
    assert snap["count"] == 6


def test_family_label_validation_and_kind_conflicts():
    reg = MetricsRegistry()
    fam = reg.counter("x_total", "x", labelnames=("k",))
    with pytest.raises(ValueError, match="takes labels"):
        fam.labels(wrong="v")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total")
    # same (name, kind, labels) is idempotent and shares children
    assert reg.counter("x_total", labelnames=("k",)) is fam
    fam.labels(k="a").inc(3)
    assert fam.labels(k="a").value == 3


def test_metric_name_validation_at_registration():
    """Prometheus-grammar violations fail the registration that
    introduced them, not a 3am scrape (ISSUE 5 satellite)."""
    reg = MetricsRegistry()
    for bad in ("0starts_with_digit", "has-dash", "has space", "", "x.y"):
        with pytest.raises(ValueError, match="invalid metric name"):
            reg.counter(bad)
    for bad_label in ("0num", "has-dash", "", "x.y"):
        with pytest.raises(ValueError, match="invalid label name"):
            reg.gauge("ok_name", labelnames=(bad_label,))
    with pytest.raises(ValueError, match="reserved"):
        reg.histogram("ok_hist", labelnames=("le",))
    with pytest.raises(ValueError, match="reserved"):
        reg.counter("ok_counter", labelnames=("__meta",))
    # colons are legal in metric names (recording-rule convention)
    reg.counter("ns:sub_total", labelnames=("k",))


def test_all_registered_names_validate_after_importing_everything():
    """Import every instrumented module (and touch the instance-level
    registrations) — every name/label the process registers must pass
    the validator. Guards against drift in modules that build metric
    names dynamically."""
    import predictionio_tpu.common.devicewatch  # noqa: F401
    import predictionio_tpu.common.resilience  # noqa: F401
    import predictionio_tpu.common.tracing  # noqa: F401
    import predictionio_tpu.data.api.stats  # noqa: F401
    import predictionio_tpu.data.storage.eventlog  # noqa: F401
    import predictionio_tpu.data.storage.remote  # noqa: F401
    import predictionio_tpu.models.recommendation.als_algorithm  # noqa: F401
    import predictionio_tpu.ops.staging  # noqa: F401
    import predictionio_tpu.serving.batcher as batcher_mod
    import predictionio_tpu.workflow.context  # noqa: F401
    import predictionio_tpu.workflow.create_server  # noqa: F401

    # instance-level registrations (batcher) on top of import-time ones
    b = batcher_mod.MicroBatcher(lambda items: items, max_batch_size=2)
    try:
        reg = telemetry.registry()
        with reg._lock:
            families = list(reg._families.values())
        assert families, "nothing registered?"
        for fam in families:
            telemetry.validate_names(fam.name, fam.labelnames)
    finally:
        b.close()


def test_metrics_scrape_under_concurrent_mutation():
    """A scraper looping against writer threads: every exposition must
    parse, and per-series counter totals must be monotone (ISSUE 5
    satellite — the scrape takes no registry-wide lock, so this is the
    test that the per-child locking story actually holds)."""
    reg = MetricsRegistry()
    c = reg.counter("mut_total", "m", labelnames=("k",))
    h = reg.histogram("mut_seconds", "m", buckets=(0.01, 0.1, 1.0)
                      ).labels()
    stop = threading.Event()
    errors = []

    def writer(label):
        child = c.labels(k=label)
        v = 0.001
        while not stop.is_set():
            child.inc()
            h.observe(v)
            v = (v * 7) % 1.7

    threads = [threading.Thread(target=writer, args=(f"w{i}",))
               for i in range(4)]
    for t in threads:
        t.start()
    try:
        last_totals = {}
        last_count = 0
        for _ in range(50):
            try:
                types, samples = parse_prometheus(reg.exposition())
            except AssertionError as e:
                errors.append(f"unparseable exposition: {e}")
                break
            for labels, v in samples.get("mut_total", []):
                prev = last_totals.get(labels, 0.0)
                if v < prev:
                    errors.append(
                        f"counter went backwards: {labels} {prev}->{v}")
                last_totals[labels] = v
            for _labels, v in samples.get("mut_seconds_count", []):
                if v < last_count:
                    errors.append(
                        f"histogram count went backwards: "
                        f"{last_count}->{v}")
                last_count = v
    finally:
        stop.set()
        for t in threads:
            t.join(5)
    assert not errors, errors[:5]


def test_registry_dict_is_dictlike_and_registry_backed():
    reg = MetricsRegistry()
    fam = reg.counter("layout_total", "t", labelnames=("result",))
    d = telemetry.RegistryDict(fam, "result", ("hits", "builds"))
    d["hits"] += 1
    d["hits"] += 1
    d["builds"] += 1
    assert d["hits"] == 2 and d["builds"] == 1
    assert fam.labels(result="hits").value == 2     # same storage
    assert dict(d.items()) == {"hits": 2, "builds": 1}


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s(\S+)$')
_LABELS_RE = re.compile(
    r'\{((?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*)\}')


def parse_prometheus(text):
    """Strict-enough 0.0.4 text parser: validates comment structure,
    sample-line grammar, numeric values, and histogram le-monotonicity.
    Returns (types, samples: name -> [(labelstr, float)])."""
    types, samples = {}, {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            assert len(line.split(" ", 3)) >= 3, line
            continue
        if line.startswith("# TYPE "):
            _h, _t, name, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram"), line
            types[name] = kind
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        # OpenMetrics exemplar suffix (waterfall stage histograms):
        # validate its grammar, then parse the sample body as usual
        body, ex_sep, exemplar = line.partition(" # ")
        if ex_sep:
            assert re.fullmatch(r'\{[^{}]*\}\s+\S+', exemplar.strip()), \
                f"malformed exemplar: {line!r}"
        m = _SAMPLE_RE.match(body)
        assert m, f"unparseable sample line: {line!r}"
        name, labels, value = m.groups()
        if labels:
            assert _LABELS_RE.fullmatch(labels), f"bad labels: {line!r}"
        v = float(value.replace("+Inf", "inf").replace("-Inf", "-inf"))
        samples.setdefault(name, []).append((labels or "", v))
    # histogram buckets must be cumulative in le order per label set
    for name in types:
        if types[name] != "histogram":
            continue
        series = {}
        for labels, v in samples.get(name + "_bucket", []):
            le = re.search(r'le="([^"]+)"', labels).group(1)
            rest = re.sub(r'le="[^"]+",?', "", labels)
            series.setdefault(rest, []).append(
                (float(le.replace("+Inf", "inf")), v))
        for rest, pts in series.items():
            pts.sort()
            counts = [c for _le, c in pts]
            assert counts == sorted(counts), f"{name}{rest} not cumulative"
            assert pts[-1][0] == float("inf"), f"{name}{rest} missing +Inf"
    return types, samples


def test_exposition_round_trips_through_parser():
    reg = MetricsRegistry()
    reg.counter("a_total", "with \"quotes\" and spaces",
                labelnames=("k",)).labels(k='va"l\nue').inc(2)
    reg.gauge("b_depth", "depth").labels().set(3.5)
    h = reg.histogram("c_seconds", "lat", labelnames=("svc",),
                      buckets=(0.001, 0.1)).labels(svc="s")
    h.observe(0.0005)
    h.observe(5.0)
    types, samples = parse_prometheus(reg.exposition())
    assert types == {"a_total": "counter", "b_depth": "gauge",
                     "c_seconds": "histogram"}
    assert samples["a_total"][0][1] == 2
    assert samples["b_depth"][0][1] == 3.5
    assert samples["c_seconds_count"][0][1] == 2
    assert samples["c_seconds_sum"][0][1] == pytest.approx(5.0005)


# ---------------------------------------------------------------------------
# daemons: GET /metrics and /traces.json next to /healthz
# ---------------------------------------------------------------------------

def _trained_query_api(storage, **config):
    """Seed, train, and deploy a small recommendation engine."""
    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "TelApp", None))
    storage.get_events().init(app_id)
    import datetime as dt
    events = []
    for u in range(8):
        for i in range(6):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap(
                    {"rating": 5.0 if (u % 2) == (i % 2) else 1.0}),
                event_time=dt.datetime(2021, 1, 1, 0, (u * 6 + i) % 60,
                                       tzinfo=dt.timezone.utc)))
    storage.get_events().insert_batch(events, app_id)
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="TelApp"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=4, numIterations=3,
                                       lambda_=0.05, seed=3)),))
    run_train(WorkflowContext(storage=storage), engine, ep,
              engine_factory="telemetry-test",
              params_json={
                  "datasource": {"params": {"appName": "TelApp"}},
                  "algorithms": [{"name": "als", "params": {
                      "rank": 4, "numIterations": 3, "lambda": 0.05,
                      "seed": 3}}]})
    return QueryAPI(storage=storage, engine=engine,
                    config=ServerConfig(**config)), app_id


def test_metrics_route_on_all_three_daemons(memory_storage, tmp_path):
    query_api, _ = _trained_query_api(memory_storage)
    event_api = EventAPI(storage=memory_storage)
    storage_api = StorageRPCAPI(memory_storage, key="sekrit")
    try:
        for api in (query_api, event_api, storage_api):
            # unauthenticated, like /healthz (note the storage server has
            # key auth on and still serves the scrape)
            status, payload, headers = api.handle("GET", "/metrics")
            assert status == 200, type(api).__name__
            assert headers["Content-Type"].startswith("text/plain")
            types, samples = parse_prometheus(payload)
            assert types, "empty exposition"
            status, traces = api.handle("GET", "/traces.json")
            assert status == 200 and "traces" in traces
    finally:
        query_api.close()


def test_metrics_content_type_over_http(memory_storage):
    api = EventAPI(storage=memory_storage)
    server, port = serve_background(api)
    try:
        with urllib.request.urlopen(
                f"http://localhost:{port}/metrics") as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            assert "version=0.0.4" in r.headers["Content-Type"]
            parse_prometheus(r.read().decode("utf-8"))
    finally:
        server.shutdown()


def test_batcher_stats_are_registry_backed(memory_storage):
    """`GET /` batching stats and `GET /metrics` read the same counters
    (single source of truth) and the legacy JSON shape is unchanged."""
    api, _ = _trained_query_api(memory_storage)
    try:
        assert api._batcher is not None
        for k in range(3):
            st, _ = api.handle("POST", "/queries.json", body=json.dumps(
                {"user": f"u{k}", "num": 2}).encode())
            assert st == 200
        _, info = api.handle("GET", "/")
        b = info["batching"]
        assert set(b) == {"enabled", "maxBatchSize", "maxDelayMs",
                          "maxQueue", "buckets", "queueDepth", "batches",
                          "queries", "rejected", "batchSizeHist",
                          "bucketHist", "avgQueueWaitMs", "avgFlushMs"}
        assert b["queries"] == 3
        # the same numbers, straight from the registry instruments
        assert int(api._batcher._m_queries.value) == 3
        assert int(api._batcher._m_batches.value) == b["batches"]
        _st, payload, _h = api.handle("GET", "/metrics")
        types, samples = parse_prometheus(payload)
        assert types["pio_batcher_queries_total"] == "counter"
        inst = api._batcher._inst["batcher"]
        got = [v for labels, v in samples["pio_batcher_queries_total"]
               if f'batcher="{inst}"' in labels]
        assert got == [3.0]
    finally:
        api.close()


def test_event_stats_book_collected_into_metrics(memory_storage):
    from predictionio_tpu.data.api import EventServerConfig
    from predictionio_tpu.data.storage import AccessKey
    app_id = memory_storage.get_meta_data_apps().insert(App(0, "SApp"))
    memory_storage.get_meta_data_access_keys().insert(
        AccessKey("sk", app_id, ()))
    memory_storage.get_events().init(app_id)
    api = EventAPI(storage=memory_storage,
                   config=EventServerConfig(stats=True))
    st, _ = api.handle("POST", "/events.json", {"accessKey": "sk"},
                       json.dumps({"event": "rate", "entityType": "user",
                                   "entityId": "u1"}).encode())
    assert st == 201
    # /stats.json keeps its byte-compatible legacy shape...
    st, stats = api.handle("GET", "/stats.json", {"accessKey": "sk"})
    assert st == 200
    assert set(stats) == {"comment", "startTime", "currentHour",
                          "prevHour", "longLive"}
    # ...and the same book feeds the scrape via its collector
    _st, payload, _h = api.handle("GET", "/metrics")
    assert re.search(
        rf'pio_events_requests_total\{{app_id="{app_id}",status="201"\}} 1',
        payload)


def test_layout_stats_visible_in_metrics(memory_storage):
    from predictionio_tpu.models.recommendation import als_algorithm
    before = als_algorithm.LAYOUT_STATS["builds"]
    _api, _ = _trained_query_api(memory_storage)
    _api.close()
    assert als_algorithm.LAYOUT_STATS["builds"] >= before + 1
    status, payload, _h = EventAPI(storage=memory_storage).handle(
        "GET", "/metrics")
    assert 'pio_layout_cache_total{result="builds"}' in payload


# ---------------------------------------------------------------------------
# tracing: propagation + the batched-serving span chain
# ---------------------------------------------------------------------------

class _LookupALS(ALSAlgorithm):
    """ALS whose batched predict does one live storage lookup — the
    side-channel shape of the e-commerce template, small enough to trace
    end to end in a test."""

    def predict_batch(self, model, queries):
        self._serving_storage.get_meta_data_apps().get_all()   # remote RPC
        return super().predict_batch(model, queries)

    def bind_serving(self, ctx) -> None:
        self._serving_storage = ctx.storage


def _lookup_engine():
    from predictionio_tpu.controller import Engine, FirstServing
    from predictionio_tpu.models.recommendation.data_source import (
        DataSource,
    )
    from predictionio_tpu.models.recommendation.preparator import Preparator
    return Engine(data_source_class=DataSource,
                  preparator_class=Preparator,
                  algorithm_class_map={"als": _LookupALS},
                  serving_class=FirstServing)


def test_trace_propagates_query_server_to_storage_server(tmp_path):
    """The acceptance trace: one batched query -> admission, flush,
    dispatch, and storage spans, plus the STORAGE SERVER's own span, all
    under ONE trace id carried by X-PIO-Trace."""
    from predictionio_tpu.data.storage.remote import serve_storage

    backing = Storage(env={
        "PIO_STORAGE_SOURCES_B_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "B",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "B",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "B",
    })
    engine = _lookup_engine()
    # train directly against the backing store (tracing off: no spans)
    apps = backing.get_meta_data_apps()
    app_id = apps.insert(App(0, "TraceApp", None))
    backing.get_events().init(app_id)
    import datetime as dt
    backing.get_events().insert_batch([
        Event(event="rate", entity_type="user", entity_id=f"u{u}",
              target_entity_type="item", target_entity_id=f"i{i}",
              properties=DataMap({"rating": float(1 + (u + i) % 5)}),
              event_time=dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc))
        for u in range(6) for i in range(5)], app_id)
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="TraceApp"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=3, numIterations=2,
                                       lambda_=0.05, seed=1)),))
    run_train(WorkflowContext(storage=backing), engine, ep,
              engine_factory="trace-test",
              params_json={
                  "datasource": {"params": {"appName": "TraceApp"}},
                  "algorithms": [{"name": "als", "params": {
                      "rank": 3, "numIterations": 2, "lambda": 0.05,
                      "seed": 1}}]})

    rpc_server = serve_storage(backing, host="127.0.0.1", port=0)
    rpc_port = rpc_server.server_address[1]
    remote = Storage(env={
        "PIO_STORAGE_SOURCES_R_TYPE": "remote",
        "PIO_STORAGE_SOURCES_R_URL": f"http://127.0.0.1:{rpc_port}",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "R",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "R",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "R",
    })
    api = QueryAPI(storage=remote, engine=engine,
                   config=ServerConfig(batching="on"))
    server, port = serve_background(api)
    tracing.clear()
    tracing.set_enabled(True)      # the query server originates the trace
    try:
        req = urllib.request.Request(
            f"http://localhost:{port}/queries.json",
            data=json.dumps({"user": "u1", "num": 3}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        snap = tracing.snapshot()
        # find the trace that carried the query (it has an admission span)
        by_name = None
        for trace in snap["traces"]:
            names = {s["name"] for s in trace["spans"]}
            if "admission" in names:
                by_name = {s["name"]: s for s in trace["spans"]}
                break
        assert by_name is not None, snap
        for expected in ("server:/queries.json", "admission", "flush",
                         "dispatch", "storage", "server:/rpc"):
            assert expected in by_name, sorted(by_name)
        # one trace id across process boundaries = propagation worked
        # (server:/rpc was recorded by the STORAGE SERVER's handler off
        # the X-PIO-Trace header the remote driver sent)
        assert by_name["server:/rpc"]["service"] == "StorageRPCAPI"
        # and /traces.json serves the same thing over the wire
        with urllib.request.urlopen(
                f"http://localhost:{port}/traces.json") as r:
            served = json.loads(r.read())
        assert served["spanCount"] >= 6
    finally:
        tracing.set_enabled(None)
        server.shutdown()
        api.close()
        rpc_server.shutdown()
        rpc_server.server_close()


# ---------------------------------------------------------------------------
# degraded: batches vs queries upper bound (KNOWN_ISSUES #6)
# ---------------------------------------------------------------------------

def test_degraded_batches_vs_queries_upper_bound(memory_storage):
    """One tainted 3-query flush: degraded_batches_total counts 1,
    degraded_queries_upper_bound (== legacy degradedCount) counts 3."""
    api, _ = _trained_query_api(
        memory_storage, batching="on", batch_max_size=3,
        batch_max_delay_ms=500.0)
    try:
        algo = api.algorithms[0]
        real = type(algo).predict_batch

        def tainted(model, queries):
            resilience.note_degraded("test side-channel failure")
            return real(algo, model, queries)

        algo.predict_batch = tainted
        results = [None] * 3

        def hit(k):
            results[k] = api.handle(
                "POST", "/queries.json",
                body=json.dumps({"user": f"u{k}", "num": 2}).encode())

        threads = [threading.Thread(target=hit, args=(k,))
                   for k in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        for st, body in results:
            assert st == 200 and body.get("degraded") is True
        assert int(api._m_degraded_batches.value) == 1
        assert int(api._m_degraded_queries.value) == 3
        _, info = api.handle("GET", "/")
        assert info["degradedCount"] == 3     # legacy field == upper bound
    finally:
        api.close()


# ---------------------------------------------------------------------------
# wire parity: telemetry off == pre-telemetry bytes
# ---------------------------------------------------------------------------

def test_no_trace_header_emitted_by_default(tmp_path):
    """With defaults (no PIO_TRACE, no active context) the remote driver
    sends exactly the legacy header set — no X-PIO-Trace."""
    from predictionio_tpu.data.api.http import make_server

    backing = Storage(env={
        "PIO_STORAGE_SOURCES_B_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "B",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "B",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "B",
    })
    rpc_api = StorageRPCAPI(backing)
    seen = []
    orig = rpc_api.handle

    def spy(method, path, query=None, body=b"", headers=None):
        seen.append({k.lower() for k in (headers or {})})
        return orig(method, path, query, body, headers)

    rpc_api.handle = spy
    server, port = serve_background(rpc_api)
    try:
        remote = Storage(env={
            "PIO_STORAGE_SOURCES_R_TYPE": "remote",
            "PIO_STORAGE_SOURCES_R_URL": f"http://127.0.0.1:{port}",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "R",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "R",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "R",
        })
        remote.get_meta_data_apps().get_all()
        assert seen and all("x-pio-trace" not in h for h in seen)

        # positive control: an ACTIVE context adds exactly that header
        seen.clear()
        with tracing.activate(tracing.new_context()):
            with tracing.span("probe"):
                remote.get_meta_data_apps().get_all()
        assert any("x-pio-trace" in h for h in seen)
    finally:
        server.shutdown()


def test_responses_byte_identical_with_telemetry_on_and_off(memory_storage):
    """Flipping PIO_TELEMETRY must never change a response byte: metrics
    observe, they do not decorate."""
    api, _ = _trained_query_api(memory_storage)
    try:
        body = json.dumps({"user": "u1", "num": 4}).encode()
        telemetry.set_enabled(False)
        st_off, off = api.handle("POST", "/queries.json", body=body)
        telemetry.set_enabled(True)
        st_on, on = api.handle("POST", "/queries.json", body=body)
        assert (st_off, json.dumps(off)) == (st_on, json.dumps(on))
        # legacy GET / key set unchanged (no telemetry keys leak in;
        # "aot" is the AOT-deploy section, present because this server
        # prebuilt its programs — PIO_AOT=0 parity is tests/test_aot.py)
        _, info = api.handle("GET", "/")
        assert set(info) == {
            "status", "engineInstance", "algorithms", "requestCount",
            "avgServingSec", "lastServingSec", "degradedCount", "draining",
            "serverStartTime", "generation", "batching", "aot"}
    finally:
        telemetry.set_enabled(None)
        api.close()


def test_telemetry_on_records_serve_latency(memory_storage):
    telemetry.set_enabled(True)
    api, _ = _trained_query_api(memory_storage)
    try:
        st, _ = api.handle("POST", "/queries.json", body=json.dumps(
            {"user": "u1", "num": 2}).encode())
        assert st == 200
        fam = telemetry.registry().histogram(
            "pio_serve_seconds", labelnames=("mode", "tenant"))
        assert fam.labels(mode="batched", tenant="default").count >= 1
    finally:
        api.close()
