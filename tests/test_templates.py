"""Template tests: classification, similarproduct, ecommerce (ref:
examples/scala-parallel-{classification,similarproduct,
ecommercerecommendation}/ DASE behavior)."""

import datetime as dt

import pytest

from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data import store
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App
from predictionio_tpu.workflow import WorkflowContext, run_train

UTC = dt.timezone.utc


def _mk_app(storage, name):
    app_id = storage.get_meta_data_apps().insert(App(0, name, None))
    storage.get_events().init(app_id)
    return app_id


def _set(entity_type, entity_id, props, minute=0):
    return Event(
        event="$set", entity_type=entity_type, entity_id=entity_id,
        properties=DataMap(props),
        event_time=dt.datetime(2021, 1, 1, 0, minute % 60, tzinfo=UTC))


def _ev(name, user, item, props=None, minute=0, hour=1):
    return Event(
        event=name, entity_type="user", entity_id=user,
        target_entity_type="item", target_entity_id=item,
        properties=DataMap(props or {}),
        event_time=dt.datetime(2021, 1, 1, hour, minute % 60, tzinfo=UTC))


def _assert_batch_matches_sequential(seq, bat):
    """Batched serving parity: same items in the same order; scores equal
    up to the last-bit difference between one BLAS gemm row and a gemv
    (the batched path's only numerical deviation)."""
    import numpy as np

    assert len(seq) == len(bat)
    for a, b in zip(seq, bat):
        assert [s.item for s in a.itemScores] == \
            [s.item for s in b.itemScores]
        np.testing.assert_allclose(
            [s.score for s in a.itemScores],
            [s.score for s in b.itemScores], rtol=1e-5, atol=1e-7)


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

class TestClassification:
    @pytest.fixture()
    def app(self, memory_storage):
        app_id = _mk_app(memory_storage, "ClsApp")
        events = []
        # multinomial NB separates by feature PROPORTIONS: plan 0 mass on
        # attr0, plan 1 mass on attr2
        for n in range(20):
            plan = n % 2
            lo, hi = 0.0 + (n % 3), 8.0 + (n % 3)
            events.append(_set("user", f"u{n}", {
                "plan": float(plan),
                "attr0": hi if plan == 0 else lo,
                "attr1": 2.0,
                "attr2": lo if plan == 0 else hi}, minute=n))
        # a user missing attributes must be excluded by `required`
        events.append(_set("user", "incomplete", {"plan": 1.0}, minute=50))
        store.write(events, app_id, storage=memory_storage)
        return app_id

    def test_train_and_predict(self, memory_storage, app):
        from predictionio_tpu.models.classification import (
            ClassificationEngine, DataSourceParams, NaiveBayesAlgorithmParams,
            Query,
        )
        engine = ClassificationEngine()
        ep = EngineParams(
            data_source_params=DataSourceParams(appName="ClsApp"),
            algorithm_params_list=(
                ("naive", NaiveBayesAlgorithmParams(lambda_=1.0)),))
        ctx = WorkflowContext(storage=memory_storage)
        ds, _prep, algos, _serv = engine._instantiate(ep)
        td = ds.read_training(ctx)
        assert len(td.labeled_points) == 20  # incomplete user excluded
        model = algos[0].train(ctx, td)
        p0 = algos[0].predict(model, Query(features=(9.0, 2.0, 1.0)))
        p1 = algos[0].predict(model, Query(features=(1.0, 2.0, 9.0)))
        assert p0.label == 0.0 and p1.label == 1.0

    def test_engine_json_and_eval(self, memory_storage, app):
        from predictionio_tpu.models.classification import (
            ClassificationEngine, DataSourceParams,
        )
        engine = ClassificationEngine()
        ep = engine.engine_params_from_json({
            "datasource": {"params": {"appName": "ClsApp", "evalK": 3}},
            "algorithms": [{"name": "naive", "params": {"lambda": 0.5}}],
        })
        assert ep.algorithm_params_list[0][1].lambda_ == 0.5
        ctx = WorkflowContext(storage=memory_storage)
        folds = engine.eval(ctx, ep)
        assert len(folds) == 3
        # accuracy over folds should be high for the separable data
        correct = total = 0
        for _ei, qpa in folds:
            for _q, p, a in qpa:
                total += 1
                correct += (p.label == a)
        assert total == 20 and correct / total >= 0.9


# ---------------------------------------------------------------------------
# similarproduct
# ---------------------------------------------------------------------------

class TestSimilarProduct:
    @pytest.fixture()
    def app(self, memory_storage):
        app_id = _mk_app(memory_storage, "SimApp")
        events = []
        for u in range(8):
            events.append(_set("user", f"u{u}", {}, minute=u))
        for i in range(6):
            cats = ["even"] if i % 2 == 0 else ["odd"]
            events.append(_set("item", f"i{i}", {"categories": cats},
                               minute=10 + i))
        # co-view structure: users view items of matching parity
        m = 0
        for u in range(8):
            for i in range(6):
                if (u % 2) == (i % 2):
                    m += 1
                    events.append(_ev("view", f"u{u}", f"i{i}", minute=m))
        # like/dislike signal for LikeAlgorithm
        m = 0
        for u in range(8):
            for i in range(6):
                m += 1
                name = "like" if (u % 2) == (i % 2) else "dislike"
                events.append(_ev(name, f"u{u}", f"i{i}", minute=m, hour=2))
        # u0 changed their mind about i1: like then dislike (latest wins)
        events.append(_ev("like", "u0", "i1", minute=58, hour=2))
        events.append(_ev("dislike", "u0", "i1", minute=59, hour=3))
        store.write(events, app_id, storage=memory_storage)
        return app_id

    def _train(self, memory_storage, algo_name="als"):
        from predictionio_tpu.models.similarproduct import (
            ALSAlgorithmParams, DataSourceParams, SimilarProductEngine,
        )
        engine = SimilarProductEngine()
        ep = EngineParams(
            data_source_params=DataSourceParams(appName="SimApp"),
            algorithm_params_list=((algo_name, ALSAlgorithmParams(
                rank=4, numIterations=10, lambda_=0.01, seed=3)),))
        ctx = WorkflowContext(storage=memory_storage)
        ds, _p, algos, _s = engine._instantiate(ep)
        td = ds.read_training(ctx)
        return algos[0], algos[0].train(ctx, td), td

    def test_similar_items_match_parity(self, memory_storage, app):
        from predictionio_tpu.models.similarproduct import Query
        algo, model, td = self._train(memory_storage)
        assert len(td.view_events) == 24
        res = algo.predict(model, Query(items=("i0",), num=2))
        assert len(res.itemScores) == 2
        assert {s.item for s in res.itemScores} <= {"i2", "i4"}
        scores = [s.score for s in res.itemScores]
        assert scores == sorted(scores, reverse=True)

    def test_filters(self, memory_storage, app):
        from predictionio_tpu.models.similarproduct import Query
        algo, model, _td = self._train(memory_storage)
        res = algo.predict(model, Query(
            items=("i0",), num=4, categories=("odd",)))
        assert all(s.item in {"i1", "i3", "i5"} for s in res.itemScores)
        res = algo.predict(model, Query(
            items=("i0",), num=4, whiteList=("i2",)))
        assert {s.item for s in res.itemScores} <= {"i2"}
        res = algo.predict(model, Query(
            items=("i0",), num=4, blackList=("i2",)))
        assert "i2" not in {s.item for s in res.itemScores}
        # query items themselves are never candidates
        res = algo.predict(model, Query(items=("i0", "i2", "i4"), num=6))
        assert not ({"i0", "i2", "i4"} & {s.item for s in res.itemScores})
        # unknown query item -> empty
        res = algo.predict(model, Query(items=("nope",), num=3))
        assert res.itemScores == ()

    def test_predict_batch_matches_sequential(self, memory_storage, app):
        """Serving micro-batch (one gemm over stacked query vectors) must
        agree with per-query predict across the full filter surface,
        including the empty paths."""
        from predictionio_tpu.models.similarproduct import Query
        algo, model, _td = self._train(memory_storage)
        queries = [
            Query(items=("i0",), num=2),
            Query(items=("i0",), num=4, categories=("odd",)),
            Query(items=("nope",), num=3),              # unknown -> empty
            Query(items=("i0", "i2", "i4"), num=6),
            Query(items=("i1",), num=3, blackList=("i3",)),
            Query(items=("i0",), num=4, whiteList=("i2",)),
        ]
        seq = [algo.predict(model, q) for q in queries]
        bat = algo.predict_batch(model, queries)
        _assert_batch_matches_sequential(seq, bat)
        assert bat[2].itemScores == ()

    def test_like_algorithm_latest_wins(self, memory_storage, app):
        algo, model, td = self._train(memory_storage, algo_name="likealgo")
        # u0 i1: like at 2:58 then dislike at 3:59 -> rating -1
        from predictionio_tpu.data.bimap import BiMap
        uv = BiMap.string_int(td.users.keys())
        iv = BiMap.string_int(td.items.keys())
        ratings = algo._ratings(td, uv, iv)
        assert ratings[(uv("u0"), iv("i1"))] == -1.0
        assert ratings[(uv("u0"), iv("i0"))] == 1.0


# ---------------------------------------------------------------------------
# ecommerce
# ---------------------------------------------------------------------------

class TestECommerce:
    @pytest.fixture()
    def app(self, memory_storage):
        app_id = _mk_app(memory_storage, "EcomApp")
        events = []
        for u in range(8):
            events.append(_set("user", f"u{u}", {}, minute=u))
        for i in range(6):
            cats = ["even"] if i % 2 == 0 else ["odd"]
            events.append(_set("item", f"i{i}", {"categories": cats},
                               minute=10 + i))
        m = 0
        for u in range(8):
            for i in range(6):
                m += 1
                r = 5.0 if (u % 2) == (i % 2) else 1.0
                events.append(_ev("rate", f"u{u}", f"i{i}",
                                  {"rating": r}, minute=m))
        # u0 re-rated i1 (1.0 -> 5.0, later timestamp wins)
        events.append(_ev("rate", "u0", "i1", {"rating": 5.0},
                          minute=30, hour=2))
        store.write(events, app_id, storage=memory_storage)
        return app_id

    def _train(self, memory_storage, **params):
        from predictionio_tpu.models.ecommerce import (
            DataSourceParams, ECommAlgorithmParams, ECommerceEngine,
        )
        engine = ECommerceEngine()
        ap = ECommAlgorithmParams(
            appName="EcomApp", rank=4, numIterations=10, lambda_=0.05,
            seed=3, **params)
        ep = EngineParams(
            data_source_params=DataSourceParams(appName="EcomApp"),
            algorithm_params_list=(("ecomm", ap),))
        ctx = WorkflowContext(storage=memory_storage)
        ds, _p, algos, _s = engine._instantiate(ep)
        td = ds.read_training(ctx)
        return algos[0], algos[0].train(ctx, td), td

    def test_known_user_scoring(self, memory_storage, app):
        from predictionio_tpu.models.ecommerce import Query
        algo, model, td = self._train(memory_storage)
        # latest-wins: u0 x i1 rating must be 5.0 in training data prep
        res = algo.predict(model, Query(user="u1", num=3))
        assert len(res.itemScores) == 3
        assert {s.item for s in res.itemScores} <= {"i1", "i3", "i5"}

    def test_unseen_only_filters_seen(self, memory_storage, app):
        from predictionio_tpu.models.ecommerce import Query
        algo, model, _td = self._train(
            memory_storage, unseenOnly=True, seenEvents=("rate",))
        res = algo.predict(model, Query(user="u1", num=6))
        # u1 rated everything -> nothing unseen remains
        assert res.itemScores == ()

    def test_unavailable_items_constraint(self, memory_storage, app):
        from predictionio_tpu.models.ecommerce import Query
        algo, model, _td = self._train(memory_storage)
        # live $set on constraint/unavailableItems (latest wins)
        store.write([Event(
            event="$set", entity_type="constraint",
            entity_id="unavailableItems",
            properties=DataMap({"items": ["i1", "i3"]}),
            event_time=dt.datetime(2021, 1, 2, tzinfo=UTC))],
            app, storage=memory_storage)
        res = algo.predict(model, Query(user="u1", num=6))
        assert not ({"i1", "i3"} & {s.item for s in res.itemScores})
        assert "i5" in {s.item for s in res.itemScores}

    def test_weighted_items_boost_scores(self, memory_storage, app):
        """weighted-items variant (weighted-items/ALSAlgorithm.scala:
        234-261): a live $set on constraint/weightedItems multiplies
        scores per item group; buried items drop out of the top, boosted
        ones rise, and queries without the constraint are untouched."""
        from predictionio_tpu.models.ecommerce import Query
        algo, model, _td = self._train(memory_storage, weightedItems=True)
        base = algo.predict(model, Query(user="u1", num=3))
        top = {s.item for s in base.itemScores}
        assert top <= {"i1", "i3", "i5"}
        # bury the odd cluster, boost i0
        store.write([Event(
            event="$set", entity_type="constraint",
            entity_id="weightedItems",
            properties=DataMap({"weights": [
                {"items": ["i1", "i3", "i5"], "weight": 0.001},
                {"items": ["i0"], "weight": 100.0}]}),
            event_time=dt.datetime(2021, 1, 2, tzinfo=UTC))],
            app, storage=memory_storage)
        res = algo.predict(model, Query(user="u1", num=3))
        assert res.itemScores[0].item == "i0"
        # latest $set wins: clearing the constraint restores base ranking
        store.write([Event(
            event="$set", entity_type="constraint",
            entity_id="weightedItems",
            properties=DataMap({"weights": []}),
            event_time=dt.datetime(2021, 1, 3, tzinfo=UTC))],
            app, storage=memory_storage)
        res = algo.predict(model, Query(user="u1", num=3))
        assert {s.item for s in res.itemScores} == top

    def test_new_user_falls_back_to_recent_views(self, memory_storage, app):
        from predictionio_tpu.models.ecommerce import Query
        algo, model, _td = self._train(memory_storage)
        # unknown user with a recent view event on i0
        store.write([_ev("view", "newbie", "i0", minute=1, hour=5)],
                    app, storage=memory_storage)
        res = algo.predict(model, Query(user="newbie", num=3))
        assert len(res.itemScores) == 3
        # reference parity: recently-viewed items stay candidates
        # (predictNewUser has no recentList exclusion), so i0 may rank first
        assert {s.item for s in res.itemScores} <= {"i0", "i2", "i4"}

    def test_predict_batch_matches_sequential(self, memory_storage, app):
        """One mixed micro-batch covering both scoring groups — known
        users (raw factors) and a recent-views fallback user (normalized
        factors) — plus the live business-rule filters and an empty
        path, vs per-query predict."""
        from predictionio_tpu.models.ecommerce import Query
        algo, model, _td = self._train(memory_storage)
        store.write([_ev("view", "newbie", "i0", minute=1, hour=5)],
                    app, storage=memory_storage)
        store.write([Event(
            event="$set", entity_type="constraint",
            entity_id="unavailableItems",
            properties=DataMap({"items": ["i3"]}),
            event_time=dt.datetime(2021, 1, 2, tzinfo=UTC))],
            app, storage=memory_storage)
        queries = [
            Query(user="u1", num=3),
            Query(user="u2", num=4, categories=("even",)),
            Query(user="newbie", num=3),             # hat-factors group
            Query(user="ghost", num=3),              # no events -> empty
            Query(user="u0", num=6, blackList=("i5",)),
        ]
        seq = [algo.predict(model, q) for q in queries]
        bat = algo.predict_batch(model, queries)
        _assert_batch_matches_sequential(seq, bat)
        assert bat[3].itemScores == ()
        assert all("i3" not in {s.item for s in r.itemScores} for r in bat)
        # unknown user with no history -> empty
        res = algo.predict(model, Query(user="ghost", num=2))
        assert res.itemScores == ()

    def test_full_train_deploy_roundtrip(self, memory_storage, app):
        """Train -> persist -> deploy (device_put) -> query: catches
        device-array immutability on the persisted-mask path."""
        import json
        from predictionio_tpu.models.ecommerce import (
            DataSourceParams, ECommAlgorithmParams, ECommerceEngine,
        )
        from predictionio_tpu.workflow.create_server import QueryAPI
        engine = ECommerceEngine()
        ep = EngineParams(
            data_source_params=DataSourceParams(appName="EcomApp"),
            algorithm_params_list=(("ecomm", ECommAlgorithmParams(
                appName="EcomApp", rank=4, numIterations=5, seed=3)),))
        iid = run_train(
            WorkflowContext(storage=memory_storage), engine, ep,
            engine_factory="x",
            params_json={
                "datasource": {"params": {"appName": "EcomApp"}},
                "algorithms": [{"name": "ecomm", "params": {
                    "appName": "EcomApp", "rank": 4, "numIterations": 5,
                    "seed": 3}}]})
        assert memory_storage.get_model_data_models().get(iid) is not None
        api = QueryAPI(storage=memory_storage, engine=engine)
        status, body = api.handle("POST", "/queries.json", body=json.dumps(
            {"user": "u1", "num": 3, "categories": ["odd"]}).encode())
        assert status == 200, body
        assert {s["item"] for s in body["itemScores"]} <= {"i1", "i3", "i5"}
        # unknown-user fallback through the deployed model too
        store.write([_ev("view", "fresh", "i0", minute=2, hour=6)],
                    app, storage=memory_storage)
        status, body = api.handle("POST", "/queries.json", body=json.dumps(
            {"user": "fresh", "num": 2}).encode())
        assert status == 200 and len(body["itemScores"]) == 2


def test_malformed_weights_group_does_not_break_serving(memory_storage):
    """A garbage weightedItems constraint must degrade to unweighted
    serving, not a per-query error (weighted-items variant hardening)."""
    from predictionio_tpu.models.ecommerce.als_algorithm import ECommAlgorithm
    from predictionio_tpu.models.ecommerce import ECommAlgorithmParams

    class FakeVocab:
        def get(self, k):
            return None
        def __len__(self):
            return 3

    algo = ECommAlgorithm(ECommAlgorithmParams(appName="nope"))
    # _item_weights reads the store lazily; feed it groups directly
    class M:
        item_vocab = FakeVocab()
    import unittest.mock as mock
    from predictionio_tpu.data import store as st
    ev = mock.Mock()
    ev.properties.get_opt.return_value = [
        {"items": 42, "weight": 2.0},          # non-iterable
        {"items": "i1", "weight": 2.0},        # string (char iteration)
        "not a dict",                          # wrong type entirely
        {"items": ["i1"], "weight": "heavy"},  # non-numeric weight
    ]
    with mock.patch.object(st, "find_by_entity", return_value=[ev]):
        w = algo._item_weights(M())
    assert w is None      # every group rejected, serving stays unweighted
