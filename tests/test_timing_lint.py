"""Mechanical enforcement of the timing rules (tier-1).

1. No ``time.time()`` anywhere in ``predictionio_tpu/``: every timed
   region must use ``time.perf_counter()`` (monotonic, not subject to
   NTP steps — a wall-clock delta can go NEGATIVE mid-measurement).
   Wall-clock timestamps, where genuinely needed (event times, span
   display timestamps), come from timezone-aware ``datetime`` instead,
   so the ban is total and the lint stays trivially greppable.

2. No ``block_until_ready`` as a timing barrier in instrumented modules:
   on the tunneled axon platform it can return before results land on
   host (KNOWN_ISSUES #3), silently under-reporting any clock stopped
   behind it. Timed regions must end in a real host transfer
   (``jax.device_get``) instead.

AST-based (not just grep) so aliased imports are caught too.
"""

import ast
import os

import pytest

PKG = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "predictionio_tpu")


def _py_files():
    for root, _dirs, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(root, f)


def _time_time_calls(tree, module_aliases, func_aliases):
    """Call sites that resolve to time.time in this module."""
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr == "time"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in module_aliases):
            hits.append(node.lineno)
        elif isinstance(fn, ast.Name) and fn.id in func_aliases:
            hits.append(node.lineno)
    return hits


def _aliases(tree):
    """(names bound to the time MODULE, names bound to time.time)."""
    module_aliases, func_aliases = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    module_aliases.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    func_aliases.add(a.asname or "time")
    return module_aliases, func_aliases


def test_no_wall_clock_time_in_package():
    offenders = []
    for path in _py_files():
        with open(path, encoding="utf-8") as f:
            src = f.read()
        if "time" not in src:        # cheap pre-filter
            continue
        tree = ast.parse(src, filename=path)
        module_aliases, func_aliases = _aliases(tree)
        if not module_aliases and not func_aliases:
            continue
        for line in _time_time_calls(tree, module_aliases, func_aliases):
            rel = os.path.relpath(path, os.path.dirname(PKG))
            offenders.append(f"{rel}:{line}")
    assert not offenders, (
        "time.time() found in timing-sensitive package code — use "
        "time.perf_counter() (monotonic) for durations or timezone-aware "
        "datetime for wall-clock timestamps:\n  " + "\n  ".join(offenders))


#: modules whose timed regions feed telemetry/phase tables; a
#: block_until_ready here is the exact KNOWN_ISSUES #3 bug shape. (ops/
#: kernels may legitimately use it for non-timing dispatch control.)
_TIMED_MODULES = (
    "common/telemetry.py", "common/tracing.py", "common/devicewatch.py",
    "serving/batcher.py", "serving/aot.py",
    "workflow/context.py", "workflow/core_workflow.py",
    "workflow/create_server.py", "data/store.py", "ops/staging.py",
    "models/recommendation/als_algorithm.py",
    "tools/benchtrend.py", "tools/doctor.py",
)


def test_no_block_until_ready_in_timed_modules():
    offenders = []
    for rel in _TIMED_MODULES:
        path = os.path.join(PKG, rel)
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):   # AST: docstrings/comments don't trip
            if ((isinstance(node, ast.Attribute)
                 and node.attr == "block_until_ready")
                    or (isinstance(node, ast.Name)
                        and node.id == "block_until_ready")):
                offenders.append(f"predictionio_tpu/{rel}:{node.lineno}")
    assert not offenders, (
        "block_until_ready in a timed module — it can return early on "
        "tunneled platforms (KNOWN_ISSUES #3); end the region in a real "
        "host transfer (jax.device_get) instead:\n  "
        + "\n  ".join(offenders))


def test_lint_actually_detects_violations():
    """The lint is live: a synthetic offender trips it."""
    tree = ast.parse("import time as t\nx = t.time()\n")
    m, f = _aliases(tree)
    assert _time_time_calls(tree, m, f) == [2]
    tree = ast.parse("from time import time\nx = time()\n")
    m, f = _aliases(tree)
    assert _time_time_calls(tree, m, f) == [2]
    # perf_counter does NOT trip it
    tree = ast.parse("import time\nx = time.perf_counter()\n")
    m, f = _aliases(tree)
    assert _time_time_calls(tree, m, f) == []


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
