"""Mechanical enforcement of the timing rules (tier-1).

1. No ``time.time()`` anywhere in ``predictionio_tpu/``: every timed
   region must use ``time.perf_counter()`` (monotonic, not subject to
   NTP steps — a wall-clock delta can go NEGATIVE mid-measurement).
   Wall-clock timestamps, where genuinely needed (event times, span
   display timestamps), come from timezone-aware ``datetime`` instead,
   so the ban is total and the lint stays trivially greppable.

2. No ``block_until_ready`` as a timing barrier in instrumented modules:
   on the tunneled axon platform it can return before results land on
   host (KNOWN_ISSUES #3), silently under-reporting any clock stopped
   behind it. Timed regions must end in a real host transfer
   (``jax.device_get``) instead.

AST-based (not just grep) so aliased imports are caught too.
"""

import ast
import os

import pytest

PKG = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "predictionio_tpu")


def _py_files():
    for root, _dirs, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(root, f)


def _time_time_calls(tree, module_aliases, func_aliases):
    """Call sites that resolve to time.time in this module."""
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr == "time"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in module_aliases):
            hits.append(node.lineno)
        elif isinstance(fn, ast.Name) and fn.id in func_aliases:
            hits.append(node.lineno)
    return hits


def _aliases(tree):
    """(names bound to the time MODULE, names bound to time.time)."""
    module_aliases, func_aliases = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    module_aliases.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    func_aliases.add(a.asname or "time")
    return module_aliases, func_aliases


def test_no_wall_clock_time_in_package():
    offenders = []
    for path in _py_files():
        with open(path, encoding="utf-8") as f:
            src = f.read()
        if "time" not in src:        # cheap pre-filter
            continue
        tree = ast.parse(src, filename=path)
        module_aliases, func_aliases = _aliases(tree)
        if not module_aliases and not func_aliases:
            continue
        for line in _time_time_calls(tree, module_aliases, func_aliases):
            rel = os.path.relpath(path, os.path.dirname(PKG))
            offenders.append(f"{rel}:{line}")
    assert not offenders, (
        "time.time() found in timing-sensitive package code — use "
        "time.perf_counter() (monotonic) for durations or timezone-aware "
        "datetime for wall-clock timestamps:\n  " + "\n  ".join(offenders))


#: modules whose timed regions feed telemetry/phase tables; a
#: block_until_ready here is the exact KNOWN_ISSUES #3 bug shape. (ops/
#: kernels may legitimately use it for non-timing dispatch control.)
_TIMED_MODULES = (
    "common/telemetry.py", "common/tracing.py", "common/devicewatch.py",
    "common/waterfall.py", "common/profiling.py", "common/slo.py",
    "serving/batcher.py", "serving/aot.py", "parallel/serve_dist.py",
    "workflow/context.py", "workflow/core_workflow.py",
    "workflow/create_server.py", "data/store.py", "ops/staging.py",
    "models/recommendation/als_algorithm.py",
    "tools/benchtrend.py", "tools/doctor.py", "tools/profile.py",
)


def test_no_block_until_ready_in_timed_modules():
    offenders = []
    for rel in _TIMED_MODULES:
        path = os.path.join(PKG, rel)
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):   # AST: docstrings/comments don't trip
            if ((isinstance(node, ast.Attribute)
                 and node.attr == "block_until_ready")
                    or (isinstance(node, ast.Name)
                        and node.id == "block_until_ready")):
                offenders.append(f"predictionio_tpu/{rel}:{node.lineno}")
    assert not offenders, (
        "block_until_ready in a timed module — it can return early on "
        "tunneled platforms (KNOWN_ISSUES #3); end the region in a real "
        "host transfer (jax.device_get) instead:\n  "
        + "\n  ".join(offenders))


# ---------------------------------------------------------------------------
# debug-surface lint: every /debug/* endpoint must ride the SHARED
# telemetry.handle_route so the three daemons can never drift apart
# (the event server once lacked a surface the query server had; this
# makes that class of bug a failing tier-1 test)
# ---------------------------------------------------------------------------

#: the daemon route handlers that must consult telemetry.handle_route
_DAEMON_MODULES = (
    "workflow/create_server.py",   # query server (QueryAPI.handle)
    "data/api/service.py",         # event server (EventAPI._route)
    "data/storage/remote.py",      # storage server (StorageRPCAPI.handle)
)


def _debug_string_constants(tree):
    return {node.value for node in ast.walk(tree)
            if isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value.startswith("/debug/")}


def test_debug_endpoints_only_defined_in_shared_handle_route():
    """Every /debug/* path compared anywhere in the package must be one
    telemetry.DEBUG_PATHS serves — a debug endpoint wired into a single
    daemon's private route table would drift off the other two."""
    from predictionio_tpu.common import telemetry
    offenders = []
    for path in _py_files():
        with open(path, encoding="utf-8") as f:
            src = f.read()
        if "/debug/" not in src:
            continue
        tree = ast.parse(src, filename=path)
        for const in _debug_string_constants(tree):
            # startswith-match so query-bearing scrape paths
            # ("/debug/slow.json?limit=3") stay legal
            if not any(const == p or const.startswith(p + "?")
                       for p in telemetry.DEBUG_PATHS):
                rel = os.path.relpath(path, os.path.dirname(PKG))
                offenders.append(f"{rel}: {const!r}")
    assert not offenders, (
        "debug endpoint(s) referenced outside telemetry.DEBUG_PATHS — "
        "register them in common/telemetry.py handle_route so all three "
        "daemons serve them:\n  " + "\n  ".join(offenders))


def test_every_daemon_consults_shared_handle_route():
    """Each daemon's route handler must call telemetry.handle_route —
    that one call is what puts every DEBUG_PATHS surface (and /metrics,
    /traces.json) on its wire."""
    missing = []
    for rel in _DAEMON_MODULES:
        path = os.path.join(PKG, rel)
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        calls = [n for n in ast.walk(tree)
                 if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Attribute)
                 and n.func.attr == "handle_route"
                 and isinstance(n.func.value, ast.Name)
                 and n.func.value.id == "telemetry"]
        if not calls:
            missing.append(rel)
    assert not missing, (
        "daemon route handler(s) never call telemetry.handle_route — "
        "their /debug/* surface has drifted off:\n  "
        + "\n  ".join(missing))


def test_debug_paths_answer_on_event_and_storage_daemons(memory_storage):
    """Runtime half of the lint: every DEBUG_PATHS surface answers
    (non-404) on the two cheap daemons. The query server's identical
    surface is covered by the waterfall e2e test (it needs a trained
    model)."""
    from predictionio_tpu.common import telemetry
    from predictionio_tpu.data.api import EventAPI
    from predictionio_tpu.data.storage.remote import StorageRPCAPI
    apis = (EventAPI(storage=memory_storage),
            StorageRPCAPI(memory_storage, key="sekrit"))
    for api in apis:
        for path in telemetry.DEBUG_PATHS:
            response = api.handle("GET", path)
            assert response[0] == 200, (type(api).__name__, path,
                                        response)


def test_lint_actually_detects_violations():
    """The lint is live: a synthetic offender trips it."""
    tree = ast.parse("import time as t\nx = t.time()\n")
    m, f = _aliases(tree)
    assert _time_time_calls(tree, m, f) == [2]
    tree = ast.parse("from time import time\nx = time()\n")
    m, f = _aliases(tree)
    assert _time_time_calls(tree, m, f) == [2]
    # perf_counter does NOT trip it
    tree = ast.parse("import time\nx = time.perf_counter()\n")
    m, f = _aliases(tree)
    assert _time_time_calls(tree, m, f) == []


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
