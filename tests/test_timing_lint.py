"""Timing honesty + debug-surface unity: the runtime halves, plus the
tier-1 delegation onto the `pio lint` passes.

The static AST lints that lived here pre-PR 9 (no `time.time()`, no
`block_until_ready` in timed modules, every `/debug/*` path on the
shared route) are now passes on the shared walker
(tools/analyze/passes/timing.py, debug_surface.py) — repo-wide with
opt-OUT pragmas instead of this file's old hand-maintained opt-in
lists. The tests below run those passes over the real tree so the rules
still gate tier-1 from their historical home; seeded-defect proofs and
the old-list-containment assertions live in tests/test_lint.py.

What stays here natively is what static analysis cannot see: the
runtime half of the debug-surface rule (every DEBUG_PATHS surface
actually answers 200 on live daemon APIs).
"""

import pytest

from predictionio_tpu.tools.analyze.passes import debug_surface, timing
from predictionio_tpu.tools.analyze.walker import discover


def _active(findings):
    """Pragma handling happens inside the passes; anything returned is
    an active violation."""
    return [f"{f.path}:{f.line} [{f.rule}]" for f in findings]


def test_no_wall_clock_time_in_package():
    """No time.time() anywhere in the repo-of-record: durations come
    from time.perf_counter() (monotonic — a wall-clock delta can go
    NEGATIVE mid-measurement under NTP steps), wall-clock timestamps
    from timezone-aware datetime. Now covers bench.py and diagnostics/
    too, not just the package."""
    findings = [f for f in timing.run(discover())
                if f.rule == "timing-wall-clock"]
    assert not findings, "\n  ".join(_active(findings))


def test_no_block_until_ready_anywhere():
    """block_until_ready can return before results land on host
    (KNOWN_ISSUES #3): timed regions end in a real host transfer
    (jax.device_get). Was opt-IN over 18 listed modules; now every
    module is covered and legitimate non-timing barriers opt OUT in
    their own source with a justified pragma."""
    findings = [f for f in timing.run(discover())
                if f.rule == "timing-block-until-ready"]
    assert not findings, "\n  ".join(_active(findings))


def test_debug_surface_unified():
    """Every /debug/* path rides telemetry.DEBUG_PATHS and all three
    daemons consult telemetry.handle_route (KNOWN shape: the event
    server once lacked a surface the query server had)."""
    findings = debug_surface.run(discover())
    assert not findings, "\n  ".join(_active(findings))


def test_debug_paths_parse_from_telemetry_source():
    """The pass reads DEBUG_PATHS statically (no jax import); it must
    agree with the imported module — if the assignment ever becomes
    dynamic the pass would abstain and this test catches it."""
    from predictionio_tpu.common import telemetry
    parsed = debug_surface.shared_debug_paths(discover())
    assert parsed == set(telemetry.DEBUG_PATHS)


def test_debug_paths_answer_on_event_and_storage_daemons(memory_storage):
    """Runtime half of the lint: every DEBUG_PATHS surface answers
    (non-404) on the cheap daemons — the event server, the storage
    server, the fleet router (a backendless one constructs fine; its
    debug surface is independent of the fleet's health), and the keyed
    dashboard + admin servers (their telemetry surface answers BEFORE
    auth — a scraper or `pio monitor` holds no key). The query server's
    identical surface is covered by the waterfall e2e test (it needs a
    trained model)."""
    import socket

    from predictionio_tpu.common import telemetry
    from predictionio_tpu.data.api import EventAPI
    from predictionio_tpu.data.storage.remote import StorageRPCAPI
    from predictionio_tpu.tools.admin import AdminAPI
    from predictionio_tpu.tools.dashboard import DashboardAPI
    from predictionio_tpu.workflow.router import RouterAPI, RouterConfig
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    router = RouterAPI(RouterConfig(
        backends=(f"http://127.0.0.1:{dead_port}",), health_ms=50.0))
    apis = (EventAPI(storage=memory_storage),
            StorageRPCAPI(memory_storage, key="sekrit"),
            DashboardAPI(storage=memory_storage, server_key="sekrit"),
            AdminAPI(storage=memory_storage, server_key="sekrit"),
            router)
    try:
        for api in apis:
            for path in telemetry.DEBUG_PATHS:
                response = api.handle("GET", path)
                assert response[0] == 200, (type(api).__name__, path,
                                            response)
    finally:
        router.close()


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
