"""Top-K scoring ops (serving hot path)."""

import numpy as np

from predictionio_tpu.ops import topk


def test_topk_scores_basic():
    V = np.array([[1.0, 0], [0, 1], [2, 0], [0.5, 0.5]], dtype=np.float32)
    q = np.array([1.0, 0.0], dtype=np.float32)
    vals, idx = topk.topk_scores(q, V, k=2)
    np.testing.assert_array_equal(np.asarray(idx), [2, 0])
    np.testing.assert_allclose(np.asarray(vals), [2.0, 1.0])


def test_topk_scores_mask_excludes():
    V = np.array([[1.0, 0], [0, 1], [2, 0], [0.5, 0.5]], dtype=np.float32)
    q = np.array([1.0, 0.0], dtype=np.float32)
    mask = np.array([True, True, False, True])  # best item excluded
    vals, idx = topk.topk_scores(q, V, mask, k=2)
    np.testing.assert_array_equal(np.asarray(idx), [0, 3])


def test_topk_batch_matches_loop():
    rng = np.random.default_rng(0)
    V = rng.normal(size=(50, 8)).astype(np.float32)
    Q = rng.normal(size=(7, 8)).astype(np.float32)
    bv, bi = topk.topk_scores_batch(Q, V, k=5)
    for row in range(7):
        sv, si = topk.topk_scores(Q[row], V, k=5)
        np.testing.assert_array_equal(np.asarray(bi)[row], np.asarray(si))


def test_cosine_topk_scale_invariant():
    V = np.array([[10.0, 0], [0, 0.1], [3, 3]], dtype=np.float32)
    q = np.array([5.0, 0.0], dtype=np.float32)
    vals, idx = topk.cosine_topk(q, V, k=3)
    # cosine ignores magnitude: item0 (parallel) wins with score 1
    assert int(np.asarray(idx)[0]) == 0
    np.testing.assert_allclose(float(np.asarray(vals)[0]), 1.0, rtol=1e-5)


def test_host_topk_nonpositive_k_returns_empty():
    """A negative num from request JSON must not return ~all entries
    (negative argpartition slice keeps n+k elements)."""
    import numpy as np
    from predictionio_tpu.ops.topk import host_topk
    scores = np.array([3.0, 1.0, 2.0])
    for k in (0, -1, -3):
        vals, idx = host_topk(scores, k)
        assert vals.size == 0 and idx.size == 0
