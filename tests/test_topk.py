"""Top-K scoring ops (serving hot path)."""

import numpy as np

from predictionio_tpu.ops import topk


def test_topk_scores_basic():
    V = np.array([[1.0, 0], [0, 1], [2, 0], [0.5, 0.5]], dtype=np.float32)
    q = np.array([1.0, 0.0], dtype=np.float32)
    vals, idx = topk.topk_scores(q, V, k=2)
    np.testing.assert_array_equal(np.asarray(idx), [2, 0])
    np.testing.assert_allclose(np.asarray(vals), [2.0, 1.0])


def test_topk_scores_mask_excludes():
    V = np.array([[1.0, 0], [0, 1], [2, 0], [0.5, 0.5]], dtype=np.float32)
    q = np.array([1.0, 0.0], dtype=np.float32)
    mask = np.array([True, True, False, True])  # best item excluded
    vals, idx = topk.topk_scores(q, V, mask, k=2)
    np.testing.assert_array_equal(np.asarray(idx), [0, 3])


def test_topk_batch_matches_loop():
    rng = np.random.default_rng(0)
    V = rng.normal(size=(50, 8)).astype(np.float32)
    Q = rng.normal(size=(7, 8)).astype(np.float32)
    bv, bi = topk.topk_scores_batch(Q, V, k=5)
    for row in range(7):
        sv, si = topk.topk_scores(Q[row], V, k=5)
        np.testing.assert_array_equal(np.asarray(bi)[row], np.asarray(si))


def test_cosine_topk_scale_invariant():
    V = np.array([[10.0, 0], [0, 0.1], [3, 3]], dtype=np.float32)
    q = np.array([5.0, 0.0], dtype=np.float32)
    vals, idx = topk.cosine_topk(q, V, k=3)
    # cosine ignores magnitude: item0 (parallel) wins with score 1
    assert int(np.asarray(idx)[0]) == 0
    np.testing.assert_allclose(float(np.asarray(vals)[0]), 1.0, rtol=1e-5)


def test_topk_for_users_tie_breaks_lowest_index():
    """Equal scores break by LOWEST item index (stable_topk): the
    contract the sharded serving merge reproduces bit-for-bit."""
    U = np.eye(2, dtype=np.float32)
    # items 1, 3, 4 score identically for user 0; 0 and 2 for user 1
    V = np.array([[0.0, 1.0], [2.0, 0.0], [0.0, 1.0],
                  [2.0, 0.0], [2.0, 0.0]], dtype=np.float32)
    vals, idx = topk.topk_for_users(U, V, np.array([0, 1], np.int32), k=4)
    np.testing.assert_array_equal(np.asarray(idx)[0], [1, 3, 4, 0])
    np.testing.assert_array_equal(np.asarray(idx)[1], [0, 2, 1, 3])
    np.testing.assert_allclose(np.asarray(vals)[0], [2, 2, 2, 0])


def test_topk_for_user_tie_breaks_lowest_index():
    U = np.eye(2, dtype=np.float32)
    V = np.array([[3.0, 0], [1.0, 0], [3.0, 0]], dtype=np.float32)
    _vals, idx = topk.topk_for_user(U, V, np.int32(0), k=3)
    np.testing.assert_array_equal(np.asarray(idx), [0, 2, 1])


def test_stable_topk_total_tie_is_iota():
    scores = np.zeros((3, 17), dtype=np.float32)
    vals, idx = topk.stable_topk(scores, 5)
    np.testing.assert_array_equal(np.asarray(idx),
                                  np.tile(np.arange(5), (3, 1)))
    assert np.asarray(vals).shape == (3, 5)


def test_host_topk_boundary_ties_lowest_index():
    """argpartition's selection at the k-th-value boundary is arbitrary
    among tied entries; host_topk must still pick (and order) the
    LOWEST indices — the same rule as stable_topk."""
    scores = np.array([2.0, 1.0, 2.0, 2.0, 0.5, 1.0], dtype=np.float32)
    vals, idx = topk.host_topk(scores, 4)
    np.testing.assert_array_equal(idx, [0, 2, 3, 1])
    np.testing.assert_allclose(vals, [2, 2, 2, 1])
    # all-equal scores: exactly the k lowest indices, in order
    ties = np.full(50, 7.0, dtype=np.float32)
    _v, i = topk.host_topk(ties, 5)
    np.testing.assert_array_equal(i, np.arange(5))
    # ties below the boundary don't disturb the strict head
    scores2 = np.array([9.0, 3.0, 3.0, 8.0, 3.0], dtype=np.float32)
    _v, i2 = topk.host_topk(scores2, 3)
    np.testing.assert_array_equal(i2, [0, 3, 1])


def test_host_masked_topk_batch_deterministic_ties():
    """The batched host kernel (per-row host_topk) breaks ties by
    lowest index with each query's own k."""
    factors = np.array([[1.0], [1.0], [2.0], [1.0]], dtype=np.float32)
    queries = np.array([[1.0], [1.0]], dtype=np.float32)
    masks = [np.ones(4, bool), np.array([True, True, False, True])]
    rows = topk.host_masked_topk_batch(factors, queries, masks, [3, 3])
    np.testing.assert_array_equal(rows[0][1], [2, 0, 1])
    np.testing.assert_array_equal(rows[1][1], [0, 1, 3])


def test_host_topk_matches_device_stable_topk():
    """Host and device kernels agree on selection AND order for data
    with engineered duplicates (low-bit float noise excluded by
    construction: scores are exact)."""
    rng = np.random.default_rng(7)
    scores = rng.integers(-5, 5, size=64).astype(np.float32)
    hv, hi = topk.host_topk(scores, 10)
    dv, di = topk.stable_topk(scores, 10)
    np.testing.assert_array_equal(hi, np.asarray(di))
    np.testing.assert_array_equal(hv, np.asarray(dv))


def test_host_topk_nonpositive_k_returns_empty():
    """A negative num from request JSON must not return ~all entries
    (negative argpartition slice keeps n+k elements)."""
    import numpy as np
    from predictionio_tpu.ops.topk import host_topk
    scores = np.array([3.0, 1.0, 2.0])
    for k in (0, -1, -3):
        vals, idx = host_topk(scores, k)
        assert vals.size == 0 and idx.size == 0
